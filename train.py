"""Training entry point (ref: train.py:33-94).

argparse -> Config -> mesh init -> dataloaders -> trainer -> epoch/iter
loop with dis_step/gen_step multipliers.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax

from imaginaire_tpu import resilience, telemetry
from imaginaire_tpu.resilience import chaos, cluster, elastic
from imaginaire_tpu.config import Config, cfg_get
from imaginaire_tpu.data import get_train_and_val_dataloader
from imaginaire_tpu.parallel.mesh import (
    create_mesh,
    fit_mesh_shape,
    honor_platform_env,
    master_only_print as print,  # noqa: A001
    maybe_init_distributed_from_env,
    mesh_from_config,
    set_mesh,
)
from imaginaire_tpu.registry import resolve
from imaginaire_tpu.utils.logging_utils import init_logging, make_logging_dir


def parse_args():
    parser = argparse.ArgumentParser(description="imaginaire-tpu training")
    parser.add_argument("--config", required=True)
    parser.add_argument("--logdir", default=None)
    parser.add_argument("--checkpoint", default="")
    parser.add_argument("--seed", type=int, default=2)
    parser.add_argument("--max_iter", type=int, default=None,
                        help="override cfg max_iter (smoke tests)")
    parser.add_argument("--debug-nans", action="store_true",
                        help="enable jax_debug_nans for CPU repro runs: "
                             "every primitive's output is checked and the "
                             "first NaN raises with the op's stack trace. "
                             "Implies trainer.donate_step_buffers=False — "
                             "the de-jitted re-run reads buffers donation "
                             "would already have invalidated. Expect a "
                             "large slowdown; pair with JAX_PLATFORMS=cpu "
                             "and a tiny config.")
    return parser.parse_args()


def _maybe_elastic_join():
    """Elastic joiner mode (ISSUE 11): ``IMAGINAIRE_ELASTIC_JOIN``
    names the logdir of a live elastic pod — this process announces a
    join request and blocks until the pod's grow plan admits it. The
    granted plan points the ``IMAGINAIRE_DIST_*`` contract at the
    agreed topology BEFORE any jax backend exists, so the normal
    startup path below needs no special casing; the plan's barrier
    epochs are adopted so the first counter-tagged rendezvous doesn't
    trip a spurious desync (satellite: barrier-epoch negotiation)."""
    logdir = os.environ.get("IMAGINAIRE_ELASTIC_JOIN")
    if not logdir:
        return None
    nonce = os.environ.get("IMAGINAIRE_ELASTIC_JOIN_NONCE",
                           f"join-{os.getpid()}")
    timeout_s = float(os.environ.get("IMAGINAIRE_ELASTIC_JOIN_TIMEOUT_S",
                                     "600"))
    elastic.request_join(logdir, nonce)
    plan = elastic.wait_for_join(logdir, nonce, timeout_s=timeout_s)
    cluster.adopt_barrier_epochs(plan.barrier_epochs)
    return plan


def main():
    honor_platform_env()
    # elastic joiner rendezvous must precede distributed init: it is
    # what PRODUCES the IMAGINAIRE_DIST_* contract for a joining host
    _maybe_elastic_join()
    # multi-process pods (ISSUE 8): IMAGINAIRE_DIST_* env vars (set by
    # scripts/launch_local_pod.py or a real pod launcher) initialize
    # jax.distributed BEFORE any backend exists — every jax.devices()
    # below then spans the whole pod
    maybe_init_distributed_from_env()
    args = parse_args()
    cfg = Config(args.config)
    if args.max_iter is not None:
        cfg.max_iter = args.max_iter
    if args.debug_nans:
        # the coarse in-run triage (diagnostics/) names the term/module;
        # this flag is the fine-grained follow-up that names the exact
        # primitive. Donation must be off: jax_debug_nans re-executes
        # the step de-jitted, and the jitted call already consumed the
        # donated state buffers.
        jax.config.update("jax_debug_nans", True)
        cfg.trainer.donate_step_buffers = False
        print("--debug-nans: jax_debug_nans on, step-buffer donation off "
              "(expect higher memory + much slower steps)")

    # single mesh entry point: cfg.parallel.mesh_shape (2-D data x model
    # + sharded update state, parallel/partition.py) wins over the
    # legacy runtime.mesh block
    set_mesh(mesh_from_config(cfg))
    date_uid, logdir = init_logging(args.config, args.logdir)
    make_logging_dir(logdir)
    cfg.logdir = logdir
    # structured run telemetry (telemetry/): spans + counters fan out to
    # the configured sinks (<logdir>/telemetry.jsonl by default); the
    # watchdog/trace knobs ride the same cfg section
    tm = telemetry.configure(cfg, logdir=logdir)
    # persistent-compile-cache guard (ISSUE 8 satellite): a warm-cache
    # RESUME rides the known-bad executable-deserialize path (flaky
    # NaN/SIGSEGV, PR-7 bisect) — off_on_resume (default) disables the
    # cache exactly when a checkpoint will be restored. Must run before
    # the first compile.
    from imaginaire_tpu.telemetry import xla_obs
    from imaginaire_tpu.utils import checkpoint as ckpt_lib

    resuming = bool(args.checkpoint) \
        or ckpt_lib.latest_checkpoint_path(logdir) is not None
    xla_obs.apply_persistent_cache_policy(cfg, resuming=resuming)
    # fault-tolerance layer (resilience/): retry policy + chaos
    # injection singleton, the SIGTERM preemption guard that drains the
    # in-flight step into an emergency checkpoint (ISSUE 7), and the
    # cluster coordination policy — timed barriers, per-step preemption
    # votes, cross-host heartbeats (ISSUE 8)
    rsettings = resilience.configure(cfg)
    guard = resilience.install_preemption_guard(cfg)
    cluster.start_heartbeat(cfg)
    sync_every = rsettings["cluster"]["sync_every_n_steps"] \
        if cluster.is_active() else 0
    # elastic pods (ISSUE 11): the coordinator owns the resize
    # lifecycle — shrink consensus over the KV store, grow rendezvous
    # through <logdir>/elastic/, in-process runtime teardown/re-init
    elastic_co = resilience.ElasticCoordinator(cfg, logdir=logdir)
    elastic_on = elastic_co.enabled and cluster.is_active()

    train_loader, val_loader = get_train_and_val_dataloader(cfg, seed=args.seed)
    trainer_cls = resolve(cfg.trainer.type, "Trainer")
    trainer = trainer_cls(cfg, train_data_loader=train_loader,
                          val_data_loader=val_loader)

    # hparams dashboard entry (ref: train.py + meters.add_hparams)
    from imaginaire_tpu.utils.meters import add_hparams

    add_hparams({
        "trainer": str(cfg.trainer.type),
        "gen": str(cfg.gen.type),
        "gen_lr": float(cfg_get(cfg.gen_opt, "lr", 0)),
        "dis_lr": float(cfg_get(cfg.dis_opt, "lr", 0)),
        "batch_size": int(cfg_get(cfg.data.train, "batch_size", 1)),
        "compute_dtype": str(cfg_get(cfg.trainer, "compute_dtype",
                                     "float32")),
        "seed": args.seed,
    }, {"metrics/placeholder": 0.0})

    sample = next(iter(train_loader))
    sample = trainer.start_of_iteration(sample, 0)
    trainer.init_state(jax.random.PRNGKey(args.seed), sample)
    if args.checkpoint:
        trainer.load_checkpoint(args.checkpoint)
    else:
        trainer.load_checkpoint()  # resume from pointer file if present

    current_iteration = trainer.current_iteration
    current_epoch = trainer.current_epoch
    # bit-exact resume (resilience/runstate.py): the checkpoint's
    # runstate sidecar recorded how many batches of the interrupted
    # epoch were already consumed; the first resumed epoch fast-forwards
    # the loader past them instead of replaying the epoch from batch 0
    # (the shuffle is seeded by (seed, epoch), so the skipped prefix is
    # exactly what the killed run already trained on).
    resume_offset = int(getattr(trainer, "resume_batch_in_epoch", 0) or 0)
    max_iter = cfg_get(cfg, "max_iter", 1000000)
    max_epoch = cfg_get(cfg, "max_epoch", 200)
    dis_steps = cfg_get(cfg.trainer, "dis_step", 1)
    gen_steps = cfg_get(cfg.trainer, "gen_step", 1)

    # Async device prefetch (data/device_prefetch.py): a producer thread
    # runs the host-side _start_of_iteration hook and commits batches to
    # device as sharded arrays while the previous step computes, so the
    # loop below never blocks on H2D. The epoch_base cell hands the hook
    # the iteration each read-ahead batch will be consumed at. With
    # data.device_prefetch off, feed is the loader and
    # start_of_iteration keeps the synchronous to_device transfer.
    epoch_base = [current_iteration]
    feed = trainer.data_prefetcher(
        train_loader, iteration_of=lambda index: epoch_base[0] + index)
    prefetching = feed is not train_loader
    timed_feed = None

    # supervise loop (ISSUE 11): the epoch loop runs inside a resume
    # loop. An ``ElasticResize`` unwinding out of it is not an error —
    # the survivors tear the distributed runtime down IN-PROCESS,
    # re-init the agreed (shrunken or grown) topology, restore through
    # the layout-agnostic no-target checkpoint path, and re-enter.
    # Every other exception propagates exactly as before.
    while True:
        try:
            for epoch in range(current_epoch, max_epoch):
                print(f"Epoch {epoch} ...")
                train_loader.set_epoch(epoch)
                trainer.start_of_epoch(epoch)
                epoch_base[0] = current_iteration
                if resume_offset:
                    if hasattr(feed, "fast_forward"):
                        feed.fast_forward(resume_offset)
                        print(f"Resume: fast-forwarding {resume_offset} "
                              f"already-consumed batch(es) of epoch "
                              f"{epoch}")
                    resume_offset = 0
                # each next(feed) is timed as a data_wait span: with the
                # prefetcher healthy it is ~0; a starved queue shows up
                # as the dominant phase in the telemetry table instead
                # of vanishing into "slow steps"
                timed_feed = tm.timed_iter(
                    feed, "data_wait",
                    step_of=lambda index: epoch_base[0] + index)
                data = None
                for it, data in enumerate(timed_feed):
                    data = trainer.start_of_iteration(data,
                                                      current_iteration)
                    data = chaos.get().maybe_nan_batch(data,
                                                       current_iteration)
                    for _ in range(dis_steps):
                        trainer.dis_update(data)
                    for _ in range(gen_steps):
                        trainer.gen_update(data)
                    current_iteration += 1
                    if prefetching:
                        trainer.write_data_meters(feed.drain_stats())
                    # distributed chaos (ISSUE 8): stall-one-of-N
                    # freezes THIS process here — after the step's
                    # collectives dispatched, before any cluster
                    # rendezvous — so the surviving hosts' next timed
                    # barrier (preemption vote or checkpoint entry)
                    # names it instead of hanging
                    chaos.get().maybe_stall(current_iteration)
                    trainer.end_of_iteration(data, epoch,
                                             current_iteration)
                    chaos.get().maybe_sigterm(current_iteration)
                    chaos.get().maybe_kill(current_iteration)
                    drain = guard is not None and guard.triggered
                    flagged = []
                    if sync_every:
                        # coordinated preemption (ISSUE 8): a SIGTERM
                        # lands on ONE host but the emergency save is
                        # collective — the per-step vote makes every
                        # host observe the same OR at the same
                        # iteration, so the pod drains together instead
                        # of deadlocking (one host in the save barrier,
                        # the rest in the next step's psum). Between
                        # vote iterations a locally-triggered guard
                        # DEFERS: draining solo is the deadlock this
                        # machinery exists to avoid.
                        if current_iteration % sync_every == 0:
                            if elastic_on:
                                # peer-loss signal 1 (ISSUE 11): a host
                                # that died WITHOUT a drain vote shows
                                # up as heartbeat staleness — shrink
                                # around it from the last checkpoint
                                stale = cluster.stalled_peers()
                                if stale and elastic_co.can_shrink(
                                        stale):
                                    print(f"Peer(s) {stale} heartbeat-"
                                          f"stale at iteration "
                                          f"{current_iteration} — "
                                          f"elastic shrink")
                                    timed_feed.close()
                                    raise elastic.ElasticResize(
                                        elastic_co.plan_shrink(
                                            stale, iteration=-1,
                                            epoch=epoch))
                            voted, flagged = \
                                cluster.coordinate_preemption(
                                    current_iteration, drain,
                                    return_flagged=True)
                            if voted and not drain and guard is not None:
                                guard.trigger_remote(flagged)
                            drain = drain or (voted and guard is not None)
                            if (elastic_on and not drain
                                    and elastic_co.settings.get(
                                        "grow_back", True)):
                                # scale-up (ISSUE 13): the master folds
                                # pending join requests into a grow
                                # announcement with a strictly-future
                                # target step (the KV write
                                # happens-before every peer's next
                                # post-barrier poll); at the target
                                # step the whole pod commits a
                                # synchronous checkpoint, publishes the
                                # new topology for the joiners, and
                                # resizes; cfg.resilience.elastic
                                # .grow_back=False pins the shrunken
                                # world (joiner requests stay queued)
                                if cluster.process_index() == 0:
                                    nonces = \
                                        elastic_co.check_join_requests()
                                    if nonces:
                                        elastic_co.announce_grow(
                                            current_iteration
                                            + 2 * sync_every, nonces)
                                grow = elastic_co.poll_grow()
                                if grow and current_iteration >= int(
                                        grow["target"]):
                                    trainer.save_checkpoint(
                                        epoch, current_iteration,
                                        emergency=True)
                                    plan = elastic_co.plan_grow(
                                        grow["joiners"],
                                        current_iteration, epoch)
                                    if cluster.process_index() == 0:
                                        elastic_co.publish_topology(plan)
                                        elastic_co.consume_join_requests(
                                            grow["joiners"])
                                    timed_feed.close()
                                    raise elastic.ElasticResize(plan)
                        else:
                            drain = False
                    if drain:
                        # preemption drain: the dispatched step already
                        # landed (save blocks on the live arrays), so
                        # commit an emergency checkpoint + run state
                        trainer.emergency_checkpoint(
                            epoch, current_iteration, guard)
                        # deterministic producer shutdown: closing the
                        # timed iterator unwinds the prefetcher's
                        # generator (stop flag + queue drain + producer
                        # join) before teardown or exit
                        timed_feed.close()
                        me = cluster.process_index()
                        if (elastic_on and me not in flagged
                                and elastic_co.can_shrink(flagged)):
                            # elastic drain split (ISSUE 11): the
                            # flagged host(s) exit below as before; the
                            # survivors run the shrink consensus and
                            # keep training in-process from the
                            # emergency checkpoint the FULL world just
                            # committed — its ZeRO shards are complete
                            plan = elastic_co.plan_shrink(
                                flagged, iteration=current_iteration,
                                epoch=epoch)
                            if guard is not None:
                                guard.reset()
                            raise elastic.ElasticResize(plan)
                        _finalize_run(trainer)
                        # the exit line prints BEFORE any teardown:
                        # print here is the master-gated wrapper, and
                        # is_master() -> jax.process_index() would try
                        # to REBUILD the cpu backend after
                        # force_teardown detached the distributed
                        # client (its gloo collectives factory then
                        # gets a None client and the process dies 1,
                        # not 75)
                        print(f"Preempted at iteration "
                              f"{current_iteration}; emergency "
                              f"checkpoint committed — exit "
                              f"{resilience.EXIT_PREEMPTED} (resumable)")
                        if elastic_on:
                            # a flagged host leaving an elastic pod
                            # detaches its distributed client before
                            # exiting: the survivors LEAK (never shut
                            # down) the old coordination service, and
                            # an attached client whose coordinator
                            # later vanishes mid-exit can abort the
                            # interpreter instead of exiting 75
                            cluster.stop_heartbeat()
                            elastic.force_teardown()
                        sys.exit(resilience.EXIT_PREEMPTED)
                    if current_iteration >= max_iter:
                        print("Done with training!!!")
                        trainer.save_checkpoint(epoch, current_iteration)
                        _finalize_run(trainer)
                        return
                if data is None:
                    # resumed exactly at an epoch boundary: every batch
                    # of this epoch was consumed before the kill —
                    # nothing to replay
                    continue
                trainer.end_of_epoch(data, epoch, current_iteration)
            print("Done with training!!!")
            _finalize_run(trainer)
            return
        except elastic.ElasticResize as resize:
            plan = resize.plan
        except cluster.ClusterDesyncError as desync:
            # peer-loss signal 2 (ISSUE 11): a timed collective expired
            # and named the absent process(es). When the survivors may
            # reshape, shrink around them; otherwise fail the pod
            # loudly, exactly as before.
            if not (elastic_on and elastic_co.can_shrink(desync.absent)):
                raise
            if timed_feed is not None:
                try:
                    timed_feed.close()
                except Exception:  # noqa: BLE001 — already unwinding
                    pass
            print(f"Cluster desync (absent: {list(desync.absent)}) — "
                  f"elastic shrink instead of pod restart")
            plan = elastic_co.plan_shrink(
                desync.absent, iteration=-1,
                epoch=int(getattr(trainer, "current_epoch", 0) or 0))
            if guard is not None:
                guard.reset()

        # ---- apply the agreed resize in-process and re-enter --------
        t_down = time.perf_counter()
        print(f"Elastic resize: generation {plan.generation}, world "
              f"{plan.old_world} -> {plan.world_size} ({plan.reason})")
        try:
            # redistribution plan (ISSUE 13): route each state leaf
            # between the checkpoint reshard path and a direct carry.
            # The gather snapshot MUST land before apply() — teardown
            # clears the backend the live arrays live on.
            rplan = elastic.RedistributionPlanner(
                plan, trainer.current_iteration, trainer.state)
            carry = (rplan.snapshot(trainer.state)
                     if trainer.state is not None and rplan.routes
                     else {})
            phases = elastic_co.apply(plan)
            t_mesh = time.perf_counter()
            axes, dims = fit_mesh_shape(cfg, jax.device_count())
            set_mesh(create_mesh(axes, dims))
            phases["mesh_ms"] = round(
                (time.perf_counter() - t_mesh) * 1000.0, 3)
            t_restore = time.perf_counter()
            trainer.elastic_rebind()
            if carry and rplan.all_gather:
                # every leaf carried live: skip the orbax round-trip
                # and re-commit directly under the new shardings
                trainer.elastic_recommit(carry, plan.iteration,
                                         plan.epoch)
            else:
                trainer.set_elastic_carry(carry)
                trainer.load_checkpoint()
            phases["restore_ms"] = round(
                (time.perf_counter() - t_restore) * 1000.0, 3)
        except Exception as e:  # noqa: BLE001 — resize is best-effort
            import traceback

            traceback.print_exc()
            # builtin print, not master_only_print: process_index()
            # would boot a LOCAL backend if the re-init died mid-way
            sys.stderr.write(
                f"elastic resize failed ({e}); the checkpointed state "
                f"is intact — exit {resilience.EXIT_ELASTIC_RESTART} "
                f"for a supervisor relaunch\n")
            try:
                telemetry.get().shutdown()
            except Exception:  # noqa: BLE001 — exiting either way
                pass
            sys.exit(resilience.EXIT_ELASTIC_RESTART)
        downtime_ms = (time.perf_counter() - t_down) * 1000.0
        elastic_co.record_resize(plan, downtime_ms, phases,
                                 redistribution=rplan.summary())
        current_iteration = trainer.current_iteration
        current_epoch = trainer.current_epoch
        resume_offset = int(getattr(trainer, "resume_batch_in_epoch", 0)
                            or 0)
        epoch_base = [current_iteration]
        feed = trainer.data_prefetcher(
            train_loader,
            iteration_of=lambda index: epoch_base[0] + index)
        prefetching = feed is not train_loader
        timed_feed = None
        print(f"Elastic resize complete in {downtime_ms:.0f}ms — "
              f"resuming at iteration {current_iteration}, epoch "
              f"{current_epoch}")


def _finalize_run(trainer=None):
    """Async checkpoint saves must commit — and the health monitor's
    pending step plus telemetry's final window must flush — before the
    process exits."""
    from imaginaire_tpu.utils.checkpoint import wait_for_pending_checkpoint

    if trainer is not None:
        # the monitor polls with one-step lag; the final step's health
        # entry (and any non-finite verdict) is still pending here
        trainer.diag.drain(trainer)
    wait_for_pending_checkpoint()
    telemetry.get().shutdown()


if __name__ == "__main__":
    main()

"""Inference entry point (ref: inference.py:37-94).

Load a config + checkpoint, run the trainer's test loop over the test
set, and write images to --output_dir.
"""

from __future__ import annotations

import argparse

import jax

from imaginaire_tpu import telemetry
from imaginaire_tpu.config import Config, cfg_get
from imaginaire_tpu.data import get_test_dataloader
from imaginaire_tpu.parallel.mesh import (
    honor_platform_env,
    master_only_print as print,  # noqa: A001
    maybe_init_distributed_from_env,
    mesh_from_config,
    set_mesh,
)
from imaginaire_tpu.registry import resolve
from imaginaire_tpu.utils.logging_utils import init_logging, make_logging_dir


def parse_args():
    parser = argparse.ArgumentParser(description="imaginaire-tpu inference")
    parser.add_argument("--config", required=True)
    parser.add_argument("--checkpoint", default="",
                        help="Checkpoint path; defaults to the logdir's "
                             "latest_checkpoint pointer.")
    parser.add_argument("--output_dir", required=True)
    parser.add_argument("--logdir", default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--no-serving-engine", action="store_true",
                        help="Run the legacy eager test loop instead of "
                             "routing through the serving engine's "
                             "ledgered executables.")
    return parser.parse_args()


def main():
    honor_platform_env()
    maybe_init_distributed_from_env()
    args = parse_args()
    cfg = Config(args.config)
    # cfg.parallel.mesh_shape wins over the legacy runtime.mesh block
    # (checkpoints restore shard-aware either way — trainers reshard on
    # load via the partition sidecar)
    set_mesh(mesh_from_config(cfg))
    date_uid, logdir = init_logging(args.config, args.logdir)
    make_logging_dir(logdir)
    cfg.logdir = logdir
    # inference runs produce the same telemetry jsonl as training:
    # data_wait/eval spans from the test loop, ckpt_load spans, and the
    # xla_obs compile ledger / memory counters (ISSUE 5 satellite)
    telemetry.configure(cfg, logdir=logdir)

    test_loader = get_test_dataloader(cfg)
    trainer_cls = resolve(cfg.trainer.type, "Trainer")
    trainer = trainer_cls(cfg, val_data_loader=test_loader)

    sample = next(iter(test_loader))
    sample = trainer.start_of_iteration(sample, 0)
    trainer.init_state(jax.random.PRNGKey(args.seed), sample)
    # serving restore rides the verified path end to end (ISSUE 8
    # satellite): discovery already quarantines + falls back to the
    # last-good checkpoint; an explicit --checkpoint that fails
    # integrity is quarantined and the newest verifiable sibling
    # restores instead — a server must never deserialize bytes the
    # training integrity layer refuses (corrupt compressed chunks fed
    # to the native decoder are a heap hazard, not a wrong pixel).
    loaded = trainer.load_checkpoint(args.checkpoint or None,
                                     fallback=bool(args.checkpoint))
    if not loaded:
        print("WARNING: no checkpoint found; running with fresh weights.")

    trainer.current_epoch = -1
    trainer.current_iteration = -1
    if not args.no_serving_engine:
        # route the test loop through the serving engine (ISSUE 19):
        # the forward compiles once into the ledgered executable pool
        # (recompile tripwire armed) and every batch lands serve/*
        # SLO counters in the same telemetry jsonl. Outputs are the
        # jitted legacy computation — same weights, same noise keys.
        from imaginaire_tpu.serving import ServingEngine

        engine = ServingEngine(cfg, trainer=trainer, logdir=logdir)
        engine.register_example(sample)
        engine.refresh_weights()
        engine.attach()
    inference_args = cfg_get(cfg, "inference_args", None)
    trainer.test(test_loader, args.output_dir,
                 dict(inference_args) if inference_args else None)
    telemetry.get().shutdown()
    print(f"Done with inference. Outputs in {args.output_dir}")


if __name__ == "__main__":
    main()

"""Mesh-sharded continuous-eval plane (ISSUE 18 tentpole).

Quality was the last unobserved axis: throughput regressions gate CI
(bench legs, `check_run_health`), but no FID ever reached telemetry —
`evaluate.py` ran offline, serial, and recomputed its reference
features every invocation. This module makes "did the model get worse"
as observable as "did the step get slower":

- **Sharded sweep**: eval batches go through ``place_committed_batch``
  (the same committed data-axis placement as training batches), the
  ledgered inception extractor runs the forward data-parallel over the
  mesh, and per-host activations join through the timed
  ``host_all_gather`` — a host lost mid-sweep raises a named desync on
  the survivors instead of hanging the pod.
- **Reference store**: real-set activations come from the
  content-addressed ``FeatureStore`` — computed once per (dataset,
  extractor weights, resolution, preprocessing) ever, hit/miss visible
  as ``eval/ref_cache_hit``.
- **One schema**: every sweep — continuous (trainers/base.py cadence
  hook) or offline (evaluate.py) — emits the same ``eval/fid``,
  ``eval/kid``, ``eval/time_to_fid_ms``, ``eval/ref_cache_hit``
  counters and ``eval/sweep`` meta into the run's jsonl, so
  `report.py` renders one "## quality" trend table and
  `check_run_health --max-fid` gates either kind of run.
- **Regression sentinel**: an EWMA baseline over sweep FIDs; a sweep
  worse than the baseline by more than ``regression_threshold``
  (relative) for ``regression_consecutive`` sweeps in a row emits an
  ``eval/regression`` meta naming the metric, step, and delta, and
  bumps the cumulative ``eval/regressions`` counter that
  ``--max-quality-regressions`` gates on.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from imaginaire_tpu import telemetry
from imaginaire_tpu.evaluation.feature_store import (
    FeatureStore,
    evaluation_settings,
    extractor_id,
    reference_key,
    resolve_store_dir,
)

logger = logging.getLogger(__name__)


def make_patch_extractor(grid=8):
    """Mean-pooled pixel-patch features: (B, H, W, C) -> (B, grid*grid*C).

    A smoke-test stand-in for the Inception extractor
    (``cfg.evaluation.extractor: patch``): distribution distances over
    pooled pixel statistics still move when the generator's output
    drifts, which is all the CI legs need — while the forward is a
    single resize and the FID covariance shrinks from 2048^2 to
    (grid^2*C)^2, turning a ~10 s scipy sqrtm into milliseconds. NOT a
    perceptual metric; never record its numbers in a tracked series.
    Compiles through the ledger like the real extractor so the plane's
    accounting path stays identical."""
    import jax

    from imaginaire_tpu.telemetry import xla_obs

    def run(images):
        b, _, _, c = images.shape
        x = jax.image.resize(images.astype("float32"),
                             (b, grid, grid, c), method="linear")
        return x.reshape(b, grid * grid * c)

    program = xla_obs.compiled_program("patch_eval_extractor", run,
                                       allow_shape_growth=True)

    def extractor(images):
        return program(images)

    extractor.program = program  # audit/ledger surface
    return extractor


class RegressionSentinel:
    """EWMA quality-trend detector over sweep FIDs.

    FID is noisy sweep-to-sweep (subset sampling, generator
    stochasticity), so the baseline is an EWMA rather than the previous
    point, the comparison is *relative* (a 0.05 threshold means "5%
    worse than trend"), and a single bad sweep never fires — only
    ``consecutive`` breaches in a row do. Lower FID is better, so only
    positive deltas (worsening) count; improvements reset the streak
    and pull the baseline down.
    """

    def __init__(self, threshold=0.05, consecutive=2, beta=0.5):
        self.threshold = float(threshold)
        self.consecutive = max(1, int(consecutive))
        self.beta = float(beta)
        self.ewma = None
        self.streak = 0
        self.fired = 0

    def observe(self, value, step=None, metric="fid"):
        """Feed one sweep's metric; returns a regression dict when the
        sentinel fires (and emits the ``eval/regression`` meta +
        ``eval/regressions`` counter), else None."""
        value = float(value)
        fired = None
        if self.ewma is not None and np.isfinite(self.ewma):
            delta = (value - self.ewma) / max(abs(self.ewma), 1e-8)
            if delta > self.threshold:
                self.streak += 1
            else:
                self.streak = 0
            if self.streak >= self.consecutive:
                self.fired += 1
                fired = {
                    "metric": metric, "step": step,
                    "value": round(value, 4),
                    "baseline": round(float(self.ewma), 4),
                    "delta": round(float(delta), 4),
                    "threshold": self.threshold,
                    "streak": self.streak,
                }
                tm = telemetry.get()
                if tm.enabled:
                    tm.meta("eval/regression", **fired)
                    tm.counter("eval/regressions", self.fired, step=step)
                logger.warning(
                    "quality regression: %s %.3f vs EWMA baseline %.3f "
                    "(+%.1f%%, %d consecutive breaches) at step %s",
                    metric, value, self.ewma, 100.0 * delta,
                    self.streak, step)
        if self.ewma is None or not np.isfinite(self.ewma):
            self.ewma = value
        else:
            self.ewma = self.beta * self.ewma + (1.0 - self.beta) * value
        return fired


class EvalPlane:
    """One training/eval process's quality-observability plane.

    Owns the reference-feature store, the regression sentinel, and the
    sweep counter; ``run_sweep`` is the single entry point both the
    continuous-eval cadence hook (trainers/base.py) and offline
    ``evaluate.py`` route through, so both emit the identical ``eval/*``
    schema.
    """

    def __init__(self, cfg=None, logdir=None, store_dir=None):
        self.settings = evaluation_settings(cfg)
        self.sentinel = RegressionSentinel(
            threshold=self.settings["regression_threshold"],
            consecutive=self.settings["regression_consecutive"],
            beta=self.settings["ewma_beta"])
        root = store_dir or resolve_store_dir(cfg)
        if root is None and logdir:
            import os

            root = os.path.join(str(logdir), "feature_store")
        self.store = (FeatureStore(root)
                      if (root and self.settings["store"]) else None)
        self.sweeps = 0

    # -- reference side -------------------------------------------------
    def reference_activations(self, data_loader, key_real, extractor,
                              dataset_name="dataset", resolution="native",
                              weights_path=None, random_init=False,
                              max_batches=None, extractor_tag=None):
        """Real-set activations through the store: content-addressed
        get, compute-on-miss (sharded, instrumented), atomic put.
        Returns (acts, hit) — ``hit`` feeds ``eval/ref_cache_hit``
        honestly (no in-memory shortcut: a second sweep's hit proves
        the on-disk shard round-trips). ``extractor_tag`` overrides the
        inception weights identity for non-inception extractors (the
        patch smoke extractor) so their shards never collide."""
        from imaginaire_tpu.evaluation.common import get_activations

        eid = extractor_tag or extractor_id(weights_path=weights_path,
                                            random_init=random_init)
        key = reference_key(dataset_name, eid, resolution,
                            max_batches=max_batches)
        if self.store is not None:
            acts = self.store.get(key)
            if acts is not None:
                return acts, True
        acts = get_activations(data_loader, key_real, None, extractor,
                               generator_fn=None, max_batches=max_batches)
        if self.store is not None and acts.shape[0]:
            self.store.put(key, acts, dataset=dataset_name,
                           extractor=eid, resolution=str(resolution))
        return acts, False

    # -- the sweep ------------------------------------------------------
    def run_sweep(self, data_loader, key_real, key_fake, extractor,
                  generator_fn, step=None, dataset_name="dataset",
                  resolution="native", weights_path=None,
                  random_init=False, max_batches=None, metrics=None,
                  extractor_tag=None):
        """One full quality sweep: reference acts via the store, fake
        acts via the sharded instrumented loop, FID (+ optional KID),
        counters, sentinel. Returns the results dict (also suitable for
        the caller's meters/jsonl)."""
        from imaginaire_tpu.evaluation.common import get_activations
        from imaginaire_tpu.evaluation.fid import (
            activation_stats,
            calculate_frechet_distance,
        )
        from imaginaire_tpu.resilience import chaos

        metrics = [m.lower() for m in (metrics or self.settings["metrics"])]
        max_batches = (max_batches if max_batches is not None
                       else self.settings["max_batches"])
        self.sweeps += 1
        sweep = self.sweeps
        t0 = time.perf_counter()
        tm = telemetry.get()

        act_real, ref_hit = self.reference_activations(
            data_loader, key_real, extractor, dataset_name=dataset_name,
            resolution=resolution, weights_path=weights_path,
            random_init=random_init, max_batches=max_batches,
            extractor_tag=extractor_tag)
        act_fake = get_activations(
            data_loader, key_real, key_fake, extractor,
            generator_fn=generator_fn, max_batches=max_batches)
        if not act_real.shape[0] or not act_fake.shape[0]:
            logger.warning("eval sweep %d produced empty activation sets "
                           "(real=%d fake=%d) — skipping metrics",
                           sweep, act_real.shape[0], act_fake.shape[0])
            return None

        mu_r, sig_r = activation_stats(act_real)
        mu_f, sig_f = activation_stats(act_fake)
        fid = float(calculate_frechet_distance(mu_r, sig_r, mu_f, sig_f))
        fid = chaos.get().maybe_degrade_eval(fid, sweep)
        out = {"fid": fid, "sweep": sweep, "step": step,
               "ref_cache_hit": bool(ref_hit),
               "num_real": int(act_real.shape[0]),
               "num_fake": int(act_fake.shape[0])}
        if "kid" in metrics:
            from imaginaire_tpu.evaluation.kid import kid_from_activations

            out["kid"] = float(kid_from_activations(act_real, act_fake))
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        out["time_to_fid_ms"] = elapsed_ms

        if tm.enabled:
            tm.counter("eval/fid", fid, step=step)
            if "kid" in out:
                tm.counter("eval/kid", out["kid"], step=step)
            tm.counter("eval/time_to_fid_ms", elapsed_ms, step=step)
            tm.counter("eval/ref_cache_hit", 1 if ref_hit else 0,
                       step=step)
            tm.meta("eval/sweep", **{k: v for k, v in out.items()
                                     if k != "step"}, step=step,
                    dataset=str(dataset_name))
        regression = self.sentinel.observe(fid, step=step)
        if regression is not None:
            out["regression"] = regression
        return out

    def store_stats(self):
        return self.store.stats() if self.store is not None else None

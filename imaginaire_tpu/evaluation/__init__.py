"""Evaluation metrics (ref: imaginaire/evaluation/): FID, KID, PRDC over
Inception-v3 activations."""

from imaginaire_tpu.evaluation.common import (
    get_activations,
    get_video_activations,
    preprocess_for_inception,
)
from imaginaire_tpu.evaluation.fid import (
    calculate_frechet_distance,
    compute_fid,
    load_or_compute_stats,
)
from imaginaire_tpu.evaluation.inception import InceptionV3, load_params, make_extractor
from imaginaire_tpu.evaluation.kid import compute_kid, kid_from_activations
from imaginaire_tpu.evaluation.prdc import compute_prdc, prdc_from_activations

__all__ = [
    "get_activations", "get_video_activations", "preprocess_for_inception",
    "calculate_frechet_distance", "compute_fid", "load_or_compute_stats",
    "InceptionV3", "load_params", "make_extractor",
    "compute_kid", "kid_from_activations",
    "compute_prdc", "prdc_from_activations",
]

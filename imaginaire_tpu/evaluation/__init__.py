"""Evaluation metrics (ref: imaginaire/evaluation/): FID, KID, PRDC over
Inception-v3 activations — plus the ISSUE-18 quality observability
plane (mesh-sharded continuous eval, content-addressed reference-
feature store, EWMA regression sentinel)."""

from imaginaire_tpu.evaluation.common import (
    get_activations,
    get_video_activations,
    preprocess_for_inception,
)
from imaginaire_tpu.evaluation.feature_store import (
    FeatureStore,
    evaluation_settings,
    extractor_id,
    reference_key,
    resolve_store_dir,
)
from imaginaire_tpu.evaluation.fid import (
    calculate_frechet_distance,
    compute_fid,
    load_or_compute_stats,
)
from imaginaire_tpu.evaluation.inception import InceptionV3, load_params, make_extractor
from imaginaire_tpu.evaluation.kid import compute_kid, kid_from_activations
from imaginaire_tpu.evaluation.plane import (
    EvalPlane,
    RegressionSentinel,
    make_patch_extractor,
)
from imaginaire_tpu.evaluation.prdc import compute_prdc, prdc_from_activations

__all__ = [
    "get_activations", "get_video_activations", "preprocess_for_inception",
    "FeatureStore", "evaluation_settings", "extractor_id",
    "reference_key", "resolve_store_dir",
    "calculate_frechet_distance", "compute_fid", "load_or_compute_stats",
    "InceptionV3", "load_params", "make_extractor",
    "compute_kid", "kid_from_activations",
    "EvalPlane", "RegressionSentinel", "make_patch_extractor",
    "compute_prdc", "prdc_from_activations",
]

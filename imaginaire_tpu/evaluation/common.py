"""Activation harness for metrics (ref: imaginaire/evaluation/common.py).

``get_activations`` loops a loader, optionally runs the generator, then
imagenet-normalizes, resizes to 299, and feeds the Inception extractor
(ref: common.py:15-76). ``get_video_activations`` shards sequences
round-robin across host processes and rolls the trainer frame by frame
(ref: common.py:79-158).

Cross-process gather: the reference all-gathers per-rank activations
(ref: common.py:68, dist_all_gather_tensor); the multi-host equivalent is
``multihost_utils.process_allgather``. Single-process runs skip it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from imaginaire_tpu.utils.misc import apply_imagenet_normalization


def preprocess_for_inception(images):
    """[-1,1] NHWC float -> imagenet-normalized 299x299 (ref: common.py:44-60).

    Only the first 3 channels are used (fork 4-channel support,
    ref: evaluation/common.py:60 — handled inside
    apply_imagenet_normalization).
    """
    x = apply_imagenet_normalization(jnp.clip(images, -1.0, 1.0))
    b, h, w, c = x.shape
    if (h, w) != (299, 299):
        x = jax.image.resize(x, (b, 299, 299, c), method="bilinear")
    return x


def _allgather_if_multihost(acts):
    """Cross-host activation gather through the TIMED collective
    (ISSUE 8): a host that died mid-sweep raises ClusterDesyncError
    naming it on every survivor instead of parking the whole pod in
    ``process_allgather`` forever."""
    if jax.process_count() > 1:
        from imaginaire_tpu.parallel.collectives import host_all_gather

        return np.asarray(
            host_all_gather(acts, tiled=False,
                            name="eval_activations")).reshape(
            -1, acts.shape[-1])
    return acts


def get_activations(data_loader, key_real, key_fake, extractor,
                    generator_fn=None, max_batches=None):
    """Per-host activation loop (ref: common.py:15-76).

    generator_fn: data -> fake images in [-1,1] NHWC, or None to read
    ``data[key_real]`` directly. Returns np (N, 2048) gathered over hosts.

    ISSUE 18: the loop no longer runs dark under the watchdog's eval
    exemption — each batch's generator forward lands in an
    ``eval_generate`` span and the extractor forward + host sync in
    ``eval_extract``, with an ``eval/batches`` counter per sweep, so
    the report's phase table attributes eval wall-clock the same way it
    does training steps. Real-image batches are placed through
    ``place_committed_batch`` so the inception forward shards over the
    mesh's data axis instead of running replicated on one device.
    """
    from imaginaire_tpu import telemetry
    from imaginaire_tpu.parallel.sharding import place_committed_batch

    tm = telemetry.get()
    acts = []
    batches = 0
    for it, data in enumerate(data_loader):
        if max_batches is not None and it >= max_batches:
            break
        if generator_fn is None:
            # device-prefetched batches are already placed jax arrays;
            # host batches get the committed data-axis placement
            images = data[key_real]
            if not isinstance(images, jax.Array):
                images = place_committed_batch(np.asarray(images))
        else:
            with tm.span("eval_generate"):
                images = generator_fn(data)
        with tm.span("eval_extract"):
            feats = extractor(preprocess_for_inception(images))
            # np.asarray is the device->host sync: the span must absorb
            # it or the extract time would be billed to the next batch
            acts.append(np.asarray(feats))
        batches += 1
    if tm.enabled and batches:
        tm.counter("eval/batches", batches)
    if not acts:
        return np.zeros((0, 2048), np.float32)
    return _allgather_if_multihost(np.concatenate(acts, axis=0))


def get_video_activations(data_loader, key_real, key_fake, trainer,
                          extractor, sample_size=None):
    """Video models: shard sequences round-robin by process index, reset
    the trainer per sequence, run test_single per frame
    (ref: common.py:79-158)."""
    from imaginaire_tpu import telemetry

    tm = telemetry.get()
    dataset = data_loader.dataset
    num_seq = dataset.num_inference_sequences()
    indices = list(range(num_seq))
    if sample_size is not None:
        # cap the TOTAL video count before sharding, so multi-host runs
        # evaluate sample_size sequences, not sample_size per process
        indices = indices[:sample_size]
    indices = indices[jax.process_index()::jax.process_count()]
    acts = []
    batches = 0
    for seq_idx in indices:
        dataset.set_inference_sequence_idx(seq_idx)
        if trainer is not None:
            trainer.reset()
        for data in data_loader:
            if trainer is None:
                images = jnp.asarray(np.asarray(data[key_real]))
                if images.ndim == 5:  # (B, T=1, H, W, C) frame windows
                    images = images.reshape((-1,) + images.shape[2:])
            else:
                with tm.span("eval_generate"):
                    out = trainer.test_single(data)
                    images = out["fake_images"]
            with tm.span("eval_extract"):
                feats = extractor(preprocess_for_inception(images))
                acts.append(np.asarray(feats))
            batches += 1
    if tm.enabled and batches:
        tm.counter("eval/batches", batches)
    if not acts:
        return np.zeros((0, 2048), np.float32)
    return _allgather_if_multihost(np.concatenate(acts, axis=0))

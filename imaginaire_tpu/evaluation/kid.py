"""KID: polynomial-kernel MMD over Inception activations
(ref: imaginaire/evaluation/kid.py:29-345).

Unbiased MMD^2 with kernel k(x,y) = (x.y/d + 1)^3, averaged over
``num_subsets`` random subsets of size ``subset_size``
(ref: kid.py, polynomial_mmd_averages semantics).
"""

from __future__ import annotations

import numpy as np

from imaginaire_tpu.evaluation.common import get_activations


def polynomial_kernel(x, y, degree=3, gamma=None, coef0=1.0):
    d = x.shape[1]
    gamma = gamma if gamma is not None else 1.0 / d
    return (x @ y.T * gamma + coef0) ** degree


def polynomial_mmd(x, y, degree=3, gamma=None, coef0=1.0):
    """Unbiased MMD^2 estimate."""
    kxx = polynomial_kernel(x, x, degree, gamma, coef0)
    kyy = polynomial_kernel(y, y, degree, gamma, coef0)
    kxy = polynomial_kernel(x, y, degree, gamma, coef0)
    m = x.shape[0]
    n = y.shape[0]
    sum_xx = (kxx.sum() - np.trace(kxx)) / (m * (m - 1))
    sum_yy = (kyy.sum() - np.trace(kyy)) / (n * (n - 1))
    sum_xy = kxy.mean()
    return sum_xx + sum_yy - 2 * sum_xy


def kid_from_activations(act_real, act_fake, num_subsets=100,
                         subset_size=1000, seed=0):
    rng = np.random.RandomState(seed)
    n = min(subset_size, act_real.shape[0], act_fake.shape[0])
    vals = []
    for _ in range(num_subsets):
        r = act_real[rng.choice(act_real.shape[0], n, replace=False)]
        f = act_fake[rng.choice(act_fake.shape[0], n, replace=False)]
        vals.append(polynomial_mmd(r, f))
    return float(np.mean(vals))


def compute_kid(data_loader, extractor, generator_fn,
                key_real="images", key_fake="fake_images",
                num_subsets=100, subset_size=1000, max_batches=None):
    """(ref: kid.py:29)."""
    act_fake = get_activations(data_loader, key_real, key_fake, extractor,
                               generator_fn=generator_fn,
                               max_batches=max_batches)
    act_real = get_activations(data_loader, key_real, key_fake, extractor,
                               max_batches=max_batches)
    return kid_from_activations(act_real, act_fake, num_subsets, subset_size)

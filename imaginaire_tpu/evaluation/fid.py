"""FID (ref: imaginaire/evaluation/fid.py:16-226).

Per-host activations are gathered (common.py), the master computes
mean/cov — real stats cached to ``.npz`` next to the data
(ref: fid.py:102-137) — and the Frechet distance runs on host CPU via
``scipy.linalg.sqrtm`` (ref: fid.py:178-226).
"""

from __future__ import annotations

import os

import numpy as np

from imaginaire_tpu.evaluation.common import get_activations, get_video_activations
from imaginaire_tpu.parallel.mesh import is_master, master_only_print as print  # noqa: A001


# Version of the inception feature graph the cached stats were computed
# with. Bump whenever the extractor's numerics change (e.g. the
# count_include_pad fix) so stale caches are recomputed, not silently
# mixed with features from a different graph.
FEATURE_GRAPH_VERSION = 2


def activation_stats(acts):
    mu = np.mean(acts, axis=0)
    sigma = np.cov(acts, rowvar=False)
    return mu, sigma


def calculate_frechet_distance(mu1, sigma1, mu2, sigma2, eps=1e-6):
    """||mu1-mu2||^2 + Tr(s1 + s2 - 2 sqrt(s1 s2)) (ref: fid.py:178-226)."""
    from scipy import linalg

    mu1, mu2 = np.atleast_1d(mu1), np.atleast_1d(mu2)
    sigma1, sigma2 = np.atleast_2d(sigma1), np.atleast_2d(sigma2)
    diff = mu1 - mu2
    covmean, _ = linalg.sqrtm(sigma1.dot(sigma2), disp=False)
    if not np.isfinite(covmean).all():
        offset = np.eye(sigma1.shape[0]) * eps
        covmean = linalg.sqrtm((sigma1 + offset).dot(sigma2 + offset))
    if np.iscomplexobj(covmean):
        if not np.allclose(np.diagonal(covmean).imag, 0, atol=1e-3):
            m = np.max(np.abs(covmean.imag))
            print(f"FID: imaginary component {m}")
        covmean = covmean.real
    return float(diff.dot(diff) + np.trace(sigma1) + np.trace(sigma2)
                 - 2 * np.trace(covmean))


def load_or_compute_stats(path, data_loader, key_real, key_fake, extractor,
                          generator_fn=None, trainer=None, is_video=False,
                          sample_size=None, max_batches=None):
    """Cache-aware stats (ref: fid.py:102-137): fake stats are always
    recomputed; real stats load from ``path`` when present."""
    if path and os.path.exists(path) and generator_fn is None and trainer is None:
        npz = np.load(path)
        if int(npz.get("graph_version", 0)) == FEATURE_GRAPH_VERSION:
            return npz["mu"], npz["sigma"]
        print(f"FID: stale real-stat cache at {path} (feature graph "
              f"v{int(npz.get('graph_version', 0))} != "
              f"v{FEATURE_GRAPH_VERSION}), recomputing")
    if is_video:
        acts = get_video_activations(data_loader, key_real, key_fake,
                                     trainer, extractor, sample_size)
    else:
        acts = get_activations(data_loader, key_real, key_fake, extractor,
                               generator_fn=generator_fn,
                               max_batches=max_batches)
    mu, sigma = activation_stats(acts)
    if path and generator_fn is None and trainer is None and is_master():
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, mu=mu, sigma=sigma,
                 graph_version=FEATURE_GRAPH_VERSION)
        print(f"FID: cached real stats to {path}")
    return mu, sigma


def compute_fid(fid_path, data_loader, extractor, generator_fn,
                key_real="images", key_fake="fake_images",
                trainer=None, is_video=False, sample_size=None,
                max_batches=None):
    """End-to-end FID (ref: fid.py:16-58). ``fid_path`` holds the cached
    real-stat ``.npz`` (named after the dataset, ref: fid.py:107-110)."""
    mu_fake, sigma_fake = load_or_compute_stats(
        None, data_loader, key_real, key_fake, extractor,
        generator_fn=generator_fn, trainer=trainer, is_video=is_video,
        sample_size=sample_size, max_batches=max_batches)
    mu_real, sigma_real = load_or_compute_stats(
        fid_path, data_loader, key_real, key_fake, extractor,
        is_video=is_video, sample_size=sample_size,
        max_batches=max_batches)
    return calculate_frechet_distance(mu_fake, sigma_fake, mu_real, sigma_real)

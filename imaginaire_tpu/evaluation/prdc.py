"""PRDC: precision / recall / density / coverage via k-NN radii
(ref: imaginaire/evaluation/prdc.py:1-127; Naeem et al. 2020).

precision = fraction of fake samples inside ANY real k-NN ball;
recall    = fraction of real samples inside ANY fake k-NN ball;
density   = mean count of real balls containing a fake sample / k;
coverage  = fraction of real balls containing at least one fake sample.
"""

from __future__ import annotations

import numpy as np

from imaginaire_tpu.evaluation.common import get_activations


def _pairwise_distances(a, b):
    aa = np.sum(a * a, axis=1, keepdims=True)
    bb = np.sum(b * b, axis=1, keepdims=True)
    d2 = aa + bb.T - 2 * (a @ b.T)
    return np.sqrt(np.maximum(d2, 0.0))


def _kth_nn_radius(x, k):
    d = _pairwise_distances(x, x)
    np.fill_diagonal(d, np.inf)
    return np.sort(d, axis=1)[:, k - 1]


def prdc_from_activations(act_real, act_fake, nearest_k=5):
    # a set of n points has at most n-1 neighbors: clamp k so tiny
    # validation sets (unit-test fixtures) evaluate instead of crashing
    nearest_k = max(1, min(nearest_k,
                           act_real.shape[0] - 1, act_fake.shape[0] - 1))
    radii_real = _kth_nn_radius(act_real, nearest_k)
    radii_fake = _kth_nn_radius(act_fake, nearest_k)
    d_rf = _pairwise_distances(act_real, act_fake)  # (Nr, Nf)

    in_real_ball = d_rf < radii_real[:, None]  # fake j inside real i's ball
    precision = float(in_real_ball.any(axis=0).mean())
    recall = float((d_rf < radii_fake[None, :]).any(axis=1).mean())
    density = float(in_real_ball.sum(axis=0).mean() / nearest_k)
    coverage = float((d_rf.min(axis=1) < radii_real).mean())
    return {"precision": precision, "recall": recall,
            "density": density, "coverage": coverage}


def compute_prdc(data_loader, extractor, generator_fn,
                 key_real="images", key_fake="fake_images",
                 nearest_k=5, max_batches=None):
    """(ref: prdc.py:50+)."""
    act_real = get_activations(data_loader, key_real, key_fake, extractor,
                               max_batches=max_batches)
    act_fake = get_activations(data_loader, key_real, key_fake, extractor,
                               generator_fn=generator_fn,
                               max_batches=max_batches)
    return prdc_from_activations(act_real, act_fake, nearest_k)

"""Content-addressed reference-feature store (ISSUE 18 tentpole).

The reference repo recomputes the real-set Inception activations on
every ``evaluate.py`` invocation — a frozen network applied to a frozen
dataset, recomputed forever. The PR-4 flow-cache insight ("a frozen
network's output over frozen data is content, not compute") applies
verbatim: reference activations are a pure function of (dataset,
extractor weights, eval resolution, preprocessing recipe), so they are
computed once per that tuple EVER and persisted in the
``flow/cache.py`` mold:

- one ``.npz`` shard per key under ``<root>/<key[:2]>/<key>.npz``,
  written atomically (uuid tmp + ``os.replace``) so concurrent eval
  sweeps — or the N hosts of a pod sharing a filesystem — never read a
  torn shard;
- multi-writer safe: ``put`` skips keys another writer already
  published (content-addressed keys make the bytes equivalent);
- quarantine-on-corrupt: a shard that fails to parse after the bounded
  retry budget is renamed ``*.corrupt`` (so it is never re-read every
  sweep), counted in ``eval/store_corrupt``, and degrades to a miss —
  the sweep simply recomputes;
- keyed by dataset + extractor-weights identity + resolution +
  preprocessing + feature-graph version, so a changed extractor (or
  the count_include_pad fix bumping ``FEATURE_GRAPH_VERSION``) misses
  instead of silently mixing feature spaces.

Random-init extractors (``trainer.fid_random_init``, tests) get a
per-process identity tag — their features differ per process, so they
may hit within one run (the continuous-eval second sweep) but can
never poison a shared store.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading

import numpy as np

from imaginaire_tpu.config import cfg_get

logger = logging.getLogger(__name__)

# Bump when the stored payload layout changes incompatibly; stale
# shards then simply miss. The *numerics* of the features are versioned
# separately by fid.FEATURE_GRAPH_VERSION, which rides every key.
STORE_VERSION = 1

# The canonical preprocessing recipe baked into every key: clip to
# [-1,1], imagenet-normalize, bilinear-resize to 299 (common.py::
# preprocess_for_inception). A future preprocessing variant must change
# this string, not silently share shards with the old one.
INCEPTION_PREPROCESS = "clip-imagenet-bilinear299"


def evaluation_settings(cfg):
    """Parse the ``cfg.evaluation`` group (missing -> disabled)."""
    ecfg = cfg_get(cfg or {}, "evaluation", None) or {}
    every = cfg_get(ecfg, "every_n_iter", None)
    metrics = cfg_get(ecfg, "metrics", None) or ["fid"]
    return {
        "every_n_iter": None if not every else int(every),
        "metrics": [str(m).lower() for m in metrics],
        # inception (the real metric) | patch (mean-pooled pixel
        # patches — a smoke-test stand-in that exercises the full plane
        # at negligible cost; its FID is NOT a perceptual number)
        "extractor": str(cfg_get(ecfg, "extractor", "inception")).lower(),
        "max_batches": cfg_get(ecfg, "max_batches", None),
        "store": bool(cfg_get(ecfg, "store", True)),
        "store_dir": cfg_get(ecfg, "store_dir", None),
        "regression_threshold": float(
            cfg_get(ecfg, "regression_threshold", 0.05) or 0.05),
        "regression_consecutive": int(
            cfg_get(ecfg, "regression_consecutive", 2) or 2),
        "ewma_beta": float(cfg_get(ecfg, "ewma_beta", 0.5) or 0.5),
    }


def resolve_store_dir(cfg):
    """The on-disk store directory: ``evaluation.store_dir`` >
    ``<logdir>/feature_store`` > None (the plane then recomputes every
    sweep — the pre-ISSUE-18 behavior)."""
    settings = evaluation_settings(cfg)
    if settings["store_dir"]:
        return str(settings["store_dir"])
    logdir = cfg_get(cfg or {}, "logdir", None)
    if logdir:
        return os.path.join(str(logdir), "feature_store")
    return None


def extractor_id(weights_path=None, random_init=False):
    """Identity of the extractor weights baked into every key: a
    converted checkpoint is identified by (name, size, mtime); a
    random-init extractor (tests, fid_random_init) gets a per-process
    tag so its features never poison a shared store."""
    from imaginaire_tpu.evaluation.fid import FEATURE_GRAPH_VERSION
    from imaginaire_tpu.evaluation.inception import DEFAULT_WEIGHTS

    graph = f"inception-g{FEATURE_GRAPH_VERSION}"
    if random_init:
        return f"{graph}:random-init:{os.getpid()}"
    path = weights_path or DEFAULT_WEIGHTS
    if path and os.path.exists(path):
        st = os.stat(path)
        return (f"{graph}:{os.path.basename(path)}:{st.st_size}"
                f":{int(st.st_mtime)}")
    return f"{graph}:random-init:{os.getpid()}"


def reference_key(dataset_name, extractor, resolution,
                  preprocessing=INCEPTION_PREPROCESS, split="val",
                  max_batches=None):
    """Content-addressed key for one reference-activation set.

    ``resolution`` is the eval-time (H, W) the loader feeds (or a
    string like "native"); ``max_batches`` rides the key because a
    truncated sweep's activations are NOT the full set's."""
    if isinstance(resolution, (tuple, list)):
        resolution = f"{int(resolution[0])}x{int(resolution[1])}"
    payload = "|".join([
        f"v{STORE_VERSION}", str(dataset_name), str(split),
        str(resolution), str(preprocessing), str(extractor),
        f"max_batches={max_batches}",
    ])
    return hashlib.sha1(payload.encode()).hexdigest()


class FeatureStore:
    """Content-addressed reference-activation shards on disk.

    One ``.npz`` per key holding the float32 (N, D) activation matrix
    (FID's covariance is what the gate thresholds — features are stored
    at full precision, unlike the flow store's fp16). Writes are atomic
    (uuid tmp + rename) so concurrent sweeps never read torn shards.
    """

    def __init__(self, root):
        self.root = str(root)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.corrupt_shards = 0

    def path(self, key):
        return os.path.join(self.root, key[:2], key + ".npz")

    def has(self, key):
        return os.path.exists(self.path(key))

    def _read(self, path):
        """One shard read — the retried unit (transient OSErrors recover
        on the next attempt) and the chaos harness's feature-store
        site."""
        from imaginaire_tpu.resilience import chaos

        chaos.get().maybe_io_error("feature_store")
        with np.load(path) as npz:
            return npz["acts"].astype(np.float32)

    def _quarantine(self, path, error):
        """A corrupt shard degrades to a miss ONCE: renamed to
        ``*.corrupt`` so it is never re-read (and re-missed) every
        sweep, counted in ``eval/store_corrupt``."""
        from imaginaire_tpu import telemetry

        with self._lock:
            self.corrupt_shards += 1
            count = self.corrupt_shards
        try:
            os.replace(path, path + ".corrupt")
        except FileNotFoundError:
            # another host of a shared store already quarantined it
            pass
        except OSError:
            try:
                os.remove(path)
            except OSError:
                pass
        logger.warning("feature store: quarantined corrupt shard %s (%s)",
                       path, error)
        tm = telemetry.get()
        if tm.enabled:
            tm.counter("eval/store_corrupt", count)
            tm.meta("eval/store_corrupt_shard", shard=str(path),
                    error=str(error)[:200])

    def get(self, key):
        """float32 (N, D) activations or None. Transient IO retries
        with bounded backoff (resilience/retry.py); a shard that still
        fails — or fails to parse — is quarantined and degrades to a
        miss (the sweep simply recomputes)."""
        import zipfile

        from imaginaire_tpu.resilience import retry_call

        path = self.path(key)
        if not os.path.exists(path):
            with self._lock:
                self.misses += 1
            return None
        try:
            acts = retry_call(self._read, path, label="feature_store")
        except (OSError, KeyError, ValueError, EOFError,
                zipfile.BadZipFile) as e:
            self._quarantine(path, e)
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return acts

    def put(self, key, acts, **meta_fields):
        from imaginaire_tpu.resilience import retry_call

        path = self.path(key)
        if os.path.exists(path):
            # multi-writer shared directory: another sweep/host already
            # published this shard — content-addressed keys make its
            # bytes equivalent, so skip the redundant write (and the
            # rename-over-live-file hazard on non-POSIX-atomic shared
            # filesystems)
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # tmp name unique across THREADS and HOSTS: pids collide between
        # machines sharing a filesystem, so a random token joins the
        # pid/tid pair (np.savez appends '.npz' unless the name already
        # ends with it)
        import uuid

        tmp = (f"{path}.{os.getpid()}.{threading.get_ident()}."
               f"{uuid.uuid4().hex[:8]}.tmp.npz")

        def _write():
            np.savez(tmp, acts=np.asarray(acts, np.float32),
                     store_version=STORE_VERSION,
                     **{k: np.asarray(v) for k, v in meta_fields.items()})
            os.replace(tmp, path)

        try:
            retry_call(_write, label="feature_store_write")
        except OSError as e:
            logger.warning("feature store write failed for %s: %s",
                           path, e)
            try:
                os.remove(tmp)
            except OSError:
                pass

    def stats(self):
        with self._lock:
            total = self.hits + self.misses
            return {"hits": self.hits, "misses": self.misses,
                    "corrupt_shards": self.corrupt_shards,
                    "hit_rate": (self.hits / total) if total else 0.0}

"""Inception-v3 feature extractor for FID/KID/PRDC.

Flax re-implementation of the torchvision ``inception_v3`` graph the
reference feeds for metrics (ref: imaginaire/evaluation/fid.py:60-100,
``inception_v3(pretrained=True)`` with the final fc stripped so forward
returns the 2048-d pool features; input 299x299, imagenet-normalized —
ref: evaluation/common.py:44-60).

Layout NHWC, kernels (kh, kw, in, out). BatchNorm runs in inference mode
with ported running stats (eps 1e-3, torchvision's value).

Weights: convert once from torchvision with
``scripts/convert_weights.py inception_v3 out.npz`` (needs a machine with
torchvision; this environment has no egress). ``load_params`` fails
loudly when the file is missing — metrics against a random-init network
are meaningless (``random_init=True`` exists for unit tests only).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

BN_EPS = 1e-3
FEATURE_DIM = 2048


class BasicConv(nn.Module):
    """Conv(bias=False) + frozen BatchNorm + ReLU (torchvision BasicConv2d)."""

    features: int
    kernel: tuple
    stride: tuple = (1, 1)
    padding: tuple = ((0, 0), (0, 0))

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, strides=self.stride,
                    padding=self.padding, use_bias=False, name="conv")(x)
        # inference-only BN: running stats are parameters, never updated
        c = self.features
        scale = self.param("bn_scale", nn.initializers.ones, (c,))
        bias = self.param("bn_bias", nn.initializers.zeros, (c,))
        mean = self.param("bn_mean", nn.initializers.zeros, (c,))
        var = self.param("bn_var", nn.initializers.ones, (c,))
        x = (x - mean) * jax.lax.rsqrt(var + BN_EPS) * scale + bias
        return nn.relu(x)


def _avg_pool3(x):
    # torchvision branch_pool is F.avg_pool2d(x, 3, stride=1, padding=1)
    # whose count_include_pad defaults to True (the reference feeds the
    # unpatched torchvision graph, ref: evaluation/common.py:32-37 — NOT
    # the pytorch-fid variant that divides by the unpadded window).
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)),
                       count_include_pad=True)


def _max_pool3s2(x):
    return nn.max_pool(x, (3, 3), strides=(2, 2))


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x):
        b1 = BasicConv(64, (1, 1), name="branch1x1")(x)
        b5 = BasicConv(48, (1, 1), name="branch5x5_1")(x)
        b5 = BasicConv(64, (5, 5), padding=((2, 2), (2, 2)), name="branch5x5_2")(b5)
        b3 = BasicConv(64, (1, 1), name="branch3x3dbl_1")(x)
        b3 = BasicConv(96, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_2")(b3)
        b3 = BasicConv(96, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_3")(b3)
        bp = BasicConv(self.pool_features, (1, 1), name="branch_pool")(_avg_pool3(x))
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    @nn.compact
    def __call__(self, x):
        b3 = BasicConv(384, (3, 3), stride=(2, 2), name="branch3x3")(x)
        bd = BasicConv(64, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv(96, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_2")(bd)
        bd = BasicConv(96, (3, 3), stride=(2, 2), name="branch3x3dbl_3")(bd)
        return jnp.concatenate([b3, bd, _max_pool3s2(x)], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int

    @nn.compact
    def __call__(self, x):
        c7 = self.channels_7x7
        p17 = ((0, 0), (3, 3))
        p71 = ((3, 3), (0, 0))
        b1 = BasicConv(192, (1, 1), name="branch1x1")(x)
        b7 = BasicConv(c7, (1, 1), name="branch7x7_1")(x)
        b7 = BasicConv(c7, (1, 7), padding=p17, name="branch7x7_2")(b7)
        b7 = BasicConv(192, (7, 1), padding=p71, name="branch7x7_3")(b7)
        bd = BasicConv(c7, (1, 1), name="branch7x7dbl_1")(x)
        bd = BasicConv(c7, (7, 1), padding=p71, name="branch7x7dbl_2")(bd)
        bd = BasicConv(c7, (1, 7), padding=p17, name="branch7x7dbl_3")(bd)
        bd = BasicConv(c7, (7, 1), padding=p71, name="branch7x7dbl_4")(bd)
        bd = BasicConv(192, (1, 7), padding=p17, name="branch7x7dbl_5")(bd)
        bp = BasicConv(192, (1, 1), name="branch_pool")(_avg_pool3(x))
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    @nn.compact
    def __call__(self, x):
        b3 = BasicConv(192, (1, 1), name="branch3x3_1")(x)
        b3 = BasicConv(320, (3, 3), stride=(2, 2), name="branch3x3_2")(b3)
        b7 = BasicConv(192, (1, 1), name="branch7x7x3_1")(x)
        b7 = BasicConv(192, (1, 7), padding=((0, 0), (3, 3)), name="branch7x7x3_2")(b7)
        b7 = BasicConv(192, (7, 1), padding=((3, 3), (0, 0)), name="branch7x7x3_3")(b7)
        b7 = BasicConv(192, (3, 3), stride=(2, 2), name="branch7x7x3_4")(b7)
        return jnp.concatenate([b3, b7, _max_pool3s2(x)], axis=-1)


class InceptionE(nn.Module):
    @nn.compact
    def __call__(self, x):
        p13 = ((0, 0), (1, 1))
        p31 = ((1, 1), (0, 0))
        b1 = BasicConv(320, (1, 1), name="branch1x1")(x)
        b3 = BasicConv(384, (1, 1), name="branch3x3_1")(x)
        b3 = jnp.concatenate([
            BasicConv(384, (1, 3), padding=p13, name="branch3x3_2a")(b3),
            BasicConv(384, (3, 1), padding=p31, name="branch3x3_2b")(b3),
        ], axis=-1)
        bd = BasicConv(448, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv(384, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_2")(bd)
        bd = jnp.concatenate([
            BasicConv(384, (1, 3), padding=p13, name="branch3x3dbl_3a")(bd),
            BasicConv(384, (3, 1), padding=p31, name="branch3x3dbl_3b")(bd),
        ], axis=-1)
        bp = BasicConv(192, (1, 1), name="branch_pool")(_avg_pool3(x))
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Returns 2048-d pooled features (fc stripped, ref: fid.py:64-66)."""

    @nn.compact
    def __call__(self, x):
        x = BasicConv(32, (3, 3), stride=(2, 2), name="Conv2d_1a_3x3")(x)
        x = BasicConv(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = BasicConv(64, (3, 3), padding=((1, 1), (1, 1)), name="Conv2d_2b_3x3")(x)
        x = _max_pool3s2(x)
        x = BasicConv(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = BasicConv(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = _max_pool3s2(x)
        x = InceptionA(32, name="Mixed_5b")(x)
        x = InceptionA(64, name="Mixed_5c")(x)
        x = InceptionA(64, name="Mixed_5d")(x)
        x = InceptionB(name="Mixed_6a")(x)
        x = InceptionC(128, name="Mixed_6b")(x)
        x = InceptionC(160, name="Mixed_6c")(x)
        x = InceptionC(160, name="Mixed_6d")(x)
        x = InceptionC(192, name="Mixed_6e")(x)
        x = InceptionD(name="Mixed_7a")(x)
        x = InceptionE(name="Mixed_7b")(x)
        x = InceptionE(name="Mixed_7c")(x)
        return jnp.mean(x, axis=(1, 2))  # global avg pool -> (B, 2048)


DEFAULT_WEIGHTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "weights", "inception_v3.npz")


def load_params(path=None, random_init=False, input_shape=(1, 299, 299, 3)):
    """Load converted torchvision weights; fail loudly when absent.

    ``random_init=True`` is for unit tests of the metric plumbing only —
    FID numbers from a random network are meaningless.
    """
    path = path or DEFAULT_WEIGHTS
    if os.path.exists(path):
        flat = dict(np.load(path))
        params = {}
        for k, v in flat.items():
            node = params
            parts = k.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = jnp.asarray(v)
        return {"params": params}
    if random_init:
        return InceptionV3().init(jax.random.PRNGKey(0),
                                  jnp.zeros(input_shape, jnp.float32))
    raise FileNotFoundError(
        f"Inception-v3 weights not found at {path}. Run "
        "`python scripts/convert_weights.py inception_v3 " + path + "` on a "
        "machine with torchvision, or pass random_init=True (tests only).")


def make_extractor(variables, compute_dtype=jnp.bfloat16):
    """Jitted (B,299,299,3) imagenet-normalized images -> (B,2048) fp32.

    Compiles through the ledger (``telemetry/xla_obs.py``) so FID/KID
    sweeps account their compile time and executable footprint like the
    step programs; allow_shape_growth — the tail batch of a sweep is
    legitimately smaller.

    The weights are a program *argument*, not a closure: closed-over
    params would be baked into the executable as ~87 MB of constants
    (the graph auditor's ``baked_constant`` rule), pinned for the
    executable's lifetime on top of the live copy."""
    from imaginaire_tpu.telemetry import xla_obs

    model = InceptionV3()

    def run(variables, images):
        feats = model.apply(variables, images.astype(compute_dtype))
        return feats.astype(jnp.float32)

    program = xla_obs.compiled_program("inception_extractor", run,
                                       allow_shape_growth=True)

    def extractor(images):
        return program(variables, images)

    extractor.program = program  # audit/ledger surface
    return extractor

"""Donation analysis: name every declared-but-dead donated argument.

A donated buffer only helps when XLA actually aliases it to an output
(``input_output_alias`` in the compiled module). Two ways a declared
donation dies silently:

- the argument is DCE'd out of the program entirely (a state leaf the
  step never reads) — it never reaches the executable, so the donation
  is a no-op and the caller still loses the buffer;
- the argument is kept but no output matches its shape/layout, so XLA
  cannot alias it (e.g. a reshaped return) and quietly copies instead.

Both cases waste HBM exactly where the activation wall bites. This
module cross-references three artifacts, all public or degradable:

- ``compiled.args_info``: the full *pre-DCE* input pytree with
  ``.donated`` flags — gives every donated leaf a tree path;
- the kept-argument set: ``lowered._lowering.compile_args
  ["kept_var_idx"]`` when available (private — guarded), otherwise
  estimated from which top-level jaxpr invars any equation reads;
- the compiled HLO's ``input_output_alias`` map (hlo_audit), whose
  parameter numbering is over the kept arguments in order.
"""

import jax

from . import hlo_audit
from .jaxpr_audit import Violation, _as_jaxpr


def flat_args_info(args_info):
    """[(flat_index, path_str, donated)] over the pre-DCE input tree."""
    leaves = jax.tree_util.tree_flatten_with_path(args_info)[0]
    out = []
    for i, (path, info) in enumerate(leaves):
        out.append((i, jax.tree_util.keystr(path),
                    bool(getattr(info, "donated", False))))
    return out


def kept_indices(lowered, closed_jaxpr, n_args):
    """Flat indices of arguments that survive DCE. Prefers the
    lowering's own ``kept_var_idx``; falls back to scanning the
    top-level jaxpr for invars any equation (or the output) reads."""
    try:
        kept = lowered._lowering.compile_args["kept_var_idx"]  # noqa: SLF001
        return set(int(i) for i in kept)
    except Exception:  # noqa: BLE001 — private API; estimate instead
        pass
    jaxpr = _as_jaxpr(closed_jaxpr)
    if jaxpr is None:
        return set(range(n_args))
    used = set()
    for eqn in jaxpr.eqns:
        for var in eqn.invars:
            used.add(id(var))
    for var in jaxpr.outvars:
        used.add(id(var))
    return {i for i, var in enumerate(jaxpr.invars) if id(var) in used}


def audit_donation(program, compiled, closed_jaxpr=None, lowered=None,
                   hlo_text=None):
    """Returns (violations, summary). Summary:
    ``{declared, aliased, dead_count, dead: [{path, reason}]}``; one
    ``dead_donation`` violation per dead arg, named by its tree path."""
    args_info = getattr(compiled, "args_info", None)
    if args_info is None:
        return [], {"declared": 0, "aliased": 0, "dead_count": 0,
                    "dead": [], "error": "no args_info"}
    flat = flat_args_info(args_info)
    donated = [(i, path) for i, path, d in flat if d]
    summary = {"declared": len(donated), "aliased": 0, "dead": []}
    if not donated:
        summary["dead_count"] = 0
        return [], summary
    kept = kept_indices(lowered, closed_jaxpr, len(flat))
    if hlo_text is None:
        try:
            hlo_text = compiled.as_text()
        except Exception:  # noqa: BLE001 — text dump is best-effort
            hlo_text = ""
    aliased_params = hlo_audit.aliased_param_indices(hlo_text)
    kept_order = sorted(kept)
    violations = []
    for i, path in donated:
        if i not in kept:
            reason = ("argument is dead code — DCE removed it, the "
                      "donated buffer is still lost to the caller")
        else:
            param_idx = kept_order.index(i)
            if param_idx in aliased_params:
                summary["aliased"] += 1
                continue
            reason = ("no output aliases this buffer (shape/layout "
                      "mismatch or unused result) — XLA copies instead")
        summary["dead"].append({"path": path, "reason": reason})
        violations.append(Violation(
            "dead_donation", program, f"args{path}",
            f"donated argument {path} is dead: {reason}"))
    summary["dead_count"] = len(summary["dead"])
    return violations, summary

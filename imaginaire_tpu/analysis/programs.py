"""Trace the repo's real ledgered programs for offline auditing.

``scripts/lint_graph.py --families`` and tests/test_graph_audit.py need
the closed jaxpr of every family's step programs WITHOUT paying a
compile or touching an accelerator: build the trainer from its
unit-test config, ``jax.eval_shape`` the init to get a state
ShapeDtypeStruct tree (no compute), and ``jit.trace`` each registered
``CompiledProgram`` on SDS inputs. Closures that must be concrete
(inception variables, flow-teacher params) are zero-filled from their
eval_shape — allocation, never computation.
"""

import os
import tempfile

import numpy as np

FAMILIES = ("spade", "pix2pixHD", "unit", "munit", "funit", "coco_funit",
            "vid2vid", "fs_vid2vid", "wc_vid2vid")
VIDEO_FAMILIES = ("vid2vid", "fs_vid2vid", "wc_vid2vid")
AUX_PROGRAMS = ("flow_teacher", "inception_extractor")

_CONFIG_FILES = {
    "vid2vid": "vid2vid_street.yaml",
}


def _repo_root():
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))


def config_path(family):
    return os.path.join(_repo_root(), "configs", "unit_test",
                        _CONFIG_FILES.get(family, f"{family}.yaml"))


def _rng():
    return np.random.RandomState(0)


def family_batch(family, h=64, w=64):
    """A one-sample numpy batch shaped like the family's unit-test
    datasets (tests/test_* helpers are the reference)."""
    rng = _rng()

    def img(*shape):
        return rng.rand(*shape).astype(np.float32) * 2 - 1

    def seg(*shape):
        return (rng.rand(*shape) > 0.9).astype(np.float32)

    if family == "spade":
        return {"images": img(1, 256, 256, 3),
                "label": seg(1, 256, 256, 14)}
    if family == "pix2pixHD":
        lab = np.concatenate(
            [seg(1, 128, 128, 8),
             rng.randint(0, 5, (1, 128, 128, 1)).astype(np.float32)],
            axis=-1)
        return {"images": img(1, 128, 128, 3), "label": lab}
    if family in ("unit", "munit"):
        return {"images_a": img(1, h, w, 3), "images_b": img(1, h, w, 3)}
    if family in ("funit", "coco_funit"):
        return {"images_content": img(1, h, w, 3),
                "images_style": img(1, h, w, 3),
                "labels_content": np.asarray([1], np.int32),
                "labels_style": np.asarray([0], np.int32)}
    if family in ("vid2vid", "fs_vid2vid", "wc_vid2vid"):
        t = 3 if family != "fs_vid2vid" else 2
        data = {"images": img(1, t, h, w, 3),
                "label": seg(1, t, h, w, 12)}
        if family == "fs_vid2vid":
            data["ref_images"] = img(1, 1, h, w, 3)
            data["ref_labels"] = seg(1, 1, h, w, 12)
        if family == "wc_vid2vid":
            infos = []
            for ti in range(t):
                n = 50
                infos.append(np.stack(
                    [rng.randint(0, h, n), rng.randint(0, w, n),
                     rng.randint(0, 500, n)], axis=1))
            data["unprojection"] = [infos]
        return data
    raise KeyError(f"unknown family {family!r}")


def _sds(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
        if not isinstance(a, jax.ShapeDtypeStruct) else a, tree)


def build_trainer(family, logdir=None):
    from imaginaire_tpu.config import Config
    from imaginaire_tpu.registry import resolve

    cfg = Config(config_path(family))
    cfg.logdir = logdir or tempfile.mkdtemp(prefix=f"audit_{family}_")
    return resolve(cfg.trainer.type, "Trainer")(cfg)


def _state_sds(trainer, batch):
    """State ShapeDtypeStruct tree via eval_shape'd init (no compute).
    eval_shape leaves SDS in trainer.state — reset it."""
    import jax

    sds = jax.eval_shape(
        lambda k, b: trainer.init_state(k, b),
        jax.ShapeDtypeStruct((2,), np.uint32), batch)
    trainer.state = None
    return sds


def _video_data_t(trainer, data):
    """Steady-state per-frame data_t (full-size history buffers), jit
    keys only — mirrors gen_update's per-frame path."""
    n_prev = trainer.num_frames_G - 1
    t_dis = trainer.num_frames_D
    scales = trainer.num_temporal_scales
    max_prev = (t_dis ** max(scales - 1, 0)) * (t_dis - 1)
    t_steady = max(n_prev, max_prev if scales > 0 else 0, 1)
    seq_len = data["images"].shape[1]
    t = min(t_steady, seq_len - 1)
    b, _, h, w, _ = data["images"].shape
    n_lab = data["label"].shape[-1]
    prev_labels = np.zeros((b, max(n_prev, 1), h, w, n_lab), np.float32)
    prev_images = np.zeros((b, max(n_prev, 1), h, w, 3), np.float32)
    if hasattr(trainer, "reset_renderer"):
        trainer.reset_renderer(False)  # wc point cloud host state
    data_t = trainer._get_data_t(data, t, prev_labels, prev_images)
    if scales > 0:
        past_real = np.zeros((b, max_prev, h, w, 3), np.float32)
        past_fake = np.zeros((b, max_prev, h, w, 3), np.float32)
        data_t["past_stacks"] = trainer._past_stacks(past_real, past_fake)
    else:
        data_t["past_stacks"] = {}
    return ({k: v for k, v in data_t.items()
             if not str(k).startswith("_")}, t_steady)


def trace_family_programs(family, logdir=None):
    """[(label, Traced)] for the family's ledgered step programs —
    trace-only, no compile, no compute."""
    trainer = build_trainer(family, logdir=logdir)
    batch = family_batch(family)
    traced = []
    if family in VIDEO_FAMILIES:
        data_t, t_steady = _video_data_t(trainer, batch)
        state = _sds(_state_sds(trainer, batch))
        args = (state, _sds(data_t))
        traced.append(("vid_dis_step",
                       trainer._jit_vid_dis._jit.trace(*args)))
        traced.append(("vid_gen_step",
                       trainer._jit_vid_gen._jit.trace(*args)))
        tail_len = batch["images"].shape[1] - t_steady
        if family == "vid2vid" and tail_len >= 1:
            n_prev = trainer.num_frames_G - 1
            scales = trainer.num_temporal_scales
            b, _, h, w, _ = batch["images"].shape
            n_lab = batch["label"].shape[-1]
            t_dis = trainer.num_frames_D
            max_prev = (t_dis ** max(scales - 1, 0)) * (t_dis - 1)
            buffers = (
                np.zeros((b, max(n_prev, 1), h, w, n_lab), np.float32),
                np.zeros((b, max(n_prev, 1), h, w, 3), np.float32),
                np.zeros((b, max_prev, h, w, 3), np.float32)
                if scales > 0 else None,
                np.zeros((b, max_prev, h, w, 3), np.float32)
                if scales > 0 else None)
            tail = {"label": batch["label"][:, t_steady:],
                    "image": batch["images"][:, t_steady:],
                    "real_prev_image":
                        batch["images"][:, t_steady - 1:-1]}
            constants = trainer._rollout_scan_constants(batch)
            traced.append(("rollout_tail",
                           trainer._jit_rollout_tail._jit.trace(
                               state, _sds(buffers), _sds(tail),
                               _sds(constants))))
        if family == "wc_vid2vid" and trainer.single_image_model \
                is not None:
            import jax

            sid = {"label": batch["label"][:, 0],
                   "images": batch["images"][:, 0]}
            vars_sds = jax.eval_shape(
                lambda k, d: trainer.single_image_model.init(
                    {"params": k, "noise": k}, d, random_style=True,
                    training=False),
                jax.ShapeDtypeStruct((2,), np.uint32), _sds(sid))
            traced.append(("wc_single_image",
                           trainer._jit_single._jit.trace(
                               vars_sds, _sds(sid),
                               jax.ShapeDtypeStruct((2,), np.uint32))))
    else:
        if family == "pix2pixHD":
            # edge/instance preprocessing happens in start_of_iteration
            batch = trainer.start_of_iteration(batch, 1)
        state = _sds(_state_sds(trainer, batch))
        args = (state, _sds(batch))
        traced.append(("dis_step",
                       trainer._jit_dis_step._jit.trace(*args)))
        traced.append(("gen_step",
                       trainer._jit_gen_step._jit.trace(*args)))
    return traced


def trace_aux_programs():
    """[(label, Traced)] for the shared non-trainer programs: the
    FlowNet2 teacher and the FID/KID inception extractor (zero-filled
    concrete closures — no init compute)."""
    import jax
    import jax.numpy as jnp

    traced = []
    from imaginaire_tpu.flow.flow_net import FlowNet

    net = FlowNet(allow_random_init=True)
    params_sds = jax.eval_shape(
        lambda k: net.model.init(k, jnp.zeros((1, 2, 64, 64, 3)))
        ["params"], jax.ShapeDtypeStruct((2,), np.uint32))
    im = jax.ShapeDtypeStruct((1, 64, 64, 3), np.float32)
    traced.append(("flow_teacher", net._jit_flow._jit.trace(
        params_sds, im, im)))

    from imaginaire_tpu.evaluation.inception import (
        InceptionV3, make_extractor,
    )

    model = InceptionV3()
    vars_sds = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 299, 299, 3))),
        jax.ShapeDtypeStruct((2,), np.uint32))
    extractor = make_extractor(vars_sds)
    traced.append(("inception_extractor", extractor.program._jit.trace(
        vars_sds, jax.ShapeDtypeStruct((2, 299, 299, 3), np.float32))))
    return traced


def audit_family(family, *, const_bytes_limit=None, logdir=None):
    """label -> audit dict (see analysis.audit_program), trace-only."""
    from . import audit_program

    out = {}
    for label, traced in trace_family_programs(family, logdir=logdir):
        out[label] = audit_program(
            f"{family}/{label}", traced=traced,
            const_bytes_limit=const_bytes_limit, include_hlo=False)
    return out

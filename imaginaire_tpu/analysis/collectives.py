"""Per-program collective accounting.

Two views, both recorded so a mesh change that doubles comm volume is a
diffable number in the ledger:

- ``jaxpr_collectives``: the collectives the program *explicitly* asks
  for (psum in a shard_map loss, all_gather in the sharded optimizer).
- ``hlo`` (from hlo_audit.collective_stats): what the SPMD partitioner
  actually emitted — includes resharding collectives invisible at the
  jaxpr level. This is the number that moves when the mesh changes.
"""

import numpy as np

from . import hlo_audit
from .jaxpr_audit import iter_eqns

# explicit collective primitives at the jaxpr level
JAXPR_COLLECTIVE_PRIMS = (
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "reduce_scatter",
)


def _outvar_bytes(eqn):
    total = 0
    for var in eqn.outvars:
        aval = getattr(var, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for dim in shape:
            try:
                n *= int(dim)
            except (TypeError, ValueError):  # symbolic dims
                n = 0
                break
        total += n * np.dtype(dtype).itemsize
    return total


def jaxpr_collectives(closed_jaxpr):
    """prim -> {count, bytes} of explicit collective equations."""
    stats = {}
    for _, eqn in iter_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        # jax's efficient-transpose rewrite renamed psum -> psum2 (and
        # may do the same to others); normalize so both spellings count
        name = prim[:-1] if prim.endswith("2") else prim
        if name in JAXPR_COLLECTIVE_PRIMS:
            entry = stats.setdefault(name, {"count": 0, "bytes": 0})
            entry["count"] += 1
            entry["bytes"] += _outvar_bytes(eqn)
    return stats


def collective_summary(closed_jaxpr=None, hlo_text=None):
    """Combined accounting dict for the ledger entry. The headline
    ``op_count``/``bytes`` prefer the HLO view (post-partitioner truth)
    and fall back to the jaxpr view when no HLO text is available."""
    explicit = jaxpr_collectives(closed_jaxpr) if closed_jaxpr is not None \
        else {}
    summary = {"jaxpr": explicit}
    if hlo_text is not None:
        hlo = hlo_audit.collective_stats(hlo_text)
        summary["hlo"] = hlo
        summary["op_count"] = sum(v["count"] for v in hlo.values())
        summary["bytes"] = sum(v["bytes"] for v in hlo.values())
    else:
        summary["op_count"] = sum(v["count"] for v in explicit.values())
        summary["bytes"] = sum(v["bytes"] for v in explicit.values())
    return summary

"""Repo-specific AST lint rules (the source half of the graph auditor).

Rules (names are what goes in allowlist comments):

- ``bare-jit``               — no ``jax.jit`` outside
                               ``telemetry/xla_obs.py``: every compiled
                               program must be a ledgered
                               ``xla_obs.compiled_program`` so the
                               recompile tripwire and the graph audit
                               see it
- ``host-sync``              — no ``jax.device_get`` /
                               ``block_until_ready`` in step-path
                               modules (trainers/models/layers/losses/
                               ops/flow/optim/parallel/diagnostics);
                               host syncs there stall the dispatch
                               pipeline every iteration
- ``untimed-barrier``        — no direct ``jax.experimental.
                               multihost_utils`` use outside the timed
                               wrappers in ``parallel/collectives.py`` /
                               ``resilience/``; a raw barrier hangs the
                               pod forever when one host dies
- ``numpy-random``           — no ``numpy.random`` inside traced-code
                               modules (models/layers/losses/ops/flow):
                               host RNG inside a traced fn bakes one
                               sample into the executable forever
- ``mutable-default-pytree`` — no mutable default (list/dict/set
                               literal or constructor) on
                               flax-module/dataclass fields: the
                               default is shared across instances and
                               silently couples modules

Allowlist syntax (inline, same line or the line above)::

    some_call()  # lint: allow(host-sync) -- reason the reader needs

The reason string is MANDATORY — an allowlist entry without one is
itself a violation (``allowlist-reason``). Zero silent suppressions.
"""

import ast
import os
import re
from dataclasses import dataclass

RULE_NAMES = ("bare-jit", "host-sync", "untimed-barrier", "numpy-random",
              "mutable-default-pytree")

# ``# lint: allow(rule[, rule]) -- reason``  (also accepts — or - )
ALLOW_RE = re.compile(
    r"#\s*lint:\s*allow\(([A-Za-z0-9_\-, ]+)\)"
    r"(?:\s*(?:--|—|-)\s*(\S.*))?")

# module scopes, as path fragments relative to the repo root
STEP_PATH_PREFIXES = tuple(
    f"imaginaire_tpu/{m}/" for m in
    ("trainers", "models", "layers", "losses", "ops", "flow", "optim",
     "parallel", "diagnostics"))
TRACED_CODE_PREFIXES = tuple(
    f"imaginaire_tpu/{m}/" for m in
    ("models", "layers", "losses", "ops", "flow"))
BARRIER_HOME = ("imaginaire_tpu/parallel/collectives.py",
                "imaginaire_tpu/resilience/")
JIT_HOME = ("imaginaire_tpu/telemetry/xla_obs.py",)


@dataclass
class LintViolation:
    rule: str
    path: str
    line: int
    message: str

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclass
class Suppression:
    rule: str
    path: str
    line: int
    reason: str


def _relpath(path, root=None):
    root = root or os.getcwd()
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def _dotted(node):
    """'jax.experimental.multihost_utils.sync_global_devices' for an
    Attribute/Name chain, or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _RuleVisitor(ast.NodeVisitor):
    def __init__(self, rel, jit_aliases):
        self.rel = rel
        self.jit_aliases = jit_aliases
        self.found = []

    def add(self, rule, node, message):
        self.found.append(LintViolation(rule, self.rel,
                                        getattr(node, "lineno", 0),
                                        message))

    # ------------------------------------------------------ bare-jit
    def _is_jit(self, node):
        dotted = _dotted(node)
        if dotted is None:
            return False
        return dotted in self.jit_aliases or dotted.endswith("jax.jit")

    def _check_jit(self, node):
        if self.rel in JIT_HOME or self.rel.startswith("tests/"):
            return
        if self._is_jit(node):
            self.add("bare-jit", node,
                     "bare jax.jit — route through xla_obs."
                     "compiled_program so the ledger, recompile "
                     "tripwire and graph audit cover this program")

    # --------------------------------------------------------- visits
    def visit_Call(self, node):
        self._check_jit(node.func)
        dotted = _dotted(node.func) or ""
        tail = dotted.rsplit(".", 1)[-1]
        if tail in ("device_get", "block_until_ready") \
                and self.rel.startswith(STEP_PATH_PREFIXES):
            self.add("host-sync", node,
                     f"{tail} in a step-path module forces a host sync "
                     f"on the dispatch path")
        if "multihost_utils" in dotted \
                and not self.rel.startswith(BARRIER_HOME):
            self.add("untimed-barrier", node,
                     f"direct multihost_utils call ({dotted}) — use the "
                     f"timed wrappers in parallel/collectives.py")
        if (".random." in dotted + "." or dotted.startswith("random.")) \
                and dotted.split(".")[0] in ("np", "numpy") \
                and self.rel.startswith(TRACED_CODE_PREFIXES):
            self.add("numpy-random", node,
                     f"{dotted} in traced-code module: host RNG inside "
                     f"a traced fn bakes one sample into the "
                     f"executable")
        self.generic_visit(node)

    def visit_Attribute(self, node):
        # method spelling: x.block_until_ready()
        if node.attr == "block_until_ready" \
                and self.rel.startswith(STEP_PATH_PREFIXES):
            self.add("host-sync", node,
                     "block_until_ready in a step-path module forces a "
                     "host sync on the dispatch path")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        for deco in node.decorator_list:
            target = deco.func if isinstance(deco, ast.Call) else deco
            self._check_jit(target)
            # functools.partial(jax.jit, ...) decorators
            if isinstance(deco, ast.Call):
                for arg in deco.args:
                    self._check_jit(arg)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        is_module = any("Module" in (_dotted(b) or "") for b in node.bases)
        is_dc = any("dataclass" in (_dotted(
            d.func if isinstance(d, ast.Call) else d) or "")
            for d in node.decorator_list)
        if is_module or is_dc:
            for stmt in node.body:
                value = None
                if isinstance(stmt, ast.AnnAssign):
                    value = stmt.value
                elif isinstance(stmt, ast.Assign):
                    value = stmt.value
                if value is not None and _is_mutable_literal(value):
                    self.add("mutable-default-pytree", stmt,
                             f"mutable default on a "
                             f"{'flax-module' if is_module else 'dataclass'}"
                             f" field in {node.name}: shared across "
                             f"instances — use a factory/None sentinel")
        self.generic_visit(node)


def _is_mutable_literal(node):
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("list", "dict", "set") and not node.args \
            and not node.keywords:
        return True
    return False


def _jit_aliases(tree):
    """Local names that are jax.jit (``from jax import jit [as j]``)."""
    aliases = {"jax.jit"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "jit":
                    aliases.add(alias.asname or alias.name)
    return aliases


def _collect_allows(src):
    allows = {}
    for lineno, line in enumerate(src.splitlines(), 1):
        match = ALLOW_RE.search(line)
        if match:
            rules = {r.strip() for r in match.group(1).split(",")
                     if r.strip()}
            reason = (match.group(2) or "").strip() or None
            allows[lineno] = (rules, reason)
    return allows


def lint_source(src, rel):
    """(violations, suppressions) for one file's source text."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [LintViolation("syntax", rel, e.lineno or 0, str(e))], []
    visitor = _RuleVisitor(rel, _jit_aliases(tree))
    visitor.visit(tree)
    allows = _collect_allows(src)
    violations, suppressions = [], []
    flagged = set()
    seen = set()
    found = []
    for v in visitor.found:  # method+call spellings can double-report
        key = (v.rule, v.line)
        if key not in seen:
            seen.add(key)
            found.append(v)
    for v in found:
        handled = False
        for lineno in (v.line, v.line - 1):
            entry = allows.get(lineno)
            if entry and v.rule in entry[0]:
                rules, reason = entry
                if reason is None:
                    if (rel, lineno) not in flagged:
                        flagged.add((rel, lineno))
                        violations.append(LintViolation(
                            "allowlist-reason", rel, lineno,
                            f"allowlist entry for {sorted(rules)} has no "
                            f"reason string — `# lint: allow(rule) -- "
                            f"why` (zero silent suppressions)"))
                else:
                    suppressions.append(
                        Suppression(v.rule, rel, v.line, reason))
                handled = True
                break
        if not handled:
            violations.append(v)
    return violations, suppressions


def lint_file(path, root=None):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, _relpath(path, root))


def iter_repo_files(root):
    """Every lintable .py under the repo: the package, scripts/, and
    the top-level entry points (tests are exercised, not linted)."""
    for base in ("imaginaire_tpu", "scripts"):
        top = os.path.join(root, base)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)
    for name in sorted(os.listdir(root)):
        if name.endswith(".py"):
            yield os.path.join(root, name)


def lint_repo(root):
    """(violations, suppressions) across the whole repo."""
    violations, suppressions = [], []
    for path in iter_repo_files(root):
        v, s = lint_file(path, root)
        violations.extend(v)
        suppressions.extend(s)
    return violations, suppressions

"""fp32-island registry: one source of truth for the numerics that must
stay in float32 regardless of the compute dtype policy.

PR 9 protected these spots with hand-written trace asserts scattered
through the layers (weight_norm power iteration, instance/layer-norm
statistics, the health-audit accumulators). This module replaces them
with a declared registry:

- ``scope(name)`` wraps the island's compute in a
  ``jax.named_scope("fp32_island[<name>]")`` marker. The marker lands on
  every equation's ``source_info.name_stack`` in the traced jaxpr, which
  is what lets the graph auditor (jaxpr_audit.py) statically reject any
  ``convert_element_type`` to bf16/f16 *inside* the island — the exit
  cast back to the compute dtype belongs OUTSIDE the scope.
- ``guard(name, **values)`` keeps the PR-9 trace-time check: it raises
  at trace time when a value entering the island is not fp32, so the
  bug is caught even when the program never reaches the auditor.

Register islands here (or via ``register``) so the rule set and the
docs enumerate the same list.
"""

import contextlib

import jax
import jax.numpy as jnp

# the literal marker prefix the jaxpr auditor greps for in name stacks
SCOPE_PREFIX = "fp32_island["

_REGISTRY = {}


class IslandViolation(TypeError):
    """A value entered a declared fp32 island with the wrong dtype."""


def register(name, description, where=""):
    """Declare an fp32 island. ``where`` is the home module, for docs
    and reports."""
    _REGISTRY[str(name)] = {"description": str(description),
                            "where": str(where)}
    return str(name)


def registered():
    """name -> {description, where} for every declared island."""
    return {k: dict(v) for k, v in _REGISTRY.items()}


@contextlib.contextmanager
def scope(name):
    """Mark the enclosed (traced) compute as belonging to the fp32
    island ``name``. Down-casts to bf16/f16 inside this scope are graph
    violations; cast back to the compute dtype after leaving it."""
    if name not in _REGISTRY:
        raise KeyError(
            f"fp32 island {name!r} is not registered — declare it with "
            f"analysis.islands.register() so the audit rule set and the "
            f"docs stay in sync")
    with jax.named_scope(f"{SCOPE_PREFIX}{name}]"):
        yield


def guard(name, **values):
    """Trace-time dtype check at an island entry: every named value
    must already be float32 (the caller up-casts explicitly so the
    reader can see where precision changes)."""
    island = _REGISTRY.get(name, {})
    for label, value in values.items():
        dtype = jnp.result_type(value)
        if dtype != jnp.float32:
            raise IslandViolation(
                f"fp32_island[{name}]: {label} entered as {dtype}, "
                f"expected float32"
                + (f" ({island['description']})" if island else ""))


def island_of(name_stack):
    """Island name embedded in a stringified jaxpr name stack, or None.

    ``str(eqn.source_info.name_stack)`` carries named scopes verbatim,
    e.g. ``"loss_fn/fp32_island[norm_stats]/mean"``.
    """
    text = str(name_stack)
    start = text.find(SCOPE_PREFIX)
    if start < 0:
        return None
    start += len(SCOPE_PREFIX)
    end = text.find("]", start)
    return text[start:end] if end >= 0 else None


# ----------------------------------------------------------- declarations
# The repo's declared islands. Keep this list in lockstep with the
# README rule table.

register("norm_stats",
         "instance/layer-norm statistics (mean/var/rsqrt) accumulate in "
         "fp32; bf16 stats destabilize small spatial grids",
         where="imaginaire_tpu/layers/activation_norm.py")
register("sn_power_iteration",
         "spectral-norm power iteration and sigma estimate run in fp32; "
         "bf16 u-vectors drift and under-estimate sigma",
         where="imaginaire_tpu/layers/weight_norm.py")
register("loss_accumulation",
         "loss totals and grad/param health norms accumulate in fp32 "
         "(tree_norm, audit guard) so the finite-check is trustworthy",
         where="imaginaire_tpu/diagnostics/audit.py")

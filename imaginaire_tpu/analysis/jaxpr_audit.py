"""Closed-jaxpr static rules: the trace-level half of the graph audit.

Walks every equation of a ``ClosedJaxpr`` (recursing into the
sub-jaxprs carried by pjit/scan/while/cond/remat params) and flags the
statically-detectable failure classes that historically reached runtime:

- ``host_callback``   — io/debug/pure callbacks on the step path stall
                        the device pipeline on every dispatch
- ``f64_leak``        — a float64/complex128 equation output (TPUs
                        emulate f64 at ~1/10 throughput; on CPU tests
                        it silently doubles memory)
- ``island_cast``     — a ``convert_element_type`` down to bf16/f16
                        whose name stack lies inside a declared
                        ``fp32_island[...]`` scope (see islands.py)
- ``baked_constant``  — a closed-over constant above the byte threshold
                        baked into the executable (HBM waste that also
                        defeats donation)

Every violation names the offending jaxpr path
(``eqns[12]:pjit/body/eqns[3]:convert_element_type``) so the report is
actionable without re-deriving the trace.
"""

from dataclasses import dataclass

import numpy as np

from . import islands

LOW_PRECISION_DTYPES = ("bfloat16", "float16")
F64_DTYPES = ("float64", "complex128")
# flag each rule at most this many times per program; the count still
# lands in stats so nothing is hidden, the report just stays readable
MAX_PER_RULE = 16
DEFAULT_CONST_BYTES_LIMIT = 4 << 20  # 4 MiB

# host-callback primitive names across jax versions
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                   "outside_call", "host_callback_call")


@dataclass
class Violation:
    rule: str
    program: str
    path: str
    message: str

    def as_dict(self):
        return {"rule": self.rule, "program": self.program,
                "path": self.path, "message": self.message}


def _is_jaxpr(obj):
    return hasattr(obj, "eqns") and hasattr(obj, "invars")


def _as_jaxpr(obj):
    """Accept Jaxpr or ClosedJaxpr (duck-typed: jax.core moved between
    versions)."""
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and _is_jaxpr(inner):
        return inner
    return obj if _is_jaxpr(obj) else None


def _sub_jaxprs(eqn):
    """(param_name, jaxpr) pairs nested inside one equation's params."""
    for key, value in eqn.params.items():
        candidates = value if isinstance(value, (list, tuple)) else (value,)
        for idx, item in enumerate(candidates):
            sub = _as_jaxpr(item)
            if sub is not None:
                name = key if len(candidates) == 1 else f"{key}[{idx}]"
                yield name, sub


def iter_eqns(jaxpr, path=""):
    """Depth-first (path, eqn) walk over a jaxpr and its sub-jaxprs."""
    jaxpr = _as_jaxpr(jaxpr)
    if jaxpr is None:
        return
    for i, eqn in enumerate(jaxpr.eqns):
        here = f"{path}eqns[{i}]:{eqn.primitive.name}"
        yield here, eqn
        for name, sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, path=f"{here}/{name}/")


def _var_dtype(var):
    aval = getattr(var, "aval", None)
    dtype = getattr(aval, "dtype", None)
    return str(dtype) if dtype is not None else None


def _name_stack(eqn):
    try:
        return str(eqn.source_info.name_stack)
    except Exception:  # noqa: BLE001 — source info is best-effort
        return ""


def _const_bytes(const):
    nbytes = getattr(const, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    try:
        return int(np.asarray(const).nbytes)
    except Exception:  # noqa: BLE001
        return 0


def audit_jaxpr(program, closed_jaxpr, *,
                const_bytes_limit=DEFAULT_CONST_BYTES_LIMIT,
                check_f64=True):
    """Run every jaxpr-level rule. Returns (violations, stats) where
    stats = {eqns, f64_eqns, callback_eqns, island_casts, const_bytes}.
    """
    violations = []
    per_rule = {}
    stats = {"eqns": 0, "f64_eqns": 0, "callback_eqns": 0,
             "island_casts": 0, "const_bytes": 0}

    def add(rule, path, message):
        per_rule[rule] = per_rule.get(rule, 0) + 1
        if per_rule[rule] <= MAX_PER_RULE:
            violations.append(Violation(rule, program, path, message))

    jaxpr = _as_jaxpr(closed_jaxpr)
    consts = list(getattr(closed_jaxpr, "consts", ()) or ())
    constvars = list(getattr(jaxpr, "constvars", ()) or ())
    for i, const in enumerate(consts):
        nbytes = _const_bytes(const)
        stats["const_bytes"] += nbytes
        if const_bytes_limit and nbytes > const_bytes_limit:
            shape = tuple(getattr(const, "shape", ()) or ())
            dtype = str(getattr(const, "dtype", type(const).__name__))
            name = constvars[i] if i < len(constvars) else i
            add("baked_constant", f"constvars[{i}]",
                f"closed-over constant {name} ({dtype}{list(shape)}, "
                f"{nbytes} bytes) baked into the executable "
                f"(limit {const_bytes_limit}); pass it as an argument "
                f"or fold it into state")

    for path, eqn in iter_eqns(jaxpr):
        stats["eqns"] += 1
        prim = eqn.primitive.name
        if prim in _CALLBACK_PRIMS or "callback" in prim:
            stats["callback_eqns"] += 1
            stack = _name_stack(eqn)
            add("host_callback", path,
                f"host callback primitive '{prim}' on the compiled path"
                + (f" (scope {stack})" if stack else "")
                + "; each dispatch round-trips to the host")
        if check_f64:
            for j, outvar in enumerate(eqn.outvars):
                dtype = _var_dtype(outvar)
                if dtype in F64_DTYPES:
                    stats["f64_eqns"] += 1
                    add("f64_leak", path,
                        f"'{prim}' produces {dtype} (outvar {j}); "
                        f"double precision leaked into the program")
        if prim == "convert_element_type":
            new_dtype = str(eqn.params.get("new_dtype", ""))
            if new_dtype in LOW_PRECISION_DTYPES:
                island = islands.island_of(_name_stack(eqn))
                if island is not None:
                    stats["island_casts"] += 1
                    add("island_cast", path,
                        f"cast to {new_dtype} inside "
                        f"fp32_island[{island}]; keep the island in "
                        f"fp32 and cast back to the compute dtype "
                        f"outside the scope")

    for rule, count in per_rule.items():
        if count > MAX_PER_RULE:
            violations.append(Violation(
                rule, program, "...",
                f"{count - MAX_PER_RULE} further {rule} violations "
                f"truncated (total {count})"))
    return violations, stats

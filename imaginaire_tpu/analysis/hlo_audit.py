"""Compiled-HLO text analysis: the post-partitioner half of the audit.

The jaxpr shows what the program *asked for*; the optimized HLO shows
what XLA actually emits after SPMD partitioning — collectives inserted
for sharded params never appear at the jaxpr level. This module parses
``compiled.as_text()`` (no private APIs) for:

- collective ops + payload bytes  -> collectives.py accounting
- the entry ``input_output_alias`` map -> donation.py dead-arg analysis
- host-callback custom-calls      -> backstop for callbacks that lower
                                     through ``custom-call`` targets
- an f64 op count                 -> cross-check of the jaxpr rule
"""

import re

from .jaxpr_audit import Violation

# optimized-HLO collective op mnemonics (all fusions keep these names)
HLO_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)

# python host-callback custom-call targets across jax versions
_HOST_CALLBACK_TARGETS = (
    "xla_python_cpu_callback", "xla_ffi_python_cpu_callback",
    "xla_python_gpu_callback", "xla_ffi_python_gpu_callback",
    "tpu_python_callback",
)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}
for _f8 in ("f8e4m3fn", "f8e5m2", "f8e4m3b11fnuz", "f8e4m3fnuz",
            "f8e5m2fnuz", "f8e3m4", "f8e4m3"):
    _DTYPE_BYTES[_f8] = 1

_SHAPE_RE = re.compile(
    r"\b(" + "|".join(sorted(_DTYPE_BYTES, key=len, reverse=True))
    + r")\[([0-9,]*)\]")
# one alias-map entry: `{out_index}: (param, {param_index_path}, kind)`
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+)\s*,\s*\{[\d,\s]*\}\s*,\s*"
    r"(?:may-alias|must-alias)\)")
# instruction rhs: `shape op(operands...)` — the result shape (a typed
# array literal or a tuple of them) precedes the op mnemonic
_INSTR_RE = re.compile(
    r"^\s*(?P<shape>\([^=]*?\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>[a-z][\w-]*)\(")


def shape_bytes(text):
    """Total bytes of every typed shape literal in ``text``
    (``f32[8,128]`` -> 4096; tuple shapes sum their elements)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_stats(hlo_text):
    """op -> {count, bytes} over the optimized HLO. Bytes are the
    result-shape payload of each collective instruction (start/done
    pairs of async collectives count once, on the -start; the -start's
    tuple shape bounds the payload)."""
    stats = {}
    for line in hlo_text.splitlines():
        if "=" not in line:
            continue
        _, _, rhs = line.partition("=")
        m = _INSTR_RE.match(rhs)
        if m is None:
            continue
        op = m.group("op")
        if op.endswith("-done"):
            continue
        if op.endswith("-start"):
            op = op[:-len("-start")]
        if op not in HLO_COLLECTIVE_OPS:
            continue
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += shape_bytes(m.group("shape"))
    return stats


def aliased_param_indices(hlo_text):
    """Parameter numbers named in the entry ``input_output_alias`` map
    (numbering is over the DCE-kept parameters)."""
    marker = hlo_text.find("input_output_alias=")
    if marker < 0:
        return set()
    # the map lives on the HloModule header line
    line_end = hlo_text.find("\n", marker)
    segment = hlo_text[marker:line_end if line_end > 0 else None]
    return {int(m) for m in _ALIAS_ENTRY_RE.findall(segment)}


def audit_hlo(program, hlo_text):
    """Returns (violations, stats): host-callback custom-call backstop
    violations plus {collectives, collective_op_count, collective_bytes,
    f64_ops, aliased_params}."""
    violations = []
    stats = {}
    collectives = collective_stats(hlo_text)
    stats["collectives"] = collectives
    stats["collective_op_count"] = sum(
        v["count"] for v in collectives.values())
    stats["collective_bytes"] = sum(
        v["bytes"] for v in collectives.values())
    stats["f64_ops"] = hlo_text.count("f64[")
    stats["aliased_params"] = sorted(aliased_param_indices(hlo_text))
    for target in _HOST_CALLBACK_TARGETS:
        count = hlo_text.count(f'custom_call_target="{target}"')
        if count:
            violations.append(Violation(
                "host_callback", program, f'custom-call:"{target}"',
                f"{count} host-callback custom-call(s) survived to the "
                f"optimized HLO"))
    return violations, stats

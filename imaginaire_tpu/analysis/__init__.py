"""Static analysis plane over every compiled program (ISSUE 12).

Two layers:

- **Graph audit** (jaxpr_audit / hlo_audit / donation / collectives):
  runs automatically on every ``xla_obs.compiled_program`` compile —
  the ledger entry gains an ``audit`` dict and the
  ``xla/graph/<label>/*`` counters feed the report and
  ``check_run_health --max-graph-violations``.
- **Source lint** (ast_rules + ``scripts/lint_graph.py``): repo-wide
  AST rules with an explicit inline-allowlist syntax.

``audit_program`` below is the orchestrator xla_obs calls with
whatever artifacts the compile produced (trace, lowering, executable);
each sub-audit degrades independently — analysis must never break a
compile.
"""

from . import islands  # noqa: F401  (registry import declares islands)
from .jaxpr_audit import (  # noqa: F401
    Violation, audit_jaxpr, iter_eqns,
)
from . import ast_rules, collectives, donation, hlo_audit  # noqa: F401


def audit_program(program, traced=None, lowered=None, compiled=None, *,
                  const_bytes_limit=None, include_hlo=True):
    """Audit one compiled program; returns the ledger ``audit`` dict:
    ``{violations, violation_count, stats, collectives, donation,
    const_bytes}``. Every sub-audit is best-effort — a failure is
    recorded under ``errors`` instead of raised."""
    from .jaxpr_audit import DEFAULT_CONST_BYTES_LIMIT

    if const_bytes_limit is None:
        const_bytes_limit = DEFAULT_CONST_BYTES_LIMIT
    violations = []
    audit = {"errors": {}}
    closed_jaxpr = getattr(traced, "jaxpr", None) if traced is not None \
        else None

    stats = {}
    if closed_jaxpr is not None:
        try:
            found, stats = audit_jaxpr(
                program, closed_jaxpr,
                const_bytes_limit=const_bytes_limit)
            violations.extend(found)
        except Exception as e:  # noqa: BLE001
            audit["errors"]["jaxpr"] = f"{type(e).__name__}: {e}"
    audit["stats"] = stats
    audit["const_bytes"] = stats.get("const_bytes", 0)

    hlo_text = None
    if include_hlo and compiled is not None:
        try:
            hlo_text = compiled.as_text()
        except Exception as e:  # noqa: BLE001
            audit["errors"]["hlo_text"] = f"{type(e).__name__}: {e}"
    if hlo_text is not None:
        try:
            found, hlo_stats = hlo_audit.audit_hlo(program, hlo_text)
            violations.extend(found)
            audit["hlo"] = {k: hlo_stats[k]
                            for k in ("f64_ops", "aliased_params")}
        except Exception as e:  # noqa: BLE001
            audit["errors"]["hlo"] = f"{type(e).__name__}: {e}"

    try:
        audit["collectives"] = collectives.collective_summary(
            closed_jaxpr, hlo_text)
    except Exception as e:  # noqa: BLE001
        audit["errors"]["collectives"] = f"{type(e).__name__}: {e}"
        audit["collectives"] = {"op_count": 0, "bytes": 0}

    if compiled is not None:
        try:
            found, summary = donation.audit_donation(
                program, compiled, closed_jaxpr, lowered,
                hlo_text=hlo_text)
            violations.extend(found)
            audit["donation"] = summary
        except Exception as e:  # noqa: BLE001
            audit["errors"]["donation"] = f"{type(e).__name__}: {e}"
            audit["donation"] = {"declared": 0, "aliased": 0,
                                 "dead_count": 0, "dead": []}
    else:
        audit["donation"] = {"declared": 0, "aliased": 0,
                             "dead_count": 0, "dead": []}

    audit["violations"] = [v.as_dict() for v in violations]
    audit["violation_count"] = len(violations)
    if not audit["errors"]:
        del audit["errors"]
    return audit

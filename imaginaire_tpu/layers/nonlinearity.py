"""Nonlinearity factory (ref: imaginaire/layers/nonlinearity.py:8-37)."""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

VALID = ("", "none", "relu", "leakyrelu", "prelu", "tanh", "sigmoid", "softmax")


def apply_nonlinearity(x, kind, prelu_alpha=None):
    if kind in ("", "none", None):
        return x
    if kind == "relu":
        return nn.relu(x)
    if kind == "leakyrelu":
        return nn.leaky_relu(x, negative_slope=0.2)
    if kind == "prelu":
        return jnp.where(x >= 0, x, prelu_alpha * x)
    if kind == "tanh":
        return jnp.tanh(x)
    if kind == "sigmoid":
        return nn.sigmoid(x)
    if kind == "softmax":
        return nn.softmax(x, axis=-1)
    raise ValueError(f"unknown nonlinearity {kind!r}")


def needs_prelu_param(kind):
    return kind == "prelu"

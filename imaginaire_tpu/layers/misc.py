"""Noise injection + partial-conv sequencing
(ref: imaginaire/layers/misc.py:9-47)."""

from __future__ import annotations

import jax
from flax import linen as nn


class PartialSequential(nn.Module):
    """Thread (activation, mask) through a chain of partial conv blocks
    (ref: layers/misc.py:32-47): the input's last channel is the initial
    validity mask; returns the final activation."""

    layers: tuple

    def __call__(self, x, training=False):
        act = x[..., :-1]
        mask = x[..., -1:]
        for layer in self.layers:
            act, mask = layer(act, mask_in=mask, training=training)
        return act


class ApplyNoise(nn.Module):
    """StyleGAN-style additive noise with a learned scalar weight.

    ``noise=None`` draws from the module's 'noise' RNG stream; passing an
    explicit noise map reproduces a fixed draw (inference determinism).
    If no stream and no explicit noise, the layer is a no-op (eval mode).
    """

    @nn.compact
    def __call__(self, x, noise=None):
        w = self.param("weight", nn.initializers.zeros, ())
        if noise is None:
            if self.has_rng("noise"):
                key = self.make_rng("noise")
                noise = jax.random.normal(key, x.shape[:-1] + (1,), x.dtype)
            else:
                return x
        return x + w * noise

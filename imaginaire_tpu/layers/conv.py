"""Conv/Linear block family with the ``order`` micro-DSL.

ref: imaginaire/layers/conv.py (``_BaseConvBlock``:14, forward
dispatch:77-91, LinearBlock:138, ConvNdBlock:194-330,
HyperConv2dBlock:438-590, PartialConv:593-1086, MultiOutConv2dBlock:851).

A block = [weight-normalized conv] + [activation norm] + [nonlinearity],
arranged by ``order`` ('CNA', 'NAC', ...). Conditional activation norms
(AdaIN/SPADE) receive conditioning through extra positional call args.
All blocks share the call contract ``block(x, *cond, training=False)``.

Layout NHWC / NDHWC; kernels (spatial..., in, out).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from imaginaire_tpu.layers import hyper_ops
from imaginaire_tpu.layers.activation_norm import CONDITIONAL_NORMS, get_activation_norm_layer
from imaginaire_tpu.layers.misc import ApplyNoise
from imaginaire_tpu.layers.nonlinearity import apply_nonlinearity, needs_prelu_param
from imaginaire_tpu.layers.weight_norm import spectral_normalize, weight_normalize, demodulate
from imaginaire_tpu.utils.init_weight import default_kernel_init

_PAD_MODES = {"zeros": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}


def _tuplify(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


class _WeightNormedConv(nn.Module):
    """N-d conv whose kernel passes through the configured weight norm."""

    features: int
    kernel_size: Sequence[int]
    stride: Sequence[int]
    padding: Sequence[int]
    dilation: Sequence[int]
    groups: int = 1
    use_bias: bool = True
    padding_mode: str = "zeros"
    weight_norm_type: str = ""
    weight_norm_params: Optional[dict] = None

    @nn.compact
    def __call__(self, x, training=False, style=None):
        nd = len(self.kernel_size)
        cin = x.shape[-1]
        kshape = tuple(self.kernel_size) + (cin // self.groups, self.features)
        kernel = self.param("kernel", default_kernel_init, kshape)
        wn = self.weight_norm_type
        p = dict(self.weight_norm_params or {})
        if wn == "spectral":
            kernel = spectral_normalize(self, kernel, training, eps=p.get("eps", 1e-12))
        elif wn == "weight":
            kernel = weight_normalize(self, kernel)
        elif wn == "weight_demod":
            if style is None:
                raise ValueError("weight_demod conv requires a style input")
            scale = nn.Dense(cin, name="demod_fc")(style) + 1.0
            kernels = demodulate(kernel, scale, eps=p.get("eps", 1e-8))
        elif wn not in ("", "none", None):
            raise ValueError(f"unknown weight norm {wn!r}")

        pads = [(0, 0)] + [(pad, pad) for pad in self.padding] + [(0, 0)]
        if any(pad > 0 for pad in self.padding):
            x = jnp.pad(x, pads, mode=_PAD_MODES[self.padding_mode])
        if wn == "weight_demod":
            out = hyper_ops.grouped_modulated_conv2d(
                x, kernels, stride=tuple(self.stride), padding="VALID",
                dilation=tuple(self.dilation)
            )
        else:
            out = lax.conv_general_dilated(
                x,
                kernel.astype(x.dtype),
                window_strides=tuple(self.stride),
                padding="VALID",
                rhs_dilation=tuple(self.dilation),
                dimension_numbers=_dim_numbers(nd),
                feature_group_count=self.groups,
            )
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,))
            out = out + bias.astype(out.dtype)
        return out


def _effective_order(order):
    """Collapse repeated order chars to their first occurrence — the
    reference keys layers by op name in a ModuleDict, so 'NACNAC' on a
    plain (non-residual) block is effectively 'NAC' (ref: conv.py:63-69);
    only residual blocks split a doubled order into two blocks."""
    seen = set()
    out = []
    for op in order:
        if op not in seen:
            seen.add(op)
            out.append(op)
    return "".join(out)


def _dim_numbers(nd):
    spatial = "DHW"[-nd:]
    return (f"N{spatial}C", f"{spatial}IO", f"N{spatial}C")


class _BaseConvBlock(nn.Module):
    """Shared order-DSL engine (ref: layers/conv.py:14-135)."""

    out_channels: int
    kernel_size: Union[int, Sequence[int]] = 3
    stride: Union[int, Sequence[int]] = 1
    padding: Optional[Union[int, Sequence[int]]] = None
    dilation: Union[int, Sequence[int]] = 1
    groups: int = 1
    bias: bool = True
    padding_mode: str = "zeros"
    weight_norm_type: str = ""
    weight_norm_params: Optional[dict] = None
    activation_norm_type: str = ""
    activation_norm_params: Optional[dict] = None
    nonlinearity: str = ""
    apply_noise: bool = False
    order: str = "CNA"
    nd: int = 2

    def _conv_module(self):
        ks = _tuplify(self.kernel_size, self.nd)
        dil = _tuplify(self.dilation, self.nd)
        if self.padding is None:
            pad = tuple(d * (k - 1) // 2 for k, d in zip(ks, dil))
        else:
            pad = _tuplify(self.padding, self.nd)
        return _WeightNormedConv(
            features=self.out_channels,
            kernel_size=ks,
            stride=_tuplify(self.stride, self.nd),
            padding=pad,
            dilation=dil,
            groups=self.groups,
            use_bias=self.bias,
            padding_mode=self.padding_mode,
            weight_norm_type=self.weight_norm_type,
            weight_norm_params=self.weight_norm_params,
            name="conv",
        )

    @property
    def conditional(self):
        return self.activation_norm_type in CONDITIONAL_NORMS

    @nn.compact
    def __call__(self, x, *cond_inputs, training=False, noise=None, style=None):
        norm = get_activation_norm_layer(
            self.activation_norm_type, self.activation_norm_params, name="norm"
        )
        prelu_alpha = (
            self.param("prelu_alpha", nn.initializers.constant(0.25), ())
            if needs_prelu_param(self.nonlinearity)
            else None
        )
        for op in _effective_order(self.order):
            if op == "C":
                x = self._conv_module()(x, training=training, style=style)
                if self.apply_noise:
                    x = ApplyNoise(name="noise")(x, noise=noise)
            elif op == "N":
                if norm is not None:
                    cond = cond_inputs if self.conditional else ()
                    x = norm(x, *cond, training=training)
            elif op == "A":
                x = apply_nonlinearity(x, self.nonlinearity, prelu_alpha)
            else:
                raise ValueError(f"invalid order char {op!r} in {self.order!r}")
        return x


class Conv1dBlock(_BaseConvBlock):
    nd: int = 1


class Conv2dBlock(_BaseConvBlock):
    nd: int = 2


class Conv3dBlock(_BaseConvBlock):
    nd: int = 3


class LinearBlock(nn.Module):
    """Dense + norm + activation with the same order DSL
    (ref: layers/conv.py:138-192)."""

    out_features: int
    bias: bool = True
    weight_norm_type: str = ""
    activation_norm_type: str = ""
    activation_norm_params: Optional[dict] = None
    nonlinearity: str = ""
    order: str = "CNA"

    @nn.compact
    def __call__(self, x, *cond_inputs, training=False):
        norm = get_activation_norm_layer(
            self.activation_norm_type, self.activation_norm_params, name="norm"
        )
        prelu_alpha = (
            self.param("prelu_alpha", nn.initializers.constant(0.25), ())
            if needs_prelu_param(self.nonlinearity)
            else None
        )
        conditional = self.activation_norm_type in CONDITIONAL_NORMS
        for op in _effective_order(self.order):
            if op == "C":
                kernel = self.param(
                    "kernel", default_kernel_init, (x.shape[-1], self.out_features)
                )
                if self.weight_norm_type == "spectral":
                    kernel = spectral_normalize(self, kernel, training)
                elif self.weight_norm_type == "weight":
                    kernel = weight_normalize(self, kernel)
                x = x @ kernel.astype(x.dtype)
                if self.bias:
                    x = x + self.param(
                        "bias", nn.initializers.zeros, (self.out_features,)
                    ).astype(x.dtype)
            elif op == "N":
                if norm is not None:
                    cond = cond_inputs if conditional else ()
                    x = norm(x, *cond, training=training)
            elif op == "A":
                x = apply_nonlinearity(x, self.nonlinearity, prelu_alpha)
        return x


class HyperConv2dBlock(_BaseConvBlock):
    """Conv block whose conv weights arrive at call time
    (ref: layers/conv.py:438-590). ``conv_weights=(w, b)`` with
    w: (B, kh, kw, cin, cout)."""

    nd: int = 2

    @nn.compact
    def __call__(self, x, *cond_inputs, conv_weights=None, training=False,
                 noise=None, style=None):
        norm = get_activation_norm_layer(
            self.activation_norm_type, self.activation_norm_params, name="norm"
        )
        prelu_alpha = (
            self.param("prelu_alpha", nn.initializers.constant(0.25), ())
            if needs_prelu_param(self.nonlinearity)
            else None
        )
        for op in _effective_order(self.order):
            if op == "C":
                if conv_weights is None or conv_weights[0] is None:
                    x = self._conv_module()(x, training=training, style=style)
                else:
                    w, b = conv_weights
                    x = hyper_ops.per_sample_conv2d(
                        x, w, b, stride=_tuplify(self.stride, 2)[0], padding="SAME"
                    )
                if self.apply_noise:
                    x = ApplyNoise(name="noise")(x, noise=noise)
            elif op == "N":
                if norm is not None:
                    cond = cond_inputs if self.conditional else ()
                    x = norm(x, *cond, training=training)
            elif op == "A":
                x = apply_nonlinearity(x, self.nonlinearity, prelu_alpha)
        return x


class PartialConv2d(nn.Module):
    """Mask-aware convolution (NVIDIA partial conv; ref:
    layers/conv.py:927-1009). Returns (out, updated_mask)."""

    features: int
    kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    use_bias: bool = True
    multi_channel: bool = False
    eps: float = 1e-8
    nd: int = 2

    @nn.compact
    def __call__(self, x, mask=None, training=False):
        ks = _tuplify(self.kernel_size, self.nd)
        cin = x.shape[-1]
        kernel = self.param("kernel", default_kernel_init, ks + (cin, self.features))
        if mask is None:
            mask = jnp.ones(x.shape[:-1] + ((cin,) if self.multi_channel else (1,)), x.dtype)
        dn = _dim_numbers(self.nd)
        strides = _tuplify(self.stride, self.nd)
        pad = [((k - 1) // 2, (k - 1) // 2) for k in ks]
        mask_cin = cin if self.multi_channel else 1
        ones_kernel = jnp.ones(ks + (mask_cin, 1), x.dtype)
        win_size = float(jnp.prod(jnp.asarray(ks))) * mask_cin
        mask_sum = lax.conv_general_dilated(
            mask, ones_kernel, strides, pad, dimension_numbers=dn
        )
        out = lax.conv_general_dilated(
            x * mask,
            kernel.astype(x.dtype),
            strides,
            pad,
            dimension_numbers=dn,
        )
        valid = mask_sum > 0
        ratio = jnp.where(valid, win_size / jnp.maximum(mask_sum, self.eps), 0.0)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,))
            out = (out * ratio + bias.astype(out.dtype)) * valid
        else:
            out = out * ratio
        return out, valid.astype(x.dtype)


class _BasePartialConvBlock(nn.Module):
    """Partial-conv block with order DSL; threads (x, mask) pairs
    (ref: layers/conv.py:593-700)."""

    out_channels: int
    kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    bias: bool = True
    multi_channel: bool = False
    activation_norm_type: str = ""
    activation_norm_params: Optional[dict] = None
    nonlinearity: str = ""
    order: str = "CNA"
    nd: int = 2

    @nn.compact
    def __call__(self, x, *cond_inputs, mask_in=None, training=False):
        norm = get_activation_norm_layer(
            self.activation_norm_type, self.activation_norm_params, name="norm"
        )
        conditional = self.activation_norm_type in CONDITIONAL_NORMS
        prelu_alpha = (
            self.param("prelu_alpha", nn.initializers.constant(0.25), ())
            if needs_prelu_param(self.nonlinearity)
            else None
        )
        mask = mask_in
        for op in _effective_order(self.order):
            if op == "C":
                x, mask = PartialConv2d(
                    features=self.out_channels,
                    kernel_size=self.kernel_size,
                    stride=self.stride,
                    use_bias=self.bias,
                    multi_channel=self.multi_channel,
                    nd=self.nd,
                    name="conv",
                )(x, mask, training=training)
            elif op == "N":
                if norm is not None:
                    cond = cond_inputs if conditional else ()
                    x = norm(x, *cond, training=training)
            elif op == "A":
                x = apply_nonlinearity(x, self.nonlinearity, prelu_alpha)
        return x, mask


class PartialConv2dBlock(_BasePartialConvBlock):
    nd: int = 2


class PartialConv3dBlock(_BasePartialConvBlock):
    nd: int = 3


class PartialConv3d(PartialConv2d):
    nd: int = 3


class MultiOutConv2dBlock(_BaseConvBlock):
    """Conv block that also returns the pre-nonlinearity features
    (ref: layers/conv.py:851-924)."""

    nd: int = 2

    @nn.compact
    def __call__(self, x, *cond_inputs, training=False, noise=None, style=None):
        norm = get_activation_norm_layer(
            self.activation_norm_type, self.activation_norm_params, name="norm"
        )
        prelu_alpha = (
            self.param("prelu_alpha", nn.initializers.constant(0.25), ())
            if needs_prelu_param(self.nonlinearity)
            else None
        )
        pre_act = x
        for op in _effective_order(self.order):
            if op == "C":
                x = self._conv_module()(x, training=training, style=style)
                if self.apply_noise:
                    x = ApplyNoise(name="noise")(x, noise=noise)
            elif op == "N":
                if norm is not None:
                    cond = cond_inputs if self.conditional else ()
                    x = norm(x, *cond, training=training)
            elif op == "A":
                pre_act = x
                x = apply_nonlinearity(x, self.nonlinearity, prelu_alpha)
        return x, pre_act

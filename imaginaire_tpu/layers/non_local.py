"""SAGAN self-attention block (ref: imaginaire/layers/non_local.py:13-79).

theta/phi/g 1x1 convs, attention over down-pooled keys/values, learned
scalar gate gamma initialized at 0. The attention einsums are plain
matmuls — MXU work — and XLA fuses the softmax chain.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from imaginaire_tpu.layers.conv import Conv2dBlock


class NonLocal2dBlock(nn.Module):
    """Self-attention block (ref: layers/non_local.py).

    ``ring_axis``: run the attention as ring attention over that mesh
    axis (sequence/context parallelism, parallel/ring_attention.py) —
    for feature maps whose token count exceeds one device. With
    ``ring_shard_map`` (the default) the block wraps ONLY its attention
    core in a shard_map island over the process mesh, sharding the
    token axis over ``ring_axis`` — so it drops into a stock jitted
    training step (XLA GSPMD partitions the surrounding convs; the
    island pins the attention to the ring schedule). Set
    ``ring_shard_map=False`` when the block already executes inside an
    outer shard_map with tokens sharded over the axis. The pooled-key
    memory optimization is skipped in ring mode (the ring already
    bounds per-device memory). Initialize with the ring_axis='' twin
    (identical param tree) — collectives are unbound outside a mesh."""

    scale: bool = True
    clamp: bool = False
    weight_norm_type: str = "spectral"
    ring_axis: str = ""
    ring_shard_map: bool = True

    @nn.compact
    def __call__(self, x, training=False):
        b, h, w, c = x.shape
        ch = max(c // 8, 1)
        cg = max(c // 2, 1)
        conv = lambda out, name: Conv2dBlock(  # noqa: E731
            out_channels=out,
            kernel_size=1,
            padding=0,
            weight_norm_type=self.weight_norm_type,
            order="C",
            name=name,
        )
        if self.ring_axis:
            from imaginaire_tpu.parallel.ring_attention import ring_attention

            q = conv(ch, "theta")(x, training=training).reshape(
                b, h * w, 1, ch)
            k = conv(ch, "phi")(x, training=training).reshape(b, h * w, 1, ch)
            v = conv(cg, "g")(x, training=training).reshape(b, h * w, 1, cg)
            if self.ring_shard_map:
                from imaginaire_tpu.parallel import shard_map
                from jax.sharding import PartitionSpec as P

                from imaginaire_tpu.parallel.mesh import get_mesh

                mesh = get_mesh()
                if mesh is None or self.ring_axis not in mesh.axis_names:
                    raise ValueError(
                        f"non_local ring_axis={self.ring_axis!r} needs a "
                        f"process mesh with that axis (have "
                        f"{getattr(mesh, 'axis_names', None)}); create it "
                        "via parallel.mesh.set_mesh or set ring_axis: ''")
                # shard the batch over 'data' too when it divides —
                # P(None, seq) would all-gather the batch into every
                # data-parallel row and redo identical attention there
                axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
                ring_size = axis_sizes[self.ring_axis]
                if (h * w) % ring_size != 0:
                    raise ValueError(
                        f"non_local ring attention shards the {h}x{w} "
                        f"feature map's {h * w} tokens over mesh axis "
                        f"{self.ring_axis!r} of size {ring_size}, which "
                        "does not divide evenly; pick a feature-map size "
                        f"divisible by {ring_size} or shrink the axis")
                batch_axis = None
                if "data" in mesh.axis_names and self.ring_axis != "data":
                    if b % axis_sizes["data"] == 0:
                        batch_axis = "data"
                spec = P(batch_axis, self.ring_axis)
                y = shard_map(
                    lambda q_, k_, v_: ring_attention(
                        q_, k_, v_, self.ring_axis, scale=1.0),
                    mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec)(q, k, v)
            else:
                y = ring_attention(q, k, v, self.ring_axis, scale=1.0)
            y = y.reshape(b, h, w, cg)
        else:
            theta = conv(ch, "theta")(x, training=training).reshape(
                b, h * w, ch)
            phi = conv(ch, "phi")(x, training=training)
            phi = nn.max_pool(phi, (2, 2), strides=(2, 2)).reshape(b, -1, ch)
            g = conv(cg, "g")(x, training=training)
            g = nn.max_pool(g, (2, 2), strides=(2, 2)).reshape(b, -1, cg)
            attn = nn.softmax(jnp.einsum("bnc,bmc->bnm", theta, phi), axis=-1)
            y = jnp.einsum("bnm,bmc->bnc", attn, g).reshape(b, h, w, cg)
        y = conv(c, "out")(y, training=training)
        gamma = self.param("gamma", nn.initializers.zeros, ())
        return x + gamma * y

"""Activation normalization layers, incl. AdaIN / SPADE / hyper-SPADE.

ref: imaginaire/layers/activation_norm.py (AdaptiveNorm:22,
SpatiallyAdaptiveNorm:109, HyperSpatiallyAdaptiveNorm:237, LayerNorm2d:329,
factory:377).

All norms here expose the uniform call signature
``norm(x, *cond_inputs, training=...)`` so conv blocks can thread
conditional inputs without caring which norm they hold. Layout NHWC;
'batch' and 'sync_batch' are the same op under jit-sharded batches (the
global-batch mean IS the cross-replica mean; see parallel/sharding.py).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from imaginaire_tpu.analysis import islands
from imaginaire_tpu.layers import hyper_ops


def _fusable_modulation(impl, base_norm, x, pairs, masked=False):
    """Whether the SPADE epilogue can route through the fused
    ``ops.spade_modulation`` op (ISSUE 16). Refusal cases fall back to
    the unfused composition: the op implements *instance*-norm
    statistics only, needs full-spatial γ/β maps (AdaptiveNorm's
    'linear' broadcast refuses via the shape check), and the
    ``partial=True`` masked path stays on the reference composition."""
    if impl in ("", "none", "off", "unfused", None):
        return False
    if masked or base_norm != "instance" or x.ndim != 4 or not pairs:
        return False
    return all(
        tuple(g.shape) == tuple(x.shape) == tuple(b.shape)
        for g, b in pairs)


def default_fused_modulation(anp, remat):
    """Generator-side default for the epilogue-fusion knob, given the
    model's remat policy. Measured (PROFILE.md ISSUE-16, spade-512
    bs4): ``custom_vjp`` residuals are OPAQUE to ``jax.checkpoint``, so
    inside a rematted block the fused op pins (x, γ, stats) residuals
    the block policy would otherwise discard and recompute — fusion
    and block-remat are alternative mechanisms for the same residuals,
    not additive (fused+blocks: 22.61 GiB at baseline flops vs
    unfused+blocks 22.09 GiB at +4% flops). So under an enabled remat
    policy the default is 'none'; an explicit config knob always wins
    (memory_autotune sets it explicitly to measure both arms)."""
    from imaginaire_tpu.optim.remat import resolve_policy

    anp = dict(anp)
    if "fused_modulation" not in anp \
            and resolve_policy(remat, where="gen.remat").enabled:
        anp["fused_modulation"] = "none"
    return anp


def _resize(x, hw, method="nearest"):
    b, h, w, c = x.shape
    if (h, w) == tuple(hw):
        return x
    import jax

    return jax.image.resize(x, (b, hw[0], hw[1], c), method=method)


def _resize_nearest(x, hw):
    return _resize(x, hw, "nearest")


class NoNorm(nn.Module):
    @nn.compact
    def __call__(self, x, *cond, training=False):
        return x


class InstanceNorm(nn.Module):
    """Per-sample, per-channel spatial normalization (torch InstanceNorm2d
    semantics: affine=True by default in the reference's usage)."""

    affine: bool = True
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, *cond, training=False):
        axes = tuple(range(1, x.ndim - 1))
        # statistics in fp32 even under a bf16 compute policy: the
        # `norm_stats` island (analysis/islands.py) — the exit cast back
        # to x.dtype stays OUTSIDE the scope
        x32 = x.astype(jnp.float32)
        with islands.scope("norm_stats"):
            mean = jnp.mean(x32, axis=axes, keepdims=True)
            var = jnp.var(x32, axis=axes, keepdims=True)
            islands.guard("norm_stats", mean=mean, var=var)
            y32 = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + self.eps))
        y = y32.astype(x.dtype)
        if self.affine:
            c = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (c,))
            bias = self.param("bias", nn.initializers.zeros, (c,))
            y = y * scale.astype(y.dtype) + bias.astype(y.dtype)
        return y


class BatchNorm(nn.Module):
    """BatchNorm over the *global* batch — the TPU-native SyncBatchNorm
    (ref: layers/activation_norm.py:403-410). flax momentum 0.9 == torch
    momentum 0.1."""

    affine: bool = True
    eps: float = 1e-5
    momentum: float = 0.9

    @nn.compact
    def __call__(self, x, *cond, training=False):
        return nn.BatchNorm(
            use_running_average=not training,
            momentum=self.momentum,
            epsilon=self.eps,
            use_bias=self.affine,
            use_scale=self.affine,
        )(x)


class LayerNorm(nn.Module):
    """Channel-dim layer norm."""

    affine: bool = True
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, *cond, training=False):
        return nn.LayerNorm(epsilon=self.eps, use_bias=self.affine, use_scale=self.affine)(x)


class LayerNorm2d(nn.Module):
    """Per-sample whole-tensor normalization with per-channel affine
    (ref: layers/activation_norm.py:329-374)."""

    affine: bool = True
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, *cond, training=False):
        axes = tuple(range(1, x.ndim))
        # `norm_stats` fp32 island — exit cast outside the scope
        x32 = x.astype(jnp.float32)
        with islands.scope("norm_stats"):
            mean = jnp.mean(x32, axis=axes, keepdims=True)
            std = jnp.sqrt(jnp.var(x32, axis=axes, keepdims=True)
                           + self.eps)
            islands.guard("norm_stats", mean=mean, std=std)
            y32 = (x32 - mean) / std
        y = y32.astype(x.dtype)
        if self.affine:
            c = x.shape[-1]
            gamma = self.param("gamma", nn.initializers.ones, (c,))
            beta = self.param("beta", nn.initializers.zeros, (c,))
            y = gamma.astype(y.dtype) * y + beta.astype(y.dtype)
        return y


class GroupNorm(nn.Module):
    num_groups: int = 32
    affine: bool = True
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, *cond, training=False):
        return nn.GroupNorm(
            num_groups=self.num_groups,
            epsilon=self.eps,
            use_bias=self.affine,
            use_scale=self.affine,
        )(x)


class AdaptiveNorm(nn.Module):
    """AdaIN: param-free base norm + γ/β projected from a style vector
    (ref: layers/activation_norm.py:22-106)."""

    projection: str = "linear"  # 'linear' | 'conv'
    base_norm: str = "instance"
    separate_projection: bool = False
    projection_bias: bool = True
    weight_norm_type: str = ""
    fused_modulation: str = "auto"  # ops.spade_modulation implementation

    @nn.compact
    def __call__(self, x, cond, training=False):
        from imaginaire_tpu.layers.conv import LinearBlock
        from imaginaire_tpu.ops.spade_modulation import spade_modulation

        c = x.shape[-1]

        def dense(feats, name):
            return LinearBlock(feats, bias=self.projection_bias, order="C",
                               weight_norm_type=self.weight_norm_type, name=name)

        if self.projection == "linear":
            if self.separate_projection:
                gamma = dense(c, "fc_gamma")(cond, training=training)
                beta = dense(c, "fc_beta")(cond, training=training)
            else:
                gb = dense(2 * c, "fc")(cond, training=training)
                gamma, beta = jnp.split(gb, 2, axis=-1)
            # broadcast (B, C) over spatial dims
            shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (c,)
            gamma = gamma.reshape(shape)
            beta = beta.reshape(shape)
        else:
            gb = nn.Conv(2 * c, (1, 1), use_bias=self.projection_bias, name="conv")(cond)
            gamma, beta = jnp.split(gb, 2, axis=-1)
        # the spatially-broadcast ('conv' projection) case fuses the
        # norm->modulate epilogue; the 'linear' broadcast maps refuse via
        # the full-spatial shape check (ISSUE 16)
        if _fusable_modulation(self.fused_modulation, self.base_norm, x,
                               [(gamma, beta)]):
            return spade_modulation(x, [gamma], [beta],
                                    implementation=self.fused_modulation)
        y = _base_norm(self.base_norm, affine=False)(x, training=training)
        return y * (1.0 + gamma) + beta


class SpatiallyAdaptiveNorm(nn.Module):
    """SPADE (ref: layers/activation_norm.py:109-234).

    Each conditioning map is resized (nearest) to x's spatial size, pushed
    through a small conv MLP, and contributes additive spatial γ/β maps:
    ``out = norm(x) * (1 + Σγ_i) + Σβ_i``. ``partial=True`` threads a
    validity mask through mask-aware convs (wc-vid2vid guidance,
    ref: activation_norm.py:184-199).
    """

    num_filters: int = 128
    kernel_size: int = 3
    base_norm: str = "sync_batch"
    separate_projection: bool = True
    partial: bool = False
    interpolation: str = "nearest"
    weight_norm_type: str = ""
    fused_modulation: str = "auto"  # ops.spade_modulation implementation

    @nn.compact
    def __call__(self, x, *cond_inputs, training=False):
        from imaginaire_tpu.layers.conv import Conv2dBlock, PartialConv2d
        from imaginaire_tpu.ops.spade_modulation import spade_modulation

        c = x.shape[-1]
        hw = x.shape[1:3]

        def conv(feats, name):
            return Conv2dBlock(feats, kernel_size=self.kernel_size, order="C",
                               weight_norm_type=self.weight_norm_type, name=name)

        pairs = []
        masked = False
        for i, cond in enumerate(cond_inputs):
            if cond is None:
                continue
            mask = None
            if isinstance(cond, (tuple, list)):
                cond, mask = cond
            cond = _resize(cond, hw, self.interpolation)
            if mask is not None:
                mask = _resize(mask, hw, self.interpolation)
            if self.partial and mask is not None:
                hidden, _ = PartialConv2d(
                    self.num_filters, self.kernel_size, name=f"mlp_{i}"
                )(cond, mask)
                hidden = nn.relu(hidden)
                masked = True
            elif self.num_filters > 0:
                hidden = nn.relu(conv(self.num_filters, f"mlp_{i}")(cond, training=training))
            else:
                hidden = cond
            if self.separate_projection:
                gamma = conv(c, f"gamma_{i}")(hidden, training=training)
                beta = conv(c, f"beta_{i}")(hidden, training=training)
            else:
                gb = conv(2 * c, f"gb_{i}")(hidden, training=training)
                gamma, beta = jnp.split(gb, 2, axis=-1)
            pairs.append((gamma, beta))
        if _fusable_modulation(self.fused_modulation, self.base_norm, x,
                               pairs, masked=masked):
            # the whole multi-cond accumulation fuses: norm(x), Σγ and
            # Σβ never materialize (ops/spade_modulation.py, ISSUE 16).
            # The base norm here is the paramless InstanceNorm, so the
            # param tree is identical across implementations.
            return spade_modulation(x, [g for g, _ in pairs],
                                    [b for _, b in pairs],
                                    implementation=self.fused_modulation)
        y = _base_norm(self.base_norm, affine=False)(x, training=training)
        gamma_sum = None
        beta_sum = None
        for gamma, beta in pairs:
            gamma_sum = gamma if gamma_sum is None else gamma_sum + gamma
            beta_sum = beta if beta_sum is None else beta_sum + beta
        if gamma_sum is None:
            return y
        return y * (1.0 + gamma_sum) + beta_sum


class HyperSpatiallyAdaptiveNorm(nn.Module):
    """SPADE whose first-cond MLP weights are *runtime inputs* predicted by a
    weight generator (fs-vid2vid; ref: layers/activation_norm.py:237-326).

    ``norm_weights=(w, b)`` with w: (B, kh, kw, cin, cout) per-sample conv
    kernels applied via vmap'd conv — replacing the reference's per-sample
    Python loop with one batched XLA conv.
    """

    num_filters: int = 0
    kernel_size: int = 3
    base_norm: str = "instance"
    fused_modulation: str = "auto"  # ops.spade_modulation implementation

    @nn.compact
    def __call__(self, x, *cond_inputs, norm_weights=None, training=False):
        from imaginaire_tpu.ops.spade_modulation import spade_modulation

        c = x.shape[-1]
        hw = x.shape[1:3]
        pairs = []  # (gamma, beta, had_mask)
        for i, cond in enumerate(cond_inputs):
            if cond is None:
                continue
            mask = None
            if isinstance(cond, (tuple, list)):
                cond, mask = cond
                mask = _resize(mask, hw, "bilinear")
            cond = _resize_nearest(cond, hw)
            if i == 0 and norm_weights is not None \
                    and norm_weights[0] is not None:
                # predicted per-sample conv emits the 2c affine params
                # directly (ref: activation_norm.py:279-283, 317-321)
                w, b = norm_weights
                affine = hyper_ops.per_sample_conv2d(cond, w, b,
                                                     padding="SAME")
            else:
                h = cond
                if self.num_filters > 0:
                    h = nn.relu(nn.Conv(
                        self.num_filters,
                        (self.kernel_size, self.kernel_size),
                        padding="SAME", name=f"mlp_{i}")(h))
                affine = nn.Conv(2 * c, (self.kernel_size, self.kernel_size),
                                 padding="SAME", name=f"gb_{i}")(h)
            gamma, beta = jnp.split(affine, 2, axis=-1)
            if mask is not None:
                gamma = gamma * (1 - mask)
                beta = beta * (1 - mask)
            pairs.append((gamma, beta, mask is not None))
        # The combine here is SEQUENTIAL per condition (not summed), so
        # only the first γ/β pair — the one applied directly to norm(x),
        # incl. the runtime-weight path — fuses with the normalization;
        # a masked first pair refuses (ISSUE 16).
        start = 0
        if pairs and _fusable_modulation(
                self.fused_modulation, self.base_norm, x,
                [pairs[0][:2]], masked=pairs[0][2]):
            out = spade_modulation(x, [pairs[0][0]], [pairs[0][1]],
                                   implementation=self.fused_modulation)
            start = 1
        else:
            out = _base_norm(self.base_norm, affine=False)(x,
                                                           training=training)
        for gamma, beta, _ in pairs[start:]:
            out = out * (1.0 + gamma) + beta
        return out


def _base_norm(kind, affine):
    if kind in ("", "none", None):
        return NoNorm()
    if kind in ("batch", "sync_batch"):
        return BatchNorm(affine=affine)
    if kind == "instance":
        return InstanceNorm(affine=affine)
    if kind == "layer":
        return LayerNorm(affine=affine)
    if kind == "layer_2d":
        return LayerNorm2d(affine=affine)
    raise ValueError(f"unknown base norm {kind!r}")


CONDITIONAL_NORMS = ("adaptive", "spatially_adaptive", "hyper_spatially_adaptive")


def get_activation_norm_layer(norm_type, norm_params=None, name=None):
    """Norm factory (ref: layers/activation_norm.py:377-432). Returns a
    module with the uniform ``(x, *cond, training=)`` signature, or None."""
    p: dict[str, Any] = dict(norm_params or {})
    kw = {"name": name} if name else {}
    # Accept the reference's '<x>_norm' spellings (e.g. mlp_multiclass
    # passes 'batch_norm', ref: discriminators/mlp_multiclass.py:28-30).
    if isinstance(norm_type, str) and norm_type.endswith("_norm"):
        norm_type = norm_type[: -len("_norm")]
    if norm_type in ("", "none", None):
        return None
    if norm_type in ("batch", "sync_batch"):
        return BatchNorm(affine=p.get("affine", True), **kw)
    if norm_type == "instance":
        return InstanceNorm(affine=p.get("affine", True), **kw)
    if norm_type == "layer":
        return LayerNorm(affine=p.get("affine", True), **kw)
    if norm_type == "layer_2d":
        return LayerNorm2d(affine=p.get("affine", True), **kw)
    if norm_type == "group":
        return GroupNorm(num_groups=p.get("num_groups", 32), affine=p.get("affine", True), **kw)
    if norm_type == "adaptive":
        return AdaptiveNorm(
            projection=p.get("projection", "linear"),
            base_norm=p.get("activation_norm_type", "instance"),
            separate_projection=p.get("separate_projection", False),
            weight_norm_type=p.get("weight_norm_type", ""),
            fused_modulation=p.get("fused_modulation", "auto"),
            **kw,
        )
    if norm_type == "spatially_adaptive":
        return SpatiallyAdaptiveNorm(
            num_filters=p.get("num_filters", 128),
            kernel_size=p.get("kernel_size", 3),
            base_norm=p.get("activation_norm_type", "sync_batch"),
            separate_projection=p.get("separate_projection", True),
            partial=p.get("partial", False),
            interpolation=p.get("interpolation", "nearest"),
            weight_norm_type=p.get("weight_norm_type", ""),
            fused_modulation=p.get("fused_modulation", "auto"),
            **kw,
        )
    if norm_type == "hyper_spatially_adaptive":
        return HyperSpatiallyAdaptiveNorm(
            num_filters=p.get("num_filters", 0),
            kernel_size=p.get("kernel_size", 3),
            base_norm=p.get("activation_norm_type", "instance"),
            fused_modulation=p.get("fused_modulation", "auto"),
            **kw,
        )
    raise ValueError(f"unknown activation norm {norm_type!r}")

"""Composable layer library (ref: imaginaire/layers/).

Blocks follow the reference's micro-DSL: an ``order`` string over
{'C': conv/linear, 'N': activation norm, 'A': nonlinearity} arranges the
sub-ops (ref: layers/conv.py:59-91), and conditional activation norms
(AdaIN / SPADE / hyper-SPADE) receive their conditioning inputs as extra
positional call arguments — the ``conditional`` flag protocol
(ref: layers/__init__.py:5-20).

TPU-first differences from the reference:
  - NHWC layout; convs lower straight onto the MXU.
  - Blocks are Flax linen modules; mutable state (BN stats, spectral-norm
    power-iteration vectors) lives in the 'batch_stats' / 'spectral'
    collections and threads functionally through train steps.
  - 'batch' and 'sync_batch' norms are the same op: under a jit-sharded
    global batch, plain batch statistics ARE cross-replica statistics
    (see parallel/sharding.py).
"""

from imaginaire_tpu.layers.conv import (
    Conv1dBlock,
    Conv2dBlock,
    Conv3dBlock,
    HyperConv2dBlock,
    LinearBlock,
    MultiOutConv2dBlock,
    PartialConv2dBlock,
    PartialConv3dBlock,
)
from imaginaire_tpu.layers.residual import (
    DownRes2dBlock,
    HyperRes2dBlock,
    MultiOutRes2dBlock,
    PartialRes2dBlock,
    PartialRes3dBlock,
    Res1dBlock,
    Res2dBlock,
    Res3dBlock,
    UpRes2dBlock,
)
from imaginaire_tpu.layers.non_local import NonLocal2dBlock
from imaginaire_tpu.layers.misc import ApplyNoise, PartialSequential

__all__ = [
    "Conv1dBlock",
    "Conv2dBlock",
    "Conv3dBlock",
    "HyperConv2dBlock",
    "LinearBlock",
    "MultiOutConv2dBlock",
    "PartialConv2dBlock",
    "PartialConv3dBlock",
    "Res1dBlock",
    "Res2dBlock",
    "Res3dBlock",
    "UpRes2dBlock",
    "DownRes2dBlock",
    "HyperRes2dBlock",
    "PartialRes2dBlock",
    "PartialRes3dBlock",
    "MultiOutRes2dBlock",
    "NonLocal2dBlock",
    "ApplyNoise",
    "PartialSequential",
]

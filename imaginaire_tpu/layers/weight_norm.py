"""Weight normalizations: spectral norm, weight norm, weight demodulation.

ref: imaginaire/layers/weight_norm.py.

Spectral norm is a stateful transform (power-iteration vector ``u``); in
this functional framework ``u`` lives in the ``'spectral'`` variable
collection of the owning module and is updated in-place only when the
call runs with ``training=True`` and the collection is mutable — the same
contract as torch's hook updating ``weight_u`` on forward. The
``sigma``-normalized weight can be materialized for EMA checkpoints
("SN collapse", ref: utils/model_average.py:183-197) by
``imaginaire_tpu.utils.model_average.collapse_spectral_norm``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from imaginaire_tpu.analysis import islands


def _l2_normalize(v, eps=1e-12):
    return v / (jnp.linalg.norm(v) + eps)


def power_iteration(w_mat, u, n_steps=1, eps=1e-12):
    """One (or more) power-iteration steps. w_mat: (out, rest), u: (out,).

    Returns (sigma, new_u). Gradients do not flow through u/v (matching
    torch.nn.utils.spectral_norm's no_grad update). The iteration is the
    ``sn_power_iteration`` fp32 island (analysis/islands.py): a bf16
    compute policy hands in a bf16 w_mat, but the normalize/matvec chain
    runs — and sigma and u come back — in fp32 (sigma is a ratio of
    near-equal quantities; bf16's 8 mantissa bits visibly bias it, and a
    drifting low-precision u never converges)."""
    islands.guard("sn_power_iteration", u=u)
    with islands.scope("sn_power_iteration"):
        w_ng = lax.stop_gradient(w_mat).astype(jnp.float32)
        v = None
        for _ in range(n_steps):
            v = _l2_normalize(w_ng.T @ u, eps)
            u = _l2_normalize(w_ng @ v, eps)
        u = lax.stop_gradient(u)
        v = lax.stop_gradient(v)
        sigma = jnp.einsum("o,or,r->", u, w_mat.astype(jnp.float32), v)
    return sigma, u


def estimate_sigma(kernel, u, eps=1e-12):
    """Read-only sigma estimate ``u^T W v`` from the stored
    power-iteration vector — the diagnostics view of a layer's spectral
    norm (``u`` is NOT advanced; the training-time update stays the
    exclusive job of ``spectral_normalize``). Same (out, rest) matrix
    view as ``power_iteration`` so tracked sigmas agree with the ones
    the normalization divides by."""
    with islands.scope("sn_power_iteration"):
        w_mat = kernel.reshape(-1, kernel.shape[-1]).T.astype(jnp.float32)
        u = u.astype(jnp.float32)
        v = _l2_normalize(w_mat.T @ u, eps)
        return jnp.einsum("o,or,r->", u, w_mat, v)


def spectral_normalize(module, kernel, training, name="u", n_steps=1, eps=1e-12):
    """Apply spectral normalization to ``kernel`` inside a linen module.

    kernel layout: (..., out) — flax convention (spatial..., in, out).
    The power-iteration matrix is (out, prod(rest)), matching torch's
    view of (out, in*kh*kw) so ported sigmas agree.
    """
    out_ch = kernel.shape[-1]
    w_mat = kernel.reshape(-1, out_ch).T  # (out, rest)
    u_var = module.variable(
        "spectral",
        name,
        lambda: _l2_normalize(
            jnp.asarray(
                # deterministic init; the first power iterations converge it
                jnp.sin(jnp.arange(out_ch, dtype=jnp.float32) + 1.0)
            )
        ),
    )
    sigma, new_u = power_iteration(w_mat, u_var.value, n_steps=n_steps, eps=eps)
    if (training and not module.is_initializing()
            and module.is_mutable_collection("spectral")):
        u_var.value = new_u
    # divide in the kernel's own dtype: sigma is fp32, and `kernel /
    # sigma` would silently promote a bf16 kernel (and every conv after
    # it) back to fp32
    return kernel * (1.0 / sigma).astype(kernel.dtype)


def weight_normalize(module, kernel, name="g", eps=1e-12):
    """Classic weight norm: kernel = g * v / ||v||, per output channel."""
    out_ch = kernel.shape[-1]
    g = module.param(name, lambda rng: jnp.linalg.norm(kernel.reshape(-1, out_ch), axis=0))
    norm = jnp.linalg.norm(kernel.reshape(-1, out_ch), axis=0) + eps
    return kernel * (g / norm)


def demodulate(kernel, style, eps=1e-8):
    """StyleGAN2 weight demodulation (ref: layers/weight_norm.py:14-68).

    kernel: (kh, kw, in, out); style: (B, in) per-sample input scales.
    Returns per-sample kernels (B, kh, kw, in, out), demodulated per
    output channel.
    """
    w = kernel[None] * style[:, None, None, :, None]
    d = jnp.sqrt(jnp.sum(w * w, axis=(1, 2, 3), keepdims=True) + eps)
    return w / d

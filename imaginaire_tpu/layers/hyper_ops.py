"""Per-sample ("hyper") convolution: weights are runtime inputs.

The reference loops over the batch applying F.conv2d per sample
(ref: layers/conv.py:545-590). Here a single ``vmap`` over
(sample, kernel) pairs produces one batched XLA conv — the per-sample
loop disappears into the compiler and the MXU sees full tiles.
"""

from __future__ import annotations

import jax
from jax import lax


def _conv2d_single(x, w, stride=1, padding="SAME", dilation=1):
    # x: (H, W, Cin), w: (kh, kw, Cin, Cout)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    out = lax.conv_general_dilated(
        x[None],
        w,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out[0]


def _vmap_conv2d(x, w, stride, padding, dilation):
    return jax.vmap(
        lambda xi, wi: _conv2d_single(xi, wi, stride, padding, dilation))(
        x, w)


def per_sample_conv2d(x, w, b=None, stride=1, padding="SAME", dilation=1):
    """x: (B, H, W, Cin); w: (B, kh, kw, Cin, Cout); b: (B, Cout) or None.

    XLA lowers the vmap'd per-sample conv to a feature-grouped conv
    whose groups carry the batch — a form GSPMD cannot partition over a
    data-sharded batch (feature/group divisibility errors inside the
    sharded training step). When a process mesh with a >1 'data' axis
    has been CONFIGURED (peek_mesh — never auto-created from a layer
    op) and the batch divides it, the conv runs inside a shard_map
    island (the non_local.py pattern): each device convolves its own
    batch shard locally and the surrounding jit program keeps its GSPMD
    shardings."""
    from imaginaire_tpu.parallel.mesh import peek_mesh

    mesh = peek_mesh()
    if (mesh is not None and "data" in mesh.axis_names
            and mesh.shape["data"] > 1
            and x.shape[0] % mesh.shape["data"] == 0):
        from imaginaire_tpu.parallel import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P("data")
        out = shard_map(
            lambda xx, ww: _vmap_conv2d(xx, ww, stride, padding,
                                        dilation),
            mesh=mesh, in_specs=(spec, spec), out_specs=spec)(x, w)
    else:
        out = _vmap_conv2d(x, w, stride, padding, dilation)
    if b is not None:
        out = out + b[:, None, None, :]
    return out


def grouped_modulated_conv2d(x, w, stride=1, padding="SAME", dilation=1):
    """Weight-demodulated conv: per-sample kernels (B, kh, kw, Cin, Cout)
    (StyleGAN2 modulation, ref: layers/weight_norm.py:14-68).

    Delegates to ``per_sample_conv2d``: the explicit StyleGAN2 grouped
    trick (batch folded into feature_group_count) is GSPMD-hostile, and
    so is the raw vmap lowering (XLA produces the same grouped form) —
    per_sample_conv2d's shard_map island is what makes the op partition
    over a configured 'data' mesh. Keep all per-sample convs routed
    through that one entry point.
    """
    return per_sample_conv2d(x, w.astype(x.dtype), stride=stride,
                             padding=padding, dilation=dilation)

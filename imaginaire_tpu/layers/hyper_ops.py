"""Per-sample ("hyper") convolution: weights are runtime inputs.

The reference loops over the batch applying F.conv2d per sample
(ref: layers/conv.py:545-590). Here a single ``vmap`` over
(sample, kernel) pairs produces one batched XLA conv — the per-sample
loop disappears into the compiler and the MXU sees full tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _conv2d_single(x, w, stride=1, padding="SAME", dilation=1):
    # x: (H, W, Cin), w: (kh, kw, Cin, Cout)
    out = lax.conv_general_dilated(
        x[None],
        w,
        window_strides=(stride, stride),
        padding=padding,
        rhs_dilation=(dilation, dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out[0]


def per_sample_conv2d(x, w, b=None, stride=1, padding="SAME", dilation=1):
    """x: (B, H, W, Cin); w: (B, kh, kw, Cin, Cout); b: (B, Cout) or None."""
    out = jax.vmap(lambda xi, wi: _conv2d_single(xi, wi, stride, padding, dilation))(x, w)
    if b is not None:
        out = out + b[:, None, None, :]
    return out


def grouped_modulated_conv2d(x, w, stride=1, padding="SAME", dilation=1):
    """Weight-demodulated conv: per-sample kernels (B, kh, kw, Cin, Cout)
    applied as one grouped conv (StyleGAN2 trick, ref:
    layers/weight_norm.py:14-68).

    Group g of the grouped kernel must hold sample g's filters, so the
    batch axis lands next to Cout (groups-major channel order) on both
    the kernel and the output.
    """
    b, h, wd, cin = x.shape
    _, kh, kw, _, cout = w.shape
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    x_g = jnp.transpose(x, (1, 2, 0, 3)).reshape(1, h, wd, b * cin)
    w_g = jnp.transpose(w, (1, 2, 3, 0, 4)).reshape(kh, kw, cin, b * cout)
    out = lax.conv_general_dilated(
        x_g,
        w_g.astype(x.dtype),
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=b,
    )
    oh, ow = out.shape[1:3]
    return jnp.transpose(out.reshape(oh, ow, b, cout), (2, 0, 1, 3))

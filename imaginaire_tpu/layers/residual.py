"""Residual block family (ref: imaginaire/layers/residual.py).

A residual block = two conv blocks on the main branch + a learned 1x1
shortcut when channel counts differ (ref: residual.py:16-151). The
``order`` string covers both main-branch convs ('CNACNA', 'NACNAC', or
'pre_act' alias); conditional norms thread through both convs and the
shortcut norm exactly as in the reference.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from flax import linen as nn

from imaginaire_tpu.layers.conv import (
    Conv1dBlock,
    Conv2dBlock,
    Conv3dBlock,
    HyperConv2dBlock,
    MultiOutConv2dBlock,
    PartialConv2dBlock,
    PartialConv3dBlock,
)

_CONV_BLOCKS = {1: Conv1dBlock, 2: Conv2dBlock, 3: Conv3dBlock}


def _split_order(order):
    if order == "pre_act":
        order = "NACNAC"
    if len(order) not in (4, 5, 6):
        raise ValueError(f"residual order must have 4-6 chars, got {order!r}")
    half = (len(order) + 1) // 2
    return order[:half], order[half:]


class _BaseResBlock(nn.Module):
    out_channels: int
    kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    dilation: int = 1
    padding: Optional[int] = None
    # bool, or a [conv_0, conv_1, shortcut] list (ref SPADE passes
    # bias=[True, True, False], generators/spade.py:262).
    bias: Union[bool, Sequence[bool]] = True
    padding_mode: str = "zeros"
    weight_norm_type: str = ""
    weight_norm_params: Optional[dict] = None
    activation_norm_type: str = ""
    activation_norm_params: Optional[dict] = None
    skip_activation_norm: bool = True
    # apply the block nonlinearity in the learned shortcut too
    # (ref: residual.py:98-106, FUNIT's decoder turns this on)
    skip_nonlinearity: bool = False
    nonlinearity: str = "leakyrelu"
    apply_noise: bool = False
    hidden_channels_equal_out_channels: bool = False
    order: str = "CNACNA"
    learn_shortcut: Optional[bool] = None
    # up/down sampling hooks (overridden by Up/Down variants)
    upsample: bool = False
    downsample: bool = False
    nd: int = 2

    def _scale_up(self, x):
        if not self.upsample:
            return x
        b, h, w, c = x.shape
        return jax.image.resize(x, (b, 2 * h, 2 * w, c), method="nearest")

    def _scale_down(self, x):
        if not self.downsample:
            return x
        return nn.avg_pool(x, (2, 2), strides=(2, 2))

    @nn.compact
    def __call__(self, x, *cond_inputs, training=False):
        conv_cls = _CONV_BLOCKS[self.nd]
        order0, order1 = _split_order(self.order)
        in_channels = x.shape[-1]
        hidden = (
            self.out_channels
            if self.hidden_channels_equal_out_channels
            else min(in_channels, self.out_channels)
        )
        learn_shortcut = (
            self.learn_shortcut
            if self.learn_shortcut is not None
            else in_channels != self.out_channels
        )
        if isinstance(self.bias, (tuple, list)):
            bias_0, bias_1, bias_s = self.bias
        else:
            bias_0 = bias_1 = bias_s = self.bias
        common = dict(
            kernel_size=self.kernel_size,
            padding=self.padding,
            dilation=self.dilation,
            padding_mode=self.padding_mode,
            weight_norm_type=self.weight_norm_type,
            weight_norm_params=self.weight_norm_params,
            activation_norm_type=self.activation_norm_type,
            activation_norm_params=self.activation_norm_params,
            nonlinearity=self.nonlinearity,
            apply_noise=self.apply_noise,
            nd=self.nd,
        )
        dx = conv_cls(out_channels=hidden, stride=1, order=order0, bias=bias_0,
                      name="conv_0", **common)(x, *cond_inputs, training=training)
        dx = self._scale_up(dx)
        dx = conv_cls(
            out_channels=self.out_channels, stride=self.stride, order=order1,
            bias=bias_1, name="conv_1", **common
        )(dx, *cond_inputs, training=training)
        dx = self._scale_down(dx)

        xs = self._scale_up(x)
        if learn_shortcut:
            sc_common = dict(common)
            sc_common["kernel_size"] = 1
            sc_common["padding"] = 0
            sc_common["dilation"] = 1
            sc_common["apply_noise"] = False
            if not self.skip_activation_norm:
                sc_common["activation_norm_type"] = ""
            # the shortcut uses the first half of the order string with the
            # block nonlinearity only when skip_nonlinearity is set
            # (ref: residual.py:98-108, conv order[0:3])
            sc_common["nonlinearity"] = (self.nonlinearity
                                         if self.skip_nonlinearity else "")
            xs = conv_cls(
                out_channels=self.out_channels, stride=self.stride,
                order=order0, bias=bias_s, name="conv_s", **sc_common
            )(xs, *cond_inputs, training=training)
        xs = self._scale_down(xs)
        return xs + dx


class Res1dBlock(_BaseResBlock):
    nd: int = 1


class Res2dBlock(_BaseResBlock):
    nd: int = 2


class Res3dBlock(_BaseResBlock):
    nd: int = 3


class UpRes2dBlock(_BaseResBlock):
    """Residual block with nearest 2x upsampling between the convs and on
    the shortcut (ref: residual.py:796-860)."""

    upsample: bool = True
    nd: int = 2


class DownRes2dBlock(_BaseResBlock):
    """Residual block with 2x average-pool downsampling
    (ref: residual.py:648-712)."""

    downsample: bool = True
    nd: int = 2


class HyperRes2dBlock(nn.Module):
    """Residual block of hyper convs + (optionally hyper) SPADE norms whose
    weights arrive at runtime (ref: residual.py:519-645; fs-vid2vid)."""

    out_channels: int
    kernel_size: Union[int, Sequence[int]] = 3
    weight_norm_type: str = ""
    activation_norm_type: str = "hyper_spatially_adaptive"
    activation_norm_params: Optional[dict] = None
    nonlinearity: str = "leakyrelu"
    order: str = "CNACNA"

    @nn.compact
    def __call__(
        self,
        x,
        *cond_inputs,
        conv_weights=(None, None),
        norm_weights=(None, None),
        training=False,
    ):
        in_channels = x.shape[-1]
        hidden = min(in_channels, self.out_channels)
        common = dict(
            kernel_size=self.kernel_size,
            weight_norm_type=self.weight_norm_type,
            activation_norm_type=self.activation_norm_type,
            activation_norm_params=self.activation_norm_params,
            nonlinearity=self.nonlinearity,
        )
        order0, order1 = _split_order(self.order)
        dx = _HyperConvNorm(
            out_channels=hidden, order=order0, name="conv_0", **common
        )(x, *cond_inputs, conv_weights=conv_weights[0], norm_weights=norm_weights[0], training=training)
        dx = _HyperConvNorm(
            out_channels=self.out_channels, order=order1, name="conv_1", **common
        )(dx, *cond_inputs, conv_weights=conv_weights[1], norm_weights=norm_weights[1], training=training)
        if in_channels != self.out_channels:
            xs = Conv2dBlock(
                out_channels=self.out_channels,
                kernel_size=1,
                padding=0,
                weight_norm_type=self.weight_norm_type,
                order="C",
                name="conv_s",
            )(x, training=training)
        else:
            xs = x
        return xs + dx


class _HyperConvNorm(nn.Module):
    """One hyper conv + hyper norm + activation step used by HyperRes2dBlock."""

    out_channels: int
    kernel_size: Union[int, Sequence[int]] = 3
    weight_norm_type: str = ""
    activation_norm_type: str = "hyper_spatially_adaptive"
    activation_norm_params: Optional[dict] = None
    nonlinearity: str = "leakyrelu"
    order: str = "CNA"

    @nn.compact
    def __call__(self, x, *cond_inputs, conv_weights=None, norm_weights=None, training=False):
        from imaginaire_tpu.layers import hyper_ops
        from imaginaire_tpu.layers.activation_norm import get_activation_norm_layer
        from imaginaire_tpu.layers.nonlinearity import apply_nonlinearity, needs_prelu_param

        norm = get_activation_norm_layer(
            self.activation_norm_type, self.activation_norm_params, name="norm"
        )
        prelu_alpha = (
            self.param("prelu_alpha", nn.initializers.constant(0.25), ())
            if needs_prelu_param(self.nonlinearity)
            else None
        )
        for op in self.order:
            if op == "C":
                if conv_weights is not None and conv_weights[0] is not None:
                    w, b = conv_weights
                    x = hyper_ops.per_sample_conv2d(x, w, b, padding="SAME")
                else:
                    x = Conv2dBlock(
                        out_channels=self.out_channels,
                        kernel_size=self.kernel_size,
                        weight_norm_type=self.weight_norm_type,
                        order="C",
                        name="conv",
                    )(x, training=training)
            elif op == "N":
                if norm is not None:
                    if self.activation_norm_type == "hyper_spatially_adaptive":
                        x = norm(x, *cond_inputs, norm_weights=norm_weights, training=training)
                    else:
                        x = norm(x, *cond_inputs, training=training)
            elif op == "A":
                x = apply_nonlinearity(x, self.nonlinearity, prelu_alpha)
        return x


class _BasePartialResBlock(nn.Module):
    """Partial-conv residual block threading (x, mask)
    (ref: residual.py:947-1086)."""

    out_channels: int
    kernel_size: Union[int, Sequence[int]] = 3
    multi_channel: bool = False
    activation_norm_type: str = ""
    activation_norm_params: Optional[dict] = None
    nonlinearity: str = "leakyrelu"
    order: str = "CNACNA"
    nd: int = 2

    @nn.compact
    def __call__(self, x, *cond_inputs, mask_in=None, training=False):
        block_cls = PartialConv2dBlock if self.nd == 2 else PartialConv3dBlock
        in_channels = x.shape[-1]
        hidden = min(in_channels, self.out_channels)
        order0, order1 = _split_order(self.order)
        common = dict(
            kernel_size=self.kernel_size,
            multi_channel=self.multi_channel,
            activation_norm_type=self.activation_norm_type,
            activation_norm_params=self.activation_norm_params,
            nonlinearity=self.nonlinearity,
            nd=self.nd,
        )
        dx, mask = block_cls(out_channels=hidden, order=order0, name="conv_0", **common)(
            x, *cond_inputs, mask_in=mask_in, training=training
        )
        dx, mask = block_cls(out_channels=self.out_channels, order=order1, name="conv_1", **common)(
            dx, *cond_inputs, mask_in=mask, training=training
        )
        if in_channels != self.out_channels:
            xs, _ = block_cls(
                out_channels=self.out_channels,
                kernel_size=1,
                multi_channel=self.multi_channel,
                order="C",
                nd=self.nd,
                name="conv_s",
            )(x, mask_in=mask_in, training=training)
        else:
            xs = x
        return xs + dx, mask


class PartialRes2dBlock(_BasePartialResBlock):
    nd: int = 2


class PartialRes3dBlock(_BasePartialResBlock):
    nd: int = 3


class MultiOutRes2dBlock(nn.Module):
    """Residual block returning (out, pre-nonlinearity aux) from its second
    conv (ref: residual.py:1157-1235)."""

    out_channels: int
    kernel_size: Union[int, Sequence[int]] = 3
    activation_norm_type: str = ""
    activation_norm_params: Optional[dict] = None
    nonlinearity: str = "leakyrelu"
    order: str = "CNACNA"

    @nn.compact
    def __call__(self, x, *cond_inputs, training=False):
        in_channels = x.shape[-1]
        hidden = min(in_channels, self.out_channels)
        order0, order1 = _split_order(self.order)
        common = dict(
            kernel_size=self.kernel_size,
            activation_norm_type=self.activation_norm_type,
            activation_norm_params=self.activation_norm_params,
            nonlinearity=self.nonlinearity,
        )
        dx, _ = MultiOutConv2dBlock(out_channels=hidden, order=order0, name="conv_0", **common)(
            x, *cond_inputs, training=training
        )
        dx, aux = MultiOutConv2dBlock(
            out_channels=self.out_channels, order=order1, name="conv_1", **common
        )(dx, *cond_inputs, training=training)
        if in_channels != self.out_channels:
            xs = Conv2dBlock(
                out_channels=self.out_channels, kernel_size=1, padding=0, order="C", name="conv_s"
            )(x, training=training)
        else:
            xs = x
        return xs + dx, aux

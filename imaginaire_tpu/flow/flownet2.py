"""FlowNet2 in Flax
(ref: imaginaire/third_party/flow_net/flownet2/models.py:20-173,
networks/flownet_c.py, flownet_s.py, flownet_sd.py, flownet_fusion.py,
submodules.py — themselves from github.com/NVIDIA/flownet2-pytorch).

The full FlowNet2 cascade: FlowNetC (correlation cost volume) ->
FlowNetS1 -> FlowNetS2 on warped concats, FlowNetSD on the raw pair,
and a fusion net combining both flow branches. The correlation, warp
and channel-norm primitives are this framework's native TPU ops
(ops/correlation, ops/resample2d, ops/channelnorm).

NHWC throughout; ``load_torch_flownet2_weights`` transposes a ported
torch checkpoint (see scripts/convert_weights.py).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from imaginaire_tpu.ops.channelnorm import channelnorm
from imaginaire_tpu.ops.correlation import correlation
from imaginaire_tpu.ops.resample2d import resample2d


def _leaky(x):
    return nn.leaky_relu(x, 0.1)


class ConvBlock(nn.Module):
    """conv(+BN)+leakyrelu (ref: submodules.py:12-34)."""

    features: int
    kernel_size: int = 3
    stride: int = 1
    use_batch_norm: bool = False
    activate: bool = True

    @nn.compact
    def __call__(self, x, training=False):
        pad = (self.kernel_size - 1) // 2
        x = nn.Conv(self.features, (self.kernel_size, self.kernel_size),
                    strides=(self.stride, self.stride),
                    padding=((pad, pad), (pad, pad)),
                    use_bias=not self.use_batch_norm, name="conv")(x)
        if self.use_batch_norm:
            x = nn.BatchNorm(use_running_average=not training,
                             momentum=0.9, epsilon=1e-5, name="bn")(x)
        if self.activate:
            x = _leaky(x)
        return x


class Deconv(nn.Module):
    """ConvTranspose k4 s2 p1 + leakyrelu (ref: submodules.py:69-75)."""

    features: int
    use_bias: bool = True
    activate: bool = True

    @nn.compact
    def __call__(self, x):
        # torch ConvTranspose2d(k=4, s=2, p=1) == lax padding k-1-p = 2
        x = nn.ConvTranspose(self.features, (4, 4), strides=(2, 2),
                             padding=((2, 2), (2, 2)),
                             use_bias=self.use_bias, name="deconv")(x)
        if self.activate:
            x = _leaky(x)
        return x


class PredictFlow(nn.Module):
    """3x3 conv to 2 channels (ref: submodules.py:64-66)."""

    @nn.compact
    def __call__(self, x):
        return nn.Conv(2, (3, 3), padding=((1, 1), (1, 1)), name="conv")(x)


class _Refine(nn.Module):
    """Shared S/C decoder rung: predict flow, upsample it, deconv the
    features, concat (ref: flownet_s.py:96-117)."""

    deconv_features: int
    upflow_bias: bool = True

    @nn.compact
    def __call__(self, feat, skip):
        flow = PredictFlow(name="predict")(feat)
        flow_up = nn.ConvTranspose(2, (4, 4), strides=(2, 2),
                                   padding=((2, 2), (2, 2)),
                                   use_bias=self.upflow_bias,
                                   name="upflow")(flow)
        de = Deconv(self.deconv_features, name="deconv")(feat)
        return flow, jnp.concatenate([skip, de, flow_up], axis=-1)


class FlowNetC(nn.Module):
    """(ref: flownet_c.py:14-160)."""

    use_batch_norm: bool = False

    @nn.compact
    def __call__(self, x, training=False):
        bn = self.use_batch_norm
        conv1 = ConvBlock(64, 7, 2, bn, name="conv1")
        conv2 = ConvBlock(128, 5, 2, bn, name="conv2")
        conv3 = ConvBlock(256, 5, 2, bn, name="conv3")
        x1, x2 = x[..., 0:3], x[..., 3:]
        out_conv1a = conv1(x1, training)
        out_conv2a = conv2(out_conv1a, training)
        out_conv3a = conv3(out_conv2a, training)
        out_conv1b = conv1(x2, training)
        out_conv2b = conv2(out_conv1b, training)
        out_conv3b = conv3(out_conv2b, training)

        out_corr = _leaky(correlation(
            out_conv3a, out_conv3b, pad_size=20, kernel_size=1,
            max_displacement=20, stride1=1, stride2=2))
        out_redir = ConvBlock(32, 1, 1, bn, name="conv_redir")(
            out_conv3a, training)
        x = jnp.concatenate([out_redir, out_corr], axis=-1)

        out_conv3_1 = ConvBlock(256, 3, 1, bn, name="conv3_1")(x, training)
        out_conv4 = ConvBlock(512, 3, 1, bn, name="conv4_1")(
            ConvBlock(512, 3, 2, bn, name="conv4")(out_conv3_1, training),
            training)
        out_conv5 = ConvBlock(512, 3, 1, bn, name="conv5_1")(
            ConvBlock(512, 3, 2, bn, name="conv5")(out_conv4, training),
            training)
        out_conv6 = ConvBlock(1024, 3, 1, bn, name="conv6_1")(
            ConvBlock(1024, 3, 2, bn, name="conv6")(out_conv5, training),
            training)

        flow6, concat5 = _Refine(512, name="refine5")(out_conv6, out_conv5)
        flow5, concat4 = _Refine(256, name="refine4")(concat5, out_conv4)
        flow4, concat3 = _Refine(128, name="refine3")(concat4, out_conv3_1)
        flow3, concat2 = _Refine(64, name="refine2")(concat3, out_conv2a)
        flow2 = PredictFlow(name="predict_flow2")(concat2)
        return flow2, flow3, flow4, flow5, flow6


class FlowNetS(nn.Module):
    """(ref: flownet_s.py:16-121)."""

    input_channels: int = 12
    use_batch_norm: bool = False

    @nn.compact
    def __call__(self, x, training=False):
        bn = self.use_batch_norm
        out_conv1 = ConvBlock(64, 7, 2, bn, name="conv1")(x, training)
        out_conv2 = ConvBlock(128, 5, 2, bn, name="conv2")(out_conv1,
                                                           training)
        out_conv3 = ConvBlock(256, 3, 1, bn, name="conv3_1")(
            ConvBlock(256, 5, 2, bn, name="conv3")(out_conv2, training),
            training)
        out_conv4 = ConvBlock(512, 3, 1, bn, name="conv4_1")(
            ConvBlock(512, 3, 2, bn, name="conv4")(out_conv3, training),
            training)
        out_conv5 = ConvBlock(512, 3, 1, bn, name="conv5_1")(
            ConvBlock(512, 3, 2, bn, name="conv5")(out_conv4, training),
            training)
        out_conv6 = ConvBlock(1024, 3, 1, bn, name="conv6_1")(
            ConvBlock(1024, 3, 2, bn, name="conv6")(out_conv5, training),
            training)
        # S variant's flow upsamplers have no bias (ref: flownet_s.py:58-66)
        flow6, concat5 = _Refine(512, False, name="refine5")(out_conv6,
                                                             out_conv5)
        flow5, concat4 = _Refine(256, False, name="refine4")(concat5,
                                                             out_conv4)
        flow4, concat3 = _Refine(128, False, name="refine3")(concat4,
                                                             out_conv3)
        flow3, concat2 = _Refine(64, False, name="refine2")(concat3,
                                                            out_conv2)
        flow2 = PredictFlow(name="predict_flow2")(concat2)
        return flow2, flow3, flow4, flow5, flow6


class _RefineSD(nn.Module):
    """SD/fusion rung with an intermediate conv before flow prediction
    (ref: flownet_sd.py:100-118)."""

    inter_features: int
    deconv_features: int
    use_batch_norm: bool = False

    @nn.compact
    def __call__(self, feat, skip):
        inter = ConvBlock(self.inter_features, 3, 1, self.use_batch_norm,
                          activate=False, name="inter")(feat)
        flow = PredictFlow(name="predict")(inter)
        flow_up = nn.ConvTranspose(2, (4, 4), strides=(2, 2),
                                   padding=((2, 2), (2, 2)),
                                   name="upflow")(flow)
        de = Deconv(self.deconv_features, name="deconv")(feat)
        return flow, jnp.concatenate([skip, de, flow_up], axis=-1)


class FlowNetSD(nn.Module):
    """(ref: flownet_sd.py:13-121)."""

    use_batch_norm: bool = False

    @nn.compact
    def __call__(self, x, training=False):
        bn = self.use_batch_norm
        out_conv0 = ConvBlock(64, 3, 1, bn, name="conv0")(x, training)
        out_conv1 = ConvBlock(128, 3, 1, bn, name="conv1_1")(
            ConvBlock(64, 3, 2, bn, name="conv1")(out_conv0, training),
            training)
        out_conv2 = ConvBlock(128, 3, 1, bn, name="conv2_1")(
            ConvBlock(128, 3, 2, bn, name="conv2")(out_conv1, training),
            training)
        out_conv3 = ConvBlock(256, 3, 1, bn, name="conv3_1")(
            ConvBlock(256, 3, 2, bn, name="conv3")(out_conv2, training),
            training)
        out_conv4 = ConvBlock(512, 3, 1, bn, name="conv4_1")(
            ConvBlock(512, 3, 2, bn, name="conv4")(out_conv3, training),
            training)
        out_conv5 = ConvBlock(512, 3, 1, bn, name="conv5_1")(
            ConvBlock(512, 3, 2, bn, name="conv5")(out_conv4, training),
            training)
        out_conv6 = ConvBlock(1024, 3, 1, bn, name="conv6_1")(
            ConvBlock(1024, 3, 2, bn, name="conv6")(out_conv5, training),
            training)
        flow6 = PredictFlow(name="predict_flow6")(out_conv6)
        flow6_up = nn.ConvTranspose(2, (4, 4), strides=(2, 2),
                                    padding=((2, 2), (2, 2)),
                                    name="upflow6")(flow6)
        de5 = Deconv(512, name="deconv5")(out_conv6)
        concat5 = jnp.concatenate([out_conv5, de5, flow6_up], axis=-1)
        flow5, concat4 = _RefineSD(512, 256, bn, name="refine4")(concat5,
                                                                 out_conv4)
        flow4, concat3 = _RefineSD(256, 128, bn, name="refine3")(concat4,
                                                                 out_conv3)
        flow3, concat2 = _RefineSD(128, 64, bn, name="refine2")(concat3,
                                                                out_conv2)
        inter2 = ConvBlock(64, 3, 1, bn, activate=False, name="inter_conv2")(
            concat2)
        flow2 = PredictFlow(name="predict_flow2")(inter2)
        return flow2, flow3, flow4, flow5, flow6


class FlowNetFusion(nn.Module):
    """(ref: flownet_fusion.py:13-85)."""

    use_batch_norm: bool = False

    @nn.compact
    def __call__(self, x, training=False):
        bn = self.use_batch_norm
        out_conv0 = ConvBlock(64, 3, 1, bn, name="conv0")(x, training)
        out_conv1 = ConvBlock(128, 3, 1, bn, name="conv1_1")(
            ConvBlock(64, 3, 2, bn, name="conv1")(out_conv0, training),
            training)
        out_conv2 = ConvBlock(128, 3, 1, bn, name="conv2_1")(
            ConvBlock(128, 3, 2, bn, name="conv2")(out_conv1, training),
            training)
        flow2 = PredictFlow(name="predict_flow2")(out_conv2)
        flow2_up = nn.ConvTranspose(2, (4, 4), strides=(2, 2),
                                    padding=((2, 2), (2, 2)),
                                    name="upflow2")(flow2)
        de1 = Deconv(32, name="deconv1")(out_conv2)
        concat1 = jnp.concatenate([out_conv1, de1, flow2_up], axis=-1)
        inter1 = ConvBlock(32, 3, 1, bn, activate=False, name="inter_conv1")(
            concat1)
        flow1 = PredictFlow(name="predict_flow1")(inter1)
        flow1_up = nn.ConvTranspose(2, (4, 4), strides=(2, 2),
                                    padding=((2, 2), (2, 2)),
                                    name="upflow1")(flow1)
        de0 = Deconv(16, name="deconv0")(concat1)
        concat0 = jnp.concatenate([out_conv0, de0, flow1_up], axis=-1)
        inter0 = ConvBlock(16, 3, 1, bn, activate=False, name="inter_conv0")(
            concat0)
        return PredictFlow(name="predict_flow0")(inter0)


def _up4(x, method="bilinear"):
    b, h, w, c = x.shape
    return jax.image.resize(x, (b, 4 * h, 4 * w, c), method=method)


class FlowNet2(nn.Module):
    """The full cascade (ref: models.py:20-173). Input: two images
    stacked on a time axis (B, 2, H, W, 3) in [0, rgb_max]; output
    pixel-unit flow (B, H, W, 2)."""

    rgb_max: float = 1.0
    div_flow: float = 20.0
    use_batch_norm: bool = False

    @nn.compact
    def __call__(self, inputs, training=False):
        rgb_mean = jnp.mean(inputs, axis=(1, 2, 3), keepdims=True)
        x = (inputs - rgb_mean) / self.rgb_max
        x1, x2 = x[:, 0], x[:, 1]
        x = jnp.concatenate([x1, x2], axis=-1)

        flownetc_flow2 = FlowNetC(self.use_batch_norm, name="flownetc")(
            x, training)[0]
        flownetc_flow = _up4(flownetc_flow2 * self.div_flow)
        resampled_img1 = resample2d(x2, flownetc_flow)
        norm_diff_img0 = channelnorm(x1 - resampled_img1)
        concat1 = jnp.concatenate(
            [x, resampled_img1, flownetc_flow / self.div_flow,
             norm_diff_img0], axis=-1)

        flownets1_flow2 = FlowNetS(12, self.use_batch_norm,
                                   name="flownets_1")(concat1, training)[0]
        flownets1_flow = _up4(flownets1_flow2 * self.div_flow)
        resampled_img1 = resample2d(x2, flownets1_flow)
        norm_diff_img0 = channelnorm(x1 - resampled_img1)
        concat2 = jnp.concatenate(
            [x, resampled_img1, flownets1_flow / self.div_flow,
             norm_diff_img0], axis=-1)

        flownets2_flow2 = FlowNetS(12, self.use_batch_norm,
                                   name="flownets_2")(concat2, training)[0]
        flownets2_flow = _up4(flownets2_flow2 * self.div_flow,
                              method="nearest")
        norm_flownets2_flow = channelnorm(flownets2_flow)
        diff_flownets2_img1 = channelnorm(
            x1 - resample2d(x2, flownets2_flow))

        flownetsd_flow2 = FlowNetSD(self.use_batch_norm, name="flownets_d")(
            x, training)[0]
        flownetsd_flow = _up4(flownetsd_flow2 / self.div_flow,
                              method="nearest")
        norm_flownetsd_flow = channelnorm(flownetsd_flow)
        diff_flownetsd_img1 = channelnorm(
            x1 - resample2d(x2, flownetsd_flow))

        concat3 = jnp.concatenate(
            [x1, flownetsd_flow, flownets2_flow, norm_flownetsd_flow,
             norm_flownets2_flow, diff_flownetsd_img1,
             diff_flownets2_img1], axis=-1)
        return FlowNetFusion(self.use_batch_norm, name="flownetfusion")(
            concat3, training)

"""FlowNet2 port (ref: imaginaire/third_party/flow_net) plus the
teacher-output amortization layer (flow/cache.py)."""

from imaginaire_tpu.flow.cache import (
    DatasetFlowCacheHook,
    FlowCacheStore,
    TeacherFlowCache,
    flow_cache_settings,
    resolve_cache_dir,
    transform_flow,
)
from imaginaire_tpu.flow.flow_net import FlowNet
from imaginaire_tpu.flow.flownet2 import (
    FlowNet2,
    FlowNetC,
    FlowNetFusion,
    FlowNetS,
    FlowNetSD,
)

__all__ = ["FlowNet", "FlowNet2", "FlowNetC", "FlowNetS", "FlowNetSD",
           "FlowNetFusion", "TeacherFlowCache", "FlowCacheStore",
           "DatasetFlowCacheHook", "flow_cache_settings",
           "resolve_cache_dir", "transform_flow"]

"""FlowNet2 port (ref: imaginaire/third_party/flow_net)."""

from imaginaire_tpu.flow.flow_net import FlowNet
from imaginaire_tpu.flow.flownet2 import (
    FlowNet2,
    FlowNetC,
    FlowNetFusion,
    FlowNetS,
    FlowNetSD,
)

__all__ = ["FlowNet", "FlowNet2", "FlowNetC", "FlowNetS", "FlowNetSD",
           "FlowNetFusion"]

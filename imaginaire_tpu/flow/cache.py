"""Teacher-output amortization for the frozen FlowNet2 ground-truth
flow supervision (ISSUE 4 tentpole).

The vid2vid FlowLoss teacher only ever sees *real* frames — its
``(flow, conf)`` output is a pure function of the data batch — yet the
reference (and our in-graph port) recomputes it inside the
differentiated step program, identically every epoch, at 52.2 ms/frame
(23% of the gen step, PROFILE.md). This module moves the teacher OFF
the step's critical path, in two layers:

1. **Off-step execution** (``TeacherFlowCache.attach``): the teacher
   runs as its own jitted, stop-gradiented program in whatever host
   thread prepares the batch — under the device-prefetch pipeline
   that is the producer thread, overlapped with the running step — and
   its outputs ride the batch as plain numeric ``flow_gt``/``conf_gt``
   entries the step programs consume as inputs. The compiled D/G step
   programs then carry no FlowNet2 parameters at all (smaller
   executables; the 162M-param cascade is what pushes 512x1024 vid2vid
   programs over the remote-compile size cap).

2. **On-disk content-addressed cache** (``FlowCacheStore``): teacher
   outputs are persisted keyed by (dataset identity, frame-pair stems,
   canonical resolution, resize chain, teacher version). Flow is
   computed at the *canonical* resolution (after the deterministic
   resize ops, before crop/flip) and the random crop/hflip
   augmentations are applied to the cached flow equivariantly — slice
   for crop, mirror + negate-u for hflip — so a sample hits the cache
   regardless of its augmentation draw: epoch >= 2 (or a
   ``scripts/precompute_flow.py`` warm) pays ~zero teacher cost.
   Batches without dataset metadata (synthetic benches) fall back to a
   whole-batch content hash.

Config group ``flow_cache`` (see config.py): ``enabled``, ``mode``
(auto | producer | disk), ``dir``, ``store_dtype``.

Telemetry: ``flow_cache/hit_rate``, ``flow_cache/compute_ms``,
``flow_cache/pairs`` counters land in the run JSONL through the
existing sinks; ``drain_stats()`` feeds the trainer meters like the
device prefetcher's.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time

import numpy as np

from imaginaire_tpu.config import AttrDict, cfg_get

logger = logging.getLogger(__name__)

# Bump when the teacher definition changes incompatibly (cascade
# architecture, confidence threshold); stale shards then simply miss.
TEACHER_VERSION = "flownet2-v1"


def flow_cache_settings(cfg):
    """Parse the ``flow_cache`` config group (missing -> disabled)."""
    fcfg = cfg_get(cfg or {}, "flow_cache", None) or {}
    return AttrDict(
        enabled=bool(cfg_get(fcfg, "enabled", False)),
        mode=str(cfg_get(fcfg, "mode", "auto")),
        dir=cfg_get(fcfg, "dir", None),
        store_dtype=str(cfg_get(fcfg, "store_dtype", "float16")),
    )


def resolve_cache_dir(cfg):
    """The on-disk cache directory: ``flow_cache.dir`` > ``<logdir>/
    flow_cache`` > None (mode 'auto' then degrades to producer-only)."""
    settings = flow_cache_settings(cfg)
    if settings.dir:
        return str(settings.dir)
    logdir = cfg_get(cfg or {}, "logdir", None)
    if logdir:
        return os.path.join(str(logdir), "flow_cache")
    return None


def teacher_id(weights_path=None):
    """Identity of the teacher weights baked into every cache key: a
    converted checkpoint is identified by (name, size, mtime); absent
    weights (allow_random_init, tests) get a per-process tag so a
    random teacher never poisons a shared cache."""
    if weights_path and os.path.exists(weights_path):
        st = os.stat(weights_path)
        return (f"{TEACHER_VERSION}:{os.path.basename(weights_path)}"
                f":{st.st_size}:{int(st.st_mtime)}")
    return f"{TEACHER_VERSION}:random-init:{os.getpid()}"


def pair_key(dataset_name, root_idx, seq, stem_a, stem_b, canonical_hw,
             teacher):
    """Content-addressed key for one (frame_a -> frame_b) teacher
    evaluation at canonical resolution. ``stem_a`` is the *target*
    frame (t), ``stem_b`` the previous frame (t-1) — matching
    ``FlowLoss._gt(tgt_image, real_prev_image)`` argument order."""
    payload = "|".join([
        str(dataset_name), str(root_idx), str(seq), str(stem_a),
        str(stem_b), f"{int(canonical_hw[0])}x{int(canonical_hw[1])}",
        str(teacher),
    ])
    return hashlib.sha1(payload.encode()).hexdigest()


def content_key(images, teacher):
    """Whole-batch fallback key for batches without dataset metadata
    (synthetic bench batches): hash of the raw image bytes + shape."""
    arr = np.ascontiguousarray(np.asarray(images))
    digest = hashlib.sha1()
    digest.update(str(arr.shape).encode())
    digest.update(str(arr.dtype).encode())
    digest.update(arr.tobytes())
    digest.update(str(teacher).encode())
    return digest.hexdigest()


def transform_flow(flow, conf, record):
    """Apply a sample's spatial augmentation to canonical-resolution
    ``(flow, conf)`` equivariantly.

    flow: (..., H, W, 2) in pixel units (u = x, v = y); conf: (..., H,
    W, 1). Crop is a pure slice (pixel units are crop-invariant);
    horizontal flip mirrors the width axis and negates u (a rightward
    motion in the source is leftward in the mirrored frame); conf
    mirrors without negation.
    """
    crop = record.get("crop")
    if crop is not None:
        top, left, ch, cw = crop
        flow = flow[..., top:top + ch, left:left + cw, :]
        conf = conf[..., top:top + ch, left:left + cw, :]
    if record.get("hflip"):
        flow = flow[..., ::-1, :] * np.asarray([-1.0, 1.0], flow.dtype)
        conf = conf[..., ::-1, :]
    return np.ascontiguousarray(flow), np.ascontiguousarray(conf)


class FlowCacheStore:
    """Content-addressed (flow, conf) shards on disk.

    One ``.npz`` per key under ``<root>/<key[:2]>/<key>.npz`` with flow
    stored at ``store_dtype`` (float16 default — |flow| <= ~40 px, so
    the quantization error is < 0.05 px) and conf as uint8 (it is a
    binary mask). Writes are atomic (tmp + rename) so concurrent
    producer threads / precompute workers never read torn shards.
    """

    def __init__(self, root, store_dtype="float16"):
        self.root = str(root)
        self.store_dtype = np.dtype(store_dtype)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.corrupt_shards = 0

    def path(self, key):
        return os.path.join(self.root, key[:2], key + ".npz")

    def has(self, key):
        return os.path.exists(self.path(key))

    def _read(self, path):
        """One shard read — the retried unit (transient OSErrors recover
        on the next attempt) and the chaos harness's flow-store site."""
        from imaginaire_tpu.resilience import chaos

        chaos.get().maybe_io_error("flow_store")
        with np.load(path) as npz:
            return (npz["flow"].astype(np.float32),
                    npz["conf"].astype(np.float32))

    def _quarantine(self, path, error):
        """A corrupt shard degrades to a miss ONCE: renamed to
        ``*.corrupt`` so it is never re-read (and re-missed) every
        epoch, counted in ``flow_cache/corrupt_shards``."""
        from imaginaire_tpu import telemetry

        with self._lock:
            self.corrupt_shards += 1
            count = self.corrupt_shards
        try:
            os.replace(path, path + ".corrupt")
        except FileNotFoundError:
            # another host of a shared store already quarantined it
            pass
        except OSError:
            try:
                os.remove(path)
            except OSError:
                pass
        logger.warning("flow cache: quarantined corrupt shard %s (%s)",
                       path, error)
        tm = telemetry.get()
        if tm.enabled:
            tm.counter("flow_cache/corrupt_shards", count)
            tm.meta("flow_cache/corrupt_shard", shard=str(path),
                    error=str(error)[:200])

    def get(self, key):
        """(flow float32, conf float32) or None. Transient IO retries
        with bounded backoff (resilience/retry.py); a shard that still
        fails — or fails to parse — is quarantined and degrades to a
        miss (the teacher simply recomputes)."""
        import zipfile

        from imaginaire_tpu.resilience import retry_call

        path = self.path(key)
        if not os.path.exists(path):
            with self._lock:
                self.misses += 1
            return None
        try:
            flow, conf = retry_call(self._read, path, label="flow_store")
        except (OSError, KeyError, ValueError, EOFError,
                zipfile.BadZipFile) as e:
            self._quarantine(path, e)
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return flow, conf

    def put(self, key, flow, conf):
        from imaginaire_tpu.resilience import retry_call

        path = self.path(key)
        if os.path.exists(path):
            # multi-writer shared directory (ISSUE 8): another host's
            # producer already published this shard — content-addressed
            # keys make its bytes equivalent, so skip the redundant
            # write (and the rename-over-live-file hazard on
            # non-POSIX-atomic shared filesystems)
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # tmp name unique across THREADS and HOSTS: pids collide between
        # machines sharing a filesystem, so a random token joins the
        # pid/tid pair (np.savez appends '.npz' unless the name already
        # ends with it)
        import uuid

        tmp = (f"{path}.{os.getpid()}.{threading.get_ident()}."
               f"{uuid.uuid4().hex[:8]}.tmp.npz")

        def _write():
            np.savez(tmp, flow=np.asarray(flow).astype(self.store_dtype),
                     conf=np.asarray(conf).astype(np.uint8))
            os.replace(tmp, path)

        try:
            retry_call(_write, label="flow_store_write")
        except OSError as e:
            logger.warning("flow cache write failed for %s: %s", path, e)
            try:
                os.remove(tmp)
            except OSError:
                pass

    def count_miss(self, n=1):
        with self._lock:
            self.misses += n

    def stats(self):
        with self._lock:
            total = self.hits + self.misses
            return {"hits": self.hits, "misses": self.misses,
                    "corrupt_shards": self.corrupt_shards,
                    "hit_rate": (self.hits / total) if total else 0.0}


class TeacherFlowCache:
    """Producer-side facade the trainer owns: runs the frozen teacher
    off the step path and attaches ``flow_gt``/``conf_gt`` to batches.

    Args:
        flow_net_wrapper: the ``flow.FlowNet`` frozen-teacher wrapper
            (params already initialized).
        settings: parsed ``flow_cache`` config group.
        cache_dir: resolved on-disk cache directory (None degrades
            'auto' to producer-only).
    """

    def __init__(self, flow_net_wrapper, settings=None, cache_dir=None):
        self.wrapper = flow_net_wrapper
        self.settings = settings or flow_cache_settings({})
        self.requested_mode = str(self.settings.mode)
        mode = str(self.settings.mode)
        if mode == "auto":
            mode = "disk" if cache_dir else "producer"
        if mode == "disk" and not cache_dir:
            logger.warning("flow_cache.mode=disk but no cache dir "
                           "resolves (set flow_cache.dir or logdir); "
                           "falling back to producer mode")
            mode = "producer"
        self.mode = mode
        self.store = (FlowCacheStore(cache_dir, self.settings.store_dtype)
                      if mode == "disk" else None)
        self.teacher = teacher_id(getattr(flow_net_wrapper, "weights_path",
                                          None))
        self._stats_lock = threading.Lock()
        self._stats = {}
        # per-pair hit/miss accounting across BOTH halves of the disk
        # path (dataset-side loads count as hits, producer recomputes as
        # misses) — the number flow_cache/hit_rate reports
        self.pair_hits = 0
        self.pair_misses = 0

    def hit_rate(self):
        total = self.pair_hits + self.pair_misses
        return (self.pair_hits / total) if total else 0.0

    # ------------------------------------------------------ observability

    def _record_stat(self, name, value):
        with self._stats_lock:
            self._stats.setdefault(name, []).append(float(value))

    def drain_stats(self):
        """Pop accumulated {meter_name: [values]} — plain host floats
        (the DevicePrefetcher ``drain_stats`` contract)."""
        with self._stats_lock:
            out, self._stats = self._stats, {}
        return out

    # ----------------------------------------------------------- teacher

    def _teacher_pairs(self, im_a, im_b):
        """Run the jitted teacher on stacked frame pairs; returns host
        float32 (flow, conf). ``im_a`` is the target frame, ``im_b``
        the previous frame (the FlowLoss._gt order)."""
        flow, conf = self.wrapper._jit_flow(
            self.wrapper.params, np.asarray(im_a, np.float32),
            np.asarray(im_b, np.float32))
        return (np.asarray(flow, np.float32),
                np.asarray(conf, np.float32))

    # ------------------------------------------------------------ attach

    def attach(self, batch):
        """Attach ``flow_gt`` (B, T-1, H, W, 2) and ``conf_gt``
        (B, T-1, H, W, 1) to a video batch, consuming any per-sample
        ``_flow_cache`` payloads the dataset prepared. ``flow_gt[:, t-1]``
        supervises frame ``t`` against frame ``t-1``. Non-video batches
        (or T < 2) pass through untouched."""
        if not isinstance(batch, dict):
            return batch
        images = batch.get("images")
        metas = batch.pop("_flow_cache", None)
        if images is None or getattr(images, "ndim", 0) != 5 \
                or images.shape[1] < 2 or "flow_gt" in batch:
            return batch
        from imaginaire_tpu import telemetry

        t0 = time.perf_counter()
        with telemetry.span("flow_teacher"):
            if isinstance(metas, (list, tuple)) \
                    and len(metas) == images.shape[0] \
                    and all(isinstance(m, dict) for m in metas):
                flow, conf = self._attach_from_meta(metas, images)
            else:
                flow, conf = self._attach_from_content(images)
        compute_ms = (time.perf_counter() - t0) * 1e3
        batch["flow_gt"] = flow
        batch["conf_gt"] = conf
        self._record_stat("flow_cache/compute_ms", compute_ms)
        n_pairs = images.shape[0] * (images.shape[1] - 1)
        self._record_stat("flow_cache/pairs", n_pairs)
        tm = telemetry.get()
        if tm.enabled:
            tm.counter("flow_cache/compute_ms", compute_ms)
            if self.mode == "disk":
                tm.counter("flow_cache/hit_rate", self.hit_rate())
        if self.mode == "disk":
            self._record_stat("flow_cache/hit_rate", self.hit_rate())
        return batch

    def _attach_from_content(self, images):
        """No dataset metadata: compute on the augmented frames
        directly (identical inputs to the in-graph teacher), with a
        whole-batch content-hash disk key so static batches (benches,
        deterministic-augmentation epochs) still hit."""
        images = np.asarray(images)
        b, t = images.shape[:2]
        n_pairs = b * (t - 1)
        key = None
        # whole-batch content keys only persist under an EXPLICIT disk
        # mode: randomly-augmented batches without dataset metadata
        # would otherwise write a never-hit shard per batch forever
        # (mode 'auto' still serves the canonical per-sample path)
        if self.store is not None and self.requested_mode == "disk":
            key = content_key(images, self.teacher)
            cached = self.store.get(key)
            if cached is not None:
                self.pair_hits += n_pairs
                return cached
        self.pair_misses += n_pairs
        im_a = images[:, 1:].reshape((-1,) + images.shape[2:])
        im_b = images[:, :-1].reshape((-1,) + images.shape[2:])
        flow, conf = self._teacher_pairs(im_a, im_b)
        flow = flow.reshape((b, t - 1) + flow.shape[1:])
        conf = conf.reshape((b, t - 1) + conf.shape[1:])
        if key is not None:
            self.store.put(key, flow, conf)
        return flow, conf

    def _attach_from_meta(self, metas, images):
        """Canonical-resolution path: per-sample payloads carry either
        disk-cached canonical (flow, conf) (dataset-side hit) or the
        canonical source frames (miss). Misses are batched per
        canonical shape, computed once, written back to the store, and
        every sample's canonical flow is transformed equivariantly to
        its augmentation draw."""
        images = np.asarray(images)
        b, t = images.shape[:2]
        hw = images.shape[2:4]
        per_sample = [None] * b
        pending = {}  # canonical shape -> [(sample_idx, meta)]
        for i, meta in enumerate(metas):
            if meta.get("flow") is not None:
                self.pair_hits += t - 1
                per_sample[i] = (meta["flow"], meta["conf"])
            elif meta.get("src") is not None:
                self.pair_misses += t - 1
                src = np.asarray(meta["src"], np.float32)
                pending.setdefault(src.shape, []).append((i, meta))
            else:
                # unsupported augmentation for the canonical path:
                # compute on this sample's augmented frames directly
                self.pair_misses += t - 1
                flow, conf = self._teacher_pairs(images[i, 1:],
                                                 images[i, :-1])
                per_sample[i] = (flow, conf)
        for _, group in pending.items():
            srcs = np.stack([np.asarray(m["src"], np.float32)
                             for _, m in group])  # (G, T, Hc, Wc, 3)
            g, tt = srcs.shape[:2]
            im_a = srcs[:, 1:].reshape((-1,) + srcs.shape[2:])
            im_b = srcs[:, :-1].reshape((-1,) + srcs.shape[2:])
            flow, conf = self._teacher_pairs(im_a, im_b)
            flow = flow.reshape((g, tt - 1) + flow.shape[1:])
            conf = conf.reshape((g, tt - 1) + conf.shape[1:])
            for j, (i, meta) in enumerate(group):
                if self.store is not None:
                    keys = meta.get("keys") or []
                    for p, key in enumerate(keys):
                        self.store.put(key, flow[j, p], conf[j, p])
                per_sample[i] = (flow[j], conf[j])
        flows, confs = [], []
        for i, meta in enumerate(metas):
            flow_i, conf_i = per_sample[i]
            record = meta.get("record") or {}
            if meta.get("flow") is not None or meta.get("src") is not None:
                # canonical-resolution entries carry the augmentation
                # still to apply (hit or freshly computed alike)
                flow_i, conf_i = transform_flow(flow_i, conf_i, record)
            if flow_i.shape[1:3] != tuple(hw):
                # transform/record mismatch — never train on misaligned
                # supervision; recompute from the augmented frames
                logger.warning(
                    "flow cache: transformed flow %s does not match the "
                    "augmented batch %s; recomputing sample %d in-place",
                    flow_i.shape, hw, i)
                flow_i, conf_i = self._teacher_pairs(images[i, 1:],
                                                     images[i, :-1])
            flows.append(flow_i)
            confs.append(conf_i)
        return np.stack(flows), np.stack(confs)


class DatasetFlowCacheHook:
    """Dataset-side half of the disk path, owned by video datasets.

    On every training item it builds the per-sample ``_flow_cache``
    payload: the augmentation record, the per-pair cache keys, and —
    on a store hit — the canonical ``(flow, conf)`` loaded in the
    loader worker thread (parallel IO, zero teacher cost), or — on a
    miss — the canonical source frames for the producer-thread teacher.
    The payload rides the batch as a host-side ('_'-prefixed) entry and
    is consumed by ``TeacherFlowCache.attach``.
    """

    def __init__(self, cfg, dataset_name, image_type, normalize,
                 weights_path=None):
        from imaginaire_tpu.flow.flow_net import DEFAULT_WEIGHTS

        # mirror the FlowNet wrapper's default so dataset-side keys
        # match the producer-side writes
        weights_path = weights_path or DEFAULT_WEIGHTS
        self.settings = flow_cache_settings(cfg)
        cache_dir = resolve_cache_dir(cfg)
        self.active = (self.settings.enabled
                       and self.settings.mode in ("auto", "disk")
                       and cache_dir is not None)
        self.store = (FlowCacheStore(cache_dir, self.settings.store_dtype)
                      if self.active else None)
        self.image_type = image_type
        self.normalize = bool(normalize)
        self.dataset_name = dataset_name
        self.teacher = teacher_id(weights_path)

    def _canonical_src(self, canonical_frames):
        """Stack captured canonical frames to (T, Hc, Wc, 3) float32 in
        the teacher's input range (mirrors process_item's normalize)."""
        frames = []
        for f in canonical_frames:
            arr = np.asarray(f)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            was_uint8 = arr.dtype == np.uint8
            arr = arr.astype(np.float32)
            if was_uint8:
                arr = arr / 255.0
            if self.normalize:
                arr = arr * 2.0 - 1.0
            frames.append(arr)
        return np.stack(frames, axis=0)

    def attach_item(self, out, root_idx, seq, stems, record, canonical):
        """Attach the per-item payload to dataset item ``out``."""
        if not self.active or len(stems) < 2:
            return out
        if not record or not record.get("canonical_ok") \
                or canonical is None:
            out["_flow_cache"] = {"record": dict(record or {})}
            return out
        hw = record["canonical_hw"]
        keys = [pair_key(self.dataset_name, root_idx, seq, stems[p + 1],
                         stems[p], hw, self.teacher)
                for p in range(len(stems) - 1)]
        cached = [self.store.get(k) if self.store.has(k) else None
                  for k in keys]
        payload = {"record": dict(record), "keys": keys}
        if all(c is not None for c in cached):
            payload["flow"] = np.stack([c[0] for c in cached])
            payload["conf"] = np.stack([c[1] for c in cached])
        else:
            # some pairs hit, some missed: recompute the whole window
            # (the producer batches per-sample anyway; partial reuse
            # would complicate the payload for a one-epoch transient)
            payload["src"] = self._canonical_src(canonical)
        out["_flow_cache"] = payload
        return out

"""Frozen FlowNet2 wrapper producing (flow, confidence)
(ref: imaginaire/third_party/flow_net/flow_net.py:17-94).

Resizes inputs to a /64 grid, runs the cascade, and derives a
confidence map from the warp error (||im1 - warp(im2, flow)||² < 0.02).
Weights load from a converted torch checkpoint
(scripts/convert_weights.py --flownet2); absent weights raise unless
``allow_random_init`` (tests only — vid2vid's fork semantics train
without a flow teacher, so this wrapper is optional at train time).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from imaginaire_tpu.flow.flownet2 import FlowNet2
from imaginaire_tpu.model_utils.fs_vid2vid import resample

DEFAULT_WEIGHTS = os.path.join(os.path.dirname(__file__), "weights",
                               "flownet2.npz")


def _sq_norm(t):
    return jnp.sum(t * t, axis=-1, keepdims=True)


class FlowNet:
    def __init__(self, weights_path=None, allow_random_init=False,
                 rgb_max=1.0):
        self.model = FlowNet2(rgb_max=rgb_max)
        self.params = None
        self.weights_path = weights_path or DEFAULT_WEIGHTS
        self.allow_random_init = allow_random_init
        # the teacher compiles through the ledger (it runs in the
        # prefetcher producer thread under flow_cache — a watchdog dump
        # during its multi-minute cold compile should say so);
        # allow_shape_growth: one executable per input resolution is by
        # design, not a recompile storm
        from imaginaire_tpu.telemetry import xla_obs

        self._jit_flow = xla_obs.compiled_program(
            "flow_teacher", self._flow_fn, allow_shape_growth=True)

    def init_params(self, key, image_shape=(1, 64, 64, 3)):
        if os.path.exists(self.weights_path):
            self.params = load_flownet2_npz(self.weights_path)
        elif self.allow_random_init:
            # param shapes are resolution-independent; init on the /64
            # grid the forward always resizes to
            self.params = self.model.init(
                key, jnp.zeros((1, 2, 64, 64, 3)))["params"]
        else:
            raise FileNotFoundError(
                f"FlowNet2 weights not found at {self.weights_path}; run "
                "scripts/convert_weights.py --flownet2 <ckpt> or pass "
                "allow_random_init=True (tests only)")
        return self.params

    def _flow_fn(self, params, im1, im2):
        """(ref: flow_net.py:54-91)."""
        b, old_h, old_w, _ = im1.shape
        new_h, new_w = old_h // 64 * 64, old_w // 64 * 64
        if (new_h, new_w) != (old_h, old_w):
            im1_r = jax.image.resize(im1, (b, new_h, new_w, 3), "bilinear")
            im2_r = jax.image.resize(im2, (b, new_h, new_w, 3), "bilinear")
        else:
            im1_r, im2_r = im1, im2
        data = jnp.stack([im1_r, im2_r], axis=1)
        flow = self.model.apply({"params": params}, data, training=False)
        conf = (_sq_norm(im1_r - resample(im2_r, flow)) < 0.02).astype(
            jnp.float32)
        if (new_h, new_w) != (old_h, old_w):
            flow = jax.image.resize(flow, (b, old_h, old_w, 2), "bilinear")
            # per-axis rescale of the pixel-unit components (the reference
            # scales both by old_h/new_h — a bug for non-uniform resizes,
            # flow_net.py:86-88; flow[...,0] is x, [...,1] is y)
            flow = flow * jnp.asarray([old_w / new_w, old_h / new_h],
                                      flow.dtype)
            conf = jax.image.resize(conf, (b, old_h, old_w, 1), "bilinear")
        return flow, conf

    def __call__(self, input_a, input_b):
        """Accepts (B,H,W,3), (B,N,H,W,3) or (B,T,N,H,W,3) pairs
        (ref: flow_net.py:35-52)."""
        if self.params is None:
            self.init_params(jax.random.PRNGKey(0), input_a.shape[-4:])
        shape = input_a.shape
        if input_a.ndim >= 5:
            flat_a = input_a.reshape((-1,) + shape[-3:])
            flat_b = input_b.reshape(flat_a.shape)
            flow, conf = self._jit_flow(self.params, flat_a, flat_b)
            lead = shape[:-3]
            return (flow.reshape(lead + flow.shape[1:]),
                    conf.reshape(lead + conf.shape[1:]))
        return self._jit_flow(self.params, input_a, input_b)


def load_flownet2_npz(path):
    """Load a converted checkpoint into the Flax param tree."""
    flat = dict(np.load(path))
    params = {}
    for key, value in flat.items():
        node = params
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(value)
    return params

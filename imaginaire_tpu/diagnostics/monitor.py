"""Host-side health monitor: consumes the in-jit health summaries the
step programs return, emits telemetry counters at the audit cadence,
tracks GAN balance, and drives the non-finite response policy.

Sync discipline (the PR 2 contract — no per-step device fences): each
``observe`` call only *stores* the freshly dispatched step's outputs and
polls the PREVIOUS entry's finite/audited flags. By the time the poll
runs, the next program is already queued behind the previous one, so the
two-scalar ``device_get`` never stalls the dispatch pipeline; it merely
caps host run-ahead at one program. Full health summaries (and the loss
breakdown) are fetched only for entries whose in-graph cadence predicate
fired.

Non-finite policy (``diagnostics.on_nonfinite``):

- ``halt``     — triage, write the report, raise ``NonFiniteLossError``.
- ``skip``     — triage once, count the event, keep running. The step
  programs guard updates in-graph whenever diagnostics are enabled, so
  the skipped step's params/opt/mutables are bit-identical to the last
  finite state — no host-side restore needed.
- ``rollback`` — like skip, but additionally restores the trainer state
  from the last audited-finite snapshot (a device copy taken every
  ``every_n_steps``; costs one extra state-sized buffer — use for runs
  where optimizer moments degrade before the loss goes non-finite).
"""

from __future__ import annotations

import logging
from collections import deque

import jax
import jax.numpy as jnp

from imaginaire_tpu.config import cfg_get

logger = logging.getLogger(__name__)

_POLICIES = ("halt", "skip", "rollback")
# health keys that are per-step control flags, not audit metrics
_CONTROL_KEYS = ("finite", "audited", "rng_step")


class NonFiniteLossError(RuntimeError):
    """Raised by ``on_nonfinite: halt`` after the triage report lands."""


def diagnostics_settings(cfg):
    """Parse the ``diagnostics`` config section (see config.py defaults)."""
    dcfg = cfg_get(cfg or {}, "diagnostics", None) or {}
    policy = str(cfg_get(dcfg, "on_nonfinite", "halt")).lower()
    if policy not in _POLICIES:
        logger.warning("unknown diagnostics.on_nonfinite=%r; using 'halt' "
                       "(supported: %s)", policy, "/".join(_POLICIES))
        policy = "halt"
    return {
        "enabled": bool(cfg_get(dcfg, "enabled", True)),
        "every_n_steps": max(int(cfg_get(dcfg, "every_n_steps", 10)), 1),
        "on_nonfinite": policy,
        "history": max(int(cfg_get(dcfg, "history", 64)), 1),
        "dg_ratio_beta": float(cfg_get(dcfg, "dg_ratio_beta", 0.9)),
        "dg_ratio_warn_low": float(cfg_get(dcfg, "dg_ratio_warn_low", 0.1)),
        "dg_ratio_warn_high": float(cfg_get(dcfg, "dg_ratio_warn_high",
                                            10.0)),
        "max_triage_terms": int(cfg_get(dcfg, "max_triage_terms", 16)),
    }


class HealthMonitor:
    def __init__(self, cfg):
        self.cfg = cfg
        s = diagnostics_settings(cfg)
        self.enabled = s["enabled"]
        self.every_n = s["every_n_steps"]
        self.on_nonfinite = s["on_nonfinite"]
        self.dg_beta = s["dg_ratio_beta"]
        self.warn_low = s["dg_ratio_warn_low"]
        self.warn_high = s["dg_ratio_warn_high"]
        self.max_triage_terms = s["max_triage_terms"]
        self.history = deque(maxlen=s["history"])
        self.dg_ratio_ewma = None
        self.dg_breaches = 0
        self._in_breach = False
        self.skip_count = 0
        self.nonfinite_events = 0
        self.last_report_path = None
        self._prev = None
        self._last_gan = {}
        self._snapshot = None
        self._snapshot_step = None
        self._triaged = False

    # --------------------------------------------------------- run state

    def state_dict(self):
        """JSON-serializable monitor state for the checkpoint's runstate
        sidecar (resilience/, ISSUE 7): a resumed run keeps its GAN
        balance EWMA, breach counts and health history instead of
        silently restarting them."""
        return {
            "dg_ratio_ewma": self.dg_ratio_ewma,
            "dg_breaches": int(self.dg_breaches),
            "in_breach": bool(self._in_breach),
            "skip_count": int(self.skip_count),
            "nonfinite_events": int(self.nonfinite_events),
            "last_gan": dict(self._last_gan),
            "history": list(self.history),
        }

    def load_state_dict(self, state):
        """Restore ``state_dict`` output (missing keys keep defaults —
        old sidecars stay loadable)."""
        if not state:
            return
        if state.get("dg_ratio_ewma") is not None:
            self.dg_ratio_ewma = float(state["dg_ratio_ewma"])
        self.dg_breaches = int(state.get("dg_breaches",
                                         self.dg_breaches))
        self._in_breach = bool(state.get("in_breach", self._in_breach))
        self.skip_count = int(state.get("skip_count", self.skip_count))
        self.nonfinite_events = int(state.get("nonfinite_events",
                                              self.nonfinite_events))
        self._last_gan = {str(k): float(v) for k, v in
                          (state.get("last_gan") or {}).items()}
        history = state.get("history")
        if history:
            self.history.clear()
            self.history.extend(history)

    # ------------------------------------------------------------ intake

    def observe(self, trainer, kind, losses, health, data, step):
        """Record one dispatched step ('G' or 'D') and poll the previous
        one. ``health`` is the step program's summary dict ({} when
        diagnostics are off — then this is a no-op)."""
        if not self.enabled or not health:
            return
        prev, self._prev = self._prev, {
            "kind": kind, "step": step, "losses": losses,
            "health": health, "data": data,
        }
        if prev is not None:
            self._check(trainer, prev)

    def drain(self, trainer):
        """Process the final pending entry (end of epoch / end of run /
        tests) — blocks on that step's completion, so never call it from
        the per-step hot path."""
        if self._prev is None:
            return
        prev, self._prev = self._prev, None
        self._check(trainer, prev)

    # --------------------------------------------------------- processing

    def _check(self, trainer, entry):
        h = entry["health"]
        # lint: allow(host-sync) -- reads the PREVIOUS step's flags, one step behind the dispatch frontier
        finite, audited = (bool(x) for x in jax.device_get(
            (h["finite"], h["audited"])))
        if audited:
            self._ingest(entry, finite=finite)
            if finite and self.on_nonfinite == "rollback":
                self._take_snapshot(trainer, entry["step"])
        if not finite:
            self._handle_nonfinite(trainer, entry)
        entry["data"] = None  # release the batch reference

    def _ingest(self, entry, finite=True):
        """Fetch and emit one audited entry's health + loss breakdown.
        Both programs have completed by now, so the ``device_get`` is a
        pure transfer."""
        from imaginaire_tpu import telemetry

        kind, step = entry["kind"], entry["step"]
        metrics = {k: v for k, v in entry["health"].items()
                   if k not in _CONTROL_KEYS}
        health = {k: float(v) for k, v in
                  # lint: allow(host-sync) -- completed-step transfer
                  jax.device_get(metrics).items()}
        lvals = {k: float(v) for k, v in
                 # lint: allow(host-sync) -- completed-step transfer
                 jax.device_get(dict(entry["losses"])).items()}
        tm = telemetry.get()
        for name, value in health.items():
            tm.counter(f"health/{kind}/{name}", value, step=step)
        if kind == "D":
            for key, ctr in (("D_real_acc", "health/D/real_acc"),
                             ("D_fake_acc", "health/D/fake_acc")):
                if key in lvals:
                    tm.counter(ctr, lvals[key], step=step)
        self.history.append({"step": step, "kind": kind, "finite": finite,
                             "health": health, "losses": lvals})
        # pod divergence sentinel intake (podview.py, ISSUE 17): these
        # are already host floats — podview adds no device syncs
        from imaginaire_tpu.telemetry import podview

        podview.get().note_losses(step, kind, lvals)
        self._update_balance(kind, step, lvals)

    def _update_balance(self, kind, step, lvals):
        """D/G GAN-loss ratio EWMA + threshold warnings."""
        from imaginaire_tpu import telemetry

        gan = lvals.get("GAN", lvals.get("gan", lvals.get("total")))
        if gan is None:
            return
        self._last_gan[kind] = gan
        if "G" not in self._last_gan or "D" not in self._last_gan:
            return
        d, g = self._last_gan["D"], self._last_gan["G"]
        ratio = abs(d) / (abs(g) + 1e-12)
        self.dg_ratio_ewma = (ratio if self.dg_ratio_ewma is None
                              else self.dg_beta * self.dg_ratio_ewma
                              + (1.0 - self.dg_beta) * ratio)
        tm = telemetry.get()
        tm.counter("health/dg_loss_ratio", ratio, step=step)
        tm.counter("health/dg_loss_ratio_ewma", self.dg_ratio_ewma,
                   step=step)
        breached = not (self.warn_low <= self.dg_ratio_ewma
                        <= self.warn_high)
        if breached:
            self.dg_breaches += 1
            tm.counter("health/dg_ratio_breach", self.dg_ratio_ewma,
                       step=step)
            if not self._in_breach:
                # warn once per excursion, not once per audit step —
                # the breach counter still counts every audited breach
                tm.meta("dg_ratio_breach", step=step,
                        ewma=self.dg_ratio_ewma, low=self.warn_low,
                        high=self.warn_high)
                logger.warning(
                    "D/G loss-ratio EWMA %.4g outside [%g, %g] at step "
                    "%s — the discriminator/generator balance is off "
                    "(diagnostics.dg_ratio_warn_{low,high})",
                    self.dg_ratio_ewma, self.warn_low, self.warn_high,
                    step)
        self._in_breach = breached

    def _take_snapshot(self, trainer, step):
        if trainer.state is None:
            return
        self._snapshot = jax.tree_util.tree_map(jnp.copy, trainer.state)
        self._snapshot_step = step

    # -------------------------------------------------------- non-finite

    def _handle_nonfinite(self, trainer, entry):
        from imaginaire_tpu import telemetry

        kind, step = entry["kind"], entry["step"]
        tm = telemetry.get()
        self.nonfinite_events += 1
        tm.counter("health/nonfinite_events", self.nonfinite_events,
                   step=step)
        if self.on_nonfinite in ("skip", "rollback"):
            self.skip_count += 1
            tm.counter("health/nonfinite_skipped", self.skip_count,
                       step=step)
        report = None
        if not self._triaged:
            # one-shot eager triage: localize the term/module, dump the
            # report. Later events only bump the counters (the first
            # report already names the provenance; re-running an eager
            # backward per event would stall the run it's meant to save).
            self._triaged = True
            from imaginaire_tpu.diagnostics.triage import (
                run_triage,
                write_report,
            )

            try:
                report = run_triage(trainer, self, entry)
                self.last_report_path = write_report(
                    cfg_get(self.cfg, "logdir", "."), report)
            except Exception:  # noqa: BLE001 — triage must not mask the event
                logger.exception("non-finite triage pass failed")
            tm.meta("nonfinite", step=step, update=kind,
                    report=self.last_report_path,
                    culprit_terms=(report or {}).get("culprit_terms"),
                    culprit_modules=(report or {}).get("culprit_modules"),
                    action=self.on_nonfinite)
            logger.error(
                "non-finite %s update at step %s — culprit terms %s, "
                "modules %s; report: %s; action=%s", kind, step,
                (report or {}).get("culprit_terms"),
                (report or {}).get("culprit_modules"),
                self.last_report_path, self.on_nonfinite)
        if self.on_nonfinite == "halt":
            raise NonFiniteLossError(
                f"non-finite {kind} update at step {step} "
                f"(culprit terms {(report or {}).get('culprit_terms')}, "
                f"modules {(report or {}).get('culprit_modules')}); "
                f"report: {self.last_report_path}. Set "
                "diagnostics.on_nonfinite: skip|rollback to keep running, "
                "or retry under `train.py --debug-nans` on CPU to trap "
                "the op.")
        if self.on_nonfinite == "rollback" and self._snapshot is not None:
            # restore a COPY: the restored buffers get donated to the
            # next step, which would otherwise invalidate the snapshot
            trainer.state = jax.tree_util.tree_map(jnp.copy,
                                                   self._snapshot)
            logger.warning(
                "rolled back trainer state to the last audited-finite "
                "snapshot (step %s)", self._snapshot_step)

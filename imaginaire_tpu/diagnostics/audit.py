"""In-jit health auditing primitives.

Everything here is traced into the D/G step programs. Two design
constraints drive the shapes:

- **recompile-free**: the health summary is a flat ``{str: f32 scalar}``
  dict whose key set depends only on the (static) parameter structure,
  and the cadence gate is a ``lax.cond`` on the traced step counter —
  one program covers both the audited and the skipped step, so
  ``diagnostics.every_n_steps`` never retraces.
- **donation-safe**: the non-finite guard (``select_finite``) reads the
  donated input buffers and selects between old and new values; XLA
  aliases the output onto the donated input either way, so guarded steps
  cost one fused select pass over the updated trees, not extra memory.
"""

from __future__ import annotations

from collections.abc import Mapping

import jax
import jax.numpy as jnp
import optax

from imaginaire_tpu.analysis import islands


def tree_norm(tree):
    """Global L2 norm of a pytree, accumulated in fp32 — the
    ``loss_accumulation`` island (analysis/islands.py). Leaves are
    upcast BEFORE the sum-of-squares — casting the finished norm would
    let a bf16 tree accumulate (and overflow/round) in bf16 first."""
    tree32 = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x).astype(jnp.float32), tree)
    with islands.scope("loss_accumulation"):
        norm = optax.global_norm(tree32)
        islands.guard("loss_accumulation", norm=norm)
    return norm


def finite_flag(total_loss, grad_norm):
    """Bool scalar: this step's loss AND gradients are finite. A single
    NaN/Inf anywhere in the grads poisons the global norm, so one
    reduction covers the whole tree."""
    return jnp.isfinite(total_loss) & jnp.isfinite(grad_norm)


def select_finite(ok, new, old):
    """Elementwise ``new if ok else old`` over matching pytrees — the
    in-graph non-finite update guard."""
    return jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o),
                                  new, old)


# ------------------------------------------------------------- sigmas

def estimate_sigma_list(params, spectral, eps=1e-12):
    """Read-only spectral-norm sigma estimates ``u^T W v`` for every
    spectrally-normalized kernel (same matrix view as
    ``layers/weight_norm.py``; the stored power-iteration ``u`` is NOT
    advanced). Returns a list of scalars in a deterministic walk order.
    """
    from imaginaire_tpu.layers.weight_norm import estimate_sigma

    sigmas = []

    def walk(spec, par):
        if not isinstance(spec, Mapping):
            return
        u = spec.get("u")
        if u is not None and not isinstance(u, Mapping):
            kernel = par.get("kernel") if isinstance(par, Mapping) else None
            if kernel is not None:
                sigmas.append(estimate_sigma(kernel, u, eps=eps))
        for key in sorted(spec):
            child = spec[key]
            if isinstance(child, Mapping):
                walk(child,
                     par.get(key, {}) if isinstance(par, Mapping) else {})

    walk(spectral or {}, params or {})
    return sigmas


# ------------------------------------------------------- health summary

def _module_items(tree):
    """Deterministic (name, subtree) pairs for the top-level modules of
    a params dict; non-Mapping leaves at the root get their own entry."""
    if not isinstance(tree, Mapping):
        return [("_root", tree)]
    return [(str(k), tree[k]) for k in sorted(tree, key=str)]


def health_keys(params, spectral=None, ema=None):
    """The static key set ``module_health`` will emit for these trees —
    used to build the zero-filled off-cadence branch of the cond."""
    keys = []
    for stat in ("grad_norm", "param_norm", "update_ratio"):
        keys.append(f"{stat}/_total")
        keys.extend(f"{stat}/{name}" for name, _ in _module_items(params))
    if spectral is not None and jax.tree_util.tree_leaves(spectral):
        keys.extend(("sn_sigma/mean", "sn_sigma/max"))
    if ema is not None:
        keys.append("ema_drift")
    return keys


def module_health(grads, params, updates, spectral=None, ema=None,
                  grad_norm_total=None, eps=1e-12):
    """The fixed-size health summary: per-top-level-module gradient
    norm, parameter norm and update/param ratio, plus spectral-sigma
    stats and EMA drift when those trees exist.

    ``ema_drift`` is ``||ema - params|| / ||params||``; with
    ``model_average_remove_sn`` the EMA copy stores sigma-collapsed
    kernels, so the drift carries a constant SN-collapse offset — the
    *trend* is the signal, not the absolute level.
    """
    h = {}
    pnorm_total = tree_norm(params)
    h["grad_norm/_total"] = (grad_norm_total if grad_norm_total is not None
                             else tree_norm(grads))
    h["param_norm/_total"] = pnorm_total
    h["update_ratio/_total"] = tree_norm(updates) / (pnorm_total + eps)
    grads_m = dict(_module_items(grads))
    updates_m = dict(_module_items(updates))
    for name, sub_p in _module_items(params):
        pn = tree_norm(sub_p)
        h[f"grad_norm/{name}"] = tree_norm(grads_m.get(name, ()))
        h[f"param_norm/{name}"] = pn
        h[f"update_ratio/{name}"] = \
            tree_norm(updates_m.get(name, ())) / (pn + eps)
    if spectral is not None and jax.tree_util.tree_leaves(spectral):
        sigmas = estimate_sigma_list(params, spectral, eps=eps)
        if sigmas:
            stack = jnp.stack([s.astype(jnp.float32) for s in sigmas])
            h["sn_sigma/mean"] = jnp.mean(stack)
            h["sn_sigma/max"] = jnp.max(stack)
        else:  # spectral collection present but no kernel pairs resolved
            h["sn_sigma/mean"] = jnp.zeros((), jnp.float32)
            h["sn_sigma/max"] = jnp.zeros((), jnp.float32)
    if ema is not None:
        diff = jax.tree_util.tree_map(lambda e, p: e - p, ema, params)
        h["ema_drift"] = tree_norm(diff) / (pnorm_total + eps)
    return {k: v.astype(jnp.float32) for k, v in h.items()}


def health_at_cadence(pred, grads, params, updates, spectral=None,
                      ema=None, grad_norm_total=None):
    """``module_health`` under ``lax.cond(pred, ...)``: off-cadence steps
    return the same fixed-size dict filled with zeros, so the norm
    reductions only execute when the audit is due and the program never
    retraces on the cadence."""
    keys = health_keys(params, spectral=spectral, ema=ema)

    def full():
        h = module_health(grads, params, updates, spectral=spectral,
                          ema=ema, grad_norm_total=grad_norm_total)
        assert sorted(h) == sorted(keys), (sorted(h), sorted(keys))
        return {k: h[k] for k in keys}

    def zeros():
        return {k: jnp.zeros((), jnp.float32) for k in keys}

    return jax.lax.cond(pred, full, zeros)

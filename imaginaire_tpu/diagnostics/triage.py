"""Non-finite provenance triage: the one-shot eager pass that runs when
a step's loss or gradients go non-finite.

The jitted step only tells us *that* the fused update exploded; this
pass re-runs the offending forward EAGERLY (no jit — a triage compile
would cost minutes on TPU and could itself fail) with the exact batch
and a reconstructed per-step RNG, and localizes the culprit:

1. every loss term is re-evaluated separately — a NaN that originates in
   the forward (a bad batch, an exploding activation) names its term
   directly;
2. the total's gradient is decomposed into per-top-level-module norms —
   a NaN that only appears in the backward (sqrt-at-zero, overflow in a
   VJP) names the module it enters through;
3. when the terms all evaluate finite but grads are non-finite, each
   registered term's gradient is re-derived separately (bounded by
   ``diagnostics.max_triage_terms``) so backward-only NaNs still name
   their term.

The report also carries per-input batch statistics (min/max/mean/
non-finite counts) and the last-K health summaries from the monitor's
ring buffer, then lands at ``logs/<run>/nonfinite_report.json``.

Faithfulness caveat: detection lags the bad step by one program, so the
re-run uses the trainer's *current* params. With diagnostics enabled the
step programs guard updates in-graph (a non-finite update never lands),
so params are the last finite values — at most one additional finite
update past the state the bad step saw.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time

import jax
import numpy as np
import optax

logger = logging.getLogger(__name__)


def _finite(x):
    return isinstance(x, (int, float)) and math.isfinite(x)


def _float(x):
    try:
        # lint: allow(host-sync) -- post-mortem triage, training halted
        return float(jax.device_get(x))
    except Exception:  # noqa: BLE001 — a fetch failure must not kill triage
        return float("nan")


def _triage_rng(trainer, entry):
    """Reconstruct the per-step RNG key the bad program folded in
    (``health['rng_step']`` recorded the pre-increment counter)."""
    stream = trainer.state["rng_G" if entry["kind"] == "G" else "rng_D"]
    # lint: allow(host-sync) -- post-mortem triage, training halted
    rng_step = int(jax.device_get(entry["health"]["rng_step"]))
    return jax.random.fold_in(stream, rng_step), rng_step


def _eval_losses(trainer, kind, data, rng, params=None):
    """Eagerly re-run gen_forward/dis_forward with the trainer's current
    state (optionally overriding the updated net's params) and return
    the raw loss dict (device scalars)."""
    st = trainer.state
    cd = trainer._to_compute_dtype
    cv = trainer._cast_net_vars  # params-only: fp32 islands keep dtype
    if kind == "D":
        vars_D = dict(st["vars_D"],
                      params=cd(params if params is not None
                                else st["vars_D"]["params"]))
        out = trainer.dis_forward(cv(st["vars_G"]), vars_D,
                                  st["loss_params"], cd(data), rng)
    else:
        vars_G = dict(st["vars_G"],
                      params=cd(params if params is not None
                                else st["vars_G"]["params"]))
        out = trainer.gen_forward(vars_G, cv(st.get("vars_D")),
                                  st["loss_params"], cd(data), rng)
    return out[0]  # (losses, new_mut[, extra]) across trainer families


def _module_grad_norms(trainer, kind, data, rng, term=None):
    """Per-top-level-module gradient norms of one term (or the weighted
    total) — eager ``jax.grad``, float results."""
    import jax.numpy as jnp

    from imaginaire_tpu.diagnostics.audit import _module_items

    pkey = "vars_G" if kind == "G" else "vars_D"
    params0 = trainer.state[pkey]["params"]

    def loss_fn(params):
        losses = _eval_losses(trainer, kind, data, rng, params=params)
        losses = {k: v.astype(jnp.float32) for k, v in losses.items()}
        if term is not None:
            return losses[term]
        return trainer._total(losses)

    grads = jax.grad(loss_fn)(params0)
    out = {"_total": _float(optax.global_norm(grads))}
    for name, sub in _module_items(grads):
        out[name] = _float(optax.global_norm(sub))
    return out


def batch_stats(data):
    """Per-input statistics: shape, dtype, min/max/mean over finite
    values, and the non-finite element count — the "was it the data?"
    column of the report."""
    stats = {}
    try:
        flat = jax.tree_util.tree_flatten_with_path(data)[0]
    except Exception:  # noqa: BLE001
        return stats
    for path, leaf in flat:
        if not hasattr(leaf, "shape") or not hasattr(leaf, "dtype"):
            continue
        name = jax.tree_util.keystr(path)
        try:
            # lint: allow(host-sync) -- post-mortem dump, training halted
            arr = np.asarray(jax.device_get(leaf))
        except Exception:  # noqa: BLE001
            continue
        entry = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
        if arr.size and arr.dtype.kind in "fiu":
            arrf = arr.astype(np.float64)
            finite = np.isfinite(arrf)
            n_bad = int(arrf.size - finite.sum())
            entry["nonfinite"] = n_bad
            if finite.any():
                vals = arrf[finite]
                entry.update(min=float(vals.min()), max=float(vals.max()),
                             mean=float(vals.mean()))
        stats[name] = entry
    return stats


def run_triage(trainer, monitor, entry):
    """Build the provenance report dict for one non-finite step."""
    kind, step, data = entry["kind"], entry["step"], entry["data"]
    t0 = time.time()
    rng, rng_step = _triage_rng(trainer, entry)

    terms = {k: _float(v)
             for k, v in _eval_losses(trainer, kind, data, rng).items()}
    culprit_terms = sorted(k for k, v in terms.items() if not _finite(v))

    module_norms = _module_grad_norms(trainer, kind, data, rng)
    culprit_modules = sorted(k for k, v in module_norms.items()
                             if k != "_total" and not _finite(v))

    per_term_grads = {}
    if not culprit_terms and not _finite(module_norms.get("_total")):
        # forward finite, backward non-finite: re-derive each registered
        # term's gradient separately to name the term it enters through
        candidates = [t for t in terms if t in trainer.weights]
        if len(candidates) <= monitor.max_triage_terms:
            for term in candidates:
                try:
                    norms = _module_grad_norms(trainer, kind, data, rng,
                                               term=term)
                except Exception as e:  # noqa: BLE001
                    norms = {"_error": str(e)}
                per_term_grads[term] = norms
                if any(not _finite(v) for v in norms.values()
                       if isinstance(v, float)):
                    culprit_terms.append(term)
        else:
            logger.warning(
                "triage: %d loss terms exceed "
                "diagnostics.max_triage_terms=%d; skipping the per-term "
                "gradient pass", len(candidates), monitor.max_triage_terms)
    culprit_terms = sorted(set(culprit_terms))

    return {
        "step": step,
        "update": kind,
        "rng_step": rng_step,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "on_nonfinite": monitor.on_nonfinite,
        "loss_terms": terms,
        "culprit_terms": culprit_terms,
        "module_grad_norms": module_norms,
        "culprit_modules": culprit_modules,
        "per_term_grad_norms": per_term_grads or None,
        "batch_stats": batch_stats(data),
        "health_history": list(monitor.history),
        "nonfinite_events": monitor.nonfinite_events,
        "triage_duration_s": round(time.time() - t0, 3),
    }


def write_report(logdir, report):
    """Dump the triage report as ``<logdir>/nonfinite_report.json``."""
    path = os.path.join(logdir or ".", "nonfinite_report.json")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(report, f, indent=1, default=str)
    logger.error("non-finite triage report written to %s", path)
    return path

"""Training-health diagnostics (ISSUE 3): in-step gradient/update
auditing, GAN balance metrics, and non-finite provenance triage.

Three pillars, all riding the PR 2 telemetry sinks:

- **norm auditing** (``audit.py``) — per-top-level-module gradient norm,
  parameter norm and update/param ratio for G and D, EMA drift, and
  spectral-norm sigma tracking, computed *inside* the jitted step
  programs at ``diagnostics.every_n_steps`` cadence via ``lax.cond`` so
  the step programs stay donation-safe and recompile-free (the health
  summary is a fixed-size pytree of fp32 scalars).
- **GAN balance** (``monitor.py``) — per-loss-term breakdown (the loss
  registry already itemizes terms), discriminator real/fake accuracy
  (``losses.gan.dis_accuracy``), and a D/G loss-ratio EWMA with
  configurable warning thresholds surfaced as telemetry counters.
- **non-finite provenance triage** (``triage.py``) — a per-step finite
  flag is computed in-graph and polled with one-step lag (the previous
  program has finished by then, so the poll never stalls dispatch).
  When a loss or grad goes non-finite, a one-shot eager triage pass
  re-evaluates each loss term and each module's grad norm separately,
  dumps ``logs/<run>/nonfinite_report.json``, and halts / skips /
  rolls back per ``diagnostics.on_nonfinite``. With diagnostics enabled
  the step programs additionally *guard* the update in-graph: a
  non-finite update never lands (params/opt/mutables keep their previous
  finite values), so "skip" recovery is exact and triage always sees
  uncorrupted parameters.
"""

from imaginaire_tpu.diagnostics.monitor import (  # noqa: F401
    HealthMonitor,
    NonFiniteLossError,
    diagnostics_settings,
)
from imaginaire_tpu.diagnostics import audit  # noqa: F401

__all__ = [
    "HealthMonitor",
    "NonFiniteLossError",
    "diagnostics_settings",
    "audit",
]

"""Loss library (ref: imaginaire/losses/).

TPU-first design: losses are pure functions over pytrees (no nn.Module
state), so they inline into the jitted train step and fuse with the
surrounding graph. Multi-scale discriminator outputs arrive as lists of
arrays; feature-matching inputs as list-of-list pytrees — both are
Python-level structures, static under jit.
"""

from imaginaire_tpu.losses.gan import dis_accuracy, gan_loss
from imaginaire_tpu.losses.feature_matching import feature_matching_loss
from imaginaire_tpu.losses.kl import gaussian_kl_loss
from imaginaire_tpu.losses.perceptual import PerceptualLoss
from imaginaire_tpu.losses.flow import masked_l1_loss, FlowLoss

__all__ = [
    "gan_loss",
    "dis_accuracy",
    "feature_matching_loss",
    "gaussian_kl_loss",
    "PerceptualLoss",
    "masked_l1_loss",
    "FlowLoss",
]

"""Perceptual loss with Flax feature extractors
(ref: imaginaire/losses/perceptual.py:15-358).

The reference wraps torchvision backbones (VGG19/VGG16/alexnet/...) and
takes weighted L1/L2 distances between named intermediate activations,
optionally over ``num_scales`` 2x-downsampled scales, optionally with
instance-normalized features.

TPU-first: the extractor is a Flax module returning a dict of named
activations; the loss is a pure function of ``(params, inp, target)`` so
it inlines into the jitted train step (the extractor runs in bf16 on the
MXU — the analogue of the reference's fp16 eval mode,
ref: perceptual.py:76-80,110-115). Pretrained torchvision weights are
loaded via :func:`load_torch_vgg_weights` from a ported ``.npz``
(``scripts/convert_weights.py``); ``init_params`` fails loudly when the
file is missing — training against a random-init VGG silently diverges
from the reference. ``allow_random_init=True`` is the explicit escape for
unit tests.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from imaginaire_tpu.utils.misc import apply_imagenet_normalization, downsample_2x

# torchvision `features` configs: numbers are conv widths, 'M' is 2x maxpool.
_VGG19_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M")
_VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M")


def _vgg_relu_names(cfg):
    """Name each conv's relu 'relu_<block>_<idx>' (ref: perceptual.py:176-208)."""
    names, block, idx = [], 1, 1
    for v in cfg:
        if v == "M":
            block += 1
            idx = 1
        else:
            names.append(f"relu_{block}_{idx}")
            idx += 1
    return names


class VGGFeatures(nn.Module):
    """VGG feature stack emitting named relu activations, NHWC."""

    cfg: Sequence = _VGG19_CFG
    capture: Sequence[str] = ()

    @nn.compact
    def __call__(self, x):
        names = _vgg_relu_names(self.cfg)
        out = {}
        conv_i = 0
        deepest = max((names.index(n) for n in self.capture if n in names), default=len(names) - 1)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
                continue
            x = nn.Conv(v, (3, 3), padding=1, name=f"conv_{conv_i}")(x)
            x = nn.relu(x)
            name = names[conv_i]
            if name in self.capture:
                out[name] = x
            if conv_i >= deepest:
                break
            conv_i += 1
        return out


class AlexNetFeatures(nn.Module):
    """torchvision alexnet.features equivalent (ref: perceptual.py:210-225)."""

    capture: Sequence[str] = ()

    @nn.compact
    def __call__(self, x):
        out = {}

        def tap(name, val):
            if name in self.capture:
                out[name] = val

        x = nn.Conv(64, (11, 11), strides=4, padding=2, name="conv_1")(x)
        tap("conv_1", x)
        x = nn.relu(x)
        tap("relu_1", x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding=2, name="conv_2")(x)
        tap("conv_2", x)
        x = nn.relu(x)
        tap("relu_2", x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding=1, name="conv_3")(x)
        tap("conv_3", x)
        x = nn.relu(x)
        tap("relu_3", x)
        x = nn.Conv(256, (3, 3), padding=1, name="conv_4")(x)
        tap("conv_4", x)
        x = nn.relu(x)
        tap("relu_4", x)
        x = nn.Conv(256, (3, 3), padding=1, name="conv_5")(x)
        tap("conv_5", x)
        x = nn.relu(x)
        tap("relu_5", x)
        return out


def _adaptive_avg_pool(x, out_h, out_w):
    """torch AdaptiveAvgPool2d semantics on NHWC: window i spans
    [floor(i*H/out), ceil((i+1)*H/out)) — exact for every input size
    (identity when the input already is (out_h, out_w)). Static window
    boundaries, so the unrolled means fuse under jit."""
    b, h, w, c = x.shape
    if (h, w) == (out_h, out_w):
        return x
    rows = []
    for i in range(out_h):
        y0, y1 = (i * h) // out_h, -((-(i + 1) * h) // out_h)
        cols = []
        for j in range(out_w):
            x0, x1 = (j * w) // out_w, -((-(j + 1) * w) // out_w)
            cols.append(jnp.mean(x[:, y0:y1, x0:x1, :], axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)


class VGGFaceFeatures(nn.Module):
    """vgg_face_dag: VGG16 trunk + 7x7 avgpool + fc6/fc7/fc8 classifier
    taps — the only layers the reference exposes for this network
    (ref: perceptual.py:299-358: avgpool, fc6, relu_6, fc7, relu_7, fc8).
    Conv weights come from the vgg_face_dag checkpoint converted into the
    vgg16 layout (scripts/convert_weights.py vgg_face_dag)."""

    capture: tuple = ("fc7",)

    @nn.compact
    def __call__(self, x):
        out = {}

        def tap(name, val):
            if name in self.capture:
                out[name] = val

        conv_i = 0
        for v in _VGG16_CFG:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
                continue
            x = nn.relu(nn.Conv(v, (3, 3), padding=1,
                                name=f"conv_{conv_i}")(x))
            conv_i += 1
        x = _adaptive_avg_pool(x, 7, 7)  # AdaptiveAvgPool2d((7, 7))
        tap("avgpool", x)
        # torch flattens NCHW -> (B, C*7*7); transpose so ported fc6
        # weights line up
        x = jnp.transpose(x, (0, 3, 1, 2)).reshape(x.shape[0], -1)
        x = nn.Dense(4096, name="fc6")(x)
        tap("fc6", x)
        x = nn.relu(x)
        tap("relu_6", x)
        x = nn.Dense(4096, name="fc7")(x)
        tap("fc7", x)
        x = nn.relu(x)
        tap("relu_7", x)
        x = nn.Dense(2622, name="fc8")(x)
        tap("fc8", x)
        return out


class InceptionFeatures(nn.Module):
    """Inception-v3 trunk with perceptual taps
    (ref: perceptual.py:227-253: pool_1, pool_2, mixed_6e, pool_3).
    Reuses the evaluation package's blocks, so the FID weight port
    (weights/inception_v3.npz) drives this loss too."""

    capture: tuple = ("pool_3",)

    _ORDER = ("pool_1", "pool_2", "mixed_6e", "pool_3")

    def _deepest(self, name):
        """True when no requested tap lies beyond ``name`` — the deeper
        (unused) trunk params are then never created (same early exit as
        VGGFeatures)."""
        idx = self._ORDER.index(name)
        return all(self._ORDER.index(c) <= idx for c in self.capture
                   if c in self._ORDER)

    @nn.compact
    def __call__(self, x):
        from imaginaire_tpu.evaluation.inception import (
            BasicConv,
            InceptionA,
            InceptionB,
            InceptionC,
            InceptionD,
            InceptionE,
            _max_pool3s2,
        )

        out = {}

        def tap(name, val):
            if name in self.capture:
                out[name] = val

        x = BasicConv(32, (3, 3), stride=(2, 2), name="Conv2d_1a_3x3")(x)
        x = BasicConv(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = BasicConv(64, (3, 3), padding=((1, 1), (1, 1)),
                      name="Conv2d_2b_3x3")(x)
        x = _max_pool3s2(x)
        tap("pool_1", x)
        if self._deepest("pool_1"):
            return out
        x = BasicConv(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = BasicConv(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = _max_pool3s2(x)
        tap("pool_2", x)
        if self._deepest("pool_2"):
            return out
        x = InceptionA(32, name="Mixed_5b")(x)
        x = InceptionA(64, name="Mixed_5c")(x)
        x = InceptionA(64, name="Mixed_5d")(x)
        x = InceptionB(name="Mixed_6a")(x)
        x = InceptionC(128, name="Mixed_6b")(x)
        x = InceptionC(160, name="Mixed_6c")(x)
        x = InceptionC(160, name="Mixed_6d")(x)
        x = InceptionC(192, name="Mixed_6e")(x)
        tap("mixed_6e", x)
        if self._deepest("mixed_6e"):
            return out
        x = InceptionD(name="Mixed_7a")(x)
        x = InceptionE(name="Mixed_7b")(x)
        x = InceptionE(name="Mixed_7c")(x)
        tap("pool_3", jnp.mean(x, axis=(1, 2), keepdims=True))
        return out


class _FrozenBN(nn.Module):
    """Inference-only BatchNorm with running stats as parameters (the
    torchvision-eval semantics; matches evaluation.inception.BasicConv)."""

    features: int

    @nn.compact
    def __call__(self, x):
        c = self.features
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        mean = self.param("mean", nn.initializers.zeros, (c,))
        var = self.param("var", nn.initializers.ones, (c,))
        return (x - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias


class Bottleneck(nn.Module):
    """ResNet bottleneck (torchvision layout, frozen BN)."""

    features: int
    stride: int = 1
    downsample: bool = False

    @nn.compact
    def __call__(self, x):
        identity = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, name="conv1")(x)
        y = nn.relu(_FrozenBN(self.features, name="bn1")(y))
        y = nn.Conv(self.features, (3, 3),
                    strides=(self.stride, self.stride),
                    padding=((1, 1), (1, 1)), use_bias=False, name="conv2")(y)
        y = nn.relu(_FrozenBN(self.features, name="bn2")(y))
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False,
                    name="conv3")(y)
        y = _FrozenBN(self.features * 4, name="bn3")(y)
        if self.downsample:
            identity = nn.Conv(self.features * 4, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, name="downsample_conv")(x)
            identity = _FrozenBN(self.features * 4,
                                 name="downsample_bn")(identity)
        return nn.relu(y + identity)


class ResNet50Features(nn.Module):
    """torchvision resnet50 trunk with taps layer_1..layer_4
    (ref: perceptual.py:256-272; robust_resnet50 shares the arch and
    differs only in the converted weight file, ref: perceptual.py:275-297)."""

    capture: tuple = ("layer_4",)

    @nn.compact
    def __call__(self, x):
        out = {}
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=((3, 3), (3, 3)),
                    use_bias=False, name="conv1")(x)
        x = nn.relu(_FrozenBN(64, name="bn1")(x))
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)),
                    constant_values=-1e30)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        deepest = max((int(c.split("_")[1]) for c in self.capture
                       if c.startswith("layer_")), default=4)
        for li, (blocks, feats) in enumerate(
                [(3, 64), (4, 128), (6, 256), (3, 512)], start=1):
            for bi in range(blocks):
                stride = 2 if (bi == 0 and li > 1) else 1
                x = Bottleneck(feats, stride=stride, downsample=(bi == 0),
                               name=f"layer{li}_{bi}")(x)
            if f"layer_{li}" in self.capture:
                out[f"layer_{li}"] = x
            if li >= deepest:
                break
        return out


_NETWORKS = {
    "vgg19": lambda capture: VGGFeatures(cfg=_VGG19_CFG, capture=tuple(capture)),
    "vgg16": lambda capture: VGGFeatures(cfg=_VGG16_CFG, capture=tuple(capture)),
    "vgg_face_dag": lambda capture: VGGFaceFeatures(capture=tuple(capture)),
    "alexnet": lambda capture: AlexNetFeatures(capture=tuple(capture)),
    "inception_v3": lambda capture: InceptionFeatures(capture=tuple(capture)),
    "resnet50": lambda capture: ResNet50Features(capture=tuple(capture)),
    "robust_resnet50": lambda capture: ResNet50Features(
        capture=tuple(capture)),
}


def _instance_norm(f, eps=1e-5):
    mean = jnp.mean(f, axis=(1, 2), keepdims=True)
    var = jnp.var(f, axis=(1, 2), keepdims=True)
    return (f - mean) * jax.lax.rsqrt(var + eps)


class PerceptualLoss:
    """Weighted multi-layer feature distance.

    Usage::

        ploss = PerceptualLoss(network='vgg19', layers=['relu_1_1', ...],
                               weights=[...])
        params = ploss.init_params(key)          # or load ported weights
        loss = ploss(params, fake, real)         # pure, jit-safe
    """

    def __init__(self, network="vgg19", layers="relu_4_1", weights=None,
                 criterion="l1", resize=False, num_scales=1,
                 instance_normalized=False, compute_dtype=jnp.bfloat16,
                 weights_path=None, allow_random_init=False):
        if isinstance(layers, str):
            layers = [layers]
        if weights is None:
            weights = [1.0] * len(layers)
        elif isinstance(weights, (int, float)):
            weights = [weights]
        if len(layers) != len(weights):
            raise ValueError(
                f"The number of layers ({len(layers)}) must equal the number "
                f"of weights ({len(weights)}).")
        if network not in _NETWORKS:
            raise ValueError(
                f"Network {network!r} is not implemented (available: "
                f"{sorted(_NETWORKS)}).")
        self.network_name = network
        self.layers = list(layers)
        self.weights = list(weights)
        self.criterion = criterion
        self.resize = resize
        self.num_scales = num_scales
        self.instance_normalized = instance_normalized
        self.compute_dtype = compute_dtype
        self.allow_random_init = allow_random_init
        if weights_path is None:
            import os

            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            if network == "inception_v3":
                # share the FID port (weights/inception_v3.npz)
                weights_path = os.path.join(root, "weights",
                                            "inception_v3.npz")
            else:
                weights_path = os.path.join(root, "weights",
                                            f"{network}_features.npz")
        self.weights_path = weights_path
        self.module = _NETWORKS[network](self.layers)

    def init_params(self, key, image_hw=(224, 224)):
        """Ported torchvision weights, or fail loudly
        (random init only with explicit ``allow_random_init``)."""
        import os

        if os.path.exists(self.weights_path):
            if self.network_name in ("vgg19", "vgg16"):
                return load_torch_vgg_weights(self.weights_path,
                                              self.network_name)
            if self.network_name == "vgg_face_dag":
                return load_torch_vgg_face_weights(self.weights_path)
            if self.network_name == "alexnet":
                return load_torch_alexnet_weights(self.weights_path)
            if self.network_name == "inception_v3":
                from imaginaire_tpu.evaluation.inception import load_params

                return load_params(self.weights_path)["params"]
            if self.network_name in ("resnet50", "robust_resnet50"):
                return load_torch_resnet50_weights(self.weights_path)
        if self.allow_random_init:
            dummy = jnp.zeros((1, image_hw[0], image_hw[1], 3))
            return self.module.init(key, dummy)["params"]
        raise FileNotFoundError(
            f"Pretrained {self.network_name} weights not found at "
            f"{self.weights_path}. Run `python scripts/convert_weights.py "
            f"{self.network_name} {self.weights_path}` on a machine with "
            "torchvision, or set trainer.perceptual_loss.allow_random_init "
            "(tests only — training quality will not match the reference).")

    def __call__(self, params, inp, target):
        inp = apply_imagenet_normalization(inp)
        target = apply_imagenet_normalization(target)
        if self.resize:
            n, _, _, c = inp.shape
            inp = jax.image.resize(inp, (n, 224, 224, c), "bilinear")
            target = jax.image.resize(target, (n, 224, 224, c), "bilinear")
        target = jax.lax.stop_gradient(target)

        loss = jnp.zeros((), dtype=jnp.float32)
        for scale in range(self.num_scales):
            in_feats = self.module.apply(
                {"params": params}, inp.astype(self.compute_dtype))
            tg_feats = self.module.apply(
                {"params": params}, target.astype(self.compute_dtype))
            for layer, weight in zip(self.layers, self.weights):
                f_in, f_tg = in_feats[layer], jax.lax.stop_gradient(tg_feats[layer])
                if self.instance_normalized:
                    f_in, f_tg = _instance_norm(f_in), _instance_norm(f_tg)
                if self.criterion == "l1":
                    term = jnp.mean(jnp.abs(f_in.astype(jnp.float32) - f_tg.astype(jnp.float32)))
                elif self.criterion in ("l2", "mse"):
                    term = jnp.mean((f_in.astype(jnp.float32) - f_tg.astype(jnp.float32)) ** 2)
                else:
                    raise ValueError(f"Criterion {self.criterion} is not recognized")
                loss = loss + weight * term
            if scale != self.num_scales - 1:
                inp, target = downsample_2x(inp), downsample_2x(target)
        return loss


def load_torch_vgg_weights(npz_path, network="vgg19"):
    """Convert a dumped torchvision VGG `features` state dict (saved as npz
    with keys 'features.<i>.weight'/'.bias', OIHW) into this module's
    {'conv_<k>': {'kernel': HWIO, 'bias': (O,)}} params tree."""
    raw = np.load(npz_path)
    cfg = {"vgg19": _VGG19_CFG, "vgg16": _VGG16_CFG}[network]
    params = {}
    conv_k, torch_i = 0, 0
    for v in cfg:
        if v == "M":
            torch_i += 1  # MaxPool2d occupies one Sequential slot
            continue
        w = raw[f"features.{torch_i}.weight"]  # (O, I, kh, kw)
        b = raw[f"features.{torch_i}.bias"]
        params[f"conv_{conv_k}"] = {
            "kernel": jnp.asarray(np.transpose(w, (2, 3, 1, 0))),
            "bias": jnp.asarray(b),
        }
        conv_k += 1
        torch_i += 2  # conv + relu
    return params


def load_torch_alexnet_weights(npz_path):
    """torchvision alexnet ``features`` dump -> {'conv_<1..5>': {...}}.

    Sequential layout: conv indices 0, 3, 6, 8, 10 (relu/maxpool between)."""
    raw = np.load(npz_path)
    params = {}
    for k, torch_i in enumerate((0, 3, 6, 8, 10), start=1):
        w = raw[f"features.{torch_i}.weight"]  # (O, I, kh, kw)
        b = raw[f"features.{torch_i}.bias"]
        params[f"conv_{k}"] = {
            "kernel": jnp.asarray(np.transpose(w, (2, 3, 1, 0))),
            "bias": jnp.asarray(b),
        }
    return params


def load_torch_resnet50_weights(npz_path):
    """torchvision resnet50 state-dict npz -> ResNet50Features params."""
    flat = dict(np.load(npz_path))
    params = {}

    def put_conv(dst, src):
        node = params
        for p in dst[:-1]:
            node = node.setdefault(p, {})
        node[dst[-1]] = jnp.asarray(np.transpose(flat[src], (2, 3, 1, 0)))

    def put_bn(dst, src):
        node = params
        for p in dst[:-1]:
            node = node.setdefault(p, {})
        node[dst[-1]] = {
            "scale": jnp.asarray(flat[f"{src}.weight"]),
            "bias": jnp.asarray(flat[f"{src}.bias"]),
            "mean": jnp.asarray(flat[f"{src}.running_mean"]),
            "var": jnp.asarray(flat[f"{src}.running_var"]),
        }

    put_conv(["conv1", "kernel"], "conv1.weight")
    put_bn(["bn1"], "bn1")
    for li, blocks in zip(range(1, 5), (3, 4, 6, 3)):
        for bi in range(blocks):
            base = f"layer{li}.{bi}"
            dst = f"layer{li}_{bi}"
            for ci in (1, 2, 3):
                put_conv([dst, f"conv{ci}", "kernel"], f"{base}.conv{ci}.weight")
                put_bn([dst, f"bn{ci}"], f"{base}.bn{ci}")
            if f"{base}.downsample.0.weight" in flat:
                put_conv([dst, "downsample_conv", "kernel"],
                         f"{base}.downsample.0.weight")
                put_bn([dst, "downsample_bn"], f"{base}.downsample.1")
    return params


def load_torch_vgg_face_weights(npz_path):
    """vgg_face_dag npz (vgg16 features layout + classifier.0/3/6) ->
    VGGFaceFeatures params."""
    flat = dict(np.load(npz_path))
    params = {}
    conv_i = 0
    torch_idx = 0
    for v in _VGG16_CFG:
        if v == "M":
            torch_idx += 1
            continue
        w = flat[f"features.{torch_idx}.weight"]
        params[f"conv_{conv_i}"] = {
            "kernel": jnp.asarray(np.transpose(w, (2, 3, 1, 0))),
            "bias": jnp.asarray(flat[f"features.{torch_idx}.bias"]),
        }
        conv_i += 1
        torch_idx += 2  # conv + relu
    for name, idx in (("fc6", 0), ("fc7", 3), ("fc8", 6)):
        w = flat[f"classifier.{idx}.weight"]  # (out, in)
        params[name] = {
            "kernel": jnp.asarray(w.T),
            "bias": jnp.asarray(flat[f"classifier.{idx}.bias"]),
        }
    return params

"""Perceptual loss with Flax feature extractors
(ref: imaginaire/losses/perceptual.py:15-358).

The reference wraps torchvision backbones (VGG19/VGG16/alexnet/...) and
takes weighted L1/L2 distances between named intermediate activations,
optionally over ``num_scales`` 2x-downsampled scales, optionally with
instance-normalized features.

TPU-first: the extractor is a Flax module returning a dict of named
activations; the loss is a pure function of ``(params, inp, target)`` so
it inlines into the jitted train step (the extractor runs in bf16 on the
MXU — the analogue of the reference's fp16 eval mode,
ref: perceptual.py:76-80,110-115). Pretrained torchvision weights are
loaded via :func:`load_torch_vgg_weights` from a ported ``.npz``
(``scripts/convert_weights.py``); ``init_params`` fails loudly when the
file is missing — training against a random-init VGG silently diverges
from the reference. ``allow_random_init=True`` is the explicit escape for
unit tests.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from imaginaire_tpu.utils.misc import apply_imagenet_normalization, downsample_2x

# torchvision `features` configs: numbers are conv widths, 'M' is 2x maxpool.
_VGG19_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M")
_VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M")


def _vgg_relu_names(cfg):
    """Name each conv's relu 'relu_<block>_<idx>' (ref: perceptual.py:176-208)."""
    names, block, idx = [], 1, 1
    for v in cfg:
        if v == "M":
            block += 1
            idx = 1
        else:
            names.append(f"relu_{block}_{idx}")
            idx += 1
    return names


class VGGFeatures(nn.Module):
    """VGG feature stack emitting named relu activations, NHWC."""

    cfg: Sequence = _VGG19_CFG
    capture: Sequence[str] = ()

    @nn.compact
    def __call__(self, x):
        names = _vgg_relu_names(self.cfg)
        out = {}
        conv_i = 0
        deepest = max((names.index(n) for n in self.capture if n in names), default=len(names) - 1)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
                continue
            x = nn.Conv(v, (3, 3), padding=1, name=f"conv_{conv_i}")(x)
            x = nn.relu(x)
            name = names[conv_i]
            if name in self.capture:
                out[name] = x
            if conv_i >= deepest:
                break
            conv_i += 1
        return out


class AlexNetFeatures(nn.Module):
    """torchvision alexnet.features equivalent (ref: perceptual.py:210-225)."""

    capture: Sequence[str] = ()

    @nn.compact
    def __call__(self, x):
        out = {}

        def tap(name, val):
            if name in self.capture:
                out[name] = val

        x = nn.Conv(64, (11, 11), strides=4, padding=2, name="conv_1")(x)
        tap("conv_1", x)
        x = nn.relu(x)
        tap("relu_1", x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding=2, name="conv_2")(x)
        tap("conv_2", x)
        x = nn.relu(x)
        tap("relu_2", x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding=1, name="conv_3")(x)
        tap("conv_3", x)
        x = nn.relu(x)
        tap("relu_3", x)
        x = nn.Conv(256, (3, 3), padding=1, name="conv_4")(x)
        tap("conv_4", x)
        x = nn.relu(x)
        tap("relu_4", x)
        x = nn.Conv(256, (3, 3), padding=1, name="conv_5")(x)
        tap("conv_5", x)
        x = nn.relu(x)
        tap("relu_5", x)
        return out


_NETWORKS = {
    "vgg19": lambda capture: VGGFeatures(cfg=_VGG19_CFG, capture=tuple(capture)),
    "vgg16": lambda capture: VGGFeatures(cfg=_VGG16_CFG, capture=tuple(capture)),
    "alexnet": lambda capture: AlexNetFeatures(capture=tuple(capture)),
}


def _instance_norm(f, eps=1e-5):
    mean = jnp.mean(f, axis=(1, 2), keepdims=True)
    var = jnp.var(f, axis=(1, 2), keepdims=True)
    return (f - mean) * jax.lax.rsqrt(var + eps)


class PerceptualLoss:
    """Weighted multi-layer feature distance.

    Usage::

        ploss = PerceptualLoss(network='vgg19', layers=['relu_1_1', ...],
                               weights=[...])
        params = ploss.init_params(key)          # or load ported weights
        loss = ploss(params, fake, real)         # pure, jit-safe
    """

    def __init__(self, network="vgg19", layers="relu_4_1", weights=None,
                 criterion="l1", resize=False, num_scales=1,
                 instance_normalized=False, compute_dtype=jnp.bfloat16,
                 weights_path=None, allow_random_init=False):
        if isinstance(layers, str):
            layers = [layers]
        if weights is None:
            weights = [1.0] * len(layers)
        elif isinstance(weights, (int, float)):
            weights = [weights]
        if len(layers) != len(weights):
            raise ValueError(
                f"The number of layers ({len(layers)}) must equal the number "
                f"of weights ({len(weights)}).")
        if network not in _NETWORKS:
            raise ValueError(
                f"Network {network!r} is not implemented (available: "
                f"{sorted(_NETWORKS)}; inception_v3/resnet50 live in "
                f"imaginaire_tpu.evaluation once ported).")
        self.network_name = network
        self.layers = list(layers)
        self.weights = list(weights)
        self.criterion = criterion
        self.resize = resize
        self.num_scales = num_scales
        self.instance_normalized = instance_normalized
        self.compute_dtype = compute_dtype
        self.allow_random_init = allow_random_init
        if weights_path is None:
            import os

            weights_path = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
                "weights", f"{network}_features.npz")
        self.weights_path = weights_path
        self.module = _NETWORKS[network](self.layers)

    def init_params(self, key, image_hw=(224, 224)):
        """Ported torchvision weights, or fail loudly
        (random init only with explicit ``allow_random_init``)."""
        import os

        if os.path.exists(self.weights_path):
            if self.network_name in ("vgg19", "vgg16"):
                return load_torch_vgg_weights(self.weights_path, self.network_name)
            return load_torch_alexnet_weights(self.weights_path)
        if self.allow_random_init:
            dummy = jnp.zeros((1, image_hw[0], image_hw[1], 3))
            return self.module.init(key, dummy)["params"]
        raise FileNotFoundError(
            f"Pretrained {self.network_name} weights not found at "
            f"{self.weights_path}. Run `python scripts/convert_weights.py "
            f"{self.network_name} {self.weights_path}` on a machine with "
            "torchvision, or set trainer.perceptual_loss.allow_random_init "
            "(tests only — training quality will not match the reference).")

    def __call__(self, params, inp, target):
        inp = apply_imagenet_normalization(inp)
        target = apply_imagenet_normalization(target)
        if self.resize:
            n, _, _, c = inp.shape
            inp = jax.image.resize(inp, (n, 224, 224, c), "bilinear")
            target = jax.image.resize(target, (n, 224, 224, c), "bilinear")
        target = jax.lax.stop_gradient(target)

        loss = jnp.zeros((), dtype=jnp.float32)
        for scale in range(self.num_scales):
            in_feats = self.module.apply(
                {"params": params}, inp.astype(self.compute_dtype))
            tg_feats = self.module.apply(
                {"params": params}, target.astype(self.compute_dtype))
            for layer, weight in zip(self.layers, self.weights):
                f_in, f_tg = in_feats[layer], jax.lax.stop_gradient(tg_feats[layer])
                if self.instance_normalized:
                    f_in, f_tg = _instance_norm(f_in), _instance_norm(f_tg)
                if self.criterion == "l1":
                    term = jnp.mean(jnp.abs(f_in.astype(jnp.float32) - f_tg.astype(jnp.float32)))
                elif self.criterion in ("l2", "mse"):
                    term = jnp.mean((f_in.astype(jnp.float32) - f_tg.astype(jnp.float32)) ** 2)
                else:
                    raise ValueError(f"Criterion {self.criterion} is not recognized")
                loss = loss + weight * term
            if scale != self.num_scales - 1:
                inp, target = downsample_2x(inp), downsample_2x(target)
        return loss


def load_torch_vgg_weights(npz_path, network="vgg19"):
    """Convert a dumped torchvision VGG `features` state dict (saved as npz
    with keys 'features.<i>.weight'/'.bias', OIHW) into this module's
    {'conv_<k>': {'kernel': HWIO, 'bias': (O,)}} params tree."""
    raw = np.load(npz_path)
    cfg = {"vgg19": _VGG19_CFG, "vgg16": _VGG16_CFG}[network]
    params = {}
    conv_k, torch_i = 0, 0
    for v in cfg:
        if v == "M":
            torch_i += 1  # MaxPool2d occupies one Sequential slot
            continue
        w = raw[f"features.{torch_i}.weight"]  # (O, I, kh, kw)
        b = raw[f"features.{torch_i}.bias"]
        params[f"conv_{conv_k}"] = {
            "kernel": jnp.asarray(np.transpose(w, (2, 3, 1, 0))),
            "bias": jnp.asarray(b),
        }
        conv_k += 1
        torch_i += 2  # conv + relu
    return params


def load_torch_alexnet_weights(npz_path):
    """torchvision alexnet ``features`` dump -> {'conv_<1..5>': {...}}.

    Sequential layout: conv indices 0, 3, 6, 8, 10 (relu/maxpool between)."""
    raw = np.load(npz_path)
    params = {}
    for k, torch_i in enumerate((0, 3, 6, 8, 10), start=1):
        w = raw[f"features.{torch_i}.weight"]  # (O, I, kh, kw)
        b = raw[f"features.{torch_i}.bias"]
        params[f"conv_{k}"] = {
            "kernel": jnp.asarray(np.transpose(w, (2, 3, 1, 0))),
            "bias": jnp.asarray(b),
        }
    return params

"""Flow / occlusion-mask losses (ref: imaginaire/losses/flow.py).

``masked_l1_loss`` reproduces MaskedL1Loss (flow.py:14-39) — the fork's
vid2vid uses it directly in place of the full FlowLoss
(ref: trainers/vid2vid.py:149-153). ``FlowLoss`` reproduces the full
version: ground-truth flow/confidence come from a flow network evaluated
*inside the loss* (flow.py:95-117), then L1-on-flow, warp, and occlusion
mask terms (flow.py:120-313).

TPU-first: the flow network is injected as a pure callable
``flow_net(im1, im2) -> (flow, conf)`` (FlowNet2-Flax under
stop_gradient), so the whole loss inlines into the jitted train step —
no Python-side module registry, no device branching. NHWC; flow maps are
(..., H, W, 2) in pixel units.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from imaginaire_tpu.ops.resample2d import resample2d


def masked_l1_loss(x, target, mask, normalize_over_valid=False):
    """L1 over mask-weighted tensors (ref: flow.py:14-39).

    The mask broadcasts against x; mean is over ALL elements unless
    ``normalize_over_valid``, which rescales by numel/sum(mask) —
    matching the reference exactly.
    """
    mask = jnp.broadcast_to(mask, x.shape)
    loss = jnp.mean(jnp.abs(x * mask - target * mask))
    if normalize_over_valid:
        loss = loss * mask.size / (jnp.sum(mask) + 1e-6)
    return loss


def _l1(a, b):
    return jnp.mean(jnp.abs(a - b))


class FlowLoss:
    """Flow supervision harness (ref: flow.py:42-313).

    Args:
        flow_net: ``(im_a, im_b) -> (flow, conf)`` frozen flow estimator;
            outputs are stop_gradient'ed here. May be ``None`` when the
            ground truth arrives precomputed (the flow-cache path): the
            data dict then carries ``flow_gt``/``conf_gt`` for the prev
            pair — computed off the step program by ``flow/cache.py`` —
            and the step program never contains the teacher cascade.
        warp_ref: also supervise reference->target warping (fs-vid2vid).
        has_fg: weight flow L1 by a foreground mask from the label map.
    """

    def __init__(self, flow_net: Optional[Callable], warp_ref: bool = False,
                 has_fg: bool = False):
        self.flow_net = flow_net
        self.warp_ref = warp_ref
        self.has_fg = has_fg

    def __call__(self, data, net_G_output, compute_prev: bool = True):
        """Returns (loss_flow_L1, loss_flow_warp, loss_mask).

        data keys: 'image' (target), optional 'real_prev_image',
        'ref_image', 'fg_mask', 'ref_fg_mask'.
        net_G_output keys: 'fake_images', 'warped_images',
        'fake_flow_maps', 'fake_occlusion_masks' — scalars or
        [ref, prev] lists, matching the reference convention.
        """
        tgt_image = data["image"]
        fake_image = net_G_output["fake_images"]
        warped = net_G_output["warped_images"]
        flows = net_G_output["fake_flow_maps"]
        occ_masks = net_G_output["fake_occlusion_masks"]
        fg_mask = data.get("fg_mask", 1.0) if self.has_fg else 1.0

        # Ground-truth flow/conf from the frozen flow net (ref: flow.py:95-117)
        # — or precomputed off-step by the flow cache (data['flow_gt']).
        flow_gt, conf_gt = [], []
        if self.warp_ref:
            f, c = self._gt(tgt_image, data["ref_image"])
            flow_gt.append(f)
            conf_gt.append(c)
        if compute_prev and data.get("flow_gt") is not None:
            flow_gt.append(jax.lax.stop_gradient(data["flow_gt"]))
            conf_gt.append(jax.lax.stop_gradient(data["conf_gt"]))
        elif compute_prev and data.get("real_prev_image") is not None \
                and self.flow_net is not None:
            f, c = self._gt(tgt_image, data["real_prev_image"])
            flow_gt.append(f)
            conf_gt.append(c)
        elif isinstance(flows, (list, tuple)):
            flow_gt.append(None)
            conf_gt.append(None)

        if not isinstance(flows, (list, tuple)):
            flows, warped, occ_masks = [flows], [warped], [occ_masks]
            flow_gt, conf_gt = flow_gt[-1:], conf_gt[-1:]

        loss_flow_l1 = jnp.zeros(())
        loss_flow_warp = jnp.zeros(())
        for flow, warp_img, f_gt, c_gt in zip(flows, warped, flow_gt, conf_gt):
            if flow is not None and f_gt is not None:
                loss_flow_l1 += masked_l1_loss(flow, f_gt, c_gt * fg_mask)
            if warp_img is not None:
                loss_flow_warp += _l1(warp_img, tgt_image)

        if self.warp_ref and self.has_fg:
            # Warped reference fg map should match target fg map
            # (ref: flow.py:186-193).
            warped_fg = resample2d(data["ref_fg_mask"], flows[0])
            loss_flow_warp += _l1(warped_fg, data["fg_mask"])

        loss_mask = jnp.zeros(())
        for occ, warp_img in zip(occ_masks, warped):
            loss_mask += self._mask_loss(occ, warp_img, tgt_image)
        if self.warp_ref and self.has_fg:
            # Hallucinate (mask→1) where fg disagrees (ref: flow.py:283-287).
            fg_diff = (data["ref_fg_mask"] - data["fg_mask"] > 0).astype(tgt_image.dtype)
            loss_mask += masked_l1_loss(occ_masks[0], jnp.ones_like(occ_masks[0]), fg_diff)

        return loss_flow_l1, loss_flow_warp, loss_mask

    def _gt(self, im_a, im_b):
        flow, conf = self.flow_net(im_a, im_b)
        return jax.lax.stop_gradient(flow), jax.lax.stop_gradient(conf)

    @staticmethod
    def _mask_loss(occ_mask, warped_image, tgt_image):
        """Occlusion mask supervision (ref: flow.py:289-313): push the mask
        toward 0 where the warp already matches, toward 1 where it doesn't."""
        if occ_mask is None:
            return jnp.zeros(())
        img_diff = jnp.sum(jnp.abs(warped_image - tgt_image), axis=-1, keepdims=True)
        conf = jnp.clip(1.0 - img_diff, 0.0, 1.0)
        loss = masked_l1_loss(occ_mask, jnp.zeros_like(occ_mask), conf)
        loss += masked_l1_loss(occ_mask, jnp.ones_like(occ_mask), 1.0 - conf)
        return loss

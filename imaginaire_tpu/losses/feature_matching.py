"""Feature-matching loss (ref: imaginaire/losses/feature_matching.py:8-38).

L1 (or L2) between discriminator features of fake vs real images, summed
over layers, weighted 1/num_discriminators. The real-branch stop_gradient
mirrors the reference's ``.detach()`` so D features of real images don't
receive generator gradients.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from imaginaire_tpu.losses.gan import _weighted_mean


def feature_matching_loss(fake_features, real_features, criterion="l1",
                          sample_weight=None):
    """fake_features / real_features: list (per D) of lists (per layer).

    ``sample_weight``: optional (B,) validity weights — region
    discriminators weight out samples whose region was absent instead of
    skipping them (static shapes under jit)."""
    num_d = len(fake_features)
    dis_weight = 1.0 / num_d
    loss = jnp.zeros(())
    for fake_per_d, real_per_d in zip(fake_features, real_features):
        for fake_f, real_f in zip(fake_per_d, real_per_d):
            real_f = jax.lax.stop_gradient(real_f)
            if criterion == "l1":
                diff = jnp.abs(fake_f - real_f)
            elif criterion in ("l2", "mse"):
                diff = (fake_f - real_f) ** 2
            else:
                raise ValueError(f"Criterion {criterion} is not recognized")
            loss = loss + dis_weight * _weighted_mean(diff, sample_weight)
    return loss

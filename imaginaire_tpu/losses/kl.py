"""Gaussian KL divergence for VAE-style encoders (ref: imaginaire/losses/kl.py:9-23).

KL(N(mu, e^logvar) || N(0, 1)) = -0.5 * sum(1 + logvar - mu^2 - e^logvar).
Sum reduction, matching the reference.
"""

from __future__ import annotations

import jax.numpy as jnp


def gaussian_kl_loss(mu, logvar=None):
    if logvar is None:
        logvar = jnp.zeros_like(mu)
    return -0.5 * jnp.sum(1.0 + logvar - mu ** 2 - jnp.exp(logvar))

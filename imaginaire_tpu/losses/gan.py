"""GAN objectives (ref: imaginaire/losses/gan.py:30-132).

Four modes — hinge / least_square / non_saturated / wasserstein — with the
reference's list-input convention: a multi-scale discriminator passes a
list of per-scale outputs and the loss is averaged per scale first, then
across scales, so high-resolution scales don't dominate the gradient
(ref: gan.py:61-72).

Written as a pure function: ``dis_update`` / ``t_real`` are Python bools
(static under jit), so each variant traces to a minimal fused graph — the
reference needed ``torch.jit.script`` fusion for the hinge terms
(ref: gan.py:12-27); XLA fuses these for free.
"""

from __future__ import annotations

import jax.numpy as jnp


def _weighted_mean(loss, sample_weight):
    """Mean over all elements, or a per-sample weighted mean when
    ``sample_weight`` (B,) is given — the static-shape replacement for
    the reference's skip-absent-regions control flow."""
    if sample_weight is None:
        return jnp.mean(loss)
    per_sample = jnp.mean(loss.reshape(loss.shape[0], -1), axis=-1)
    denom = jnp.maximum(jnp.sum(sample_weight), 1e-6)
    return jnp.sum(per_sample * sample_weight) / denom


def _single_gan_loss(logits, t_real, mode, dis_update, real_label, fake_label,
                     sample_weight=None):
    if not dis_update and not t_real:
        raise ValueError("The target should be real when updating the generator.")
    if mode == "non_saturated":
        target = jnp.full_like(logits, real_label if t_real else fake_label)
        # BCE-with-logits, mean reduction (ref: gan.py:92-95).
        loss = jnp.maximum(logits, 0) - logits * target + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return _weighted_mean(loss, sample_weight)
    if mode == "least_square":
        target = jnp.full_like(logits, real_label if t_real else fake_label)
        return 0.5 * _weighted_mean((logits - target) ** 2, sample_weight)
    if mode == "hinge":
        if dis_update:
            if t_real:
                return -_weighted_mean(jnp.minimum(logits - 1.0, 0.0),
                                       sample_weight)
            return -_weighted_mean(jnp.minimum(-logits - 1.0, 0.0),
                                   sample_weight)
        return -_weighted_mean(logits, sample_weight)
    if mode == "wasserstein":
        m = _weighted_mean(logits, sample_weight)
        return -m if t_real else m
    raise ValueError(f"Unexpected gan_mode {mode!r}")


def gan_loss(dis_output, t_real, gan_mode="hinge", dis_update=True,
             target_real_label=1.0, target_fake_label=0.0,
             sample_weight=None):
    """GAN loss over a single logits array or a list of per-scale arrays.

    Args:
        dis_output: logits array, or list of logits arrays (multi-scale).
        t_real: target is the real label (static Python bool).
        gan_mode: 'hinge' | 'least_square' | 'non_saturated' | 'wasserstein'.
        dis_update: True → discriminator form, False → generator form.
        sample_weight: optional (B,) validity weights (region Ds).
    """
    if isinstance(dis_output, (list, tuple)):
        per_scale = [
            _single_gan_loss(o, t_real, gan_mode, dis_update,
                             target_real_label, target_fake_label,
                             sample_weight)
            for o in dis_output
        ]
        return sum(per_scale) / len(per_scale)
    return _single_gan_loss(dis_output, t_real, gan_mode, dis_update,
                            target_real_label, target_fake_label,
                            sample_weight)


def dis_accuracy(real_outputs, fake_outputs, gan_mode="hinge",
                 target_real_label=1.0, target_fake_label=0.0):
    """(real_acc, fake_acc): fraction of discriminator logits on the
    correct side of the decision boundary — the GAN-balance metric the
    diagnostics layer tracks (a D pinned at ~100%/~100% starves G of
    gradient; ~50%/~50% means D learned nothing).

    The boundary is 0 for the logit modes (hinge / non_saturated /
    wasserstein — for wasserstein the critic is unbounded, so read the
    number as a separation indicator, not a true accuracy) and the
    label midpoint for least_square. Accepts the same (possibly nested)
    list-of-scales structure as ``gan_loss``; scales average equally.
    """
    thr = (0.5 * (target_real_label + target_fake_label)
           if gan_mode == "least_square" else 0.0)

    def frac(out, is_real):
        if isinstance(out, (list, tuple)):
            per_scale = [frac(o, is_real) for o in out]
            return sum(per_scale) / len(per_scale)
        correct = (out > thr) if is_real else (out <= thr)
        return jnp.mean(correct.astype(jnp.float32))

    return frac(real_outputs, True), frac(fake_outputs, False)

"""GAN objectives (ref: imaginaire/losses/gan.py:30-132).

Four modes — hinge / least_square / non_saturated / wasserstein — with the
reference's list-input convention: a multi-scale discriminator passes a
list of per-scale outputs and the loss is averaged per scale first, then
across scales, so high-resolution scales don't dominate the gradient
(ref: gan.py:61-72).

Written as a pure function: ``dis_update`` / ``t_real`` are Python bools
(static under jit), so each variant traces to a minimal fused graph — the
reference needed ``torch.jit.script`` fusion for the hinge terms
(ref: gan.py:12-27); XLA fuses these for free.
"""

from __future__ import annotations

import jax.numpy as jnp


def _single_gan_loss(logits, t_real, mode, dis_update, real_label, fake_label):
    if not dis_update and not t_real:
        raise ValueError("The target should be real when updating the generator.")
    if mode == "non_saturated":
        target = jnp.full_like(logits, real_label if t_real else fake_label)
        # BCE-with-logits, mean reduction (ref: gan.py:92-95).
        loss = jnp.maximum(logits, 0) - logits * target + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.mean(loss)
    if mode == "least_square":
        target = jnp.full_like(logits, real_label if t_real else fake_label)
        return 0.5 * jnp.mean((logits - target) ** 2)
    if mode == "hinge":
        if dis_update:
            if t_real:
                return -jnp.mean(jnp.minimum(logits - 1.0, 0.0))
            return -jnp.mean(jnp.minimum(-logits - 1.0, 0.0))
        return -jnp.mean(logits)
    if mode == "wasserstein":
        return -jnp.mean(logits) if t_real else jnp.mean(logits)
    raise ValueError(f"Unexpected gan_mode {mode!r}")


def gan_loss(dis_output, t_real, gan_mode="hinge", dis_update=True,
             target_real_label=1.0, target_fake_label=0.0):
    """GAN loss over a single logits array or a list of per-scale arrays.

    Args:
        dis_output: logits array, or list of logits arrays (multi-scale).
        t_real: target is the real label (static Python bool).
        gan_mode: 'hinge' | 'least_square' | 'non_saturated' | 'wasserstein'.
        dis_update: True → discriminator form, False → generator form.
    """
    if isinstance(dis_output, (list, tuple)):
        per_scale = [
            _single_gan_loss(o, t_real, gan_mode, dis_update,
                             target_real_label, target_fake_label)
            for o in dis_output
        ]
        return sum(per_scale) / len(per_scale)
    return _single_gan_loss(dis_output, t_real, gan_mode, dis_update,
                            target_real_label, target_fake_label)

"""UNIT trainer (ref: imaginaire/trainers/unit.py:14-229).

Loss terms: two-domain GAN, within-domain image reconstruction, cycle
reconstruction, optional perceptual (ref: unit.py:55-140). Shares the
unpaired two-domain scaffolding with the MUNIT trainer; UNIT has no
style code, so the style/content/kl terms never activate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from imaginaire_tpu.losses import gan_loss
from imaginaire_tpu.trainers.munit import Trainer as MUNITTrainer, _l1


class Trainer(MUNITTrainer):
    def _apply_G(self, vars_G, data, rng, training, **flags):
        """UNIT's generator takes no style flags (ref: generators/unit.py:26)."""
        from imaginaire_tpu.trainers.base import MUTABLE

        flags.pop("random_style", None)
        flags.pop("latent_recon", None)
        flags.pop("within_latent_recon", None)
        return self.net_G.apply(vars_G, data, training=training,
                                rngs={"noise": rng}, mutable=list(MUTABLE),
                                **flags)

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/unit.py:79-140)."""
        cycle = "cycle_recon" in self.weights
        out, new_mut = self._apply_G(vars_G, data, rng, training,
                                     image_recon=True, cycle_recon=cycle)
        d_out = self.net_D.apply(vars_D, data, out, real=False,
                                 training=training)
        losses = {}
        losses["gan"] = (
            gan_loss(d_out["out_ba"], True, self.gan_mode, dis_update=False)
            + gan_loss(d_out["out_ab"], True, self.gan_mode, dis_update=False))
        if self.perceptual is not None:
            losses["perceptual"] = (
                self.perceptual(loss_params["perceptual"], out["images_ab"],
                                data["images_a"])
                + self.perceptual(loss_params["perceptual"], out["images_ba"],
                                  data["images_b"]))
        losses["image_recon"] = (_l1(out["images_aa"], data["images_a"])
                                 + _l1(out["images_bb"], data["images_b"]))
        if cycle:
            losses["cycle_recon"] = (_l1(out["images_aba"], data["images_a"])
                                     + _l1(out["images_bab"], data["images_b"]))
        return losses, new_mut

    def dis_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/unit.py:142-173)."""
        from imaginaire_tpu.trainers.base import MUTABLE

        out, _ = self._apply_G(vars_G, data, rng, training,
                               image_recon=False, cycle_recon=False)
        out = jax.lax.stop_gradient(
            {k: v for k, v in out.items() if k.startswith("images_")})
        d_out, new_mut_D = self.net_D.apply(
            vars_D, data, out, real=True, training=training,
            mutable=list(MUTABLE))
        losses = {"gan": (
            gan_loss(d_out["out_a"], True, self.gan_mode, dis_update=True)
            + gan_loss(d_out["out_ba"], False, self.gan_mode, dis_update=True)
            + gan_loss(d_out["out_b"], True, self.gan_mode, dis_update=True)
            + gan_loss(d_out["out_ab"], False, self.gan_mode, dis_update=True))}
        from imaginaire_tpu.losses import dis_accuracy

        losses["D_real_acc"], losses["D_fake_acc"] = dis_accuracy(
            [d_out["out_a"], d_out["out_b"]],
            [d_out["out_ba"], d_out["out_ab"]], self.gan_mode)
        return losses, new_mut_D

    def _get_visualizations(self, data):
        """(ref: trainers/unit.py:175-198)."""
        from imaginaire_tpu.utils.misc import to_device

        data = to_device(dict(data))
        variables = self.inference_params()
        out, _ = self._apply_G(variables, data, jax.random.PRNGKey(0),
                               training=False, image_recon=True,
                               cycle_recon=True)
        return [data["images_a"], data["images_b"],
                out["images_aa"], out["images_bb"],
                out["images_ab"], out["images_ba"],
                out["images_aba"], out["images_bab"]]

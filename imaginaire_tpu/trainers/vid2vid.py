"""vid2vid trainer (ref: imaginaire/trainers/vid2vid.py:30-766).

Training is an interleaved per-frame rollout: for each frame t of the
sequence, one discriminator update then one generator update, feeding
the generator its own (detached) previous outputs
(ref: vid2vid.py:238-288). The sequence-length curriculum starts at a
single frame and doubles every ``num_epochs_temporal_step`` epochs
(ref: vid2vid.py:162-204).

TPU-first: each (prev-frame-count, active-temporal-scale) combination
is one jitted step program; jax.jit's structure cache handles the
variants (bounded: prev counts ≤ num_frames_G-1, scale activations ≤
num_scales). Temporal-discriminator inputs come from host-threaded
device ring buffers sliced with static strides (the reference's
get_skipped_frames bookkeeping, discriminators/fs_vid2vid.py:225-256) —
no dynamic shapes inside any step.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from imaginaire_tpu import telemetry
from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.losses import (
    PerceptualLoss,
    dis_accuracy,
    feature_matching_loss,
    gan_loss,
)
from imaginaire_tpu.losses.flow import masked_l1_loss
from imaginaire_tpu.model_utils.fs_vid2vid import concat_frames, skip_stride_span
from imaginaire_tpu.optim import init_optimizer_state
from imaginaire_tpu.parallel.pipeline import RolloutPipeline, hoist_invariants
from imaginaire_tpu.trainers.base import MUTABLE, BaseTrainer
from imaginaire_tpu.utils.misc import numeric_only, to_device
from imaginaire_tpu.utils.model_average import ema_init, ema_update


class Trainer(BaseTrainer):
    def __init__(self, cfg, *args, **kwargs):
        super().__init__(cfg, *args, **kwargs)
        self.num_frames_G = cfg_get(cfg.data, "num_frames_G", 3)
        self.num_frames_D = cfg_get(cfg.data, "num_frames_D", 3)
        self.has_fg = cfg_get(cfg.data, "has_foreground", False)
        self.sequence_length = 1
        self.sequence_length_max = cfg_get(
            cfg_get(cfg.data, "train", {}) or {}, "max_sequence_length", 16)
        if self.train_data_loader is not None:
            ds = getattr(self.train_data_loader, "dataset", None)
            if ds is not None and hasattr(ds, "sequence_length_max"):
                self.sequence_length_max = min(self.sequence_length_max,
                                               ds.sequence_length_max)
        # per-frame programs ride the compile ledger like the base step
        # programs; allow_shape_growth: the sequence-length curriculum
        # and ring-buffer warm-up legitimately re-specialize on new
        # shapes (same dtypes/shardings), which must not trip the
        # recompile tripwire
        from imaginaire_tpu.telemetry import xla_obs

        self._jit_vid_dis = xla_obs.compiled_program(
            "vid_dis_step", self._vid_dis_step_fn,
            donate_argnums=self._donate, allow_shape_growth=True)
        self._jit_vid_gen = xla_obs.compiled_program(
            "vid_gen_step", self._vid_gen_step_fn,
            donate_argnums=self._donate, allow_shape_growth=True)
        # Whole-rollout mode (SURVEY §7 hard-part #3): once the history
        # ring buffers reach their steady-state shapes, the remaining
        # frames run as ONE lax.scan program — per-frame D+G updates with
        # (params, opt state, ring buffers) in carry — instead of 2
        # host-dispatched programs per frame. Opt-in via
        # trainer.rollout_scan; see gen_update/_rollout_scan_tail.
        self.rollout_scan = bool(cfg_get(cfg.trainer, "rollout_scan",
                                         False))
        if self.rollout_scan:
            # Demoted knob (ISSUE 14 / PROFILE.md Round 5): the whole-rollout
            # scan measured ~19% SLOWER than the per-frame path (5.93 vs
            # 7.28 frames/s) because one fused program forfeits the D/G
            # async-dispatch overlap. Kept opt-in for the program-count
            # story; warn once so nobody re-discovers the regression.
            logging.warning(
                "trainer.rollout_scan is a measured regression on the "
                "per-frame path (5.93 vs 7.28 frames/s, see PROFILE.md "
                "Round 5); prefer trainer.pipeline for rollout overlap")
            telemetry.get().meta(
                "rollout_scan_enabled",
                verdict="PROFILE.md Round 5: ~19% slower than per-frame",
                per_frame_fps=7.28, rollout_scan_fps=5.93)
        self._jit_rollout_tail = xla_obs.compiled_program(
            "rollout_tail", self._rollout_tail_fn,
            donate_argnums=self._donate, allow_shape_growth=True)
        # Software-pipelined rollout dispatch (parallel/pipeline.py,
        # ISSUE 14): one persistent scheduler per trainer, reset at each
        # rollout. The sequential path runs the same instrument at
        # depth=0, so the dispatch-gap/overlap meters are always live.
        self._rollout_pipeline = RolloutPipeline(
            depth=self.pipeline_cfg["depth"],
            overlap_collectives=self.pipeline_cfg["overlap_collectives"])
        self._seq_pipeline = RolloutPipeline(depth=0)

    # ---------------------------------------------------------------- loss

    def _init_loss(self, cfg):
        """(ref: trainers/vid2vid.py:89-157)."""
        tcfg = cfg.trainer
        lw = tcfg.loss_weight
        self.gan_mode = cfg_get(tcfg, "gan_mode", "hinge")
        self.weights["GAN"] = lw.gan
        self.weights["FeatureMatching"] = lw.feature_matching
        self.perceptual = None
        if cfg_get(tcfg, "perceptual_loss", None) is not None:
            p = tcfg.perceptual_loss
            self.perceptual = PerceptualLoss(
                network=p.mode, layers=list(p.layers),
                weights=list(cfg_get(p, "weights", None) or []) or None,
                weights_path=cfg_get(p, "weights_path", None),
                allow_random_init=cfg_get(p, "allow_random_init", False))
            self.weights["Perceptual"] = lw.perceptual
        if cfg_get(lw, "L1", 0) > 0:
            self.weights["L1"] = lw.L1
        self.use_flow = cfg_get(cfg.gen, "flow", None) is not None
        self.flow_net_wrapper = None
        self.flow_cache = None
        if self.use_flow:
            self.weights["Flow"] = lw.flow
            # Full FlowLoss with a frozen FlowNet2 teacher when
            # cfg.flow_network is configured and weights resolve
            # (ref: trainers/vid2vid.py:147-152, third_party flow_net);
            # otherwise the fork's warp-consistency masked L1.
            fn_cfg = cfg_get(cfg, "flow_network", None)
            if fn_cfg is not None:
                from imaginaire_tpu.flow import FlowNet

                try:
                    self.flow_net_wrapper = FlowNet(
                        weights_path=cfg_get(fn_cfg, "weights_path", None),
                        allow_random_init=cfg_get(fn_cfg,
                                                  "allow_random_init", False))
                    self.flow_net_wrapper.init_params(jax.random.PRNGKey(0))
                    self.weights["Flow_L1"] = self.weights["Flow_Warp"] = \
                        self.weights["Flow_Mask"] = lw.flow
                except FileNotFoundError as e:
                    import logging

                    msg = (f"FlowNet2 teacher unavailable ({e}); using "
                           "warp-consistency flow loss.")
                    logging.getLogger(__name__).warning(msg)
                    # mirror into the run JSONL so a post-hoc reader can
                    # tell a teacherless run from a teacher-supervised one
                    telemetry.get().meta("flow_teacher_unavailable",
                                         reason=str(e), fallback="warp_"
                                         "consistency_masked_l1")
                    self.flow_net_wrapper = None
        if self.flow_net_wrapper is not None:
            # teacher amortization (flow/cache.py): run the frozen
            # teacher OFF the step program — in the prefetch producer
            # thread, with an optional on-disk canonical-resolution
            # cache — so the compiled D/G steps carry no FlowNet2
            # params. flow_cache.enabled: false keeps the reference's
            # in-graph teacher.
            from imaginaire_tpu.flow.cache import (
                TeacherFlowCache,
                flow_cache_settings,
                resolve_cache_dir,
            )

            settings = flow_cache_settings(cfg)
            if settings.enabled:
                self.flow_cache = TeacherFlowCache(
                    self.flow_net_wrapper, settings,
                    cache_dir=resolve_cache_dir(cfg))
        self.num_temporal_scales = cfg_get(
            cfg_get(cfg.dis, "temporal", {}) or {}, "num_scales", 0)
        for s in range(self.num_temporal_scales):
            self.weights[f"GAN_T{s}"] = cfg_get(lw, "temporal_gan", 0)
            self.weights[f"FeatureMatching_T{s}"] = lw.feature_matching
        # Per-region additional discriminators: each carries its own
        # loss_weight (ref: trainers/vid2vid.py:120-129, configs'
        # additional_discriminators blocks).
        add_cfg = cfg_get(cfg.dis, "additional_discriminators", None)
        add_cfg = as_attrdict(add_cfg) if add_cfg else {}
        self.add_dis_names = sorted(add_cfg.keys())
        for name in self.add_dis_names:
            self.weights[f"GAN_{name}"] = cfg_get(add_cfg[name],
                                                  "loss_weight", 1.0)
            self.weights[f"FeatureMatching_{name}"] = lw.feature_matching

    def init_loss_params(self, key):
        params = {}
        if self.perceptual is not None:
            params["perceptual"] = self.perceptual.init_params(key)
        if self.flow_net_wrapper is not None and self.flow_cache is None:
            # with the flow cache active the teacher runs off-step and
            # its 162M-param tree must NOT enter the step programs —
            # the gen executable shrinks and never re-ships the cascade
            params["flownet"] = self.flow_net_wrapper.params
        return params

    # ---------------------------------------------------------- data hooks

    def _start_of_iteration(self, data, current_iteration):
        """DensePose preprocessing for pose datasets
        (ref: trainers/vid2vid.py:206-233 pre_process), plus the
        off-step teacher: under the device-prefetch pipeline this hook
        runs in the producer thread, so the FlowNet2 forward overlaps
        the main step and its (flow, conf) outputs ride the prefetch
        queue as committed sharded arrays."""
        if self.flow_cache is not None and current_iteration >= 0:
            # eval/test sweeps (current_iteration == -1) never consume
            # flow supervision — don't pay the teacher for them
            data = self.flow_cache.attach(dict(data))
        elif isinstance(data, dict) and "_flow_cache" in data:
            # dataset-side payloads with no consumer (cache disabled at
            # the trainer after the dataset attached them) must not
            # reach the jit boundary
            data = dict(data)
            data.pop("_flow_cache")
        pose_cfg = cfg_get(self.cfg.data, "for_pose_dataset", None)
        if pose_cfg is not None and \
                "pose_maps-densepose" in (cfg_get(self.cfg.data,
                                                  "input_labels", []) or []):
            from imaginaire_tpu.model_utils.fs_vid2vid import (
                pre_process_densepose,
            )

            data = dict(data)
            data["label"] = pre_process_densepose(
                pose_cfg, np.asarray(data["label"]),
                is_infer=current_iteration < 0)
            if "ref_labels" in data:
                # few-shot reference labels share the scale; never drop
                # parts from them (ref preprocesses few_shot_label with
                # is_infer=True)
                data["ref_labels"] = pre_process_densepose(
                    pose_cfg, np.asarray(data["ref_labels"]), is_infer=True)
        return data

    # --------------------------------------------------------------- state

    def _frame0(self, data):
        label = data["label"]
        images = data["images"]
        if label.ndim == 5:
            label = label[:, 0]
        if images.ndim == 5:
            images = images[:, 0]
        return {"label": label, "image": images}

    def init_state(self, key, data):
        """All generator submodules (temporal path included) and all
        temporal discriminator scales materialize here — the curriculum
        only flips static flags later."""
        data = to_device(numeric_only(dict(data)))
        data_t = self._frame0(data)
        k_g, k_d, k_loss, k_noise, k_rg, k_rd = jax.random.split(key, 6)
        # lint: allow(bare-jit) -- one-shot flax init at t=0
        vars_G = dict(jax.jit(
            lambda rngs, d: self.net_G.init(rngs, d, training=True,
                                            init_all=True))(
            {"params": k_g, "noise": k_noise}, data_t))
        state: Dict[str, Any] = {
            "vars_G": vars_G,
            "opt_G": init_optimizer_state(self.tx_G, vars_G["params"],
                                          self.partition),
            "step": jnp.zeros((), jnp.int32),
            "rng_G": k_rg,
            "rng_D": k_rd,
            "loss_params": self.init_loss_params(k_loss),
        }
        b, h, w, _ = data_t["label"].shape
        c_img = data_t["image"].shape[-1]
        fake_out = {"fake_images": jnp.zeros_like(data_t["image"]),
                    "fake_raw_images": jnp.zeros_like(data_t["image"])}
        tD = self.num_frames_D
        stacks = {f"s{s}": (jnp.zeros((b, tD - 1, h, w, c_img)),
                            jnp.zeros((b, tD - 1, h, w, c_img)))
                  for s in range(self.num_temporal_scales)}
        # lint: allow(bare-jit) -- one-shot flax init at t=0
        vars_D = dict(jax.jit(
            lambda rngs, d, f, st: self.net_D.init(
                rngs, d, f, past_stacks=st, training=True))(
            {"params": k_d, "dropout": k_d}, data_t, fake_out,
            self._stacks_list(stacks)))
        state["vars_D"] = vars_D
        state["opt_D"] = init_optimizer_state(self.tx_D, vars_D["params"],
                                              self.partition)
        state["step_D"] = jnp.zeros((), jnp.int32)
        if self.model_average:
            state["ema_G"] = ema_init(
                vars_G["params"], vars_G.get("spectral"),
                remove_sn=self.model_average_remove_sn)
            state["num_ema_updates"] = jnp.zeros((), jnp.int32)
        # 2-D partition plan (parallel/partition.py): commit the state
        # under its shardings before the first per-frame program compiles
        self.state = self._place_state(state)
        return self.state

    def _stacks_list(self, stacks):
        """dict {'s0': (real, fake)} -> list indexed by scale, None when
        absent (the discriminator's past_stacks contract)."""
        return [stacks.get(f"s{s}") for s in range(self.num_temporal_scales)]

    # ------------------------------------------------------------ forwards

    def _apply_G(self, vars_G, data_t, rng, training):
        return self.net_G.apply(vars_G, data_t, training=training,
                                rngs={"noise": rng}, mutable=list(MUTABLE))

    def _apply_D(self, vars_D, data_t, out, stacks, training, mutable=False):
        kwargs = dict(past_stacks=self._stacks_list(stacks),
                      training=training)
        if mutable:
            return self.net_D.apply(vars_D, data_t, out,
                                    mutable=list(MUTABLE), **kwargs)
        return self.net_D.apply(vars_D, data_t, out, **kwargs)

    def _gan_fm_losses(self, d_out_part, dis_update, sample_weight=None):
        """(ref: trainers/vid2vid.py:609-635). ``sample_weight`` carries
        the region-validity mask of additional discriminators."""
        fake = d_out_part["pred_fake"]
        real = d_out_part["pred_real"]
        if dis_update:
            gan = 0.5 * (
                gan_loss(fake["outputs"], False, self.gan_mode, True,
                         sample_weight=sample_weight)
                + gan_loss(real["outputs"], True, self.gan_mode, True,
                           sample_weight=sample_weight))
            return gan, None
        gan = gan_loss(fake["outputs"], True, self.gan_mode, False,
                       sample_weight=sample_weight)
        fm = feature_matching_loss(fake["features"], real["features"],
                                   sample_weight=sample_weight)
        return gan, fm

    def _region_d_losses(self, d_out, losses, dis_update):
        """Collect per-region (face/hand) GAN/FM losses; the validity
        mask of fixed-shape region crops weights out absent regions
        (ref: trainers/vid2vid.py additional-D loss collection)."""
        for name in self.add_dis_names:
            if name in d_out:
                gan_r, fm_r = self._gan_fm_losses(
                    d_out[name], dis_update=dis_update,
                    sample_weight=d_out[name].get("valid"))
                losses[f"GAN_{name}"] = gan_r
                if not dis_update:
                    losses[f"FeatureMatching_{name}"] = fm_r
        return losses

    def _split_data_t(self, data):
        data = dict(data)
        stacks = data.pop("past_stacks", {})
        return data, stacks

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng,
                    training=True):
        """Per-frame G losses (ref: trainers/vid2vid.py:469-553)."""
        data_t, stacks = self._split_data_t(data)
        out, new_mut = self._apply_G(vars_G, data_t, rng, training)
        d_out = self._apply_D(vars_D, data_t, out, stacks, training)

        losses = {}
        losses["GAN"], losses["FeatureMatching"] = self._gan_fm_losses(
            d_out["indv"], dis_update=False)
        if self.perceptual is not None:
            losses["Perceptual"] = self.perceptual(
                loss_params["perceptual"], out["fake_images"],
                data_t["image"])
        if "L1" in self.weights:
            losses["L1"] = jnp.mean(jnp.abs(out["fake_images"]
                                            - data_t["image"]))
        if "raw" in d_out:
            raw_gan, raw_fm = self._gan_fm_losses(d_out["raw"],
                                                  dis_update=False)
            losses["GAN"] = losses["GAN"] + raw_gan
            losses["FeatureMatching"] = losses["FeatureMatching"] + raw_fm
            if self.perceptual is not None:
                from imaginaire_tpu.model_utils.fs_vid2vid import get_fg_mask

                fg = get_fg_mask(data_t["label"], self.has_fg)
                losses["Perceptual"] = losses["Perceptual"] + self.perceptual(
                    loss_params["perceptual"],
                    out["fake_raw_images"] * fg, data_t["image"] * fg)
        if self.use_flow and out.get("warped_images") is not None:
            cached_gt = data_t.get("flow_gt") is not None
            if self.flow_net_wrapper is not None and \
                    (cached_gt or
                     data_t.get("real_prev_image") is not None):
                from imaginaire_tpu.losses.flow import FlowLoss

                if cached_gt:
                    # amortized teacher: (flow, conf) arrived with the
                    # batch (flow/cache.py) — the step program contains
                    # no FlowNet2 cascade
                    flow_loss = FlowLoss(None, has_fg=self.has_fg)
                    loss_data = {"image": data_t["image"],
                                 "flow_gt": data_t["flow_gt"],
                                 "conf_gt": data_t["conf_gt"]}
                else:
                    fn_params = loss_params["flownet"]
                    flow_loss = FlowLoss(
                        lambda a, b: self.flow_net_wrapper._flow_fn(
                            fn_params, a, b),
                        has_fg=self.has_fg)
                    loss_data = {"image": data_t["image"],
                                 "real_prev_image":
                                     data_t["real_prev_image"]}
                l1, warp, mask_l = flow_loss(loss_data, out)
                losses["Flow_L1"] = l1
                losses["Flow_Warp"] = warp
                losses["Flow_Mask"] = mask_l
            else:
                # fork semantics: warp-consistency masked L1; stop-grad the
                # occlusion mask (it weights its own loss — a learnable
                # weight has a degenerate mask->0 optimum)
                losses["Flow"] = masked_l1_loss(
                    out["fake_images"], out["warped_images"],
                    jax.lax.stop_gradient(out["fake_occlusion_masks"]))
        for s in range(self.num_temporal_scales):
            if f"temporal_{s}" in d_out:
                gan_t, fm_t = self._gan_fm_losses(d_out[f"temporal_{s}"],
                                                  dis_update=False)
                losses[f"GAN_T{s}"] = gan_t
                losses[f"FeatureMatching_T{s}"] = fm_t
        losses = self._region_d_losses(d_out, losses, dis_update=False)
        return losses, new_mut, out

    def dis_forward(self, vars_G, vars_D, loss_params, data, rng,
                    training=True):
        """Per-frame D losses (ref: trainers/vid2vid.py:555-599)."""
        data_t, stacks = self._split_data_t(data)
        out, _ = self._apply_G(vars_G, data_t, rng, training)
        out = jax.lax.stop_gradient(
            {k: v for k, v in out.items() if v is not None})
        d_out, new_mut_D = self._apply_D(vars_D, data_t, out, stacks,
                                         training, mutable=True)
        losses = {}
        losses["GAN"], _ = self._gan_fm_losses(d_out["indv"], dis_update=True)
        # GAN-balance diagnostics: per-frame D accuracy on the image D
        # (unweighted keys never enter the total)
        losses["D_real_acc"], losses["D_fake_acc"] = dis_accuracy(
            d_out["indv"]["pred_real"]["outputs"],
            d_out["indv"]["pred_fake"]["outputs"], self.gan_mode)
        if "raw" in d_out:
            raw_gan, _ = self._gan_fm_losses(d_out["raw"], dis_update=True)
            losses["GAN"] = losses["GAN"] + raw_gan
        for s in range(self.num_temporal_scales):
            if f"temporal_{s}" in d_out:
                gan_t, _ = self._gan_fm_losses(d_out[f"temporal_{s}"],
                                               dis_update=True)
                losses[f"GAN_T{s}"] = gan_t
        losses = self._region_d_losses(d_out, losses, dis_update=True)
        return losses, new_mut_D

    # --------------------------------------------------------- jitted steps

    def _vid_gen_step_fn(self, state, data):
        step0 = state["step"]
        rng = jax.random.fold_in(state["rng_G"], step0)

        def loss_fn(params_G):
            vars_G = dict(state["vars_G"],
                          params=self._to_compute_dtype(params_G))
            losses, new_mut, out = self.gen_forward(
                vars_G, self._cast_net_vars(state["vars_D"]),
                state["loss_params"], self._to_compute_dtype(data), rng)
            losses = {k: v.astype(jnp.float32) for k, v in losses.items()}
            total = self._total(losses)
            return total, (dict(losses, total=total), new_mut,
                           out["fake_images"])

        (_, (losses, new_mut, fake)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["vars_G"]["params"])
        if self.clip_grad_norm_G:
            grads, _ = optax.clip_by_global_norm(
                self.clip_grad_norm_G).update(grads, optax.EmptyState())
        updates, new_opt = self.tx_G.update(
            grads, state["opt_G"], state["vars_G"]["params"])
        new_params = optax.apply_updates(state["vars_G"]["params"], updates)
        new_params, new_opt, new_mut, ok, grad_norm = self._audit_guard(
            losses, grads, state, "vars_G", "opt_G",
            new_params, new_opt, new_mut)
        new_vars_G = dict(state["vars_G"], params=new_params, **new_mut)
        state = dict(state, vars_G=new_vars_G, opt_G=new_opt,
                     step=step0 + 1)
        if self.model_average:
            n = state["num_ema_updates"] + 1
            state["ema_G"] = ema_update(
                state["ema_G"], new_params, n,
                beta=self.model_average_beta,
                start_iteration=self.model_average_start,
                spectral=new_vars_G.get("spectral"),
                remove_sn=self.model_average_remove_sn)
            state["num_ema_updates"] = n
        health = self._audit_health(
            ok, grad_norm, step0, grads, new_params, updates,
            spectral=new_vars_G.get("spectral"),
            ema=state.get("ema_G") if self.model_average else None)
        return (self._constrain_state(state), losses,
                jax.lax.stop_gradient(fake), health)

    def _vid_dis_step_fn(self, state, data):
        step0 = state["step_D"]
        rng = jax.random.fold_in(state["rng_D"], step0)

        def loss_fn(params_D):
            vars_D = dict(state["vars_D"],
                          params=self._to_compute_dtype(params_D))
            losses, new_mut = self.dis_forward(
                self._cast_net_vars(state["vars_G"]), vars_D,
                state["loss_params"], self._to_compute_dtype(data), rng)
            losses = {k: v.astype(jnp.float32) for k, v in losses.items()}
            total = self._total(losses)
            return total, (dict(losses, total=total), new_mut)

        (_, (losses, new_mut)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["vars_D"]["params"])
        if self.clip_grad_norm_D:
            grads, _ = optax.clip_by_global_norm(
                self.clip_grad_norm_D).update(grads, optax.EmptyState())
        updates, new_opt = self.tx_D.update(
            grads, state["opt_D"], state["vars_D"]["params"])
        new_params = optax.apply_updates(state["vars_D"]["params"], updates)
        new_params, new_opt, new_mut, ok, grad_norm = self._audit_guard(
            losses, grads, state, "vars_D", "opt_D",
            new_params, new_opt, new_mut)
        new_vars_D = dict(state["vars_D"], params=new_params, **new_mut)
        state = dict(state, vars_D=new_vars_D,
                     opt_D=new_opt, step_D=step0 + 1)
        health = self._audit_health(
            ok, grad_norm, step0, grads, new_params, updates,
            spectral=new_vars_D.get("spectral"))
        return self._constrain_state(state), losses, health

    # ------------------------------------------------------------- rollout

    def _get_data_t(self, data, t, prev_labels, prev_images):
        """(ref: trainers/vid2vid.py:637-668)."""
        label = data["label"][:, t] if data["label"].ndim == 5 \
            else data["label"]
        image = data["images"][:, t] if data["images"].ndim == 5 \
            else data["images"]
        data_t = {"label": label, "image": image}
        if prev_images is not None:
            data_t["prev_labels"] = prev_labels
            data_t["prev_images"] = prev_images
        if t > 0 and data["images"].ndim == 5:
            # real previous frame for the FlowNet2 teacher's GT flow
            data_t["real_prev_image"] = data["images"][:, t - 1]
            if data.get("flow_gt") is not None:
                # amortized teacher output (flow/cache.py):
                # flow_gt[:, t-1] supervises frame t against frame t-1
                data_t["flow_gt"] = data["flow_gt"][:, t - 1]
                data_t["conf_gt"] = data["conf_gt"][:, t - 1]
        return data_t

    def _past_stacks(self, past_real, past_fake):
        """Per-scale strided past stacks from the ring buffers
        (ref: discriminators/fs_vid2vid.py:225-256); the current frame is
        appended inside the discriminator so G gradients reach it."""
        stacks = {}
        if past_real is None:
            return stacks
        tD = self.num_frames_D
        L = past_real.shape[1]
        for s in range(self.num_temporal_scales):
            # buffer here EXCLUDES the current frame (the discriminator
            # appends it so G gradients reach it), hence >= t_span where
            # get_skipped_frames (current included) uses > t_span
            t_step, t_span = skip_stride_span(tD, s)
            if L >= t_span:
                stacks[f"s{s}"] = (past_real[:, -t_span::t_step],
                                   past_fake[:, -t_span::t_step])
        return stacks

    def _rollout_tail_fn(self, state, buffers, tail, constants):
        """Steady-state rollout tail as ONE program: lax.scan over frames
        with (trainer state, history ring buffers) in carry and the
        per-frame D then G updates in the body (SURVEY §7 hard-part #3).

        Replaces 2 host dispatches + host-side ring-buffer concats per
        frame with a single XLA while-loop — the compiler pipelines the
        buffer rolls into the step programs, and dispatch/tunnel latency
        is paid once per clip instead of twice per frame. Only valid
        once every buffer has its steady shape (see gen_update's
        t_steady); the warm-up frames keep the per-frame programs, whose
        shapes differ structurally (no prev / growing stacks).
        """
        prev_labels, prev_images, past_real, past_fake = buffers
        use_past = self.num_temporal_scales > 0 and past_real is not None
        tD = self.num_frames_D
        max_prev = (tD ** max(self.num_temporal_scales - 1, 0)) * (tD - 1)

        def body(carry, xs):
            if use_past:
                state, prev_labels, prev_images, past_real, past_fake = carry
            else:
                state, prev_labels, prev_images = carry
            data_t = dict(constants, label=xs["label"], image=xs["image"],
                          real_prev_image=xs["real_prev_image"],
                          prev_labels=prev_labels, prev_images=prev_images)
            if "flow_gt" in xs:
                data_t["flow_gt"] = xs["flow_gt"]
                data_t["conf_gt"] = xs["conf_gt"]
            data_t["past_stacks"] = (
                self._past_stacks(past_real, past_fake) if use_past else {})
            # per-frame health summaries are dropped inside the scan
            # (stacking them would defeat the fixed-size contract); the
            # in-graph non-finite guard still protects every tail frame
            state, d_losses, _ = self._vid_dis_step_fn(state, data_t)
            state, g_losses, fake, _ = self._vid_gen_step_fn(state, data_t)
            prev_labels = concat_frames(prev_labels, xs["label"],
                                        self.num_frames_G - 1)
            prev_images = concat_frames(prev_images, fake,
                                        self.num_frames_G - 1)
            if use_past:
                past_real = concat_frames(past_real, xs["image"], max_prev)
                past_fake = concat_frames(past_fake, fake, max_prev)
                carry = (state, prev_labels, prev_images, past_real,
                         past_fake)
            else:
                carry = (state, prev_labels, prev_images)
            return carry, (d_losses, g_losses)

        xs = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 1, 0), tail)
        carry0 = ((state, prev_labels, prev_images, past_real, past_fake)
                  if use_past else (state, prev_labels, prev_images))
        carry, (d_hist, g_hist) = jax.lax.scan(body, carry0, xs)
        return carry[0], d_hist, g_hist

    def _rollout_scan_constants(self, data):
        """Per-frame-constant keys the scan-tail body must thread into
        each data_t. A subclass that overrides ``_get_data_t`` MUST also
        override this to declare its extra keys (fs-vid2vid does) — the
        scan body builds data_t itself and would otherwise silently drop
        them; _scan_eligible enforces the pairing."""
        return {}

    def _scan_eligible(self, data, seq_len):
        """The scan tail is semantics-preserving only when the per-frame
        host hooks are the defaults (wc-vid2vid colors point clouds per
        frame), any ``_get_data_t`` override has declared its constant
        keys, and the clip is a real 5-D sequence."""
        cls = type(self)
        data_t_accounted = (
            cls._get_data_t is Trainer._get_data_t
            or cls._rollout_scan_constants
            is not Trainer._rollout_scan_constants)
        return (self.rollout_scan and seq_len > 1
                and data["images"].ndim == 5
                and data["label"].ndim == 5  # static 4-D labels use the
                # per-frame path (the tail slices labels along time)
                and data_t_accounted
                and cls._frame_override is Trainer._frame_override
                and cls._after_gen_frame is Trainer._after_gen_frame
                and self._scan_keys_consistent(data, seq_len))

    def _scan_keys_consistent(self, data, seq_len):
        """Runtime cross-check of the ``_rollout_scan_constants``
        pairing: probe ``_get_data_t`` at a steady-state frame and
        require every key it emits to be one the scan body rebuilds
        (label/image/real_prev_image/prev_*/past_stacks) or a declared
        constant. An override whose extra keys vary per frame would
        otherwise silently train the tail on stale constants — disable
        the scan instead. Verdict cached per batch key-set (the probe
        slices device arrays; once per data layout is enough)."""
        cache_key = tuple(sorted(str(k) for k in data))
        cached = getattr(self, "_scan_key_verdict", None)
        if cached is not None and cached[0] == cache_key:
            return cached[1]
        t_probe = min(max(self.num_frames_G - 1, 1), seq_len - 1)
        probe = self._get_data_t(data, t_probe,
                                 data["label"][:, :1],
                                 data["images"][:, :1])
        rebuilt = {"label", "image", "prev_labels", "prev_images",
                   "real_prev_image", "past_stacks", "flow_gt", "conf_gt"}
        rebuilt |= set(self._rollout_scan_constants(data))
        extra = sorted(k for k in probe
                       if not str(k).startswith("_") and k not in rebuilt)
        if extra:
            print(f"rollout_scan disabled: _get_data_t emits per-frame "
                  f"keys {extra} the scan tail would not rebuild")
        self._scan_key_verdict = (cache_key, not extra)
        return not extra

    def _pipeline_eligible(self, data, seq_len):
        """The software-pipelined dispatch (parallel/pipeline.py) defers
        the monitor's one-behind finite polls by ``depth`` frames. That is
        bit-identical to the sequential loop — same programs, same inputs,
        same observation order — except for three cases it must refuse:
        per-frame host hooks (wc-vid2vid reads back each generated frame,
        so deferral would feed its renderer stale data), the ``rollback``
        non-finite policy (its per-observation state snapshots must be
        taken before later frames mutate the state), and overridden
        ``_frame_override`` (same readback coupling)."""
        cls = type(self)
        return (self.pipeline_cfg["enabled"]
                and self._rollout_pipeline.depth > 0
                and cls._frame_override is Trainer._frame_override
                and cls._after_gen_frame is Trainer._after_gen_frame
                and getattr(self.diag, "on_nonfinite", "halt") != "rollback")

    def gen_update(self, data):
        """Interleaved per-frame D/G rollout (ref: vid2vid.py:238-288).

        With trainer.rollout_scan, frames past the ring-buffer warm-up
        run inside one lax.scan program (_rollout_tail_fn)."""
        # the gen_step span covers the whole rollout (per-frame dis_step
        # spans nest inside it — D updates happen here, dis_update is a
        # no-op for this family)
        with telemetry.span("gen_step", step=self.current_iteration):
            return self._gen_update_rollout(data)

    def _gen_update_rollout(self, data):
        if self.flow_cache is not None and isinstance(data, dict) \
                and "flow_gt" not in data \
                and getattr(data.get("images"), "ndim", 0) == 5:
            # safety net for callers that skip start_of_iteration
            # (direct gen_update in tests/benches): the amortized
            # teacher must still supply the supervision the cached step
            # program expects
            data = self.flow_cache.attach(dict(data))
        data = numeric_only(data)
        seq_len = (data["images"].shape[1] if data["images"].ndim == 5
                   else 1)
        tD = self.num_frames_D
        max_prev = (tD ** max(self.num_temporal_scales - 1, 0)) * (tD - 1)
        # first frame at which every history buffer has its final shape
        t_steady = max(self.num_frames_G - 1,
                       max_prev if self.num_temporal_scales > 0 else 0, 1)
        use_scan = self._scan_eligible(data, seq_len) and seq_len > t_steady
        use_pipeline = self._pipeline_eligible(data, seq_len)
        head_len = t_steady if use_scan else seq_len
        # both paths run the same dispatch-gap/overlap instrument; the
        # sequential loop at depth=0 keeps its inline observes, so the
        # meters measure the old behaviour unchanged
        pipe = self._rollout_pipeline if use_pipeline else self._seq_pipeline
        pipe.begin()
        tm = telemetry.get()
        if use_pipeline and pipe.overlap_collectives:
            # ISSUE-14 satellite: loop-invariant per-frame operands
            # (fs-vid2vid's reference window) gather ONCE per rollout
            # instead of once per frame program — the gather overlaps
            # frame 0's issue window and the per-frame collective bytes
            # drop out of the graph-audit counters
            data, hoisted = hoist_invariants(
                data, self._rollout_scan_constants(data))
            if hoisted:
                tm.counter("pipeline/hoisted_bytes", hoisted,
                           step=self.current_iteration)
        prev_labels = prev_images = None
        past_real = past_fake = None
        t0 = time.time() if self.speed_benchmark else None
        d_hist, g_hist = [], []
        for t in range(head_len):
            data_t = self._get_data_t(data, t, prev_labels, prev_images)
            fake = self._frame_override(data_t)
            if fake is None:
                data_t["past_stacks"] = self._past_stacks(past_real,
                                                          past_fake)
                # keys starting with '_' carry host-side objects (e.g.
                # wc-vid2vid point clouds) and must not cross the jit
                # boundary
                data_jit = {k: v for k, v in data_t.items()
                            if not k.startswith("_")}
                if use_pipeline:
                    # pipelined: issue D_t/G_t back-to-back and DEFER the
                    # monitor's finite polls by `depth` frames — the host
                    # runs ahead slicing/dispatching while frame t's
                    # programs and their gradient all-reduce are in
                    # flight. Observation ORDER is unchanged; the DAG
                    # marks prove the donated state handle threads
                    # legally (G_{t-1} returned before D_t consumes it).
                    with pipe.frame(t, tm, self.current_iteration):
                        pipe.mark("data", t)
                        with telemetry.span("dis_step",
                                            step=self.current_iteration):
                            self.state, d_losses, d_health = \
                                self._jit_vid_dis(self.state, data_jit)
                        pipe.mark("D", t)
                        self.state, g_losses, fake, g_health = \
                            self._jit_vid_gen(self.state, data_jit)
                        pipe.mark("G", t)
                        pipe.mark("grads", t)
                    pipe.defer(lambda dl=d_losses, dh=d_health,
                               gl=g_losses, gh=g_health, dj=data_jit,
                               it=self.current_iteration: (
                        self.diag.observe(self, "D", dl, dh, dj, it),
                        self.diag.observe(self, "G", gl, gh, dj, it)))
                else:
                    with pipe.frame(t, tm, self.current_iteration):
                        pipe.mark("data", t)
                        with telemetry.span("dis_step",
                                            step=self.current_iteration):
                            self.state, d_losses, d_health = \
                                self._jit_vid_dis(self.state, data_jit)
                        pipe.mark("D", t)
                    # per-frame health hooks: each frame's D and G update
                    # reports its own summary/finite flag (the monitor's
                    # cadence runs on the per-frame step counters). The
                    # one-behind poll inside observe is what the frame
                    # windows exclude — it lands in the dispatch gap.
                    self.diag.observe(self, "D", d_losses, d_health,
                                      data_jit, self.current_iteration)
                    with pipe.frame(t, tm, self.current_iteration):
                        self.state, g_losses, fake, g_health = \
                            self._jit_vid_gen(self.state, data_jit)
                        pipe.mark("G", t)
                        pipe.mark("grads", t)
                    self.diag.observe(self, "G", g_losses, g_health,
                                      data_jit, self.current_iteration)
                d_hist.append(d_losses)
                g_hist.append(g_losses)
                if self.num_temporal_scales > 0:
                    past_real = concat_frames(past_real, data_t["image"],
                                              max_prev)
                    past_fake = concat_frames(past_fake, fake, max_prev)
            else:
                pipe.override(t)
            self._after_gen_frame(data_t, fake)
            prev_labels = concat_frames(prev_labels, data_t["label"],
                                        self.num_frames_G - 1)
            prev_images = concat_frames(prev_images, fake,
                                        self.num_frames_G - 1)
        # drain every deferred observation before anything else consumes
        # the state: the monitor leaves this rollout in exactly the state
        # the sequential loop would (one pending entry, same order)
        pipe.finish(tm, step=self.current_iteration)
        tail_counts = 0
        if use_scan:
            # constants every frame of the tail shares (few-shot refs)
            constants = self._rollout_scan_constants(data)
            tail = {"label": data["label"][:, t_steady:],
                    "image": data["images"][:, t_steady:],
                    "real_prev_image": data["images"][:, t_steady - 1:-1]}
            if data.get("flow_gt") is not None:
                # pair index t-1 supervises frame t
                tail["flow_gt"] = data["flow_gt"][:, t_steady - 1:]
                tail["conf_gt"] = data["conf_gt"][:, t_steady - 1:]
            buffers = (prev_labels, prev_images, past_real, past_fake)
            self.state, d_tail, g_tail = self._jit_rollout_tail(
                self.state, buffers, tail, constants)
            tail_counts = seq_len - t_steady
            d_hist.append({k: jnp.sum(v) for k, v in d_tail.items()})
            g_hist.append({k: jnp.sum(v) for k, v in g_tail.items()})
        if self.speed_benchmark:
            # lint: allow(host-sync) -- speed_benchmark timing fence
            jax.block_until_ready(self.state["vars_G"]["params"])
            self._meter("time/gen_step").write(time.time() - t0)

        def mean_losses(hist, tail_n):
            # the last entry may be a summed tail worth tail_n frames
            keys = set().union(*(h.keys() for h in hist))
            out = {}
            for k in keys:
                total = 0.0
                count = 0
                for i, h in enumerate(hist):
                    if k not in h:
                        continue
                    is_tail = tail_n and i == len(hist) - 1
                    total = total + h[k]
                    count += tail_n if is_tail else 1
                out[k] = total / count
            return out

        d_losses = mean_losses(d_hist, tail_counts)
        g_losses = mean_losses(g_hist, tail_counts)
        self._log_losses("dis_update", d_losses)
        self._log_losses("gen_update", g_losses)
        return g_losses

    def _end_of_iteration(self, data, current_epoch, current_iteration):
        """Flush the amortized-teacher stats into the meters (the
        DevicePrefetcher drain_stats pattern): flow_cache/hit_rate and
        flow_cache/compute_ms land beside the loss meters on
        logging_iter, never a device sync."""
        if self.flow_cache is not None:
            self.write_data_meters(self.flow_cache.drain_stats())

    def _after_gen_frame(self, data_t, fake):
        """Hook after each frame's G step (wc-vid2vid colors its point
        cloud here). Default: no-op."""
        pass

    def _frame_override(self, data_t):
        """Hook: return a replacement fake frame for ``data_t``, or None
        to run the normal D/G steps. Override frames skip both updates
        and the temporal-D past stacks but still feed the prev-frame
        history (ref: trainers/vid2vid.py:264-284, the
        ``fake_images_source == 'pretrained'`` gating; wc-vid2vid's
        frozen single-image takeover lives here). Default: None."""
        return None

    def _start_of_test_sequence(self, data):
        """Hook before generating a test sequence (wc-vid2vid resets its
        renderer here, ref: trainers/wc_vid2vid.py:70-87). No-op."""
        pass

    def recalculate_model_average_batch_norm_statistics(self,
                                                        data_loader=None):
        """No-op for the video family: the base implementation feeds
        whole loader batches into _apply_G, which here takes per-frame
        data_t — and the reference likewise never recalibrates EMA BN
        stats for its video trainers (only spade/pix2pixHD do,
        ref: trainers/spade.py:196)."""
        return

    def reset(self):
        """Reset per-sequence rollout state before generating a new test
        sequence (ref: trainers/vid2vid.py:298-312). The sequence
        counter keeps advancing so each sequence draws distinct noise."""
        self._test_prev_labels = None
        self._test_prev_images = None
        self._test_t = 0
        self._test_seq = getattr(self, "_test_seq", -1) + 1

    def _generate_frame(self, data, t):
        """Generate frame ``t`` of ``data`` carrying the stored rollout
        history; advances the history buffers."""
        data_t = self._get_data_t(data, t,
                                  getattr(self, "_test_prev_labels", None),
                                  getattr(self, "_test_prev_images", None))
        fake = self._frame_override(data_t)
        if fake is None:
            out, _ = self._apply_G(
                self.inference_params(),
                {k: v for k, v in data_t.items() if not k.startswith("_")},
                jax.random.PRNGKey(getattr(self, "_test_seq", 0) * 100003
                                   + getattr(self, "_test_t", 0)),
                training=False)
            fake = out["fake_images"]
        self._after_gen_frame(data_t, fake)
        self._test_prev_labels = concat_frames(
            getattr(self, "_test_prev_labels", None), data_t["label"],
            self.num_frames_G - 1)
        self._test_prev_images = concat_frames(
            getattr(self, "_test_prev_images", None), fake,
            self.num_frames_G - 1)
        self._test_t = getattr(self, "_test_t", 0) + 1
        return fake

    def test_single(self, data):
        """Generate the next frame of the current test sequence — the
        per-frame entry the video FID/eval harness drives
        (ref: trainers/vid2vid.py:419-467, evaluation/common.py:79-158).
        Call reset() at each sequence start."""
        data = to_device(self._start_of_iteration(
            numeric_only(dict(data)), -1))
        return {"fake_images": self._generate_frame(data, 0)}

    def test(self, data_loader, output_dir, inference_args=None):
        """Frame-by-frame video generation (ref: trainers/vid2vid.py:
        330-417). With a sequence-pinning dataset, every inference
        sequence is rolled out frame by frame; direct batch iterables
        (tests, ad-hoc data) roll out each batch's time axis."""
        inference_args = dict(inference_args or {})
        dataset = getattr(data_loader, "dataset", None)
        if dataset is not None \
                and getattr(dataset, "is_inference", False) \
                and hasattr(dataset, "set_inference_sequence_idx"):
            return self._test_sequences(dataset, output_dir,
                                        inference_args)
        return self._test_batches(data_loader, output_dir)

    def _inference_sequence_indices(self, dataset, inference_args):
        # sequences shard round-robin per process, mirroring the video
        # FID harness (evaluation/common.py), so multi-host inference
        # neither duplicates rollouts nor races on output files
        return list(range(dataset.num_inference_sequences()))[
            jax.process_index()::jax.process_count()]

    def _frame_loader(self, dataset):
        """Batch-1 unsharded loader over a pinned sequence's frames —
        the strictly-sequential contract test_single/_generate_frame
        require (frames of one sequence must never rank-shard)."""
        from imaginaire_tpu.data.loader import DataLoader

        return DataLoader(dataset, batch_size=1, shuffle=False,
                          drop_last=False, shard_by_process=False)

    def _pin_inference_sequence(self, dataset, seq_idx, inference_args):
        dataset.set_inference_sequence_idx(seq_idx)

    def _save_test_frame(self, output_dir, key, t, fake):
        import os

        from imaginaire_tpu.utils.visualization import (
            save_image_grid,
            tensor2im,
        )

        path = os.path.join(output_dir, str(key), f"{t:04d}.jpg")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        save_image_grid(
            # lint: allow(host-sync) -- offline inference image dump
            [tensor2im(np.asarray(jax.device_get(fake))[0])], path)

    def _test_sequences(self, dataset, output_dir, inference_args):
        """(ref: trainers/vid2vid.py:339-417): pin each sequence, build
        a batch-1 unsharded frame loader, roll out with carried
        generated history."""
        import os

        os.makedirs(output_dir, exist_ok=True)
        frame_loader = self._frame_loader(dataset)
        for seq_idx in self._inference_sequence_indices(dataset,
                                                        inference_args):
            self._pin_inference_sequence(dataset, seq_idx, inference_args)
            self.reset()
            started = False
            for t, data in enumerate(frame_loader):
                data = self.start_of_iteration(data, current_iteration=-1)
                data = numeric_only(data)
                if not started:
                    self._start_of_test_sequence(data)
                    started = True
                fake = self._generate_frame(data, 0)
                self._save_test_frame(output_dir, f"seq{seq_idx:04d}", t,
                                      fake)

    def _test_batches(self, data_loader, output_dir):
        import os

        os.makedirs(output_dir, exist_ok=True)
        for it, data in enumerate(data_loader):
            data = self.start_of_iteration(data, current_iteration=-1)
            key = data.get("key", f"{it:06d}")
            if isinstance(key, (list, tuple)):
                key = key[0]
            if not isinstance(key, (str, bytes)):
                key = f"{it:06d}"
            data = numeric_only(data)
            self.reset()
            self._start_of_test_sequence(data)
            seq_len = (data["images"].shape[1]
                       if data["images"].ndim == 5 else 1)
            for t in range(seq_len):
                fake = self._generate_frame(data, t)
                self._save_test_frame(output_dir, str(key), t, fake)

    def _compute_fid(self):
        """Video FID over generated sequences
        (ref: trainers/vid2vid.py:697-757): shard the validation
        sequences, reset + roll out per sequence via test_single, gather
        Inception activations."""
        if self.val_data_loader is None:
            return None
        dataset = getattr(self.val_data_loader, "dataset", None)
        if dataset is None or not hasattr(dataset,
                                          "set_inference_sequence_idx"):
            print("Video FID skipped: val dataset has no sequence "
                  "pinning (set_inference_sequence_idx).")
            return None
        import os

        from imaginaire_tpu.evaluation import compute_fid

        try:
            extractor = self._fid_extractor()
        except FileNotFoundError as e:
            print(f"FID skipped: {e}")
            return None
        logdir = cfg_get(self.cfg, "logdir", ".")
        data_name = cfg_get(cfg_get(self.cfg, "data", {}), "name", "data")
        fid_path = os.path.join(logdir,
                                f"real_stats_video_{data_name}.npz")
        sample_size = cfg_get(self.cfg.trainer, "num_videos_to_test", 64)
        return float(compute_fid(
            fid_path, self._frame_loader(dataset), extractor, None,
            trainer=self, is_video=True, sample_size=sample_size))

    def _extra_metric_activations(self, extractor):
        """Video-family activations for KID/PRDC (base template at
        trainers/base.py::compute_extra_metrics): the same pinned-sequence
        rollout as video FID (``get_video_activations``); real-set
        activations are cached across a checkpoint sweep
        (ref: evaluation/kid.py:29, prdc.py)."""
        dataset = getattr(self.val_data_loader, "dataset", None)
        if dataset is None or not hasattr(dataset,
                                          "set_inference_sequence_idx"):
            print("Video KID/PRDC skipped: val dataset has no sequence "
                  "pinning (set_inference_sequence_idx).")
            return None

        from imaginaire_tpu.evaluation.common import get_video_activations

        sample_size = cfg_get(self.cfg.trainer, "num_videos_to_test", 64)
        frame_loader = self._frame_loader(dataset)
        act_fake = get_video_activations(frame_loader, "images",
                                         "fake_images", self, extractor,
                                         sample_size=sample_size)
        data_name = cfg_get(cfg_get(self.cfg, "data", {}), "name", "data")
        act_real = self._cached_real_activations(
            f"real_acts_video_{data_name}.npz",
            lambda: get_video_activations(frame_loader, "images",
                                          "fake_images", None, extractor,
                                          sample_size=sample_size))
        return act_real, act_fake

    def dis_update(self, data):
        """D updates happen inside gen_update's rollout
        (ref: trainers/vid2vid.py:290-296)."""
        return None

    def _register_step_flops(self, data):
        """No-op: the video families step through per-frame programs
        (+ an optional scan tail), not the base two-program step —
        lowering those unused programs here would trigger pointless
        compiles. MFU for this family comes from scripts/perf_lab.py."""
        return None

    # ----------------------------------------------------------- curriculum

    def _start_of_epoch(self, current_epoch):
        """Sequence-length curriculum (ref: trainers/vid2vid.py:162-204)."""
        cfg = self.cfg
        dataset = getattr(self.train_data_loader, "dataset", None)
        single_frame_epoch = cfg_get(cfg, "single_frame_epoch", 0)
        if current_epoch < single_frame_epoch:
            if dataset is not None:
                dataset.set_sequence_length(1)
            self.sequence_length = 1
            return
        if current_epoch == single_frame_epoch:
            self.init_temporal_network()
        temp_epoch = current_epoch - single_frame_epoch
        if temp_epoch > 0:
            initial = cfg_get(cfg_get(cfg.data, "train", {}) or {},
                              "initial_sequence_length", 4)
            step = cfg_get(cfg, "num_epochs_temporal_step", 1)
            seq = min(initial * (2 ** (temp_epoch // step)),
                      self.sequence_length_max)
            if seq > self.sequence_length:
                self.sequence_length = seq
                if dataset is not None:
                    dataset.set_sequence_length(seq)
                print(f"------- Updating sequence length to {seq} -------")

    def init_temporal_network(self):
        """(ref: trainers/vid2vid.py:194-204). Params already exist (built
        at init); only the data curriculum changes."""
        self.sequence_length = cfg_get(
            cfg_get(self.cfg.data, "train", {}) or {},
            "initial_sequence_length", 4)
        self.sequence_length = min(self.sequence_length,
                                   self.sequence_length_max)
        dataset = getattr(self.train_data_loader, "dataset", None)
        if dataset is not None:
            dataset.set_sequence_length(self.sequence_length)
        print(f"------ Now start training {self.sequence_length} frames "
              "-------")

    # -------------------------------------------------------- visualization

    def _get_visualizations(self, data):
        """Rollout the sequence with the inference params
        (ref: trainers/vid2vid.py:672-716)."""
        data = to_device(numeric_only(dict(data)))
        variables = self.inference_params()
        seq_len = (data["images"].shape[1] if data["images"].ndim == 5
                   else 1)
        prev_labels = prev_images = None
        fakes = []
        for t in range(seq_len):
            data_t = self._get_data_t(data, t, prev_labels, prev_images)
            fake = self._frame_override(data_t)
            if fake is None:
                out, _ = self._apply_G(variables, data_t,
                                       jax.random.PRNGKey(0),
                                       training=False)
                fake = out["fake_images"]
            fakes.append(fake)
            prev_labels = concat_frames(prev_labels, data_t["label"],
                                        self.num_frames_G - 1)
            prev_images = concat_frames(prev_images, fake,
                                        self.num_frames_G - 1)
        label = data["label"][:, -1] if data["label"].ndim == 5 \
            else data["label"]
        image = data["images"][:, -1] if data["images"].ndim == 5 \
            else data["images"]
        return [image, label[..., :3], fakes[-1]]

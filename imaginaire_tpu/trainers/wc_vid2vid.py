"""World-consistent vid2vid trainer
(ref: imaginaire/trainers/wc_vid2vid.py — vid2vid plus the renderer
lifecycle: reset per sequence, update the point-cloud colors with every
generated frame, and feed rendered guidance into the generator).

The SplatRenderer is host-side numpy (ragged point clouds can't live in
a jitted program); guidance enters each jitted step as a dense
(B, H, W, 4) tensor and the returned fake frame colors the point cloud
between steps.
"""

from __future__ import annotations

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.model_utils.wc_vid2vid import (
    SplatRenderer,
    guidance_tensor,
)
from imaginaire_tpu.trainers.vid2vid import Trainer as Vid2VidTrainer


class Trainer(Vid2VidTrainer):
    def __init__(self, cfg, *args, **kwargs):
        super().__init__(cfg, *args, **kwargs)
        self.renderers = {}  # per batch element
        self.is_flipped_input = False

    def _init_loss(self, cfg):
        """vid2vid losses plus the guidance term: masked L1 between the
        generated frame and the splat-rendered guidance colors
        (ref: trainers/wc_vid2vid.py:43-47, MaskedL1Loss
        normalize_over_valid)."""
        super()._init_loss(cfg)
        lw = cfg.trainer.loss_weight
        if cfg_get(lw, "guidance", None) is not None:
            self.weights["Guidance"] = lw.guidance

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng,
                    training=True):
        losses, new_mut, out = super().gen_forward(
            vars_G, vars_D, loss_params, data, rng, training)
        if "Guidance" in self.weights:
            from imaginaire_tpu.losses.flow import masked_l1_loss

            guidance = data.get("guidance")
            if guidance is not None:
                losses["Guidance"] = masked_l1_loss(
                    out["fake_images"], guidance[..., :3],
                    guidance[..., 3:], normalize_over_valid=True)
            else:
                import jax.numpy as jnp

                losses["Guidance"] = jnp.zeros(())
        return losses, new_mut, out

    def reset_renderer(self, is_flipped_input=False):
        """(ref: generators/wc_vid2vid.py:72-80)."""
        self.renderers = {}
        self.is_flipped_input = is_flipped_input

    def _renderer(self, b):
        if b not in self.renderers:
            self.renderers[b] = SplatRenderer()
        return self.renderers[b]

    @staticmethod
    def _finest_resolution(mapping, target_hw=None):
        """Pick the '<H>x<W>' entry matching ``target_hw`` when present
        (its pixel coordinates index the guidance canvas of exactly that
        size), else the finest (string max would sort '64x64' above
        '256x256'); None when the window recorded no mappings at all."""
        if not mapping:
            return None
        if target_hw is not None:
            key = f"{target_hw[0]}x{target_hw[1]}"
            if key in mapping:
                return mapping[key]

        def pixel_count(key):
            try:
                h, w = str(key).lower().split("x")
                return int(h) * int(w)
            except ValueError:
                return -1

        return mapping[max(mapping.keys(), key=pixel_count)]

    def _point_info(self, data, t, b, target_hw=None):
        """Per-sample (N, 3) pixel->point mapping for frame t, or None.

        Accepted forms:
        - nested [batch][frame] list of raw (N, 3) arrays, or a stacked
          (B, T, N, 3) array (the device-upload path converts uniform
          lists to arrays);
        - the ``decode_unprojections`` output ``{resolution: (T, N, 3)}``
          for a single sample (b must be 0);
        - what the DataLoader collation makes of it: a list of such
          per-sample dicts, or a dict of (B, T, N, 3) stacks.
        Decoded mappings pick the resolution matching ``target_hw`` (the
        guidance canvas size) when present, else the finest, and strip
        the -1 padding via the count sentinel row
        (model_utils/wc_vid2vid.py::decode_unprojections)."""
        unproj = data.get("unprojection")
        if unproj is None:
            unproj = data.get("unprojections")
        if unproj is None:
            return None

        decoded = False
        if isinstance(unproj, dict):
            unproj = self._finest_resolution(unproj, target_hw)
            decoded = True
            if hasattr(unproj, "ndim") and unproj.ndim == 4:
                entry = unproj[b]  # {res: (B, T, N, 3)}
            elif b == 0:
                entry = unproj  # single-sample {res: (T, N, 3)}
            else:
                return None  # no mapping recorded for this sample
        else:
            entry = unproj[b]
            if isinstance(entry, dict):  # collated list of sample dicts
                entry = self._finest_resolution(entry, target_hw)
                decoded = True

        if isinstance(entry, (list, tuple)):
            entry = entry[t] if t < len(entry) else None
        elif hasattr(entry, "ndim") and entry.ndim >= 3:
            entry = entry[t] if t < entry.shape[0] else None
        if entry is None:
            return None
        entry = np.asarray(entry)
        if decoded and entry.ndim == 2 and entry.shape[0]:
            n = int(entry[-1, 0])
            entry = entry[:max(n, 0)]
        return entry

    def _get_data_t(self, data, t, prev_labels, prev_images):
        data_t = super()._get_data_t(data, t, prev_labels, prev_images)
        label = data_t["label"]
        b, h, w, _ = label.shape
        guidance = []
        infos = [self._point_info(data, t, bi, target_hw=(h, w))
                 for bi in range(b)]
        for bi, info in enumerate(infos):
            if info is not None:
                guidance.append(guidance_tensor(
                    self._renderer(bi), info, w, h,
                    flipped=self.is_flipped_input))
            else:
                guidance.append(np.zeros((h, w, 4), np.float32))
        if any(info is not None for info in infos):
            data_t["guidance"] = np.stack(guidance)
            data_t["_point_infos"] = infos
        return data_t

    def gen_update(self, data):
        # a new iteration starts a new clip: reset the point cloud
        # (ref: trainers/wc_vid2vid.py reset path)
        flipped = data.get("is_flipped")
        self.reset_renderer(bool(np.any(np.asarray(flipped)))
                            if flipped is not None else False)
        return super().gen_update(data)

    def _start_of_test_sequence(self, data):
        """Fresh point cloud per test sequence
        (ref: trainers/wc_vid2vid.py:70-87)."""
        flipped = data.get("is_flipped")
        self.reset_renderer(bool(np.asarray(flipped).any())
                            if flipped is not None else False)

    def reset(self):
        """(ref: trainers/wc_vid2vid.py:70-87): the per-frame eval
        harness calls reset() directly — clear the point cloud too.
        Eval sequences are unflipped; a flip flag left over from the
        last *training* batch must not leak in (the test() path
        re-derives it from the data in _start_of_test_sequence)."""
        super().reset()
        self.reset_renderer(False)

    def _after_gen_frame(self, data_t, fake):
        """Color the point cloud with the freshly generated frame."""
        infos = data_t.get("_point_infos")
        if not infos:
            return
        fake_np = np.asarray(fake)
        for bi, info in enumerate(infos):
            if info is None:
                continue
            img = ((fake_np[bi] * 0.5 + 0.5) * 255).clip(0, 255).astype(
                np.uint8)
            if self.is_flipped_input:
                img = np.fliplr(img).copy()
            self._renderer(bi).update_point_cloud(img, info)

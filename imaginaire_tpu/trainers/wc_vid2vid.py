"""World-consistent vid2vid trainer
(ref: imaginaire/trainers/wc_vid2vid.py — vid2vid plus the renderer
lifecycle: reset per sequence, update the point-cloud colors with every
generated frame, and feed rendered guidance into the generator).

The SplatRenderer is host-side numpy (ragged point clouds can't live in
a jitted program); guidance enters each jitted step as a dense
(B, H, W, 4) tensor and the returned fake frame colors the point cloud
between steps.
"""

from __future__ import annotations

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.model_utils.wc_vid2vid import (
    SplatRenderer,
    guidance_tensor,
)
from imaginaire_tpu.trainers.vid2vid import Trainer as Vid2VidTrainer


class Trainer(Vid2VidTrainer):
    def __init__(self, cfg, *args, **kwargs):
        super().__init__(cfg, *args, **kwargs)
        self.renderers = {}  # per batch element
        self.is_flipped_input = False

    def reset_renderer(self, is_flipped_input=False):
        """(ref: generators/wc_vid2vid.py:72-80)."""
        self.renderers = {}
        self.is_flipped_input = is_flipped_input

    def _renderer(self, b):
        if b not in self.renderers:
            self.renderers[b] = SplatRenderer()
        return self.renderers[b]

    def _point_info(self, data, t, b):
        """Per-sample (N, 3) pixel->point mapping for frame t, or None.
        Accepts a nested [batch][frame] list or a stacked (B, T, N, 3)
        array (the device-upload path converts uniform lists to arrays)."""
        unproj = data.get("unprojection")
        if unproj is None:
            return None
        entry = unproj[b]
        if isinstance(entry, (list, tuple)):
            entry = entry[t] if t < len(entry) else None
        elif hasattr(entry, "ndim") and entry.ndim >= 3:
            entry = entry[t] if t < entry.shape[0] else None
        if entry is None:
            return None
        return np.asarray(entry)

    def _get_data_t(self, data, t, prev_labels, prev_images):
        data_t = super()._get_data_t(data, t, prev_labels, prev_images)
        label = data_t["label"]
        b, h, w, _ = label.shape
        guidance = []
        any_guidance = False
        for bi in range(b):
            info = self._point_info(data, t, bi)
            if info is not None:
                any_guidance = True
                guidance.append(guidance_tensor(
                    self._renderer(bi), info, w, h,
                    flipped=self.is_flipped_input))
            else:
                guidance.append(np.zeros((h, w, 4), np.float32))
        if any_guidance:
            data_t["guidance"] = np.stack(guidance)
            data_t["_point_infos"] = [self._point_info(data, t, bi)
                                      for bi in range(b)]
        return data_t

    def gen_update(self, data):
        # a new iteration starts a new clip: reset the point cloud
        # (ref: trainers/wc_vid2vid.py reset path)
        flipped = data.get("is_flipped")
        self.reset_renderer(bool(np.any(np.asarray(flipped)))
                            if flipped is not None else False)
        return super().gen_update(data)

    def _start_of_test_sequence(self, data):
        """Fresh point cloud per test sequence
        (ref: trainers/wc_vid2vid.py:70-87)."""
        flipped = data.get("is_flipped")
        self.reset_renderer(bool(np.asarray(flipped).any())
                            if flipped is not None else False)

    def reset(self):
        """(ref: trainers/wc_vid2vid.py:70-87): the per-frame eval
        harness calls reset() directly — clear the point cloud too.
        Eval sequences are unflipped; a flip flag left over from the
        last *training* batch must not leak in (the test() path
        re-derives it from the data in _start_of_test_sequence)."""
        super().reset()
        self.reset_renderer(False)

    def _after_gen_frame(self, data_t, fake):
        """Color the point cloud with the freshly generated frame."""
        infos = data_t.get("_point_infos")
        if not infos:
            return
        fake_np = np.asarray(fake)
        for bi, info in enumerate(infos):
            if info is None:
                continue
            img = ((fake_np[bi] * 0.5 + 0.5) * 255).clip(0, 255).astype(
                np.uint8)
            if self.is_flipped_input:
                img = np.fliplr(img).copy()
            self._renderer(bi).update_point_cloud(img, info)

"""World-consistent vid2vid trainer
(ref: imaginaire/trainers/wc_vid2vid.py — vid2vid plus the renderer
lifecycle: reset per sequence, update the point-cloud colors with every
generated frame, and feed rendered guidance into the generator).

The SplatRenderer is host-side numpy (ragged point clouds can't live in
a jitted program); guidance enters each jitted step as a dense
(B, H, W, 4) tensor and the returned fake frame colors the point cloud
between steps.
"""

from __future__ import annotations

import re

import numpy as np

from imaginaire_tpu import telemetry
from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.model_utils.wc_vid2vid import (
    SplatRenderer,
    guidance_tensor,
)
from imaginaire_tpu.trainers.vid2vid import Trainer as Vid2VidTrainer


class Trainer(Vid2VidTrainer):
    def __init__(self, cfg, *args, **kwargs):
        super().__init__(cfg, *args, **kwargs)
        self.renderers = {}  # per batch element
        self.is_flipped_input = False
        self.single_image_model = None
        self.single_image_vars = None
        self._single_z_key = None
        self._init_single_image_model(cfg)

    # --------------------------------------------------- single-image model

    def _init_single_image_model(self, cfg):
        """Frozen, separately-trained SPADE generator that synthesizes
        frames until the flow estimate warms up
        (ref: generators/wc_vid2vid.py:45-70 init,
        trainers/wc_vid2vid.py:504-510 weight loading).

        ``gen.single_image_model.config`` names the single-image stage's
        config (the ``*_single.yaml``); ``.checkpoint`` names its trained
        checkpoint (dir or a logdir with latest_checkpoint.txt). A
        missing checkpoint fails loudly; ``allow_random_init: True``
        permits random weights for tests."""
        import os

        from imaginaire_tpu.config import Config, as_attrdict
        from imaginaire_tpu.registry import resolve

        sim_cfg = cfg_get(cfg.gen, "single_image_model", None)
        if sim_cfg is None:
            return
        sim_cfg = as_attrdict(sim_cfg)
        cfg_path = cfg_get(sim_cfg, "config", None)
        if cfg_path is None:
            raise ValueError(
                "gen.single_image_model needs a 'config' key naming the "
                "single-image stage's yaml")
        cfg_path = self._resolve_config_path(
            cfg_path, cfg_get(cfg, "source_filename", None))
        single_cfg = Config(cfg_path)
        self.single_image_model = resolve(
            single_cfg.gen.type, "Generator")(single_cfg.gen,
                                              single_cfg.data)
        ckpt = cfg_get(sim_cfg, "checkpoint", None)
        if ckpt:
            from imaginaire_tpu.utils.checkpoint import (
                latest_checkpoint_path,
                load_checkpoint,
            )

            path = ckpt
            if os.path.isdir(ckpt) and os.path.exists(
                    os.path.join(ckpt, "latest_checkpoint.txt")):
                path = latest_checkpoint_path(ckpt)
            if path is None or not os.path.exists(path):
                raise FileNotFoundError(
                    f"gen.single_image_model.checkpoint={ckpt!r} does not "
                    "resolve to a checkpoint; train the single-image stage "
                    f"({cfg_path}) first")
            state = load_checkpoint(path)
            if "vars_G" not in state:
                raise ValueError(
                    f"checkpoint {path} has no generator variables "
                    "('vars_G'); is it a training checkpoint?")
            self.single_image_vars = state["vars_G"]
            print(f"Loaded single image model from {path}")
        elif not cfg_get(sim_cfg, "allow_random_init", False):
            raise ValueError(
                "gen.single_image_model needs a 'checkpoint' key (or "
                "allow_random_init: True for tests) — without trained "
                "weights the early-sequence takeover would emit noise")
        else:
            print("single_image_model: RANDOM weights "
                  "(allow_random_init) — test use only")
        from imaginaire_tpu.telemetry import xla_obs

        self._jit_single = xla_obs.compiled_program(
            "wc_single_image",
            lambda v, d, k: self.single_image_model.apply(
                v, d, random_style=True, training=False,
                rngs={"noise": k}),
            allow_shape_growth=True)

    @staticmethod
    def _resolve_config_path(path, parent_config_path):
        """Resolve the single-image config path like the repo-root-
        relative paths the configs ship ('configs/projects/...'): try
        the CWD first, then walk up from the PARENT config's directory —
        so training works from any working directory, not just the repo
        root."""
        import os

        if os.path.isabs(path) or os.path.exists(path):
            return path
        base = os.path.dirname(os.path.abspath(parent_config_path)) \
            if parent_config_path else None
        while base:
            candidate = os.path.join(base, path)
            if os.path.exists(candidate):
                return candidate
            parent = os.path.dirname(base)
            if parent == base:
                break
            base = parent
        return path  # let Config() raise its own FileNotFoundError

    def _pipeline_eligible(self, data, seq_len):
        """Never pipeline (ISSUE 14): every frame here round-trips through
        host-side hooks — ``_frame_override`` below and the point-cloud
        coloring in ``_after_gen_frame`` read back the generated frame
        before the next one may be sliced, so there is nothing to overlap.
        The base eligibility check would already refuse on the hook
        overrides; stating it explicitly keeps the contract visible."""
        return False

    def _frame_override(self, data_t):
        """Frozen single-image SPADE takeover while flow features are
        unavailable (ref: generators/wc_vid2vid.py:169-185): the same
        not-``warp_prev`` frames the wc generator would synthesize from
        scratch come from the pretrained model instead, with a
        per-sequence cached style z (here: a cached rng key — same key,
        same z). Those frames skip the D/G updates (the base rollout's
        override contract) and still color the point cloud + feed the
        prev-frame history."""
        import jax

        if self.single_image_model is None:
            return None
        prev = data_t.get("prev_images")
        warp_prev = (self.use_flow and prev is not None
                     and prev.shape[1] == self.num_frames_G - 1)
        if warp_prev:
            return None
        if self.single_image_vars is None:  # allow_random_init path
            # lint: allow(bare-jit) -- one-shot flax init of the frozen single-image generator (tests-only fallback)
            self.single_image_vars = jax.jit(
                lambda k, d: self.single_image_model.init(
                    {"params": k, "noise": k}, d, random_style=True,
                    training=False))(
                jax.random.PRNGKey(0),
                {"label": data_t["label"], "images": data_t["image"]})
        if self._single_z_key is None:
            self._single_seq = getattr(self, "_single_seq", -1) + 1
            self._single_z_key = jax.random.PRNGKey(
                77321 + self._single_seq)
        out = self._jit_single(
            self.single_image_vars,
            {"label": data_t["label"], "images": data_t["image"]},
            self._single_z_key)
        return out["fake_images"].astype(data_t["image"].dtype)

    def _init_loss(self, cfg):
        """vid2vid losses plus the guidance term: masked L1 between the
        generated frame and the splat-rendered guidance colors
        (ref: trainers/wc_vid2vid.py:43-47, MaskedL1Loss
        normalize_over_valid)."""
        super()._init_loss(cfg)
        lw = cfg.trainer.loss_weight
        if cfg_get(lw, "guidance", None) is not None:
            self.weights["Guidance"] = lw.guidance

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng,
                    training=True):
        losses, new_mut, out = super().gen_forward(
            vars_G, vars_D, loss_params, data, rng, training)
        if "Guidance" in self.weights:
            from imaginaire_tpu.losses.flow import masked_l1_loss

            guidance = data.get("guidance")
            if guidance is not None:
                losses["Guidance"] = masked_l1_loss(
                    out["fake_images"], guidance[..., :3],
                    guidance[..., 3:], normalize_over_valid=True)
            else:
                import jax.numpy as jnp

                losses["Guidance"] = jnp.zeros(())
        return losses, new_mut, out

    def reset_renderer(self, is_flipped_input=False):
        """(ref: generators/wc_vid2vid.py:72-80; the per-sequence style z
        of the single-image model resets with the point cloud,
        ref: wc_vid2vid.py:79 ``single_image_model_z = None``)."""
        self.renderers = {}
        self.is_flipped_input = is_flipped_input
        self._single_z_key = None

    def _renderer(self, b):
        if b not in self.renderers:
            self.renderers[b] = SplatRenderer()
        return self.renderers[b]

    @staticmethod
    def _resolution_hw(key):
        """(H, W) parsed from a resolution key, or None.

        Two formats exist in the wild: the reference pickles
        unprojections under 'w{W}xh{H}' keys (ref:
        generators/wc_vid2vid.py:103 hardcodes 'w1024xh512') while this
        repo's decode path emits '{H}x{W}'."""
        m = re.fullmatch(r"w(\d+)xh(\d+)", str(key).lower())
        if m:
            return int(m.group(2)), int(m.group(1))
        m = re.fullmatch(r"(\d+)x(\d+)", str(key).lower())
        if m:
            return int(m.group(1)), int(m.group(2))
        return None

    @staticmethod
    def _finest_resolution(mapping, target_hw=None):
        """Pick the entry whose resolution key matches ``target_hw``
        when present (its pixel coordinates index the guidance canvas of
        exactly that size), else the finest (string max would sort
        '64x64' above '256x256'); None when the window recorded no
        mappings at all. Accepts both '{H}x{W}' and the reference's
        'w{W}xh{H}' key formats."""
        if not mapping:
            return None
        if target_hw is not None:
            for key in mapping:
                if Trainer._resolution_hw(key) == tuple(target_hw):
                    return mapping[key]

        def pixel_count(key):
            hw = Trainer._resolution_hw(key)
            return hw[0] * hw[1] if hw else -1

        return mapping[max(mapping.keys(), key=pixel_count)]

    def _point_info(self, data, t, b, target_hw=None):
        """Per-sample (N, 3) pixel->point mapping for frame t, or None.

        Accepted forms:
        - nested [batch][frame] list of raw (N, 3) arrays, or a stacked
          (B, T, N, 3) array (the device-upload path converts uniform
          lists to arrays);
        - the ``decode_unprojections`` output ``{resolution: (T, N, 3)}``
          for a single sample (b must be 0);
        - what the DataLoader collation makes of it: a list of such
          per-sample dicts, or a dict of (B, T, N, 3) stacks.
        Decoded mappings pick the resolution matching ``target_hw`` (the
        guidance canvas size) when present, else the finest, and strip
        the -1 padding via the count sentinel row
        (model_utils/wc_vid2vid.py::decode_unprojections)."""
        unproj = data.get("unprojection")
        if unproj is None:
            unproj = data.get("unprojections")
        if unproj is None:
            return None

        decoded = False
        if isinstance(unproj, dict):
            unproj = self._finest_resolution(unproj, target_hw)
            decoded = True
            if hasattr(unproj, "ndim") and unproj.ndim == 4:
                entry = unproj[b]  # {res: (B, T, N, 3)}
            elif b == 0:
                entry = unproj  # single-sample {res: (T, N, 3)}
            else:
                # a per-sample dict reaching a b>0 lookup means an
                # uncollated sample met batch_size>1 — guidance would
                # silently vanish for every sample past the first
                raise ValueError(
                    "wc_vid2vid: got a single-sample unprojection dict "
                    f"but was asked for batch element {b}; collate "
                    "per-sample dicts into a list (or stack) before "
                    "handing them to the trainer")
        else:
            entry = unproj[b]
            if isinstance(entry, dict):  # collated list of sample dicts
                entry = self._finest_resolution(entry, target_hw)
                decoded = True

        if isinstance(entry, (list, tuple)):
            entry = entry[t] if t < len(entry) else None
        elif hasattr(entry, "ndim") and entry.ndim >= 3:
            entry = entry[t] if t < entry.shape[0] else None
        if entry is None:
            return None
        entry = np.asarray(entry)
        if decoded and entry.ndim == 2 and entry.shape[0]:
            n = int(entry[-1, 0])
            entry = entry[:max(n, 0)]
        return entry

    def _get_data_t(self, data, t, prev_labels, prev_images):
        data_t = super()._get_data_t(data, t, prev_labels, prev_images)
        label = data_t["label"]
        b, h, w, _ = label.shape
        # host-side point-cloud projection runs inside the rollout's
        # gen_step span — give it its own phase so the telemetry table
        # separates CPU guidance rendering from XLA dispatch
        with telemetry.span("wc_guidance", step=self.current_iteration):
            guidance = []
            infos = [self._point_info(data, t, bi, target_hw=(h, w))
                     for bi in range(b)]
            for bi, info in enumerate(infos):
                if info is not None:
                    guidance.append(guidance_tensor(
                        self._renderer(bi), info, w, h,
                        flipped=self.is_flipped_input))
                else:
                    guidance.append(np.zeros((h, w, 4), np.float32))
        if any(info is not None for info in infos):
            data_t["guidance"] = np.stack(guidance)
            data_t["_point_infos"] = infos
        return data_t

    def gen_update(self, data):
        # a new iteration starts a new clip: reset the point cloud
        # (ref: trainers/wc_vid2vid.py reset path)
        flipped = data.get("is_flipped")
        self.reset_renderer(bool(np.any(np.asarray(flipped)))
                            if flipped is not None else False)
        return super().gen_update(data)

    def _start_of_test_sequence(self, data):
        """Fresh point cloud per test sequence
        (ref: trainers/wc_vid2vid.py:70-87)."""
        flipped = data.get("is_flipped")
        self.reset_renderer(bool(np.asarray(flipped).any())
                            if flipped is not None else False)

    def reset(self):
        """(ref: trainers/wc_vid2vid.py:70-87): the per-frame eval
        harness calls reset() directly — clear the point cloud too.
        Eval sequences are unflipped; a flip flag left over from the
        last *training* batch must not leak in (the test() path
        re-derives it from the data in _start_of_test_sequence)."""
        super().reset()
        self.reset_renderer(False)

    def _after_gen_frame(self, data_t, fake):
        """Color the point cloud with the freshly generated frame."""
        infos = data_t.get("_point_infos")
        if not infos:
            return
        fake_np = np.asarray(fake)
        for bi, info in enumerate(infos):
            if info is None:
                continue
            img = ((fake_np[bi] * 0.5 + 0.5) * 255).clip(0, 255).astype(
                np.uint8)
            if self.is_flipped_input:
                img = np.fliplr(img).copy()
            self._renderer(bi).update_point_cloud(img, info)

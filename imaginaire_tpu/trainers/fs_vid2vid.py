"""Few-shot vid2vid trainer (ref: imaginaire/trainers/fs_vid2vid.py:24-280).

Inherits the vid2vid interleaved rollout; the generator additionally
consumes K reference frames, and the flow outputs are [ref, prev]
pairs — the flow loss sums over whichever entries are live.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from imaginaire_tpu.losses.flow import masked_l1_loss
from imaginaire_tpu.model_utils.fs_vid2vid import concat_frames
from imaginaire_tpu.trainers.base import MUTABLE
from imaginaire_tpu.trainers.vid2vid import Trainer as Vid2VidTrainer
from imaginaire_tpu.utils.misc import numeric_only, to_device


class Trainer(Vid2VidTrainer):
    def _frame0(self, data):
        out = super()._frame0(data)
        out["ref_images"] = data["ref_images"]
        if "ref_labels" in data:
            out["ref_labels"] = data["ref_labels"]
        return out

    def _get_data_t(self, data, t, prev_labels, prev_images):
        data_t = super()._get_data_t(data, t, prev_labels, prev_images)
        data_t.update(self._rollout_scan_constants(data))
        return data_t

    def _rollout_scan_constants(self, data):
        """The few-shot reference window is constant across the clip —
        declared here so the rollout-scan tail threads it into every
        frame's data_t (see Vid2VidTrainer._scan_eligible)."""
        out = {"ref_images": data["ref_images"]}
        if "ref_labels" in data:
            out["ref_labels"] = data["ref_labels"]
        return out

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng,
                    training=True):
        """vid2vid losses with the two-entry (ref, prev) flow outputs
        (ref: trainers/fs_vid2vid.py — flow losses iterate both)."""
        data_t, stacks = self._split_data_t(data)
        out, new_mut = self._apply_G(vars_G, data_t, rng, training)
        d_out = self._apply_D(vars_D, data_t, out, stacks, training)

        losses = {}
        losses["GAN"], losses["FeatureMatching"] = self._gan_fm_losses(
            d_out["indv"], dis_update=False)
        if self.perceptual is not None:
            losses["Perceptual"] = self.perceptual(
                loss_params["perceptual"], out["fake_images"],
                data_t["image"])
        if "L1" in self.weights:
            losses["L1"] = jnp.mean(jnp.abs(out["fake_images"]
                                            - data_t["image"]))
        if self.use_flow:
            flow_terms = []
            for warp, occ in zip(out["warped_images"],
                                 out["fake_occlusion_masks"]):
                if warp is not None:
                    flow_terms.append(masked_l1_loss(
                        out["fake_images"], warp,
                        jax.lax.stop_gradient(occ)))
            if flow_terms:
                losses["Flow"] = sum(flow_terms) / len(flow_terms)
            if "Flow_L1" in self.weights \
                    and data_t.get("flow_gt") is not None:
                # amortized-teacher direct flow supervision on the prev
                # branch (the reference's FlowLoss L1 term,
                # flow.py:120-160, previously skipped by this fork): the
                # cached (flow, conf) makes it free at step time
                flows = out.get("fake_flow_maps")
                prev_flow = flows[-1] if isinstance(flows, (list, tuple)) \
                    else flows
                if prev_flow is not None:
                    losses["Flow_L1"] = masked_l1_loss(
                        prev_flow,
                        jax.lax.stop_gradient(data_t["flow_gt"]),
                        jax.lax.stop_gradient(data_t["conf_gt"]))
        for s in range(self.num_temporal_scales):
            if f"temporal_{s}" in d_out:
                gan_t, fm_t = self._gan_fm_losses(d_out[f"temporal_{s}"],
                                                  dis_update=False)
                losses[f"GAN_T{s}"] = gan_t
                losses[f"FeatureMatching_T{s}"] = fm_t
        losses = self._region_d_losses(d_out, losses, dis_update=False)
        return losses, new_mut, out

    def dis_forward(self, vars_G, vars_D, loss_params, data, rng,
                    training=True):
        data_t, stacks = self._split_data_t(data)
        out, _ = self._apply_G(vars_G, data_t, rng, training)
        out = jax.lax.stop_gradient(
            {k: v for k, v in out.items() if v is not None})
        d_out, new_mut_D = self._apply_D(vars_D, data_t, out, stacks,
                                         training, mutable=True)
        losses = {}
        losses["GAN"], _ = self._gan_fm_losses(d_out["indv"], dis_update=True)
        from imaginaire_tpu.losses import dis_accuracy

        losses["D_real_acc"], losses["D_fake_acc"] = dis_accuracy(
            d_out["indv"]["pred_real"]["outputs"],
            d_out["indv"]["pred_fake"]["outputs"], self.gan_mode)
        for s in range(self.num_temporal_scales):
            if f"temporal_{s}" in d_out:
                gan_t, _ = self._gan_fm_losses(d_out[f"temporal_{s}"],
                                               dis_update=True)
                losses[f"GAN_T{s}"] = gan_t
        losses = self._region_d_losses(d_out, losses, dis_update=True)
        return losses, new_mut_D

    # ------------------------------------------------- inference finetune

    def finetune(self, data, inference_args=None):
        """Adapt the model to the K reference frames at inference time
        (ref: trainers/fs_vid2vid.py:264-292): restrict G updates to the
        weight-generator FCs / output conv / up-ladder, then run a few
        D+G iterations on randomly rolled+flipped reference targets.
        random_roll supplies the shift/flip augmentation the reference
        uses to avoid overfitting the handful of frames."""
        import optax

        from imaginaire_tpu.config import cfg_get
        from imaginaire_tpu.model_utils.fs_vid2vid import random_roll

        inference_args = inference_args or {}
        prefixes = tuple(cfg_get(inference_args, "finetune_param_prefixes",
                                 None)
                         or ("weight_generator", "conv_img", "up"))
        iterations = int(cfg_get(inference_args, "finetune_iter", 100))

        def _mask(path, _):
            names = [p.key for p in path if hasattr(p, "key")]
            return any(str(n).startswith(pref)
                       for n in names for pref in prefixes)

        params_G = self.state["vars_G"]["params"]
        mask = jax.tree_util.tree_map_with_path(_mask, params_G)
        inv_mask = jax.tree_util.tree_map(lambda m: not m, mask)
        # masked() leaves unmasked updates untouched — zero them
        # explicitly so frozen params stay frozen
        from imaginaire_tpu.optim import init_optimizer_state

        self.tx_G = optax.chain(
            optax.masked(optax.set_to_zero(), inv_mask),
            optax.masked(self.tx_G, mask))
        self.state["opt_G"] = init_optimizer_state(self.tx_G, params_G,
                                                   self.partition)
        self.state["opt_D"] = init_optimizer_state(
            self.tx_D, self.state["vars_D"]["params"], self.partition)
        # the masked chain changed the opt_G tree STRUCTURE: rebuild the
        # partition shardings (and re-place) before the re-traced
        # programs constrain against them
        self.state = self._place_state(self.state)
        # the step programs closed over the old optimizer: drop the
        # cached executables and re-trace. This is the one legitimate
        # re-jit in the codebase — the ledger records it as expected
        # (allowlisted) so the recompile tripwire stays silent. Any
        # deferred pipeline observations must land first — they hold
        # outputs of the about-to-be-dropped executables (gen_update
        # drains at rollout end, so this is a no-op outside mid-rollout
        # callers; see parallel/pipeline.py).
        self._rollout_pipeline.drain()
        self._jit_vid_dis.retrace("fs_vid2vid finetune re-jit")
        self._jit_vid_gen.retrace("fs_vid2vid finetune re-jit")

        ref_labels = data["ref_labels"]
        ref_images = data["ref_images"]
        k = ref_images.shape[1]
        import numpy as np

        for it in range(1, iterations + 1):
            idx = int(np.random.randint(k))
            tgt_label, tgt_image = random_roll(
                [ref_labels[:, idx], ref_images[:, idx]])
            d = dict(data)
            d["label"] = tgt_label[:, None]
            d["images"] = tgt_image[:, None]
            # gen_update runs the interleaved D+G rollout (dis_update is
            # a no-op by the vid2vid contract)
            self.gen_update(d)
        self.has_finetuned = True

    def test(self, data_loader, output_dir, inference_args=None):
        """(ref: trainers/fs_vid2vid.py:240-262): optional few-shot
        finetune on the first batch's reference frames before testing."""
        inference_args = dict(inference_args or {})
        if inference_args.pop("finetune", False) \
                and not getattr(self, "has_finetuned", False):
            first = next(iter(data_loader))
            first = self.start_of_iteration(first, current_iteration=-1)
            self.finetune(first, inference_args)
        inference_args.pop("finetune_iter", None)
        inference_args.pop("finetune_param_prefixes", None)
        return super().test(data_loader, output_dir, inference_args)

    def _inference_sequence_indices(self, dataset, inference_args):
        """(ref: trainers/fs_vid2vid.py:146-160): an explicit
        driving_seq_index tests that single sequence."""
        if "driving_seq_index" in inference_args:
            return [int(inference_args["driving_seq_index"])]
        return super()._inference_sequence_indices(dataset, inference_args)

    def _pin_inference_sequence(self, dataset, seq_idx, inference_args):
        dataset.set_inference_sequence_idx(
            seq_idx,
            inference_args.get("few_shot_seq_index"),
            inference_args.get("few_shot_frame_index", 0))

    def _get_visualizations(self, data):
        """(ref: trainers/fs_vid2vid.py:196-260)."""
        data = to_device(numeric_only(dict(data)))
        variables = self.inference_params()
        seq_len = (data["images"].shape[1] if data["images"].ndim == 5
                   else 1)
        prev_labels = prev_images = None
        fakes = []
        for t in range(seq_len):
            data_t = self._get_data_t(data, t, prev_labels, prev_images)
            out, _ = self._apply_G(variables, data_t, jax.random.PRNGKey(0),
                                   training=False)
            fake = out["fake_images"]
            fakes.append(fake)
            prev_labels = concat_frames(prev_labels, data_t["label"],
                                        self.num_frames_G - 1)
            prev_images = concat_frames(prev_images, fake,
                                        self.num_frames_G - 1)
        image = data["images"][:, -1] if data["images"].ndim == 5 \
            else data["images"]
        vis = [data["ref_images"][:, 0], image, fakes[-1]]
        if out.get("warped_images") and out["warped_images"][0] is not None:
            vis.append(out["warped_images"][0])
        return vis

"""pix2pixHD trainer (ref: imaginaire/trainers/pix2pixHD.py:17-203).

Losses: GAN + FeatureMatching + Perceptual — SPADE's set minus the
style-VAE GaussianKL (ref: pix2pixHD.py:49-73). Preprocessing replaces
the label's trailing instance-map channel with an edge map and exposes
the raw ids as ``instance_maps`` (ref: pix2pixHD.py:135-157). Before a
checkpoint is written, instance features are K-means clustered so
multi-modal inference can sample cluster centers
(ref: pix2pixHD.py:159-173, model_utils/pix2pixHD.py:17-71).

TPU-first: the edge map is pure jnp shifts (no host loop), computed in
``_start_of_iteration`` alongside the device upload; the cluster pass
reuses the jitted encoder apply.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.model_utils.pix2pixHD import cluster_features, get_edges
from imaginaire_tpu.trainers.spade import Trainer as SPADETrainer


class Trainer(SPADETrainer):
    def __init__(self, cfg, *args, **kwargs):
        super().__init__(cfg, *args, **kwargs)
        # Flax setup() attributes are only visible inside apply, so derive
        # the instance-map flag from the config exactly as the generator
        # does (models/generators/pix2pixHD.py:203-205).
        input_labels = list(cfg_get(cfg.data, "input_labels", []) or [])
        self.contain_instance_map = (
            bool(input_labels) and input_labels[-1] == "instance_maps")

    # _init_loss: SPADE's (spade.py:36-51) registers the KL weight only
    # when cfg.trainer.loss_weight.kl exists, so pix2pixHD configs get
    # exactly GAN + FeatureMatching + Perceptual (ref: pix2pixHD.py:49-73).

    # ------------------------------------------------------- preprocessing

    def pre_process(self, data):
        """Swap the trailing instance channel for an edge map
        (ref: trainers/pix2pixHD.py:135-157). jnp-traced; safe both
        host-side and under jit. Idempotent: a batch that already carries
        ``instance_maps`` passes through (end_of_iteration re-feeds the
        preprocessed batch to the visualization path)."""
        if not self.contain_instance_map or "instance_maps" in data:
            return data
        data = dict(data)
        label = jnp.asarray(data["label"])
        inst = label[..., -1:]
        # int32: ids must survive the bf16 compute-dtype cast (packed
        # Cityscapes ids like 26001/26002 collide in bf16's 8-bit mantissa);
        # _to_compute_dtype only touches float32 leaves.
        data["instance_maps"] = inst.astype(jnp.int32)
        data["label"] = jnp.concatenate([label[..., :-1], get_edges(inst)],
                                        axis=-1)
        return data

    def _init_data(self, data):
        return self.pre_process(super()._init_data(data))

    def _start_of_iteration(self, data, current_iteration):
        return self.pre_process(
            super()._start_of_iteration(data, current_iteration))

    # --------------------------------------------------------- checkpoints

    def _has_encoder(self):
        enc_cfg = cfg_get(self.cfg.gen, "enc", None)
        return (enc_cfg is not None and self.contain_instance_map
                and cfg_get(enc_cfg, "num_feat_channels", 0) > 0)

    def init_state(self, key, data):
        """Reserve the cluster-center leaf up front so the state pytree
        structure never changes mid-training (a late insert would force the
        jitted steps to recompile and break orbax resume targets)."""
        state = super().init_state(key, data)
        if self._has_encoder():
            from imaginaire_tpu.utils.data import (
                get_paired_input_label_channel_number,
            )

            enc_cfg = self.cfg.gen.enc
            state["cluster_centers"] = jnp.zeros(
                (get_paired_input_label_channel_number(self.cfg.data),
                 cfg_get(enc_cfg, "num_clusters", 10),
                 enc_cfg.num_feat_channels), jnp.float32)
            # the partition shardings super() computed predate the new
            # leaf — rebuild them so the plan's structure matches
            self.state = self._place_state(state)
            return self.state
        return state

    def _pre_save_checkpoint(self):
        """K-means over encoder instance features → state['cluster_centers']
        (ref: trainers/pix2pixHD.py:159-173). The reference writes the
        centers into encoder buffers; our state pytree keeps them beside
        the params so they ride the same checkpoint."""
        if not self._has_encoder() or self.val_data_loader is None:
            return
        enc_cfg = self.cfg.gen.enc
        feat_nc = enc_cfg.num_feat_channels
        from imaginaire_tpu.utils.data import (
            get_paired_input_label_channel_number,
        )

        label_nc = get_paired_input_label_channel_number(self.cfg.data)
        variables = self.inference_params()
        from imaginaire_tpu.telemetry import xla_obs

        # ledgered (and graph-audited) like every compile site; the
        # variables ride as an argument so they never bake into the
        # executable as constants
        def encode(variables, images, instance_maps):
            return self.net_G.apply(
                variables, images, instance_maps, training=False,
                method=lambda mdl, im, inst, training: mdl.encoder(
                    im, inst, training=training))

        encode_program = xla_obs.compiled_program(
            "pix2pixHD_encode", encode, allow_shape_growth=True)

        def encode_fn(data):
            return encode_program(variables, data["images"],
                                  data["instance_maps"])

        preprocessed = (self._init_data(dict(d)) for d in self.val_data_loader)
        centers = cluster_features(
            encode_fn, preprocessed, label_nc, feat_nc,
            n_clusters=cfg_get(enc_cfg, "num_clusters", 10),
            is_cityscapes=cfg_get(self.cfg.gen, "is_cityscapes", False))
        self.state["cluster_centers"] = jnp.asarray(centers)

    # ------------------------------------------------------ visualizations

    def _get_visualizations(self, data):
        """(input, label-viz, fake) strip — pix2pixHD has no style path."""
        data = self._init_data(dict(data))
        out, _ = self._apply_G(self.state["vars_G"], data,
                               jax.random.PRNGKey(0), training=False)
        vis = [data["images"][..., :3], data["label"][..., :1],
               out["fake_images"][..., :3]]
        if self.model_average:
            ema_vars = dict(self.state["vars_G"], params=self.state["ema_G"])
            ema_out, _ = self._apply_G(ema_vars, data, jax.random.PRNGKey(0),
                                       training=False)
            vis.append(ema_out["fake_images"][..., :3])
        return vis

"""SPADE trainer (ref: imaginaire/trainers/spade.py).

Losses: GAN(hinge) + Perceptual(VGG19 5-layer pyramid) + FeatureMatching +
GaussianKL (ref: spade.py:56-81). Video batches fold previous frames into
the label channels (ref: spade.py:97-126); input H/W are rounded to the
generator's base multiple (ref: spade.py:297-312).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.losses import (
    PerceptualLoss,
    dis_accuracy,
    feature_matching_loss,
    gan_loss,
    gaussian_kl_loss,
)
from imaginaire_tpu.trainers.base import MUTABLE, BaseTrainer
from imaginaire_tpu.utils.misc import to_device


class Trainer(BaseTrainer):
    def __init__(self, cfg, *args, **kwargs):
        super().__init__(cfg, *args, **kwargs)
        self.video_mode = str(cfg_get(cfg.data, "type", "")).endswith("paired_videos")
        try:
            from imaginaire_tpu.utils.data import get_crop_or_resize_h_w

            # same crop-else-resize sizing the generator uses — trainer
            # input rounding and the generator ladder must agree on base
            crop_h, crop_w = get_crop_or_resize_h_w(
                cfg.data.train.augmentations)
            self.base = {256: 16, 512: 32, 1024: 64}.get(min(crop_h, crop_w), 32)
        except (AttributeError, KeyError, ValueError):
            self.base = 32  # size-less config: tests feed 256-class inputs

    def _init_loss(self, cfg):
        """(ref: trainers/spade.py:56-81)."""
        tcfg = cfg.trainer
        self.gan_mode = cfg_get(tcfg, "gan_mode", "hinge")
        self.weights["GAN"] = tcfg.loss_weight.gan
        self.weights["FeatureMatching"] = tcfg.loss_weight.feature_matching
        if cfg_get(tcfg.loss_weight, "kl", None) is not None:
            self.weights["GaussianKL"] = tcfg.loss_weight.kl
        self.perceptual = None
        if cfg_get(tcfg, "perceptual_loss", None) is not None:
            p = tcfg.perceptual_loss
            self.perceptual = PerceptualLoss(
                network=p.mode, layers=list(p.layers),
                weights=list(cfg_get(p, "weights", None) or []) or None,
                weights_path=cfg_get(p, "weights_path", None),
                allow_random_init=cfg_get(p, "allow_random_init", False))
            self.weights["Perceptual"] = tcfg.loss_weight.perceptual

    def init_loss_params(self, key):
        if self.perceptual is None:
            return {}
        return {"perceptual": self.perceptual.init_params(key)}

    # ------------------------------------------------------------ forwards

    def _expand_labels(self, data):
        """On-device one-hot for integer label maps (traced under jit).

        TPU-idiomatic data path: the host ships (B,H,W) int labels
        (~KB) instead of (B,H,W,C) one-hot floats (~C× more H2D
        bandwidth — at COCO's 184 classes that is the difference between
        a 0.3MB and a 48MB transfer per image). Float label tensors pass
        through untouched (the reference's host-side one-hot,
        ref: datasets/base.py:272).
        """
        label = data.get("label")
        if label is None or not jnp.issubdtype(label.dtype, jnp.integer):
            return data
        from imaginaire_tpu.utils.data import get_paired_input_label_channel_number

        n = get_paired_input_label_channel_number(self.cfg.data)
        extra = data.get("label_float")
        if extra is not None:
            # datasets with one_hot_on_device ship non-mask label types
            # (e.g. COCO edge maps) separately; they occupy the trailing
            # channels, mask one-hot first (data/base.concat_labels)
            n = n - extra.shape[-1]
        onehot = jax.nn.one_hot(label, n, dtype=self.compute_dtype)
        if extra is not None:
            onehot = jnp.concatenate(
                [onehot, extra.astype(onehot.dtype)], axis=-1)
        out = dict(data, label=onehot)
        out.pop("label_float", None)
        return out

    def _init_data(self, data):
        return self._expand_labels(
            to_device(dict(data)))

    def _apply_G(self, vars_G, data, rng, training, random_style=False):
        data = self._expand_labels(data)
        out, new_mut = self.net_G.apply(
            vars_G, data, training=training, random_style=random_style,
            rngs={"noise": rng}, mutable=list(MUTABLE))
        return out, new_mut

    def _apply_D(self, vars_D, data, net_G_output, training, mutable=False):
        data = self._expand_labels(data)
        if mutable:
            return self.net_D.apply(vars_D, data, net_G_output,
                                    training=training, mutable=list(MUTABLE))
        return self.net_D.apply(vars_D, data, net_G_output, training=training)

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/spade.py:128-163)."""
        net_G_output, new_mut = self._apply_G(vars_G, data, rng, training)
        net_D_output = self._apply_D(vars_D, data, net_G_output, training)

        losses = {}
        output_fake = self._get_outputs(net_D_output, real=False)
        losses["GAN"] = gan_loss(output_fake, True, self.gan_mode, dis_update=False)
        losses["FeatureMatching"] = feature_matching_loss(
            net_D_output["fake_features"], net_D_output["real_features"])
        if net_G_output.get("mu") is not None:
            losses["GaussianKL"] = gaussian_kl_loss(
                net_G_output["mu"], net_G_output["logvar"])
        else:
            losses["GaussianKL"] = jnp.zeros(())
        if self.perceptual is not None:
            losses["Perceptual"] = self.perceptual(
                loss_params["perceptual"], net_G_output["fake_images"],
                data["images"])
        return losses, new_mut

    def dis_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/spade.py:165-187)."""
        net_G_output, _ = self._apply_G(vars_G, data, rng, training)
        net_G_output = jax.lax.stop_gradient(
            {"fake_images": net_G_output["fake_images"]})
        # D runs with mutable spectral/batch_stats so the power-iteration
        # vector u advances every dis step (torch spectral_norm updates
        # weight_u on every training forward, ref: layers/weight_norm.py).
        net_D_output, new_mut_D = self._apply_D(
            vars_D, data, net_G_output, training, mutable=True)

        fake_loss = gan_loss(self._get_outputs(net_D_output, real=False),
                             False, self.gan_mode, dis_update=True)
        true_loss = gan_loss(self._get_outputs(net_D_output, real=True),
                             True, self.gan_mode, dis_update=True)
        losses = {"GAN/fake": fake_loss, "GAN/true": true_loss,
                  "GAN": fake_loss + true_loss}
        # GAN-balance diagnostics: D real/fake accuracy rides the loss
        # dict (unweighted keys never enter the total — _total only sums
        # registered weights) so it reaches the meters and the health
        # monitor without an extra forward
        losses["D_real_acc"], losses["D_fake_acc"] = dis_accuracy(
            net_D_output["real_outputs"], net_D_output["fake_outputs"],
            self.gan_mode)
        return losses, new_mut_D

    # ---------------------------------------------------------- data hooks

    def _start_of_iteration(self, data, current_iteration):
        """Fold 5-D video batches into label channels
        (ref: trainers/spade.py:97-126); NHWC: (N,T,H,W,C)."""
        import numpy as np

        label = np.asarray(data["label"])
        if label.ndim == 5:
            images = np.asarray(data["images"])
            prev_images = images[:, :-1]
            n, tm1, h, w, c = prev_images.shape
            label_image = prev_images.transpose(0, 2, 3, 1, 4).reshape(n, h, w, tm1 * c)
            t = label.shape[1]
            label_flat = label.transpose(0, 2, 3, 1, 4).reshape(
                n, h, w, t * label.shape[-1])
            data = dict(data)
            data["label"] = np.concatenate([label_flat, label_image], axis=-1)
            data["images"] = images[:, -1]
        return self._resize_data(data)

    def _resize_data(self, data):
        """Round H/W down to the generator base multiple
        (ref: trainers/spade.py:297-312)."""
        import numpy as np

        base = self.base
        out = dict(data)
        # label_float rides alongside int label maps (one_hot_on_device
        # datasets) and must stay spatially aligned for the device concat
        for key in ("label", "images", "label_float"):
            if key in out:
                arr = np.asarray(out[key])
                h, w = arr.shape[1:3]
                h2, w2 = (h // base) * base, (w // base) * base
                if (h2, w2) != (h, w):
                    out[key] = arr[:, :h2, :w2]
        return out

    # ------------------------------------------------------------------ FID

    def _make_eval_gen_fn(self, variables):
        """Validation-set generator closure shared by FID/KID/PRDC.
        Uses the side-effect-free _start_of_iteration (the full hook
        would clobber current_iteration/timers mid-metrics)."""
        def gen_fn(data):
            data = self._eval_preprocess(data)
            out, _ = self._apply_G(variables, data, jax.random.PRNGKey(0),
                                   training=False)
            return out["fake_images"]
        return gen_fn

    def _extra_metric_activations(self, extractor):
        """Image-family activations for KID/PRDC (base template at
        trainers/base.py::compute_extra_metrics); real-set activations
        are cached across a checkpoint sweep."""
        from imaginaire_tpu.evaluation.common import get_activations

        gen_fn = self._make_eval_gen_fn(self.inference_params())
        # device-prefetch the sweep: the next batch transfers while the
        # extractor chews on this one (gen_fn skips re-prep for wrapped
        # batches)
        val_loader = self.data_prefetcher(self.val_data_loader)
        act_fake = get_activations(val_loader, "images",
                                   "fake_images", extractor,
                                   generator_fn=gen_fn)
        data_name = cfg_get(cfg_get(self.cfg, "data", {}), "name", "data")
        act_real = self._cached_real_activations(
            f"real_acts_{data_name}.npz",
            lambda: get_activations(val_loader, "images",
                                    "fake_images", extractor))
        return act_real, act_fake

    def _compute_fid(self):
        """FID for the regular and (if enabled) EMA generator
        (ref: trainers/spade.py:264-295)."""
        if self.val_data_loader is None:
            return None
        import os

        from imaginaire_tpu.evaluation import compute_fid

        try:
            extractor = self._fid_extractor()
        except FileNotFoundError as e:
            print(f"FID skipped: {e}")
            return None

        logdir = cfg_get(self.cfg, "logdir", ".")
        data_name = cfg_get(cfg_get(self.cfg, "data", {}), "name", "data")
        fid_path = os.path.join(logdir, f"real_stats_{data_name}.npz")

        val_loader = self.data_prefetcher(self.val_data_loader)
        fid = compute_fid(fid_path, val_loader, extractor,
                          self._make_eval_gen_fn(self.state["vars_G"]))
        if self.model_average:
            self.recalculate_model_average_batch_norm_statistics()
            fid_ema = compute_fid(
                fid_path, val_loader, extractor,
                self._make_eval_gen_fn(self.inference_params()))
            self._meter("FID_ema").write(float(fid_ema))
        return fid

    def _get_visualizations(self, data):
        """(input, label-viz, fake, [ema-fake]) strip
        (ref: trainers/spade.py:189-215)."""
        data = self._expand_labels(
            to_device(dict(data)))
        rng = jax.random.PRNGKey(0)
        out, _ = self._apply_G(self.state["vars_G"], data, rng,
                               training=False, random_style=True)
        vis = [data["images"][..., :3],
               data["label"][..., :1],
               out["fake_images"][..., :3]]
        if self.model_average:
            # the EMA copy's BN stats are re-estimated over training
            # batches first (ref: trainers/spade.py:189-215, base 415-443)
            self.recalculate_model_average_batch_norm_statistics()
            ema_out, _ = self._apply_G(self.inference_params(), data, rng,
                                       training=False, random_style=True)
            vis.append(ema_out["fake_images"][..., :3])
        return vis

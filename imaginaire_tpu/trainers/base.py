"""GAN training loop skeleton (ref: imaginaire/trainers/base.py).

The reference BaseTrainer owns: a loss registry (criteria + weights),
alternating D/G updates with AMP, EMA model averaging, checkpointing,
image snapshots, FID scheduling, and speed-benchmark timers
(ref: base.py:27-829).

TPU-first redesign:
  - Training state is an explicit pytree
    ``{vars_G, vars_D, opt_G, opt_D, ema_G, num_ema_updates, step, rng_G,
    rng_D, loss_params}`` threaded through two jitted step functions
    (gen_step / dis_step). No wrapper nesting, no .module chains
    (contrast ref: base.py:58-63).
  - The whole update — forward, losses, backward, optimizer, EMA — is one
    XLA program per step type. The reference's per-phase CUDA-sync timers
    (base.py:723-787) map to whole-step wall times under
    ``block_until_ready`` (phases inside one fused program are not
    separable, by design).
  - bf16 is a compute-dtype policy instead of AMP loss scaling (bf16 has
    fp32's exponent range, so no scaler is needed).
  - Data parallelism: batches arrive sharded over the 'data' mesh axis;
    jit partitions the step SPMD-style and inserts gradient all-reduces
    (replaces DDP, ref: utils/trainer.py:193-216).
  - RNG: per-step keys are fold_in(stream, step) — deterministic resume,
    distinct noise per step; per-shard noise diversity comes from XLA
    partitioning the random op itself.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from imaginaire_tpu import telemetry
from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.telemetry import podview
from imaginaire_tpu.optim import (
    get_optimizer_for_params,
    get_scheduler,
    init_optimizer_state,
)
from imaginaire_tpu.parallel.mesh import is_master, master_only_print as print  # noqa: A001
from imaginaire_tpu.parallel.partition import PartitionPlan
from imaginaire_tpu.registry import resolve
from imaginaire_tpu.utils import checkpoint as ckpt_lib
from imaginaire_tpu.utils.meters import Meter
from imaginaire_tpu.utils.model_average import ema_init, ema_update

MUTABLE = ("batch_stats", "spectral")


class BaseTrainer:
    """Lifecycle: start_of_epoch / start_of_iteration / dis_update /
    gen_update / end_of_iteration / end_of_epoch / save_checkpoint /
    load_checkpoint / test (ref: base.py:267-405, 594-670)."""

    def __init__(self, cfg, net_G=None, net_D=None,
                 train_data_loader=None, val_data_loader=None):
        self.cfg = cfg = as_attrdict(cfg)
        self.train_data_loader = train_data_loader
        self.val_data_loader = val_data_loader

        if net_G is None:
            net_G = resolve(cfg.gen.type, "Generator")(cfg.gen, cfg.data)
        if net_D is None and cfg_get(cfg, "dis", None) is not None:
            net_D = resolve(cfg.dis.type, "Discriminator")(cfg.dis, cfg.data)
        self.net_G = net_G
        self.net_D = net_D

        iters_per_epoch = len(train_data_loader) if train_data_loader is not None else 1
        self.tx_G = get_optimizer_for_params(
            cfg.gen_opt, get_scheduler(cfg.gen_opt, iters_per_epoch))
        self.tx_D = get_optimizer_for_params(
            cfg.dis_opt, get_scheduler(cfg.dis_opt, iters_per_epoch))

        tcfg = cfg_get(cfg, "trainer", None) or {}
        self.model_average = cfg_get(tcfg, "model_average", False)
        self.model_average_beta = cfg_get(tcfg, "model_average_beta", 0.9999)
        self.model_average_start = cfg_get(tcfg, "model_average_start_iteration", 1000)
        self.model_average_remove_sn = cfg_get(tcfg, "model_average_remove_sn", True)
        self.clip_grad_norm_G = cfg_get(cfg_get(cfg, "gen_opt", {}), "clip_grad_norm", None)
        self.clip_grad_norm_D = cfg_get(cfg_get(cfg, "dis_opt", {}), "clip_grad_norm", None)
        self.speed_benchmark = cfg_get(tcfg, "speed_benchmark", False)
        # bf16 compute policy — the XLA-native replacement for apex AMP
        # (ref: utils/trainer.py:152-154). Master params stay fp32; the
        # forward/backward runs in compute_dtype (the cast is differentiable,
        # so grads accumulate back into fp32). bf16 shares fp32's exponent
        # range, so no loss scaler is needed. fp32 islands survive the
        # cast: norm statistics (activation_norm), SN power iteration
        # ('spectral' collection), loss accumulation, and audit norms.
        # cfg.trainer.mixed_precision is the structured knob; the legacy
        # scalar cfg.trainer.compute_dtype still works when it is absent
        # or disabled.
        mp = as_attrdict(cfg_get(tcfg, "mixed_precision", None) or {})
        if cfg_get(mp, "enabled", False):
            self.compute_dtype = jnp.dtype(
                cfg_get(mp, "compute_dtype", "bfloat16"))
        else:
            self.compute_dtype = jnp.dtype(
                cfg_get(tcfg, "compute_dtype", "float32"))
        self.mixed_precision = self.compute_dtype != jnp.float32

        # Loss registry (ref: base.py:163-197): subclasses fill weights in
        # _init_loss; loss values come from gen_forward/dis_forward.
        self.weights: Dict[str, float] = {}
        self._init_loss(cfg)

        self.current_epoch = 0
        self.current_iteration = 0
        # bit-exact resume bookkeeping (resilience/, ISSUE 7): the
        # epoch-relative batches-consumed offset rides the checkpoint's
        # runstate sidecar; on resume the train loop fast-forwards the
        # loader by ``resume_batch_in_epoch`` instead of replaying the
        # epoch from batch 0.
        self._epoch_start_iteration = 0
        self.resume_batch_in_epoch = 0
        self.state: Optional[dict] = None
        self.meters: Dict[str, Meter] = {}
        self.time_iteration = None
        self.time_epoch = None
        self._step_flops_probed = False
        # Training-health diagnostics (diagnostics/): the step programs
        # compute a fixed-size health summary at diagnostics.every_n_steps
        # cadence and guard non-finite updates in-graph; the monitor
        # polls with one-step lag so the loop stays fence-free.
        from imaginaire_tpu.diagnostics import HealthMonitor

        self.diag = HealthMonitor(cfg)
        # 2-D (data x model) partition plan (parallel/partition.py):
        # inactive (the seed's replicated-state semantics, byte-identical
        # programs) unless cfg.parallel opted in via mesh_shape/enabled.
        # When active, init_state commits the train state under the
        # plan's NamedShardings — wide conv channels over 'model',
        # optimizer/EMA trees over 'data' (arXiv:2004.13336) — and the
        # step programs constrain their output state to the same
        # layout, so warm steps keep one stable fingerprint.
        self.partition = PartitionPlan(cfg)
        self._state_shardings = None
        # --debug-nans repro runs disable donation: jax_debug_nans
        # re-runs the op eagerly, which would read already-invalidated
        # donated buffers (see train.py)
        self._donate = ((0,) if cfg_get(tcfg, "donate_step_buffers", True)
                        else ())
        # Software-pipelined rollout dispatch (parallel/pipeline.py,
        # ISSUE 14): resolved here so every trainer shares one knob
        # group; only the video trainers' per-frame rollout consumes it.
        from imaginaire_tpu.parallel.pipeline import pipeline_settings

        self.pipeline_cfg = pipeline_settings(cfg)
        # step programs dispatch through the compile ledger
        # (telemetry/xla_obs.py): the same compile that runs the step
        # records memory_analysis/cost_analysis and arms the recompile
        # tripwire; a disabled cfg.xla_obs degrades to plain jax.jit
        from imaginaire_tpu.telemetry import xla_obs

        self._jit_gen_step = xla_obs.compiled_program(
            "gen_step", self._gen_step_fn, donate_argnums=self._donate)
        self._jit_dis_step = xla_obs.compiled_program(
            "dis_step", self._dis_step_fn, donate_argnums=self._donate)

    # ------------------------------------------------------------------ setup

    def _init_loss(self, cfg):
        raise NotImplementedError

    def init_loss_params(self, key):
        """Parameters of loss networks (e.g. VGG); frozen, stored in state."""
        return {}

    def _init_data(self, data):
        """Hook: device-side data prep applied before module init (e.g.
        int-label one-hot expansion). Default: identity."""
        return data

    def _fake_output_for_init(self, data):
        """Shape-example generator output used to init the discriminator
        (unpaired trainers override: their D consumes images_ab/ba)."""
        return {"fake_images": jnp.zeros_like(data["images"])}

    def init_state(self, key, data):
        """Build the full train-state pytree from one example batch.

        The Flax inits run under jit: eager init dispatches every op
        separately (minutes on CPU for a full generator); one traced
        program initializes in seconds.
        """
        from imaginaire_tpu.utils.misc import numeric_only

        data = self._init_data(numeric_only(data))
        k_g, k_d, k_loss, k_noise, k_rg, k_rd = jax.random.split(key, 6)
        # lint: allow(bare-jit) -- one-shot flax init at t=0, before the ledger's first step program
        vars_G = jax.jit(lambda rngs, d: self.net_G.init(rngs, d, training=True))(
            {"params": k_g, "noise": k_noise}, data)
        vars_G = dict(vars_G)
        state: Dict[str, Any] = {
            "vars_G": vars_G,
            "opt_G": init_optimizer_state(self.tx_G, vars_G["params"],
                                          self.partition),
            "step": jnp.zeros((), jnp.int32),
            "rng_G": k_rg,
            "rng_D": k_rd,
            "loss_params": self.init_loss_params(k_loss),
        }
        if self.net_D is not None:
            fake_out = self._fake_output_for_init(data)
            # lint: allow(bare-jit) -- one-shot flax init at t=0
            vars_D = dict(jax.jit(
                lambda rngs, d, f: self.net_D.init(rngs, d, f, training=True))(
                {"params": k_d, "dropout": k_d}, data, fake_out))
            state["vars_D"] = vars_D
            state["opt_D"] = init_optimizer_state(self.tx_D,
                                                  vars_D["params"],
                                                  self.partition)
            # Separate D step counter: with cfg.trainer.dis_step > 1 each
            # sub-step must draw distinct randomness (the G step only
            # advances 'step' once per iteration).
            state["step_D"] = jnp.zeros((), jnp.int32)
        if self.model_average:
            state["ema_G"] = ema_init(
                vars_G["params"], vars_G.get("spectral"),
                remove_sn=self.model_average_remove_sn)
            state["num_ema_updates"] = jnp.zeros((), jnp.int32)
        self.state = self._place_state(state)
        return self.state

    def _place_state(self, state):
        """Commit the state pytree under the partition plan's shardings
        (no-op without an active plan): params model-sharded per the
        rules, optimizer/EMA trees cross-replica sharded over 'data',
        everything committed BEFORE the first step so the compiled
        programs see their final layout from call one — no
        ``sharding_commit`` re-specialization, ``xla/recompiles`` 0.

        Multi-process without a partition plan (ISSUE 8): the state
        commits REPLICATED over the pod-global mesh. Leaving it on
        per-host local devices (the old behavior) silently compiled N
        independent single-host programs — each host trained its own
        replica with no gradient all-reduce at all. Committing globally
        makes the jitted step one SPMD program over every host's
        devices, with XLA inserting the cross-process collectives."""
        if self.partition.active:
            state, self._state_shardings = self.partition.place_state(
                state)
            return state
        if jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from imaginaire_tpu.parallel.mesh import get_mesh
            from imaginaire_tpu.parallel.sharding import assemble_global

            return assemble_global(state,
                                   NamedSharding(get_mesh(), P()))
        return state

    def _constrain_state(self, state):
        """Pin a step program's output state to the placement layout
        (traced; no-op without an active plan). Keeping outputs on the
        exact input shardings is what makes the update-state sharding a
        steady state: moments stay 1/N-resident across steps, donation
        aliases input buffers, and the recompile tripwire stays
        quiet."""
        if not self.partition.active or self._state_shardings is None:
            return state
        return self.partition.constrain_state(state, self._state_shardings)

    # ------------------------------------------------------- subclass hooks

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """Return (loss_dict, new_mutables_G). Traced under jit."""
        raise NotImplementedError

    def dis_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """Return (loss_dict, new_mutables_D). Traced under jit."""
        raise NotImplementedError

    def _get_outputs(self, net_D_output, real=True):
        """Relativistic GAN support: difference of D outputs
        (ref: base.py:498-536)."""
        relativistic = cfg_get(cfg_get(self.cfg, "trainer", {}), "gan_relativistic", False)

        def diff(a, b):
            return [diff(x, y) if isinstance(x, list) else x - y
                    for x, y in zip(a, b)]

        if real:
            if relativistic:
                return diff(net_D_output["real_outputs"], net_D_output["fake_outputs"])
            return net_D_output["real_outputs"]
        if relativistic:
            return diff(net_D_output["fake_outputs"], net_D_output["real_outputs"])
        return net_D_output["fake_outputs"]

    def _to_compute_dtype(self, tree):
        """Cast fp32 leaves to the compute dtype (identity for fp32 policy)."""
        if self.compute_dtype == jnp.float32:
            return tree
        dt = self.compute_dtype
        return jax.tree_util.tree_map(
            lambda x: x.astype(dt)
            if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, tree)

    def _cast_net_vars(self, variables):
        """Compute-dtype view of a network's variables: cast ONLY the
        ``params`` collection. The fp32 islands — ``batch_stats`` running
        moments and the SN ``spectral`` u vectors — keep their dtype so
        statistics/power-iteration stay full-precision under bf16."""
        if variables is None or self.compute_dtype == jnp.float32:
            return variables
        return dict(variables,
                    params=self._to_compute_dtype(variables["params"]))

    def _total(self, losses):
        """Weighted sum over registered losses (ref: base.py:698-714)."""
        total = jnp.zeros(())
        for name, w in self.weights.items():
            if name in losses:
                total = total + losses[name].astype(jnp.float32) * w
        return total

    # --------------------------------------------------------- jitted steps

    def _audit_guard(self, losses, grads, state, net_key, opt_key,
                     new_params, new_opt, new_mut):
        """Diagnostics seam shared by the G/D step fns: compute the
        per-step finite flag, guard the update in-graph (a non-finite
        update never lands — params/opt/mutables keep their previous
        finite values), and hand back the guarded trees plus the
        (flag, grad-norm) pair the health summary reuses. Traced into
        the step programs; a no-op returning ``None`` flags when
        diagnostics are off."""
        if not self.diag.enabled:
            return new_params, new_opt, new_mut, None, None
        from imaginaire_tpu.diagnostics import audit

        grad_norm = audit.tree_norm(grads)
        ok = audit.finite_flag(losses["total"], grad_norm)
        old_vars = state[net_key]
        new_params = audit.select_finite(ok, new_params, old_vars["params"])
        new_opt = audit.select_finite(ok, new_opt, state[opt_key])
        new_mut = {k: (audit.select_finite(ok, v, old_vars[k])
                       if k in old_vars else v)
                   for k, v in new_mut.items()}
        return new_params, new_opt, new_mut, ok, grad_norm

    def _audit_health(self, ok, grad_norm, step_counter, grads, params,
                      updates, spectral=None, ema=None):
        """The step program's health summary: per-module norms under the
        cadence cond, plus the per-step control flags the monitor polls.
        Returns {} when diagnostics are off (stable step-fn arity)."""
        if ok is None:
            return {}
        from imaginaire_tpu.diagnostics import audit

        pred = (step_counter % self.diag.every_n) == 0
        health = audit.health_at_cadence(pred, grads, params, updates,
                                         spectral=spectral, ema=ema,
                                         grad_norm_total=grad_norm)
        health["finite"] = ok
        health["audited"] = pred
        health["rng_step"] = step_counter
        return health

    def _gen_step_fn(self, state, data):
        step0 = state["step"]
        rng = jax.random.fold_in(state["rng_G"], step0)

        def loss_fn(params_G):
            vars_G = dict(state["vars_G"], params=self._to_compute_dtype(params_G))
            losses, new_mut = self.gen_forward(
                vars_G, self._cast_net_vars(state.get("vars_D")),
                state["loss_params"], self._to_compute_dtype(data), rng)
            losses = {k: v.astype(jnp.float32) for k, v in losses.items()}
            total = self._total(losses)
            return total, (dict(losses, total=total), new_mut)

        (_, (losses, new_mut)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["vars_G"]["params"])
        if self.clip_grad_norm_G:
            grads, _ = optax.clip_by_global_norm(self.clip_grad_norm_G).update(grads, optax.EmptyState())
        updates, new_opt = self.tx_G.update(
            grads, state["opt_G"], state["vars_G"]["params"])
        new_params = optax.apply_updates(state["vars_G"]["params"], updates)
        new_params, new_opt, new_mut, ok, grad_norm = self._audit_guard(
            losses, grads, state, "vars_G", "opt_G",
            new_params, new_opt, new_mut)
        new_vars_G = dict(state["vars_G"], params=new_params, **new_mut)
        state = dict(state, vars_G=new_vars_G, opt_G=new_opt,
                     step=step0 + 1)
        if self.model_average:
            n = state["num_ema_updates"] + 1
            state["ema_G"] = ema_update(
                state["ema_G"], new_params, n,
                beta=self.model_average_beta,
                start_iteration=self.model_average_start,
                spectral=new_vars_G.get("spectral"),
                remove_sn=self.model_average_remove_sn)
            state["num_ema_updates"] = n
        health = self._audit_health(
            ok, grad_norm, step0, grads, new_params, updates,
            spectral=new_vars_G.get("spectral"),
            ema=state.get("ema_G") if self.model_average else None)
        return self._constrain_state(state), losses, health

    def _dis_step_fn(self, state, data):
        step0 = state["step_D"]
        rng = jax.random.fold_in(state["rng_D"], step0)

        def loss_fn(params_D):
            vars_D = dict(state["vars_D"], params=self._to_compute_dtype(params_D))
            losses, new_mut = self.dis_forward(
                self._cast_net_vars(state["vars_G"]), vars_D,
                state["loss_params"], self._to_compute_dtype(data), rng)
            losses = {k: v.astype(jnp.float32) for k, v in losses.items()}
            total = self._total(losses)
            return total, (dict(losses, total=total), new_mut)

        (_, (losses, new_mut)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["vars_D"]["params"])
        if self.clip_grad_norm_D:
            grads, _ = optax.clip_by_global_norm(self.clip_grad_norm_D).update(grads, optax.EmptyState())
        updates, new_opt = self.tx_D.update(
            grads, state["opt_D"], state["vars_D"]["params"])
        new_params = optax.apply_updates(state["vars_D"]["params"], updates)
        new_params, new_opt, new_mut, ok, grad_norm = self._audit_guard(
            losses, grads, state, "vars_D", "opt_D",
            new_params, new_opt, new_mut)
        new_vars_D = dict(state["vars_D"], params=new_params, **new_mut)
        state = dict(state, vars_D=new_vars_D,
                     opt_D=new_opt, step_D=step0 + 1)
        health = self._audit_health(
            ok, grad_norm, step0, grads, new_params, updates,
            spectral=new_vars_D.get("spectral"))
        return self._constrain_state(state), losses, health

    # ------------------------------------------------------------ lifecycle

    def gen_update(self, data):
        """(ref: base.py:594-632)."""
        t0 = time.time() if self.speed_benchmark else None
        from imaginaire_tpu.utils.misc import numeric_only

        batch = numeric_only(data)
        with telemetry.span("gen_step", step=self.current_iteration):
            self.state, losses, health = self._jit_gen_step(self.state,
                                                            batch)
        # polls the PREVIOUS step's finite flag (already complete — no
        # pipeline stall) and triggers triage/skip/halt on non-finite
        self.diag.observe(self, "G", losses, health, batch,
                          self.current_iteration)
        if self.speed_benchmark:
            # lint: allow(host-sync) -- speed_benchmark timing fence, opt-in flag only
            jax.block_until_ready(self.state["vars_G"]["params"])
            self._meter("time/gen_step").write(time.time() - t0)
        self._log_losses("gen_update", losses)
        return losses

    def dis_update(self, data):
        """(ref: base.py:638-666)."""
        if self.net_D is None:
            return None
        t0 = time.time() if self.speed_benchmark else None
        from imaginaire_tpu.utils.misc import numeric_only

        batch = numeric_only(data)
        with telemetry.span("dis_step", step=self.current_iteration):
            self.state, losses, health = self._jit_dis_step(self.state,
                                                            batch)
        self.diag.observe(self, "D", losses, health, batch,
                          self.current_iteration)
        if self.speed_benchmark:
            # lint: allow(host-sync) -- speed_benchmark timing fence
            jax.block_until_ready(self.state["vars_D"]["params"])
            self._meter("time/dis_step").write(time.time() - t0)
        self._log_losses("dis_update", losses)
        return losses

    def start_of_epoch(self, current_epoch):
        self._start_of_epoch(current_epoch)
        self.current_epoch = current_epoch
        self.start_epoch_time = time.time()
        # epoch-relative batch accounting: normally this epoch starts at
        # the current iteration; on the first epoch after a mid-epoch
        # resume, ``resume_batch_in_epoch`` batches were already
        # consumed before the kill (the train loop fast-forwards the
        # loader past them), so the epoch's true start lies behind us.
        offset = int(self.resume_batch_in_epoch or 0)
        self._epoch_start_iteration = self.current_iteration - offset
        self.resume_batch_in_epoch = 0

    def start_of_iteration(self, data, current_iteration):
        from imaginaire_tpu.data.device_prefetch import PrefetchedBatch

        # the data_wait span covers the host hook + H2D transfer (the
        # per-step input cost this process pays; the feed wait itself is
        # a sibling span in the train loop). Near-zero for prefetched
        # batches — exactly what the phase table should show.
        with telemetry.span("data_wait", step=current_iteration):
            prefetched = isinstance(data, PrefetchedBatch)
            if not prefetched:
                data = self._start_of_iteration(data, current_iteration)
            self.current_iteration = current_iteration
            self.start_iteration_time = time.time()
            self._maybe_profile(current_iteration)
            if prefetched:
                # a DevicePrefetcher already ran the host hook and
                # committed the numeric leaves as sharded device arrays
                # — re-running either would drag them back through the
                # host
                return data
            from imaginaire_tpu.utils.misc import to_device

            return to_device(data)

    def data_prefetcher(self, loader, iteration_of=None):
        """Wrap ``loader`` in a DevicePrefetcher honoring the
        ``data.device_prefetch`` knob; the loader comes back unchanged
        when prefetch is off (the synchronous to_device path) or the
        loader is already wrapped.

        ``iteration_of``: optional ``index -> current_iteration``
        mapping handed to the host-side ``_start_of_iteration`` hook
        (the train loop's epoch-relative counter); metric/test sweeps
        omit it and the hook sees -1, the side-effect-free mode.
        """
        from imaginaire_tpu.data.device_prefetch import (
            DevicePrefetcher,
            prefetch_settings,
        )

        enabled, depth = prefetch_settings(self.cfg)
        if not enabled or loader is None \
                or isinstance(loader, DevicePrefetcher):
            return loader

        def host_preprocess(batch, index):
            it = iteration_of(index) if iteration_of is not None else -1
            return self._start_of_iteration(batch, it)

        return DevicePrefetcher(loader, host_preprocess=host_preprocess,
                                depth=depth)

    def write_data_meters(self, stats):
        """Record drained DevicePrefetcher stats ({meter: [floats]}) —
        flushed with the loss meters on logging_iter, never a device
        sync (values are already host floats)."""
        for name, values in (stats or {}).items():
            meter = self._meter(name)
            for value in values:
                meter.write(value)

    def _eval_preprocess(self, data):
        """Side-effect-free per-batch prep for metric sweeps: host hook
        + transfer, skipped when a DevicePrefetcher already did both.
        ISSUE 18: the transfer is the committed data-axis placement, so
        the eval generator forward shards over the mesh exactly like a
        training step instead of running replicated."""
        from imaginaire_tpu.data.device_prefetch import PrefetchedBatch

        if isinstance(data, PrefetchedBatch):
            return data
        from imaginaire_tpu.parallel.sharding import place_committed_batch

        return place_committed_batch(self._start_of_iteration(data, -1))

    def _maybe_profile(self, current_iteration):
        """XLA profiler trace window (the jax-native replacement for the
        reference's speed_benchmark nvprof runs, SURVEY §5.1): configure
        cfg.trainer.profile = {start_iteration: N, num_iterations: K} to
        capture steps [N, N+K) into <logdir>/profile for perfetto/xprof."""
        pcfg = cfg_get(cfg_get(self.cfg, "trainer", {}) or {}, "profile",
                       None)
        if pcfg is None:
            return
        start = cfg_get(pcfg, "start_iteration", 10)
        num = cfg_get(pcfg, "num_iterations", 5)
        if current_iteration == start and not getattr(self, "_profiling",
                                                      False):
            path = os.path.join(cfg_get(self.cfg, "logdir", "."), "profile")
            jax.profiler.start_trace(path)
            self._profiling = True
            print(f"jax.profiler trace started -> {path}")
        elif getattr(self, "_profiling", False) and \
                current_iteration >= start + num:
            jax.profiler.stop_trace()
            self._profiling = False
            print("jax.profiler trace stopped")

    def end_of_iteration(self, data, current_epoch, current_iteration):
        """(ref: base.py:294-373)."""
        self.current_epoch = current_epoch
        self.current_iteration = current_iteration
        self._end_of_iteration(data, current_epoch, current_iteration)
        self.time_iteration = time.time() - self.start_iteration_time
        tm = telemetry.get()
        if tm.enabled:
            self._register_step_flops(data)
            # heartbeat + ring-buffer accounting; the fence only runs at
            # the flush interval (never a per-step device sync)
            tm.step_complete(
                current_iteration, items=self._batch_items(data),
                dur_s=self.time_iteration,
                # lint: allow(host-sync) -- heartbeat fence, runs only at the telemetry flush interval
                fence=lambda: jax.block_until_ready(self.state))
            # pod digest (podview.py, ISSUE 17): publish/aggregate at
            # the digest cadence; inert null object single-process
            podview.get().on_step(current_iteration)
        cfg = self.cfg
        if current_iteration % cfg_get(cfg, "logging_iter", 100) == 0:
            self._meter("time/iteration").write(self.time_iteration)
            self._flush_meters(current_iteration)
            if cfg_get(cfg.trainer, "log_weight_stats", False):
                self._write_weight_stats(current_iteration)
        if current_iteration % cfg_get(cfg, "snapshot_save_iter", 10000) == 0:
            self.save_checkpoint(current_epoch, current_iteration)
            self.write_metrics()
        if current_iteration % cfg_get(cfg, "image_save_iter", 10000) == 0:
            self.save_image(self._image_path(current_iteration), data)
        # continuous eval (ISSUE 18): mid-training FID/KID sweeps at the
        # cfg.evaluation.every_n_iter cadence, through the sharded plane
        # + reference store — quality lands in the same jsonl the
        # throughput counters do
        eval_every = cfg_get(cfg_get(cfg, "evaluation", {}) or {},
                             "every_n_iter", None)
        if eval_every and current_iteration % int(eval_every) == 0:
            self.continuous_eval(current_iteration)

    def end_of_epoch(self, data, current_epoch, current_iteration):
        """(ref: base.py:375-405)."""
        self.current_epoch = current_epoch
        self.current_iteration = current_iteration
        # the last step's health entry is still pending (the monitor
        # polls with one-step lag); the epoch boundary is a safe place
        # to block on it
        self.diag.drain(self)
        self._end_of_epoch(data, current_epoch, current_iteration)
        self.time_epoch = time.time() - self.start_epoch_time
        print(f"Epoch: {current_epoch}, total time: {self.time_epoch:6f}.")
        if current_epoch % cfg_get(self.cfg, "snapshot_save_epoch", 20) == 0:
            self.save_checkpoint(current_epoch, current_iteration)
            self.write_metrics()

    @staticmethod
    def _batch_items(data):
        """Samples in a batch (imgs/sec accounting): leading dim of the
        first array leaf; video batches count frames (B*T)."""
        try:
            leaves = [v for v in (data or {}).values()
                      if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1]
            if not leaves:
                return 0
            lead = leaves[0]
            if getattr(lead, "ndim", 0) >= 5:  # (B, T, H, W, C)
                return int(lead.shape[0]) * int(lead.shape[1])
            return int(lead.shape[0])
        except Exception:  # noqa: BLE001 — accounting must never raise
            return 0

    def _register_step_flops(self, data):
        """Register per-iteration FLOPs with telemetry ONCE, from the
        compile ledger's cost analysis of the two step programs (the
        ``scripts/perf_lab.py`` numbers, but recorded by the SAME
        compile that runs the step — no duplicate lower/compile),
        weighted by the dis_step/gen_step multipliers. Also emits the
        one-shot static memory-budget report (executable footprints +
        state tree sizes). Falls back to an explicit lower/compile when
        the ledger is disabled. Guarded by ``telemetry.mfu``; failures
        degrade to a debug log (MFU simply stays absent). Trainers
        whose update is not the base two-program step (vid2vid's
        per-frame rollout) override this to a no-op."""
        tm = telemetry.get()
        if self._step_flops_probed or not (tm.enabled and tm.wants_mfu) \
                or tm.step_flops is not None:
            return
        self._step_flops_probed = True
        from imaginaire_tpu.telemetry import xla_obs

        programs = [("gen_step", self._jit_gen_step,
                     cfg_get(self.cfg.trainer, "gen_step", 1))]
        if self.net_D is not None:
            programs.append(("dis_step", self._jit_dis_step,
                             cfg_get(self.cfg.trainer, "dis_step", 1)))
        ledger_flops = xla_obs.ledger_flops()
        total = 0.0
        try:
            for label, fn, mult in programs:
                flops = ledger_flops.get(label)
                if flops is None:
                    # ledger disabled/passthrough: the one-time
                    # explicit compile the ledger otherwise replaces
                    from imaginaire_tpu.utils.misc import numeric_only

                    with telemetry.span("cost_analysis"):
                        cost = fn.lower(self.state,
                                        numeric_only(data)).compile() \
                            .cost_analysis()
                    if isinstance(cost, list):
                        cost = cost[0]
                    flops = (cost or {}).get("flops")
                if flops is None or not math.isfinite(float(flops)):
                    return
                total += float(flops) * mult
        except Exception as e:  # noqa: BLE001 — MFU is best-effort
            import logging

            logging.getLogger(__name__).debug(
                "step cost analysis unavailable: %s", e)
            return
        tm.set_step_flops(total)
        # both step executables exist by now: report whether the run
        # fits (per-executable memory_analysis + param/opt/EMA bytes)
        xla_obs.emit_budget_report(self.state, tm=tm)

    def _write_weight_stats(self, step):
        """Spectral-norm σ/weight-norm stats per logging interval
        (ref: utils/meters.py:19-51, get_weight_stats — the reference
        ships it unwired; enable via trainer.log_weight_stats)."""
        from imaginaire_tpu.utils.meters import write_weight_stats

        for net_key, prefix in (("vars_G", "weights/G"),
                                ("vars_D", "weights/D")):
            tree = (self.state or {}).get(net_key)
            if tree and tree.get("spectral"):
                write_weight_stats(
                    prefix,
                    # lint: allow(host-sync) -- logging-cadence stat dump
                    jax.device_get(tree["params"]),
                    # lint: allow(host-sync) -- logging-cadence stat dump
                    jax.device_get(tree["spectral"]), step)

    # subclass extension points (ref: base.py:481-585)
    def _start_of_epoch(self, current_epoch):
        pass

    def _start_of_iteration(self, data, current_iteration):
        return data

    def _end_of_iteration(self, data, current_epoch, current_iteration):
        pass

    def _end_of_epoch(self, data, current_epoch, current_iteration):
        pass

    def _get_visualizations(self, data):
        return None

    def _fid_extractor(self):
        """Cached Inception-v3 feature extractor for FID
        (ref: evaluation/fid.py:16-58); fails loudly without ported
        weights unless trainer.fid_random_init."""
        if getattr(self, "_cached_fid_extractor", None) is None:
            from imaginaire_tpu.evaluation import inception

            variables = inception.load_params(
                random_init=cfg_get(cfg_get(self.cfg, "trainer", {}),
                                    "fid_random_init", False))
            self._cached_fid_extractor = inception.make_extractor(variables)
        return self._cached_fid_extractor

    def _compute_fid(self):
        return None

    def _extra_metric_activations(self, extractor):
        """Return (act_real, act_fake) Inception activations for KID/PRDC,
        or None when the trainer family doesn't support them. Image
        trainers use get_activations over the val loader; video trainers
        the pinned-sequence rollout (get_video_activations)."""
        return None

    def _cached_real_activations(self, cache_name, compute):
        """Real-set activations are identical across a checkpoint sweep —
        cache them beside the logdir like the FID real stats (tagged with
        the inception feature-graph version so a changed extractor
        recomputes). Random-init extractors (tests) never cache: their
        features change per process."""
        import os

        import numpy as np

        from imaginaire_tpu.evaluation.fid import FEATURE_GRAPH_VERSION

        if cfg_get(cfg_get(self.cfg, "trainer", {}), "fid_random_init",
                   False):
            return compute()
        path = os.path.join(cfg_get(self.cfg, "logdir", "."), cache_name)
        if os.path.exists(path):
            npz = np.load(path)
            if int(npz.get("graph_version", 0)) == FEATURE_GRAPH_VERSION:
                return npz["acts"]
        acts = compute()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, acts=acts, graph_version=FEATURE_GRAPH_VERSION)
        return acts

    def compute_extra_metrics(self, metrics):
        """KID / PRDC -> {name: value} — metrics the reference ships as
        library code (evaluation/kid.py, prdc.py) but never wires into
        its evaluate sweep; here evaluate.py --metrics does. The trainer
        family supplies activations via _extra_metric_activations; one
        (real, fake) pass feeds both metrics."""
        out = {}
        metrics = {str(m).lower() for m in (metrics or ())}
        unknown = metrics - {"kid", "prdc"}
        if unknown:
            print(f"Unknown extra metrics ignored: {sorted(unknown)}")
        metrics &= {"kid", "prdc"}
        if not metrics or self.val_data_loader is None:
            return out
        try:
            extractor = self._fid_extractor()
        except FileNotFoundError as e:
            print(f"extra metrics skipped: {e}")
            return out
        with telemetry.span("eval", step=self.current_iteration):
            acts = self._extra_metric_activations(extractor)
        if acts is None:
            return out
        act_real, act_fake = acts

        from imaginaire_tpu.evaluation.kid import kid_from_activations
        from imaginaire_tpu.evaluation.prdc import prdc_from_activations

        if "kid" in metrics:
            out["KID"] = float(kid_from_activations(act_real, act_fake))
        if "prdc" in metrics:
            prdc = prdc_from_activations(act_real, act_fake)
            out.update({f"PRDC_{k}": float(v) for k, v in prdc.items()})
        for name, value in out.items():
            self._meter(name).write(value)
        self._flush_meters(self.current_iteration)
        return out

    def write_metrics(self):
        """FID + best-FID tracking (ref: base.py:467-479)."""
        with telemetry.span("eval", step=self.current_iteration):
            fid = self._compute_fid()
        telemetry.get().heartbeat(self.current_iteration)
        if fid is not None:
            if getattr(self, "best_fid", None) is None or fid < self.best_fid:
                self.best_fid = fid
            self._meter("FID").write(float(fid))
            self._meter("best_FID").write(float(self.best_fid))
            self._flush_meters(self.current_iteration)

    # -------------------------------------------- quality plane (ISSUE 18)

    def eval_plane(self):
        """The trainer's quality-observability plane (lazy: the store
        directory and sentinel state live for the whole run, so sweep N
        hits the reference shard sweep 1 wrote and the EWMA trend spans
        the run)."""
        if getattr(self, "_eval_plane", None) is None:
            from imaginaire_tpu.evaluation.plane import EvalPlane

            self._eval_plane = EvalPlane(
                self.cfg, logdir=cfg_get(self.cfg, "logdir", "."))
        return self._eval_plane

    def _eval_resolution(self):
        """The eval-time resolution tag riding the reference-store key
        (from the val pipeline's deterministic sizing knobs; 'native'
        when none constrain it)."""
        data_cfg = cfg_get(self.cfg, "data", {}) or {}
        for group in (cfg_get(data_cfg, "val", None) or {}, data_cfg):
            aug = cfg_get(group, "augmentations", None) or {}
            for key in ("center_crop_h_w", "resize_h_w",
                        "random_crop_h_w"):
                value = cfg_get(aug, key, None)
                if value:
                    return str(value).replace(" ", "").replace(",", "x")
            side = cfg_get(aug, "resize_smallest_side", None)
            if side:
                return f"ss{int(side)}"
        return "native"

    def run_quality_sweep(self, step=None, metrics=None, max_batches=None):
        """One sweep through the sharded eval plane: reference acts via
        the content-addressed store, fake acts via the instrumented
        mesh-placed loop, FID (+KID) with ``eval/*`` counters and the
        regression sentinel. The single entry point continuous eval
        (``continuous_eval``) and offline ``evaluate.py`` share, so
        both emit one schema. Returns the plane's results dict or None
        (no val loader / no image-family generator closure / missing
        inception weights)."""
        if self.val_data_loader is None:
            return None
        make_gen = getattr(self, "_make_eval_gen_fn", None)
        vars_g = (self.state or {}).get("vars_G") \
            if isinstance(self.state, dict) else None
        if make_gen is None or vars_g is None:
            return None
        plane = self.eval_plane()
        extractor_tag = None
        if plane.settings.get("extractor") == "patch":
            # CI smoke extractor: the whole plane (placement, ledger,
            # store, sentinel) at negligible cost; tagged so its shards
            # never collide with real inception features
            from imaginaire_tpu.evaluation.plane import make_patch_extractor

            if getattr(self, "_cached_patch_extractor", None) is None:
                self._cached_patch_extractor = make_patch_extractor()
            extractor = self._cached_patch_extractor
            extractor_tag = "patch-v1:g8"
            random_init = False
        else:
            try:
                extractor = self._fid_extractor()
            except FileNotFoundError as e:
                print(f"quality sweep skipped: {e}")
                return None
            random_init = cfg_get(cfg_get(self.cfg, "trainer", {}),
                                  "fid_random_init", False)
        dataset_name = cfg_get(cfg_get(self.cfg, "data", {}) or {},
                               "name", "data")
        val_loader = self.data_prefetcher(self.val_data_loader)
        return plane.run_sweep(
            val_loader, "images", "fake_images", extractor,
            make_gen(vars_g),
            step=self.current_iteration if step is None else step,
            dataset_name=dataset_name, resolution=self._eval_resolution(),
            random_init=random_init, max_batches=max_batches,
            metrics=metrics, extractor_tag=extractor_tag)

    def continuous_eval(self, step, metrics=None):
        """The ``cfg.evaluation.every_n_iter`` cadence hook: a full
        quality sweep inside the watchdog-exempt eval span (sweeps are
        legitimately step-shaped-free time; the heartbeat re-arms from
        span exit), feeding the FID/best_FID meters like the
        snapshot-time ``write_metrics`` path does. ``evaluate.py``
        calls it per checkpoint with an explicit metrics list."""
        with telemetry.span("eval", step=step):
            result = self.run_quality_sweep(step=step, metrics=metrics)
        telemetry.get().heartbeat(step)
        if result is None:
            return None
        fid = result["fid"]
        if getattr(self, "best_fid", None) is None or fid < self.best_fid:
            self.best_fid = fid
        self._meter("FID").write(float(fid))
        self._meter("best_FID").write(float(self.best_fid))
        if "kid" in result:
            self._meter("KID").write(float(result["kid"]))
        self._flush_meters(step)
        return result

    # --------------------------------------------------------- persistence

    def _pre_save_checkpoint(self):
        """Hook run before checkpoint serialization (ref: base.py:408-414,
        e.g. pix2pixHD computes K-means cluster centers here)."""
        pass

    def save_checkpoint(self, current_epoch, current_iteration,
                        emergency=False):
        """(ref: base.py:790-829).

        ``emergency``: the preemption-guard path — forces a synchronous
        commit (the process is about to exit; an async save would race
        the teardown) and stamps the run state so resume is bit-exact.
        """
        from imaginaire_tpu import resilience

        self._pre_save_checkpoint()
        logdir = cfg_get(self.cfg, "logdir", ".")
        rset = resilience.resilience_settings(self.cfg)
        meta = {"epoch": current_epoch, "iteration": current_iteration}
        path = ckpt_lib.save_checkpoint(
            logdir, {"state": self.state, "meta": meta},
            current_epoch, current_iteration,
            max_to_keep=cfg_get(self.cfg, "checkpoints_to_keep", None),
            async_save=(not emergency
                        and bool(cfg_get(self.cfg.trainer,
                                         "async_checkpoint", False))),
            # Partition descriptor sidecar: restore compares it against
            # the live plan and reshards (jax.device_put) on any
            # mesh-shape / sharding-policy change instead of crashing or
            # silently replicating (see load_checkpoint). ISSUE 7: the
            # per-leaf checksums ride the same sidecar.
            partition_descriptor=(self.partition.describe()
                                  if self.partition.active else None),
            checksum=rset["checksum"])
        # Run-state sidecar (resilience/runstate.py): the host-side half
        # of a bit-exact resume — mid-epoch data position plus the
        # HealthMonitor and telemetry-ring state the pointer-file
        # restart used to silently reset.
        resilience.write_runstate(path, resilience.build_runstate(
            current_epoch, current_iteration,
            current_iteration - self._epoch_start_iteration,
            monitor=self.diag.state_dict(),
            telemetry_state=telemetry.get().state_dict()))
        # Recalibrated EMA BN stats ride alongside (a sibling file keeps
        # the state tree's structure stable across checkpoint versions);
        # the reference persists them inside the averaged model's buffers.
        if getattr(self, "_ema_batch_stats", None) is not None \
                and is_master():
            import pickle

            with open(path + ".ema_bn.pkl", "wb") as f:
                # lint: allow(host-sync) -- checkpoint serialization path
                pickle.dump(jax.device_get(self._ema_batch_stats), f)
        print(f"Save checkpoint to {path}")
        return path

    def load_checkpoint(self, checkpoint_path=None, resume=None,
                        fallback=False):
        """(ref: base.py:210-265): explicit path = weights-only unless
        resume=True; pointer-file discovery = resume.

        The discovery path verifies checksums and falls back: a corrupt
        / truncated pointed checkpoint is quarantined and the newest
        verifiable one restores instead (``ckpt_lib.load_latest_verified``).
        An explicit path never falls back by default — the caller asked
        for that exact checkpoint, so corruption raises; serving entry
        points (inference.py) pass ``fallback=True`` to quarantine the
        bad checkpoint and restore the newest verifiable sibling
        instead (ISSUE 8: serving must never deserialize a checkpoint
        training would refuse)."""
        from imaginaire_tpu import resilience

        logdir = cfg_get(self.cfg, "logdir", ".")
        verify = resilience.resilience_settings(self.cfg)["verify_on_load"]
        # restore-structure donor: the live state, or — after an
        # elastic rebind dropped it — the abstract template captured
        # from it (ISSUE 11). Orbax only needs per-leaf shape/dtype
        # plus the tree structure; without a donor the no-target path
        # returns nested dicts and the optimizer NamedTuples are lost.
        template = self.state if self.state is not None else getattr(
            self, "_elastic_state_template", None)
        target = ({"state": template,
                   "meta": {"epoch": 0, "iteration": 0}}
                  if template is not None else None)
        # an in-flight async save must commit before we read anything back
        ckpt_lib.wait_for_pending_checkpoint()
        if checkpoint_path is None:
            payload, checkpoint_path, fallbacks = \
                ckpt_lib.load_latest_verified(logdir, target=target,
                                              verify=verify)
            # Pod resume agreement (ISSUE 8): every host verified its
            # own candidate above; the cluster restores ONE checkpoint
            # (min over verified) or a host that disagreed follows it.
            payload, checkpoint_path = self._consensus_restore(
                payload, checkpoint_path, logdir, target, verify)
            if payload is None:
                print("No checkpoint found.")
                return False
            if fallbacks:
                print(f"Checkpoint fallback: restored {checkpoint_path} "
                      f"after quarantining {fallbacks} corrupt "
                      f"checkpoint(s)")
            resume = True if resume is None else resume
        else:
            try:
                payload = ckpt_lib.load_checkpoint(checkpoint_path,
                                                   target=target,
                                                   verify=verify)
            except Exception as e:  # noqa: BLE001 — corrupt/truncated
                if not fallback:
                    raise
                # serving fallback (ISSUE 8 satellite): quarantine the
                # named checkpoint and restore the newest one in its
                # directory that training itself would accept — a
                # server must never deserialize bytes the training
                # integrity layer refuses
                from imaginaire_tpu.resilience import (
                    quarantine_checkpoint,
                )

                print(f"WARNING: checkpoint {checkpoint_path} failed "
                      f"to restore ({type(e).__name__}: {str(e)[:200]});"
                      f" falling back to the newest verifiable "
                      f"checkpoint in its directory")
                quarantine_checkpoint(checkpoint_path,
                                      reason=f"serving restore failed: "
                                             f"{type(e).__name__}")
                ckpt_dir = os.path.dirname(
                    os.path.abspath(str(checkpoint_path)))
                payload, checkpoint_path, fallbacks = \
                    ckpt_lib.load_latest_verified(ckpt_dir,
                                                  target=target,
                                                  verify=verify)
                if payload is None:
                    raise RuntimeError(
                        f"no verifiable fallback checkpoint in "
                        f"{ckpt_dir} (no pointer file)") from e
                print(f"Serving fallback: restored {checkpoint_path}")
        restored = payload["state"]
        if resume:
            self.state = restored
            self.current_epoch = int(payload["meta"]["epoch"])
            self.current_iteration = int(payload["meta"]["iteration"])
            self._restore_runstate(checkpoint_path)
        elif self.state is None:
            # weights-only load before init_state: adopt the restored
            # state wholesale (counters stay at 0).
            self.state = restored
        else:
            # weights only
            self.state["vars_G"] = restored["vars_G"]
            if "vars_D" in restored and "vars_D" in self.state:
                self.state["vars_D"] = restored["vars_D"]
            if "ema_G" in restored:
                self.state["ema_G"] = restored["ema_G"]
        self._elastic_state_template = None  # structure donor consumed
        if resume:
            # mixed redistribution plan (ISSUE 13): leaves the
            # RedistributionPlanner routed "gather" were carried live
            # across the resize — overwrite the restored copies before
            # the re-commit so the carried bytes (bit-identical to the
            # emergency checkpoint by the planner's iteration guard)
            # are what lands under the new shardings
            self._apply_elastic_carry()
        self._reshard_restored_state(checkpoint_path)
        bn_path = str(checkpoint_path) + ".ema_bn.pkl"
        if os.path.exists(bn_path):
            import pickle

            with open(bn_path, "rb") as f:
                self._ema_batch_stats = pickle.load(f)
        print(f"Done with loading the checkpoint (resume={bool(resume)}).")
        return True

    def _consensus_restore(self, payload, checkpoint_path, logdir,
                           target, verify):
        """Pod resume agreement (ISSUE 8): every host publishes the
        iteration of the newest checkpoint IT verified; the cluster
        restores the min over verified. A host whose local candidate
        was newer (its copy of the consensus target verified, a peer's
        did not) — or whose own verification failed where a peer's
        succeeded — follows the consensus instead of silently training
        from different weights than the rest of the pod. A host that
        cannot restore the agreed checkpoint at all raises
        ``ClusterDesyncError`` (diverging silently is the one
        unacceptable outcome; ``resilience/resume_divergence`` stays
        fatal in the health gate). Single-process: identity."""
        from imaginaire_tpu.resilience import cluster

        if not cluster.is_active():
            return payload, checkpoint_path
        if cluster.membership_epoch() > 0:
            # post-resize membership (ISSUE 13): the checkpoint to
            # resume from was already agreed cluster-wide by the
            # ResizePlan, and restores are now legitimately asymmetric
            # — survivors on the live-gather route never call
            # load_checkpoint, so a joiner voting here would wait on
            # peers that are already training and desync the pod
            return payload, checkpoint_path
        it_local = (ckpt_lib.parse_checkpoint_name(checkpoint_path)[1]
                    if checkpoint_path else -1)
        name_local = (os.path.basename(str(checkpoint_path))
                      if checkpoint_path else None)
        consensus, votes = cluster.agree_min("resume", it_local,
                                             extra=name_local)
        if consensus < 0 or it_local == consensus:
            # nobody has a checkpoint, or this host already holds the
            # agreed one
            return payload, checkpoint_path
        name = next((x for v, x in votes.values()
                     if v == consensus and x), None)
        tm = telemetry.get()
        if tm.enabled:
            tm.meta("resilience/consensus_resume",
                    local_iteration=it_local, consensus=consensus,
                    consensus_checkpoint=name,
                    votes={str(p): v for p, (v, _) in votes.items()})
            tm.counter("resilience/consensus_overrides", 1)
        print(f"Pod resume consensus: this host verified iteration "
              f"{it_local if it_local >= 0 else '<none>'} but the "
              f"cluster agreed on {consensus} ({name}); following the "
              f"consensus")
        path = os.path.join(logdir, name)
        try:
            payload = ckpt_lib.load_checkpoint(path, target=target,
                                               verify=verify)
        except Exception as e:  # noqa: BLE001
            raise cluster.ClusterDesyncError(
                f"process {cluster.process_index()} cannot restore the "
                f"cluster-agreed checkpoint {path} "
                f"({type(e).__name__}: {str(e)[:300]}); refusing to "
                f"resume divergent — restart the pod after repairing "
                f"the checkpoint directory") from e
        return payload, path

    def _restore_runstate(self, checkpoint_path):
        """Replay the checkpoint's host-side run state (runstate
        sidecar): mid-epoch data position, HealthMonitor history, and
        the telemetry ring. A sidecar whose counters disagree with the
        checkpoint's own meta emits a ``resilience/resume_divergence``
        meta event — ``check_run_health`` fails any run that carries
        one (a stale or cross-wired sidecar would desynchronize the
        data stream from the RNG/step state)."""
        from imaginaire_tpu import resilience

        runstate = resilience.read_runstate(checkpoint_path)
        tm = telemetry.get()
        if runstate is None:
            # legacy checkpoint: coarse resume (epoch restarts at batch
            # 0, monitor/telemetry state fresh) — still correct weights,
            # just not bit-exact against an uninterrupted run
            self.resume_batch_in_epoch = 0
            if tm.enabled:
                tm.meta("resilience/resume", checkpoint=str(checkpoint_path),
                        iteration=self.current_iteration,
                        runstate=False)
            return
        if (int(runstate.get("iteration", -1)) != self.current_iteration
                or int(runstate.get("epoch", -1)) != self.current_epoch):
            if tm.enabled:
                tm.meta("resilience/resume_divergence",
                        checkpoint=str(checkpoint_path),
                        checkpoint_iteration=self.current_iteration,
                        runstate_iteration=runstate.get("iteration"),
                        checkpoint_epoch=self.current_epoch,
                        runstate_epoch=runstate.get("epoch"))
            import logging

            logging.getLogger(__name__).error(
                "runstate sidecar disagrees with checkpoint meta "
                "(ckpt epoch/iter %s/%s vs runstate %s/%s); ignoring "
                "the sidecar — resume will be coarse, not bit-exact",
                self.current_epoch, self.current_iteration,
                runstate.get("epoch"), runstate.get("iteration"))
            self.resume_batch_in_epoch = 0
            return
        self.resume_batch_in_epoch = int(runstate.get("batch_in_epoch",
                                                      0) or 0)
        try:
            self.diag.load_state_dict(runstate.get("monitor") or {})
        except Exception as e:  # noqa: BLE001 — observability only
            import logging

            logging.getLogger(__name__).warning(
                "health-monitor state restore failed: %s", e)
        try:
            tm.load_state_dict(runstate.get("telemetry") or {})
        except Exception as e:  # noqa: BLE001
            import logging

            logging.getLogger(__name__).warning(
                "telemetry state restore failed: %s", e)
        if tm.enabled:
            tm.meta("resilience/resume", checkpoint=str(checkpoint_path),
                    iteration=self.current_iteration,
                    batch_in_epoch=self.resume_batch_in_epoch,
                    runstate=True)

    def emergency_checkpoint(self, current_epoch, current_iteration,
                             guard=None):
        """Preemption drain: synchronous checkpoint + run-state sidecar
        under the ``ckpt_emergency`` span; disarms the guard's deadline
        timer once the commit lands. Returns the checkpoint path."""
        import time as _time

        t0 = _time.perf_counter()
        with telemetry.span("ckpt_emergency", step=current_iteration):
            path = self.save_checkpoint(current_epoch, current_iteration,
                                        emergency=True)
        ckpt_lib.wait_for_pending_checkpoint()
        dur_ms = (_time.perf_counter() - t0) * 1e3
        tm = telemetry.get()
        if tm.enabled:
            tm.counter("resilience/emergency_ckpt_ms", dur_ms,
                       step=current_iteration)
            tm.meta("resilience/emergency_checkpoint", path=str(path),
                    iteration=current_iteration, dur_ms=round(dur_ms, 2))
        if guard is not None:
            guard.disarm()
        print(f"Emergency checkpoint committed in {dur_ms:.0f}ms -> "
              f"{path}")
        return path

    def _reshard_restored_state(self, checkpoint_path):
        """Re-place a restored state under the CURRENT partition plan.

        ``load_checkpoint`` hands back host arrays (layout-agnostic by
        design), so a checkpoint written on one mesh shape loads on any
        other: here they are committed under the live plan's
        NamedShardings via ``jax.device_put`` — orbax never sees a
        spec mismatch, nothing silently replicates, and the step
        programs meet their expected layout on the first post-restore
        call. A saved-vs-current descriptor difference (mesh shape,
        sharding knobs, plan on/off) is surfaced as a ``ckpt/reshard``
        telemetry meta event."""
        saved = ckpt_lib.read_partition_sidecar(checkpoint_path)
        current = self.partition.describe() if self.partition.active \
            else None
        if saved != current and (saved is not None
                                 or current is not None):
            telemetry.get().meta("ckpt/reshard", saved=saved,
                                 current=current,
                                 checkpoint=str(checkpoint_path))
            print(f"Resharding restored checkpoint: saved partition "
                  f"{saved} -> current {current}")
        if self.partition.active or jax.process_count() > 1:
            # the pod resume re-commits under the global mesh
            # (replicated when no plan is active) — the same placement
            # init_state produced, so the warm step programs keep their
            # fingerprint
            self.state = self._place_state(self.state)
        else:
            # the restored leaves are host numpy (load_checkpoint is
            # layout-agnostic by design); commit them to device arrays
            # jax OWNS before the first post-restore step. A plain
            # ``device_put`` is not enough: on the CPU backend it
            # zero-copy-aliases an aligned numpy buffer, and the step
            # programs DONATE their state argument — freeing a buffer
            # numpy still owns is a use-after-free. ``jnp.array``
            # (copy=True by default) guarantees an owned buffer.
            import jax.numpy as jnp

            self.state = jax.tree_util.tree_map(jnp.array, self.state)

    def set_elastic_carry(self, carry):
        """Stash the gather-routed leaves a ``RedistributionPlanner``
        snapshot carried across the resize; the next resuming
        ``load_checkpoint`` splices them over the restored tree."""
        self._elastic_carry = dict(carry) if carry else None

    def _apply_elastic_carry(self):
        """Overwrite restored leaves with their carried live values
        (keyed by ``jax.tree_util.keystr`` path). Returns the number of
        leaves spliced. One-shot: the carry is consumed either way."""
        carry = getattr(self, "_elastic_carry", None)
        self._elastic_carry = None
        if not carry or self.state is None:
            return 0
        applied = [0]

        def _splice(path, leaf):
            key = jax.tree_util.keystr(path)
            if key in carry:
                applied[0] += 1
                return carry[key]
            return leaf

        self.state = jax.tree_util.tree_map_with_path(_splice, self.state)
        return applied[0]

    def elastic_recommit(self, carry, iteration, epoch):
        """All-gather elastic restore (ISSUE 13): every state leaf was
        carried across the resize as an owned host copy — rebuild the
        tree from the rebind template's STRUCTURE and commit it under
        the new world's shardings without touching the checkpoint (the
        downtime win the RedistributionPlanner exists for). The
        partition sidecar + runstate still come from the pointed
        checkpoint so batch-offset resume and reshard telemetry match
        the checkpoint route bit for bit."""
        template = getattr(self, "_elastic_state_template", None)
        if template is None:
            raise RuntimeError(
                "elastic_recommit needs the rebind template — call "
                "elastic_rebind() first")

        def _rebuild(path, leaf):
            key = jax.tree_util.keystr(path)
            if key not in carry:
                raise KeyError(
                    f"elastic_recommit: leaf {key} missing from the "
                    f"carry — the planner routed it 'gather' but no "
                    f"snapshot landed")
            return carry[key]

        self.state = jax.tree_util.tree_map_with_path(_rebuild, template)
        self.current_iteration = int(iteration)
        self.current_epoch = int(epoch)
        self._elastic_state_template = None
        checkpoint_path = ckpt_lib.latest_checkpoint_path(
            cfg_get(self.cfg, "logdir", "."))
        if checkpoint_path is not None:
            self._restore_runstate(checkpoint_path)
        self._reshard_restored_state(checkpoint_path)
        print(f"Done with the elastic re-commit (iteration "
              f"{self.current_iteration}, no checkpoint round-trip).")
        return True

    def elastic_rebind(self):
        """Rebind the trainer to a freshly resized pod (ISSUE 11).

        Called by the supervise loop AFTER ``elastic.apply`` tore the
        old distributed runtime down and the new mesh is installed. The
        old state arrays lived on backends that no longer exist, so
        ``self.state`` drops to None — an abstract shape/dtype template
        keeps its tree structure so the next ``load_checkpoint``
        restores into it (host numpy, layout-agnostic) and
        ``_reshard_restored_state`` commits the optimizer/EMA shards
        under the new world's NamedShardings (the PR-6 reshard-on-load,
        not a second reshard path). Every ledgered step program is
        retraced under ``retrace('elastic_resize')``: the executables
        baked the dead world's device ids into their bindings, and the
        named retrace keeps the recompile tripwire quiet."""
        from imaginaire_tpu.telemetry import xla_obs

        self.partition = PartitionPlan(self.cfg)
        self._state_shardings = None
        # the state's tree STRUCTURE must survive the rebind: the
        # no-target restore hands back plain nested dicts, and optax
        # update() needs its NamedTuples (ScaleByAdamState.mu) back.
        # An abstract shape/dtype template costs no memory and reads
        # only aval metadata — safe even though the arrays' backend is
        # already gone.
        self._elastic_state_template = jax.tree_util.tree_map(
            lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype)
                       if hasattr(x, "shape") and hasattr(x, "dtype")
                       else x),
            self.state) if self.state is not None else None
        self.state = None
        self._ema_batch_stats = None  # device arrays of the dead world
        retraced = []
        for name, value in vars(self).items():
            if isinstance(value, xla_obs.CompiledProgram):
                value.retrace("elastic_resize")
                retraced.append(value.label)
        return retraced

    # ------------------------------------------------------------ inference

    def inference_params(self):
        """EMA params when model averaging is on (ref: base.py:674-678);
        recalibrated BN stats when they have been estimated."""
        if self.model_average:
            variables = dict(self.state["vars_G"],
                             params=self.state["ema_G"])
            if getattr(self, "_ema_batch_stats", None) is not None:
                variables["batch_stats"] = self._ema_batch_stats
            return variables
        return self.state["vars_G"]

    def recalculate_model_average_batch_norm_statistics(self,
                                                        data_loader=None):
        """Re-estimate the EMA model's BN running stats as the
        cumulative mean of per-batch statistics over
        ``model_average_batch_norm_estimation_iteration`` training
        batches (ref: trainers/base.py:415-443 momentum=1/(n+1) loop,
        utils/model_average.py:9-33). The per-batch statistic is
        recovered from flax's linear running update
        (new = m*old + (1-m)*batch, m=0.9 — the layer default)."""
        if data_loader is None:
            data_loader = self.train_data_loader
        if not self.model_average or data_loader is None:
            return
        if getattr(self, "_ema_bn_recal_iter", None) == \
                self.current_iteration:
            return  # already estimated this iteration (FID + image save)
        n_iters = cfg_get(self.cfg.trainer,
                          "model_average_batch_norm_estimation_iteration",
                          30)
        old_stats = self.state["vars_G"].get("batch_stats")
        if not n_iters or old_stats is None or not jax.tree_util.tree_leaves(
                old_stats):
            return
        from imaginaire_tpu.utils.misc import numeric_only, to_device

        momentum = 0.9
        ema_vars = dict(self.state["vars_G"], params=self.state["ema_G"])
        mean_stats = None
        count = 0
        rng = jax.random.PRNGKey(1234)
        for it, data in enumerate(data_loader):
            if it >= n_iters:
                break
            # side-effect-free preprocessing: start_of_iteration would
            # reset timers / re-trigger the profiler window mid-metrics
            data = to_device(self._start_of_iteration(
                data, self.current_iteration))
            _, new_mut = self._apply_G(ema_vars, numeric_only(data),
                                       jax.random.fold_in(rng, it),
                                       training=True)
            new_stats = new_mut.get("batch_stats")
            if new_stats is None:
                return
            batch_stat = jax.tree_util.tree_map(
                lambda new, old: (new - momentum * old) / (1 - momentum),
                new_stats, old_stats)
            count += 1
            if mean_stats is None:
                mean_stats = batch_stat
            else:
                mean_stats = jax.tree_util.tree_map(
                    lambda m, b: m + (b - m) / count, mean_stats,
                    batch_stat)
        if mean_stats is not None:
            self._ema_batch_stats = mean_stats
            self._ema_bn_recal_iter = self.current_iteration

    def inference_forward(self, variables, data, rng,
                          inference_args=None):
        """One inference forward of net_G. Routed through the attached
        serving engine when one is present (``ServingEngine.attach``) —
        the one-shot entry points then inherit the ledgered warm
        executables and serve/* SLO telemetry for free — else the
        legacy eager apply (byte-for-byte the seed behavior)."""
        engine = getattr(self, "_serving_engine", None)
        if engine is not None:
            return engine.forward(variables, data, rng,
                                  inference_args=inference_args)
        return self.net_G.apply(
            variables, data, training=False, rngs={"noise": rng},
            method=self.net_G.inference, **(inference_args or {}))

    def test(self, data_loader, output_dir, inference_args=None):
        """(ref: base.py:672-696)."""
        from imaginaire_tpu.utils.visualization import tensor2im, save_image_grid

        os.makedirs(output_dir, exist_ok=True)
        inference_args = inference_args or {}
        variables = self.inference_params()
        # overlap the next batch's host load + H2D with this batch's
        # generate (start_of_iteration skips re-prep for wrapped batches)
        data_loader = self.data_prefetcher(data_loader)
        tm = telemetry.get()
        for it, data in enumerate(tm.timed_iter(data_loader, "data_wait")):
            tm.heartbeat()
            data = self.start_of_iteration(data, current_iteration=-1)
            with tm.span("eval"):
                images = self.inference_forward(
                    variables, data, jax.random.PRNGKey(it),
                    inference_args=inference_args)
            keys = data.get("key", [f"{it:06d}_{i}" for i in range(images.shape[0])])
            if isinstance(keys, (str, bytes)):
                keys = [keys]
            for img, name in zip(np.asarray(images), keys):
                path = os.path.join(output_dir, f"{name}.jpg")
                os.makedirs(os.path.dirname(path), exist_ok=True)
                save_image_grid([tensor2im(img)], path)

    def save_image(self, path, data):
        """Visualization snapshot (ref: base.py:445-465)."""
        if not is_master():
            return
        vis = self._get_visualizations(data)
        if vis is None:
            return
        from imaginaire_tpu.utils.visualization import save_tensor_strip

        os.makedirs(os.path.dirname(path), exist_ok=True)
        save_tensor_strip(vis, path)
        print(f"Save output images to {path}")

    # -------------------------------------------------------------- meters

    def _meter(self, name):
        if name not in self.meters:
            self.meters[name] = Meter(name)
        return self.meters[name]

    def _log_losses(self, update_type, losses):
        # values stay on device; Meter.flush materializes them at
        # logging_iter so the step loop never blocks on a host sync.
        for name, value in losses.items():
            self._meter(f"{update_type}/{name}").write(value)

    def _flush_meters(self, step):
        for meter in self.meters.values():
            meter.flush(step)

    def _image_path(self, iteration):
        return os.path.join(cfg_get(self.cfg, "logdir", "."), "images",
                            f"{iteration:09d}.jpg")

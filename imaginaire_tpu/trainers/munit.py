"""MUNIT trainer (ref: imaginaire/trainers/munit.py:16-307).

Loss terms: two-domain GAN, image/style/content/cycle L1
reconstructions, style-prior KL, optional perceptual, optional R1
gradient penalty and consistency regularization on the discriminator
(ref: munit.py:58-247). Loss weights come straight from
cfg.trainer.loss_weight — any entry with weight > 0 is active
(ref: munit.py:80-83).

TPU-first: both updates are single jitted programs; the consistency
regularization's random shift uses reflect-pad + per-sample
dynamic_slice instead of a grid_sample gather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.losses import PerceptualLoss, gan_loss, gaussian_kl_loss
from imaginaire_tpu.trainers.base import MUTABLE, BaseTrainer
from imaginaire_tpu.utils.misc import random_shift


def _l1(a, b):
    return jnp.mean(jnp.abs(a - b))


class Trainer(BaseTrainer):
    def _init_loss(self, cfg):
        tcfg = cfg.trainer
        self.gan_mode = cfg_get(tcfg, "gan_mode", "hinge")
        self.gan_recon = cfg_get(tcfg, "gan_recon", False)
        for name, w in as_attrdict(cfg_get(tcfg, "loss_weight", {}) or {}).items():
            if w and float(w) > 0:
                self.weights[name] = float(w)
        self.perceptual = None
        if "perceptual" in self.weights:
            self.perceptual = PerceptualLoss(
                network=cfg_get(tcfg, "perceptual_mode", "vgg19"),
                layers=list(cfg_get(tcfg, "perceptual_layers", None)
                            or ["relu_4_1"]),
                instance_normalized=True,
                weights_path=cfg_get(tcfg, "perceptual_weights_path", None),
                allow_random_init=cfg_get(tcfg, "perceptual_allow_random_init",
                                          False))

    def init_loss_params(self, key):
        if self.perceptual is None:
            return {}
        return {"perceptual": self.perceptual.init_params(key)}

    def _fake_output_for_init(self, data):
        return {"images_ab": jnp.zeros_like(data["images_b"]),
                "images_ba": jnp.zeros_like(data["images_a"]),
                "images_aa": jnp.zeros_like(data["images_a"]),
                "images_bb": jnp.zeros_like(data["images_b"])}

    # ------------------------------------------------------------ forwards

    def _apply_G(self, vars_G, data, rng, training, **flags):
        return self.net_G.apply(vars_G, data, training=training,
                                rngs={"noise": rng}, mutable=list(MUTABLE),
                                **flags)

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/munit.py:85-182)."""
        cycle = "cycle_recon" in self.weights
        image_recon = "image_recon" in self.weights
        out, new_mut = self._apply_G(
            vars_G, data, rng, training, random_style=True,
            image_recon=image_recon, latent_recon=True, cycle_recon=cycle)
        d_out = self.net_D.apply(vars_D, data, out, real=False,
                                 gan_recon=self.gan_recon, training=training)

        losses = {}
        if self.gan_recon:
            gan_a = 0.5 * (gan_loss(d_out["out_ba"], True, self.gan_mode, False)
                           + gan_loss(d_out["out_aa"], True, self.gan_mode, False))
            gan_b = 0.5 * (gan_loss(d_out["out_ab"], True, self.gan_mode, False)
                           + gan_loss(d_out["out_bb"], True, self.gan_mode, False))
        else:
            gan_a = gan_loss(d_out["out_ba"], True, self.gan_mode, dis_update=False)
            gan_b = gan_loss(d_out["out_ab"], True, self.gan_mode, dis_update=False)
        losses["gan"] = gan_a + gan_b

        if self.perceptual is not None:
            losses["perceptual"] = (
                self.perceptual(loss_params["perceptual"], out["images_ab"],
                                data["images_a"])
                + self.perceptual(loss_params["perceptual"], out["images_ba"],
                                  data["images_b"]))
        if image_recon:
            losses["image_recon"] = (_l1(out["images_aa"], data["images_a"])
                                     + _l1(out["images_bb"], data["images_b"]))
        losses["style_recon"] = (_l1(out["style_ba"], out["style_a_rand"])
                                 + _l1(out["style_ab"], out["style_b_rand"]))
        losses["content_recon"] = (
            _l1(out["content_ab"], jax.lax.stop_gradient(out["content_a"]))
            + _l1(out["content_ba"], jax.lax.stop_gradient(out["content_b"])))
        losses["kl"] = (gaussian_kl_loss(out["style_a"])
                        + gaussian_kl_loss(out["style_b"]))
        if cycle:
            losses["cycle_recon"] = (_l1(out["images_aba"], data["images_a"])
                                     + _l1(out["images_bab"], data["images_b"]))
        return losses, new_mut

    def dis_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/munit.py:184-247)."""
        out, _ = self._apply_G(
            vars_G, data, rng, training, random_style=True,
            image_recon=self.gan_recon, latent_recon=False, cycle_recon=False)
        out = jax.lax.stop_gradient(
            {k: v for k, v in out.items() if k.startswith("images_")})
        d_out, new_mut_D = self.net_D.apply(
            vars_D, data, out, real=True, gan_recon=self.gan_recon,
            training=training, mutable=list(MUTABLE))

        losses = {}
        gan_a = (gan_loss(d_out["out_a"], True, self.gan_mode, dis_update=True)
                 + gan_loss(d_out["out_ba"], False, self.gan_mode, dis_update=True))
        gan_b = (gan_loss(d_out["out_b"], True, self.gan_mode, dis_update=True)
                 + gan_loss(d_out["out_ab"], False, self.gan_mode, dis_update=True))
        losses["gan"] = gan_a + gan_b
        # GAN-balance diagnostics over both domain discriminators
        # (unweighted keys never enter the total)
        from imaginaire_tpu.losses import dis_accuracy

        losses["D_real_acc"], losses["D_fake_acc"] = dis_accuracy(
            [d_out["out_a"], d_out["out_b"]],
            [d_out["out_ba"], d_out["out_ab"]], self.gan_mode)

        if "gp" in self.weights:
            from imaginaire_tpu.utils.misc import gradient_penalty

            def d_a(params, x):
                o, _, _ = self.net_D.apply(
                    vars_D, x, training=training,
                    method=lambda mdl, im, training: mdl.discriminator_a(
                        im, training=training))
                return o

            def d_b(params, x):
                o, _, _ = self.net_D.apply(
                    vars_D, x, training=training,
                    method=lambda mdl, im, training: mdl.discriminator_b(
                        im, training=training))
                return o

            k1, k2 = jax.random.split(rng)
            losses["gp"] = (
                gradient_penalty(d_a, None, out["images_ba"], k1)
                + gradient_penalty(d_b, None, out["images_ab"], k2))

        if "consistency_reg" in self.weights:
            k = jax.random.fold_in(rng, 7)
            ka, kb, kab, kba = jax.random.split(k, 4)
            aug_data = {
                "images_a": random_shift(jnp.flip(data["images_a"], 2), ka),
                "images_b": random_shift(jnp.flip(data["images_b"], 2), kb)}
            aug_out = {
                "images_ab": random_shift(jnp.flip(out["images_ab"], 2), kab),
                "images_ba": random_shift(jnp.flip(out["images_ba"], 2), kba)}
            d_aug = self.net_D.apply(vars_D, aug_data, aug_out, real=True,
                                     training=training)
            reg = jnp.zeros(())
            for name in ("fea_ba", "fea_ab", "fea_a", "fea_b"):
                fa, fb = d_aug[name], d_out[name]
                if isinstance(fa, (list, tuple)):  # multi-scale feature lists
                    for xa, xb in zip(jax.tree_util.tree_leaves(fa),
                                      jax.tree_util.tree_leaves(fb)):
                        reg = reg + jnp.mean((xa - xb) ** 2)
                else:
                    reg = reg + jnp.mean((fa - fb) ** 2)
            losses["consistency_reg"] = reg
        return losses, new_mut_D

    # --------------------------------------------------------------- extras

    def _get_visualizations(self, data):
        """(ref: trainers/munit.py:249-272)."""
        from imaginaire_tpu.utils.misc import to_device

        data = to_device(dict(data))
        variables = self.inference_params()
        rng = jax.random.PRNGKey(0)
        out, _ = self._apply_G(variables, data, rng, training=False,
                               random_style=False, image_recon=True,
                               latent_recon=False, cycle_recon=True)
        out_rand, _ = self._apply_G(variables, data, rng, training=False,
                                    random_style=True, image_recon=False,
                                    latent_recon=False, cycle_recon=False)
        return [data["images_a"], data["images_b"],
                out["images_aa"], out["images_bb"],
                out["images_ab"], out_rand["images_ab"],
                out["images_ba"], out_rand["images_ba"],
                out["images_aba"], out["images_bab"]]

    def _compute_fid(self):
        """Two FIDs — one per domain (ref: trainers/munit.py:288-307)."""
        if self.val_data_loader is None:
            return None
        import os

        from imaginaire_tpu.evaluation import compute_fid, inception

        try:
            variables = inception.load_params(
                random_init=cfg_get(cfg_get(self.cfg, "trainer", {}),
                                    "fid_random_init", False))
        except FileNotFoundError as e:
            print(f"FID skipped: {e}")
            return None
        extractor = inception.make_extractor(variables)
        logdir = cfg_get(self.cfg, "logdir", ".")
        gen_vars = self.inference_params()

        def gen_fn(a2b):
            def fn(data):
                from imaginaire_tpu.utils.misc import to_device

                data = to_device(dict(data))
                return self.net_G.apply(
                    gen_vars, data, a2b=a2b, random_style=True,
                    rngs={"noise": jax.random.PRNGKey(0)},
                    method=self.net_G.inference)
            return fn

        fids = {}
        # device-prefetched sweep (gen_fn's to_device is a no-op on the
        # already-placed batches)
        val_loader = self.data_prefetcher(self.val_data_loader)
        for domain, a2b, real_key in (("a", False, "images_a"),
                                      ("b", True, "images_b")):
            path = os.path.join(logdir, f"real_stats_{domain}.npz")
            fids[domain] = compute_fid(path, val_loader, extractor,
                                       gen_fn(a2b), key_real=real_key)
            self._meter(f"FID_{domain}").write(float(fids[domain]))
        return 0.5 * (fids["a"] + fids["b"])

"""FUNIT trainer (ref: imaginaire/trainers/funit.py:17-200).

Losses: GAN over translation+reconstruction streams, L1 image
reconstruction, discriminator feature matching (pooled features), and
optional gradient penalty (ref: funit.py:38-110). Serves FUNIT and
COCO-FUNIT (the COCO variant only swaps the generator).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.losses import gan_loss
from imaginaire_tpu.trainers.base import MUTABLE, BaseTrainer


def _l1(a, b):
    return jnp.mean(jnp.abs(a - b))


class Trainer(BaseTrainer):
    def _init_loss(self, cfg):
        """(ref: trainers/funit.py:38-52)."""
        tcfg = cfg.trainer
        self.gan_mode = cfg_get(tcfg, "gan_mode", "hinge")
        for name, w in as_attrdict(cfg_get(tcfg, "loss_weight", {}) or {}).items():
            if w and float(w) > 0:
                self.weights[name] = float(w)

    def _fake_output_for_init(self, data):
        return {"images_trans": jnp.zeros_like(data["images_style"]),
                "images_recon": jnp.zeros_like(data["images_content"])}

    def gen_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/funit.py:54-87)."""
        out, new_mut = self.net_G.apply(
            vars_G, data, training=training, rngs={"noise": rng},
            mutable=list(MUTABLE))
        d_out = self.net_D.apply(vars_D, data, out, recon=True,
                                 training=training)
        losses = {}
        losses["gan"] = 0.5 * (
            gan_loss(d_out["fake_out_trans"], True, self.gan_mode,
                     dis_update=False)
            + gan_loss(d_out["fake_out_recon"], True, self.gan_mode,
                       dis_update=False))
        losses["image_recon"] = _l1(out["images_recon"],
                                    data["images_content"])
        losses["feature_matching"] = _l1(d_out["fake_features_trans"],
                                         d_out["real_features_style"])
        return losses, new_mut

    def dis_forward(self, vars_G, vars_D, loss_params, data, rng, training=True):
        """(ref: trainers/funit.py:89-110)."""
        out, _ = self.net_G.apply(
            vars_G, data, training=training, rngs={"noise": rng},
            mutable=list(MUTABLE))
        out = jax.lax.stop_gradient(out)
        d_out, new_mut_D = self.net_D.apply(
            vars_D, data, out, recon=False, training=training,
            mutable=list(MUTABLE))
        losses = {"gan": (
            gan_loss(d_out["real_out_style"], True, self.gan_mode,
                     dis_update=True)
            + gan_loss(d_out["fake_out_trans"], False, self.gan_mode,
                       dis_update=True))}
        from imaginaire_tpu.losses import dis_accuracy

        losses["D_real_acc"], losses["D_fake_acc"] = dis_accuracy(
            d_out["real_out_style"], d_out["fake_out_trans"],
            self.gan_mode)
        if "gp" in self.weights:
            from imaginaire_tpu.utils.misc import gradient_penalty

            def d_apply(params, x):
                o, _ = self.net_D.apply(
                    vars_D, x, data["labels_style"], training=training,
                    method=lambda mdl, im, lbl, training: mdl.model(
                        im, lbl, training=training))
                return o

            losses["gp"] = gradient_penalty(d_apply, None,
                                            out["images_trans"], rng)
        return losses, new_mut_D

    def _get_visualizations(self, data):
        """(ref: trainers/funit.py:112-131)."""
        from imaginaire_tpu.utils.misc import to_device

        data = to_device(dict(data))
        out, _ = self.net_G.apply(
            self.inference_params(), data, training=False,
            rngs={"noise": jax.random.PRNGKey(0)}, mutable=list(MUTABLE))
        return [data["images_content"], data["images_style"],
                out["images_recon"], out["images_trans"]]

    def _compute_fid(self):
        """Mean per-style-class FID (ref: trainers/funit.py:133-166)."""
        if self.val_data_loader is None:
            return None
        import numpy as np

        from imaginaire_tpu.evaluation import compute_fid, inception

        dataset = getattr(self.val_data_loader, "dataset", None)
        if dataset is None or not hasattr(dataset, "num_style_classes"):
            return None
        try:
            variables = inception.load_params(
                random_init=cfg_get(cfg_get(self.cfg, "trainer", {}),
                                    "fid_random_init", False))
        except FileNotFoundError as e:
            print(f"FID skipped: {e}")
            return None
        extractor = inception.make_extractor(variables)
        gen_vars = self.inference_params()

        def gen_fn(data):
            from imaginaire_tpu.utils.misc import to_device

            return self.net_G.apply(
                gen_vars, to_device(dict(data)),
                rngs={"noise": jax.random.PRNGKey(0)},
                method=self.net_G.inference)

        import os

        logdir = cfg_get(self.cfg, "logdir", ".")
        fids = []
        # device-prefetched sweep: each compute_fid opens fresh passes,
        # so the per-class dataset re-pinning below stays race-free (the
        # producer only reads ahead within one pass)
        val_loader = self.data_prefetcher(self.val_data_loader)
        for class_idx in range(dataset.num_style_classes):
            dataset.set_sample_class_idx(class_idx)
            path = os.path.join(logdir, f"real_stats_style{class_idx}.npz")
            fids.append(compute_fid(path, val_loader, extractor,
                                    gen_fn, key_real="images_style"))
        dataset.set_sample_class_idx(None)
        return float(np.mean(fids))

"""Trainer harness (ref: imaginaire/trainers/)."""

from imaginaire_tpu.trainers.base import BaseTrainer

__all__ = ["BaseTrainer"]

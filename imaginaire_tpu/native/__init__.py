"""Native (C++) runtime components.

The reference backs its hot host paths with C++/CUDA (DataLoader worker
pools, LMDB readers, the apex/op extensions). The TPU compute path here
is XLA/Pallas; this package holds the native HOST runtime: a
thread-pooled blob reader that feeds the packed-shard data pipeline
with concurrent positioned reads (ctypes ABI — pybind11 is not in the
image). Built on first use with g++ -O3; every consumer falls back to
pure-Python IO when a toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "blob_reader.cc")
_SO = os.path.join(_HERE, "build", "libblob_reader.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _build():
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # build to a temp path and rename atomically: an interrupted or
    # concurrent build must never leave a corrupt .so at the final path
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _SO)


def load_library():
    """The ctypes handle, building the .so on first call; None when no
    toolchain is available."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or (
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"native blob reader unavailable ({e}); "
                  "falling back to Python IO")
            _build_failed = True
            return None
        lib.br_open.argtypes = [ctypes.c_char_p]
        lib.br_open.restype = ctypes.c_int
        lib.br_close.argtypes = [ctypes.c_int]
        lib.br_read.argtypes = [ctypes.c_int, ctypes.c_uint64,
                                ctypes.c_uint64, ctypes.c_char_p]
        lib.br_read.restype = ctypes.c_int64
        lib.br_read_batch.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        _lib = lib
        return _lib


class NativeBlobReader:
    """Concurrent positioned reads over one packed data.bin."""

    def __init__(self, path, n_threads=4):
        """n_threads sizes the process-wide pool on its FIRST use; later
        readers share that pool (per-call completion keeps concurrent
        batches independent)."""
        self._lib = load_library()
        if self._lib is None:
            raise RuntimeError("native blob reader unavailable")
        self._fd = self._lib.br_open(path.encode())
        if self._fd < 0:
            raise FileNotFoundError(path)
        self.n_threads = n_threads

    def read(self, offset, length):
        buf = ctypes.create_string_buffer(length)
        n = self._lib.br_read(self._fd, offset, length, buf)
        if n != length:
            raise IOError(f"short read: {n} of {length} bytes")
        return buf.raw

    def read_batch(self, extents):
        """extents: [(offset, length)] -> list of bytes, read
        concurrently by the native thread pool."""
        count = len(extents)
        if count == 0:
            return []
        offs = (ctypes.c_uint64 * count)(*[e[0] for e in extents])
        lens = (ctypes.c_uint64 * count)(*[e[1] for e in extents])
        total = sum(e[1] for e in extents)
        arena = ctypes.create_string_buffer(total)
        done = (ctypes.c_int64 * count)()
        self._lib.br_read_batch(self._fd, offs, lens, count, arena, done,
                                self.n_threads)
        out = []
        pos = 0
        for i, (_, length) in enumerate(extents):
            if done[i] != length:
                raise IOError(
                    f"short batched read: extent {i} got {done[i]} of "
                    f"{length} bytes")
            out.append(arena.raw[pos:pos + length])
            pos += length
        return out

    def close(self):
        if self._fd >= 0:
            self._lib.br_close(self._fd)
            self._fd = -1

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

// Native IO runtime for the packed-shard data path
// (the TPU-native counterpart of the reference's C++ DataLoader workers
// and LMDB readers — large sequential reads feeding TPU-VM hosts).
//
// Exposes a C ABI consumed via ctypes (no pybind11 in this image):
//   - br_open/br_close: file handles
//   - br_read: positioned read into a caller buffer
//   - br_prefetch_submit/br_prefetch_wait: a thread pool reads a batch of
//     (offset, length) extents concurrently into one contiguous arena,
//     overlapping disk latency with host-side decode of the previous batch.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <queue>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// Per-br_read_batch completion tracker so concurrent callers never
// barrier on each other's extents.
struct BatchState {
  std::mutex mu;
  std::condition_variable cv;
  int remaining;
};

struct Task {
  int fd;
  uint64_t offset;
  uint64_t length;
  uint8_t* dst;
  int64_t* bytes_read;  // per-extent status for the caller
  BatchState* batch;
};

class ThreadPool {
 public:
  explicit ThreadPool(int n_threads) : stop_(false) {
    for (int i = 0; i < n_threads; ++i) {
      workers_.emplace_back([this] { Run(); });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  void Submit(Task t) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      tasks_.push(t);
    }
    cv_.notify_one();
  }

 private:
  void Run() {
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> lock(mu_);
        cv_.wait(lock, [this] { return stop_ || !tasks_.empty(); });
        if (stop_ && tasks_.empty()) return;
        t = tasks_.front();
        tasks_.pop();
      }
      uint64_t done = 0;
      while (done < t.length) {
        ssize_t n = pread(t.fd, t.dst + done, t.length - done,
                          static_cast<off_t>(t.offset + done));
        if (n <= 0) break;
        done += static_cast<uint64_t>(n);
      }
      if (t.bytes_read != nullptr) {
        *t.bytes_read = static_cast<int64_t>(done);
      }
      {
        std::lock_guard<std::mutex> lock(t.batch->mu);
        if (--t.batch->remaining == 0) t.batch->cv.notify_all();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::queue<Task> tasks_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
};

ThreadPool* pool = nullptr;
std::mutex pool_mu;

ThreadPool* GetPool(int n_threads) {
  std::lock_guard<std::mutex> lock(pool_mu);
  if (pool == nullptr) pool = new ThreadPool(n_threads > 0 ? n_threads : 4);
  return pool;
}

}  // namespace

extern "C" {

int br_open(const char* path) { return open(path, O_RDONLY); }

void br_close(int fd) {
  if (fd >= 0) close(fd);
}

// Positioned read; returns bytes read or -1.
int64_t br_read(int fd, uint64_t offset, uint64_t length, uint8_t* dst) {
  uint64_t done = 0;
  while (done < length) {
    ssize_t n = pread(fd, dst + done, length - done,
                      static_cast<off_t>(offset + done));
    if (n < 0) return -1;
    if (n == 0) break;
    done += static_cast<uint64_t>(n);
  }
  return static_cast<int64_t>(done);
}

// Read `count` extents concurrently into `arena`, which is laid out as the
// concatenation of the extents (caller computes dst offsets = prefix sums).
// bytes_read (len `count`, caller-allocated) receives per-extent byte
// counts so short reads surface instead of silently zero-filling.
void br_read_batch(int fd, const uint64_t* offsets, const uint64_t* lengths,
                   int count, uint8_t* arena, int64_t* bytes_read,
                   int n_threads) {
  if (count <= 0) return;
  ThreadPool* p = GetPool(n_threads);
  BatchState batch;
  batch.remaining = count;
  uint64_t dst_off = 0;
  for (int i = 0; i < count; ++i) {
    p->Submit(Task{fd, offsets[i], lengths[i], arena + dst_off,
                   bytes_read == nullptr ? nullptr : bytes_read + i, &batch});
    dst_off += lengths[i];
  }
  std::unique_lock<std::mutex> lock(batch.mu);
  batch.cv.wait(lock, [&batch] { return batch.remaining == 0; });
}

}  // extern "C"

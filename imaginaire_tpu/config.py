"""Config system: YAML overlaid on a defaults tree, attribute access.

Reproduces the semantics of the reference config system
(ref: imaginaire/config.py:16-213): an attribute-accessible nested dict,
a defaults tree pre-seeded before the user YAML is overlaid recursively,
a YAML float resolver so ``1e-4`` parses as a float (YAML 1.1 quirk), and
a ``common:`` section broadcast into both ``gen`` and ``dis`` sub-configs.

Design difference from the reference: components are selected by registry
key (see registry.py) with dotted-module fallback, and the defaults tree
reflects the TPU runtime (mesh axes, bf16 policy, orbax checkpointing)
rather than cudnn/apex knobs.
"""

from __future__ import annotations

import copy
import re

import yaml


class AttrDict(dict):
    """Dict with attribute access, recursive construction and yaml round-trip."""

    def __init__(self, mapping=None, **kwargs):
        super().__init__()
        mapping = dict(mapping or {}, **kwargs)
        for key, value in mapping.items():
            self[key] = _wrap(value)

    def __setitem__(self, key, value):
        super().__setitem__(key, _wrap(value))

    def __setattr__(self, key, value):
        self[key] = value

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError as exc:
            raise AttributeError(key) from exc

    def __deepcopy__(self, memo):
        return AttrDict({k: copy.deepcopy(v, memo) for k, v in self.items()})

    def to_dict(self):
        out = {}
        for key, value in self.items():
            if isinstance(value, AttrDict):
                out[key] = value.to_dict()
            elif isinstance(value, list):
                out[key] = [v.to_dict() if isinstance(v, AttrDict) else v for v in value]
            else:
                out[key] = value
        return out

    def yaml(self):
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    def __repr__(self):
        return self.yaml()


def _wrap(value):
    if isinstance(value, AttrDict):
        return value
    if isinstance(value, dict):
        return AttrDict(value)
    if isinstance(value, (list, tuple)):
        return [_wrap(v) for v in value]
    return value


def as_attrdict(obj):
    """Recursively convert any Mapping (incl. flax FrozenDict — linen
    converts dict module fields to FrozenDict) back to AttrDict."""
    from collections.abc import Mapping

    if isinstance(obj, Mapping):
        return AttrDict({k: as_attrdict(v) for k, v in obj.items()})
    if isinstance(obj, (list, tuple)):
        return [as_attrdict(v) for v in obj]
    return obj


def recursive_update(base, overlay):
    """Recursively overlay ``overlay`` onto AttrDict ``base`` in place.

    Matches the reference's overlay rule (ref: imaginaire/config.py:201-213):
    dicts merge recursively; any other value (including lists) replaces.
    """
    for key, value in overlay.items():
        if isinstance(value, dict) and isinstance(base.get(key), dict):
            recursive_update(base[key], value)
        else:
            base[key] = _wrap(value)
    return base


# YAML 1.1 fails to parse `1e-4` (no dot) as a float; install an implicit
# resolver that accepts full scientific notation (ref: imaginaire/config.py:154-164).
class _ConfigLoader(yaml.SafeLoader):
    pass


_ConfigLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:
            [-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
           |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
           |\.[0-9_]+(?:[eE][-+][0-9]+)?
           |[-+]?\.(?:inf|Inf|INF)
           |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def load_yaml(path_or_stream):
    if hasattr(path_or_stream, "read"):
        return yaml.load(path_or_stream, Loader=_ConfigLoader)
    with open(path_or_stream, "r") as f:
        return yaml.load(f, Loader=_ConfigLoader)


def default_config():
    """The defaults tree every experiment config is overlaid on.

    Mirrors the coverage of the reference defaults (ref: imaginaire/config.py:80-150)
    with TPU-native runtime knobs replacing cudnn/apex/DDP ones.
    """
    return AttrDict(
        # -- logging / snapshot cadence (ref: config.py:82-93)
        image_save_iter=5000,
        image_display_iter=500,
        metrics_iter=None,
        metrics_epoch=None,
        snapshot_save_iter=5000,
        snapshot_save_epoch=5,
        max_epoch=200,
        max_iter=1000000,
        logging_iter=100,
        speed_benchmark=False,
        checkpoints_to_keep=3,
        trainer=AttrDict(
            type="imaginaire_tpu.trainers.base",
            model_average=False,
            model_average_beta=0.9999,
            model_average_start_iteration=1000,
            model_average_batch_norm_estimation_iteration=30,
            model_average_remove_sn=True,
            image_to_tensorboard=False,
            hparam_to_tensorboard=False,
            distributed_data_parallel="jit",  # jit-sharded DP (replaces pytorch/apex DDP)
            delay_allreduce=True,  # accepted for config parity; XLA fuses collectives itself
            gan_relativistic=False,
            gen_step=1,
            dis_step=1,
            gan_mode="hinge",
            # bf16 matmul/conv compute with fp32 params replaces apex AMP O1.
            mixed_precision=AttrDict(enabled=False, compute_dtype="bfloat16"),
            loss_weight=AttrDict(),
            init=AttrDict(type="xavier", gain=0.02),
            grad_clip_norm=None,
            # donate the train-state buffers to the jitted steps (the
            # memory-optimal default); train.py --debug-nans turns this
            # off, since jax_debug_nans re-runs ops against buffers
            # donation already invalidated
            donate_step_buffers=True,
            # software-pipelined rollout dispatch (parallel/pipeline.py,
            # ISSUE 14): defer the health monitor's one-behind finite
            # polls by `depth` frames so the host issues frame t+1 while
            # frame t's programs and gradient all-reduce are in flight.
            # Bit-identical to the sequential loop; depth=0 or
            # enabled=False restores it exactly.
            pipeline=AttrDict(enabled=True, depth=2,
                              overlap_collectives=True),
        ),
        gen=AttrDict(type="imaginaire_tpu.models.generators.dummy"),
        dis=AttrDict(type="imaginaire_tpu.models.discriminators.dummy"),
        gen_opt=AttrDict(
            type="adam",
            fused_opt=False,
            lr=0.0001,
            adam_beta1=0.0,
            adam_beta2=0.999,
            eps=1e-8,
            lr_policy=AttrDict(iteration_mode=False, type="step", step_size=10000000, gamma=1.0),
        ),
        dis_opt=AttrDict(
            type="adam",
            fused_opt=False,
            lr=0.0001,
            adam_beta1=0.0,
            adam_beta2=0.999,
            eps=1e-8,
            lr_policy=AttrDict(iteration_mode=False, type="step", step_size=10000000, gamma=1.0),
        ),
        data=AttrDict(
            name="dummy",
            type="imaginaire_tpu.data.images",
            num_workers=0,
            prefetch=2,
            # Async device-prefetch (data/device_prefetch.py): keep
            # ``depth`` batches resident on device as committed sharded
            # arrays ahead of the step loop — the jax replacement for
            # the reference's pin_memory + non_blocking CUDA transfers.
            # ``enabled: False`` restores the synchronous to_device path.
            device_prefetch=AttrDict(enabled=True, depth=2),
        ),
        test_data=AttrDict(
            name="dummy",
            type="imaginaire_tpu.data.images",
            num_workers=0,
        ),
        # -- structured run telemetry (telemetry/): step-phase spans +
        # derived counters (imgs/sec, step p50/p99, MFU) fanned out to
        # pluggable sinks; jsonl writes <logdir>/telemetry.jsonl and
        # tensorboard forwards counters into the meters writer.
        # hang_timeout_s > 0 arms the watchdog (all-thread stack dump
        # when no step completes in time); trace_at_step=N captures a
        # jax.profiler trace for steps [N, N+trace_num_steps).
        telemetry=AttrDict(
            enabled=True,
            sinks=["jsonl", "tensorboard"],
            flush_every_n_steps=50,
            ring_size=512,
            hang_timeout_s=0,
            trace_at_step=None,
            trace_num_steps=5,
            mfu=True,  # one-time XLA cost analysis of the step programs
            peak_flops=None,  # None => per-device-kind table (v5e default)
            # spans that suspend the hang watchdog while open (long
            # FID/KID eval sweeps complete no training steps by design)
            watchdog_exempt_spans=["eval"],
            # -- pod observability plane (telemetry/podview.py, ISSUE
            # 17): each process publishes a per-step digest (step, wall
            # t, p50 step ms, span ms, loss crc32) over the
            # coordination KV store and aggregates peers into
            # pod/step_skew_ms, pod/straggler/<p> and the
            # pod/divergence sentinel. enabled="auto" activates exactly
            # when the cluster layer is (multi-process with a KV
            # client). divergence="auto" picks crc bit-identity for
            # pure data-parallel fp32 runs and the EWMA relative-delta
            # threshold for mp/bf16; stale_after_s=None inherits the
            # cluster heartbeat timeout.
            pod=AttrDict(
                enabled="auto",
                digest_every_n_steps=10,
                history=8,  # digests kept per host in the KV record
                divergence="auto",  # crc | ewma | off
                ewma_rel_threshold=0.05,
                stale_after_s=None,
            ),
        ),
        # -- XLA compile ledger + device-memory observability
        # (telemetry/xla_obs.py): every labeled program (dis_step /
        # gen_step, vid2vid per-frame programs, flow teacher, inception
        # extractor) compiles through a ledger that records lowering/
        # compile time, memory_analysis (temp/argument/output bytes)
        # and cost_analysis FLOPs into xla/compile/* counters plus
        # logs/<run>/compile_ledger.jsonl; a recompile tripwire
        # fingerprints (shapes, dtypes, shardings) per program and any
        # post-warmup recompile logs a structural diff naming the
        # changed leaf + increments xla/recompiles (raise instead under
        # strict_recompile; expected_recompiles allowlists labels whose
        # re-jits are legitimate). mem_sample adds per-device
        # memory_stats() watermarks (mem/<dev>/*) on the telemetry
        # flush cadence (no-op on CPU), and a RESOURCE_EXHAUSTED
        # escaping a ledgered program dumps logs/<run>/oom_report.json
        # (watermark history, live-array census, per-executable
        # footprints) before re-raising.
        xla_obs=AttrDict(
            enabled=True,
            strict_recompile=False,
            expected_recompiles=[],  # labels whose re-jits never count
            ledger_file=True,  # write logs/<run>/compile_ledger.jsonl
            mem_sample=True,  # HBM watermarks on the flush cadence
            mem_budget_frac=0.9,  # check_run_health watermark gate
            census_top=20,  # live-array census rows kept in reports
            oom_report=True,  # RESOURCE_EXHAUSTED forensics dump
            # Persistent-compile-cache guard (ISSUE 8 satellite): the
            # PR-7 bisect pinned a flaky NaN/SIGSEGV on executables
            # DESERIALIZED from the jax persistent compile cache during
            # warm-cache *resume* runs (fresh compiles never fail).
            # off_on_resume (default) disables the cache only when the
            # run restores a checkpoint — cold runs keep their compile
            # amortization; 'off' always disables; 'on' never touches
            # the configured cache. Tripping emits an
            # xla/persistent_cache_disabled meta event.
            persistent_cache="off_on_resume",  # on | off | off_on_resume
            # Graph audit (imaginaire_tpu/analysis, ISSUE 12): every
            # ledgered compile statically checks its closed jaxpr + the
            # optimized HLO (host callbacks, f64 leaks, bf16 casts
            # inside declared fp32 islands, oversized baked constants,
            # dead donated args, per-program collective bytes). The
            # verdict rides the ledger entry ('audit'), feeds the
            # xla/graph/<label>/* counters and the report's graph-audit
            # section, and gates via check_run_health
            # --max-graph-violations. audit_hlo=False skips the HLO
            # text pass (collectives/donation) when as_text() is too
            # slow for a huge program; audit_const_bytes is the
            # baked_constant threshold.
            graph_audit=True,
            audit_hlo=True,
            audit_const_bytes=4194304,  # 4 MiB
        ),
        # -- training-health diagnostics (diagnostics/): in-step norm
        # auditing (per-module grad/param norms, update/param ratio,
        # spectral-norm sigma, EMA drift) computed INSIDE the jitted D/G
        # step programs every `every_n_steps` (lax.cond — zero extra
        # recompiles, donation-safe), GAN balance metrics (D real/fake
        # accuracy, D/G loss-ratio EWMA with warning thresholds), and
        # non-finite provenance triage: a non-finite update never lands
        # (in-graph guard), the culprit loss term / module is localized
        # by a one-shot eager pass, and logs/<run>/nonfinite_report.json
        # records the provenance. on_nonfinite: halt | skip | rollback
        # (rollback restores the last audited-finite device snapshot —
        # costs one extra state-sized buffer).
        diagnostics=AttrDict(
            enabled=True,
            every_n_steps=10,
            on_nonfinite="halt",
            history=64,  # health ring buffer (last-K context in reports)
            dg_ratio_beta=0.9,  # D/G loss-ratio EWMA smoothing
            dg_ratio_warn_low=0.1,
            dg_ratio_warn_high=10.0,
            max_triage_terms=16,  # cap on the per-term grad triage pass
        ),
        # -- frozen-teacher flow amortization (flow/cache.py): with
        # enabled, the FlowNet2 teacher's (flow, conf) ground truth is
        # computed OFF the step program's critical path — in the
        # DevicePrefetcher producer thread, overlapped with the running
        # step — and rides the batch as plain numeric inputs, so the
        # compiled D/G step programs carry no FlowNet2 parameters.
        # mode: 'producer' recomputes every epoch (overlap only);
        # 'disk' adds the content-addressed on-disk cache (keyed by
        # sample id + frame pair + canonical resolution — epoch >= 2 is
        # a hit and pays ~zero teacher cost; crop/hflip augmentations
        # are applied to the cached canonical-resolution flow
        # equivariantly); 'auto' uses disk when a cache dir resolves
        # (flow_cache.dir or <logdir>/flow_cache), else producer.
        # enabled: false keeps the reference's in-graph teacher.
        flow_cache=AttrDict(
            enabled=False,
            mode="auto",  # auto | producer | disk
            dir=None,  # None -> <logdir>/flow_cache
            store_dtype="float16",  # on-disk flow dtype (conf is uint8)
        ),
        # -- fault tolerance (resilience/, ISSUE 7). checksum: per-leaf
        # crc32 checksums of the saved state ride the checkpoint sidecar
        # (one device_get of the addressable leaves per save — see
        # PROFILE.md for the cost); verify_on_load replays them on
        # restore and a mismatch quarantines the checkpoint (*.corrupt)
        # and falls back to the newest verifiable one.
        # emergency_checkpoint arms the SIGTERM preemption guard in
        # train.py: the in-flight step drains into a synchronous
        # emergency checkpoint within emergency_deadline_s (past the
        # deadline the process force-exits with code 75/EX_TEMPFAIL —
        # the supervisor's SIGKILL was coming anyway). retry bounds the
        # backoff wrapper for transient IO on checkpoint commit /
        # pointer / flow-cache shards (resilience/retry.py; counted in
        # resilience/retry/* telemetry).
        resilience=AttrDict(
            enabled=True,
            checksum=True,
            verify_on_load=True,
            emergency_checkpoint=True,
            emergency_deadline_s=60.0,
            retry=AttrDict(retries=3, backoff_s=0.1, max_backoff_s=2.0),
            # multi-process hardening (resilience/cluster.py, ISSUE 8):
            # with jax.distributed initialized, collectives that used to
            # hang forever on a dead/stalled host become TIMED — a
            # barrier that times out raises ClusterDesyncError naming
            # the absent process index(es). barrier_timeout_s bounds
            # every cluster rendezvous (checkpoint entry/commit, resume
            # consensus, the per-step preemption vote); it must exceed
            # the slowest legitimate straggler (a long compile or eval
            # sweep on one host). sync_every_n_steps is the per-step
            # preemption vote cadence (N iterations between votes; 0
            # disables — a SIGTERM'd pod then hangs in the next
            # collective instead of draining together). heartbeat_*
            # feed the cross-host liveness record the watchdog dump
            # reads to name the stalled process.
            cluster=AttrDict(
                enabled="auto",  # auto: active iff process_count > 1
                barrier_timeout_s=300.0,
                sync_every_n_steps=1,
                heartbeat_interval_s=10.0,
                heartbeat_timeout_s=60.0,
            ),
            # elastic pods (resilience/elastic.py, ISSUE 11): on a
            # peer-loss signal the survivors run a KV consensus, re-init
            # jax.distributed in-process with the shrunken world, and
            # resume from the emergency checkpoint — the pod keeps
            # training at N-1 hosts instead of idling until capacity
            # returns; a respawned host rejoins through
            # <logdir>/elastic/ and the pod grows back (gate with
            # grow_back=False to pin the shrunken world). min_world_size is
            # the smallest world the survivors may reshape to (below
            # it: the classic all-exit-75 stop-the-world).
            # resize_timeout_s bounds the survivor vote;
            # port_stride spaces each generation's fresh coordination
            # service along the port line from the base coordinator;
            # heartbeat/init knobs tune the raw distributed client
            # (fast peer-loss detection, bounded teardown). Off by
            # default: elastic re-init is only exercised where the
            # launcher opted in (launch_local_pod --elastic).
            elastic=AttrDict(
                enabled=False,
                min_world_size=2,
                resize_timeout_s=60.0,
                grow_back=True,
                join_poll_s=0.25,
                join_timeout_s=600.0,
                port_stride=17,
                heartbeat_interval_s=1.0,
                max_missing_heartbeats=5,
                init_timeout_s=120.0,
                shutdown_timeout_s=5.0,
            ),
        ),
        # -- chaos harness (resilience/chaos.py): deterministic fault
        # injection at configured steps so the recovery paths above stay
        # tested product code (the dryrun spade_chaos leg and
        # tests/test_resilience.py drive these). All *_at_step knobs are
        # one-shot; io_error_site picks which IO path the transient
        # error hits (flow_store | loader). Off by default — never
        # enable in a run you care about.
        chaos=AttrDict(
            enabled=False,
            sigterm_at_step=None,
            corrupt_checkpoint_at_step=None,
            nan_batch_at_step=None,
            io_error_at_step=None,
            io_error_site="flow_store",
            # distributed chaos (ISSUE 8): kill-one-of-N delivers
            # SIGTERM to the process whose index matches (the
            # coordinated-drain path: every host must still exit
            # EXIT_PREEMPTED with one emergency checkpoint), and
            # stall-one-of-N freezes that process for stall_duration_s
            # (the timed-barrier path: surviving hosts must raise
            # ClusterDesyncError naming it instead of hanging).
            kill_at_step=None,
            kill_process_index=0,
            stall_at_step=None,
            stall_process_index=0,
            stall_duration_s=30.0,
            # divergence injection (ISSUE 17): perturb the OBSERVED
            # loss stream of one process at the digest boundary. A
            # healthy pod's cross-host all-reduce homogenizes any
            # in-graph perturbation before the loss scalar exists, so
            # the measurable signature of a desynced replica is a
            # disagreeing observed loss — which is exactly what the
            # podview divergence sentinel must trip on.
            diverge_loss_at_step=None,
            diverge_process_index=0,
            diverge_scale=1e-3,
            # quality degradation (ISSUE 18): inflate the measured FID
            # of every eval sweep from the Nth (1-based) onward by
            # degrade_eval_scale (relative). Persistent, not one-shot:
            # the regression sentinel requires K *consecutive* bad
            # sweeps, so a single degraded point would never trip it —
            # this models a genuinely regressed model, which stays bad.
            degrade_eval_at_sweep=None,
            degrade_eval_scale=1.0,
            # serving latency spike (ISSUE 20): sleep delay_serve_ms
            # inside the execute span of delay_serve_count consecutive
            # requests starting at the Nth served request (1-based) —
            # the red path of the SLO burn-rate gate.
            delay_serve_at_request=None,
            delay_serve_ms=50.0,
            delay_serve_count=1,
        ),
        # -- quality observability plane (evaluation/plane.py, ISSUE
        # 18): continuous FID/KID during training. every_n_iter sets
        # the sweep cadence (None = off, the default — offline
        # evaluate.py still routes through the same plane); metrics
        # picks which of fid|kid each sweep computes; max_batches
        # truncates the sweep's loader walk (rides the reference-store
        # key, so truncated and full reference sets never mix). store
        # toggles the content-addressed reference-feature store
        # (store_dir overrides its <logdir>/feature_store default —
        # point it at shared storage to share reference activations
        # across runs/hosts). The regression sentinel fires when a
        # sweep's FID is regression_threshold (relative) worse than the
        # EWMA baseline (ewma_beta) for regression_consecutive sweeps
        # in a row — `check_run_health --max-quality-regressions`
        # gates on the resulting eval/regressions counter.
        # extractor inception|patch: patch swaps the Inception network
        # for mean-pooled pixel patches — CI smoke legs exercise the
        # whole plane (placement, ledger, store, sentinel, gates) in
        # seconds instead of minutes; its FID is NOT a perceptual
        # number and must never appear in a tracked quality series.
        evaluation=AttrDict(
            every_n_iter=None,
            metrics=["fid"],
            extractor="inception",
            max_batches=None,
            store=True,
            store_dir=None,
            regression_threshold=0.05,
            regression_consecutive=2,
            ewma_beta=0.5,
        ),
        # -- 2-D (data x model) parallelism (parallel/partition.py,
        # ISSUE 6). mesh_shape opts in: {"data": N, "model": M} (or an
        # [N, M] list aligned with axes) builds the 2-D mesh through
        # mesh.mesh_from_config — the single mesh entry point — and
        # activates the partition plan: wide generator/discriminator
        # conv channel dims shard over 'model' per the logical-axis
        # rules (DEFAULT_RULES; the rules mapping here overlays it,
        # e.g. {conv_in: null} to keep in-channels replicated), while
        # optimizer moments + the EMA tree additionally shard over the
        # 'data' axis (cross-replica weight-update sharding, ZeRO-1 /
        # arXiv:2004.13336) — each replica owns 1/N of the update
        # state and params are re-gathered for the forward. Leaves
        # narrower than min_shard_size (or indivisible by the axis)
        # stay replicated. mesh_shape null keeps the legacy 1-D
        # runtime.mesh data-parallel layout with fully replicated
        # state, byte-identical to the seed's programs.
        parallel=AttrDict(
            mesh_shape=None,
            axes=["data", "model"],
            rules=AttrDict(),
            min_shard_size=64,
            shard_update_state=True,
            enabled="auto",  # auto: active iff mesh_shape is set
        ),
        # -- Production serving (serving/engine.py, ISSUE 19). The
        # engine AOT-warms one ledgered executable per (bucket,
        # batch_size); requests pad-and-bucket into the nearest one
        # (padded lanes sliced off before return). buckets entries are
        # [H, W] pairs inheriting the global knobs, or mappings
        # {hw: [H, W], batch_sizes: [...], compute_dtype: bfloat16,
        # remat: blocks, fused_modulation: auto} for per-bucket
        # overrides (the ISSUE-9/15 memory levers, applied at serving
        # granularity). queue_timeout_ms bounds how long a request may
        # wait for batch-mates; max_queue is backpressure, not a goal.
        serving=AttrDict(
            families=["spade"],
            buckets=[[256, 256]],
            batch_sizes=[1, 4],
            queue_timeout_ms=5.0,
            max_queue=64,
            compute_dtype=None,
            remat=None,
            max_executables=16,
            seed=0,
            # -- request-scoped observability (ISSUE 20).
            # trace_sample_rate: fraction of requests whose trace is
            # emitted to the jsonl (deterministic per request id; SLO-
            # breaching requests are ALWAYS emitted regardless).
            trace_sample_rate=1.0,
            # slo: the serving contract. p99_ms None disables the SLO
            # layer entirely; availability is the fraction of requests
            # allowed to meet p99_ms (burn rate = observed bad frac /
            # allowed bad frac over the last `window` requests).
            slo=AttrDict(
                p99_ms=None,
                availability=0.999,
                window=256,
            ),
        ),
        # -- TPU runtime (replaces ref cudnn/local_rank blocks, config.py:143-150)
        runtime=AttrDict(
            mesh=AttrDict(axes=["data"], shape=None),  # shape None => all devices on 'data'
            param_dtype="float32",
            seed=2,
            deterministic=False,
        ),
        pretrained_weight=None,
        inference_args=AttrDict(),
    )


class Config(AttrDict):
    """Load an experiment config: defaults <- yaml overlay (+ ``common`` broadcast).

    ref: imaginaire/config.py:73-183.
    """

    def __init__(self, filename=None, overrides=None):
        super().__init__(default_config())
        if filename is not None:
            user = load_yaml(filename)
            if user:
                recursive_update(self, user)
        if overrides:
            recursive_update(self, overrides)
        # Broadcast the `common:` section into gen and dis configs
        # (ref: imaginaire/config.py:173-177).
        if "common" in self:
            common = self["common"]
            for section in ("gen", "dis"):
                if section in self:
                    for key, value in common.items():
                        if key not in self[section]:
                            self[section][key] = copy.deepcopy(value)
        self["source_filename"] = str(filename) if filename is not None else None


def cfg_get(cfg, key, default=None):
    from collections.abc import Mapping

    if isinstance(cfg, Mapping) and not isinstance(cfg, AttrDict):
        return cfg.get(key, default)
    """`getattr(cfg, key, default)` idiom used pervasively by the reference
    (ref: generators/spade.py:40-42)."""
    try:
        return cfg[key]
    except (KeyError, TypeError):
        return default

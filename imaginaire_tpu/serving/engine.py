"""Production serving engine (ISSUE 19): AOT-warmed executable pool,
bucketed micro-batching, and streaming vid2vid sessions.

Training got sharded, fault-tolerant, pipelined and quality-gated; this
module is the serving path the "millions of users" north star was still
missing. Three pieces, composed from machinery previous PRs landed:

- :class:`ExecutablePool` — an LRU table of per-(family,
  resolution-bucket, batch-size) inference executables, each dispatched
  through ``xla_obs.compiled_program`` so every compile is ledgered,
  recompiles trip the tripwire, and ``warm()`` AOT-compiles an
  executable *without executing it* (the PR-5 ``aot_compile`` entry).
  Per-bucket knobs (``compute_dtype`` / ``remat`` /
  ``fused_modulation`` — the PR-9/PR-15 memory levers) ride the pool
  key, so a 512² bucket can run bf16+remat while 256² stays fp32.
- :class:`RequestQueue` — pads and buckets incoming requests into the
  nearest (bucket, batch-size) executable. Padding correctness is a
  contract, not a hope: the queue's executables vmap the bs=1
  computation over lanes with one noise key per request, so each
  lane's graph (including its noise draw) is independent of its
  batch-mates; zero pad lanes appended after the real ones are sliced
  off before return and provably cannot contaminate real-lane outputs
  (bit-identical to the same requests in an unpadded batch of the same
  executable; across different batch-size programs the math is
  identical and equality is bitwise on deterministic backends, float-
  scheduling-tight on multithreaded XLA:CPU).
- :class:`StreamSession` — per-stream vid2vid conditioning state. The
  trainer keeps ONE global ``_test_prev_labels/_test_prev_images`` pair
  (vid2vid.py ``reset``/``_generate_frame``); a server interleaves many
  streams, so each session owns its own device-resident ring buffers
  and frame t+1 of a stream reuses frame t's arrays instead of
  re-uploading history from the host.

Weights load ONLY through the verified-restore path
(``load_latest_verified`` / the trainer's quarantine-and-fallback
explicit path): serving never deserializes bytes the training integrity
layer would quarantine. The engine emits SLO telemetry — serve/p50_ms,
serve/p99_ms, serve/queue_depth, serve/bucket_hit_rate,
serve/pad_waste_frac, serve/hbm_headroom_frac — through the existing
Telemetry/jsonl plane, so ``report.py`` renders a "## serving" section
and ``check_run_health --max-p99-latency-ms / --max-queue-depth`` gate
it like any training run.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from collections import OrderedDict, deque
from collections.abc import Mapping
from dataclasses import dataclass, field

import numpy as np

from imaginaire_tpu.config import as_attrdict, cfg_get, recursive_update

logger = logging.getLogger(__name__)


class ServingError(RuntimeError):
    """The engine cannot (or refuses to) serve."""


# ------------------------------------------------------------- settings


@dataclass(frozen=True)
class BucketCfg:
    """One configured resolution bucket and its executable knobs."""

    height: int
    width: int
    batch_sizes: tuple = (1,)
    compute_dtype: str = None  # None -> trainer's fp32 inference dtype
    remat: str = None          # None -> the generator config's policy
    fused_modulation: str = None

    @property
    def hw(self):
        return (self.height, self.width)


@dataclass(frozen=True)
class ExecKey:
    """Pool key: everything that selects a distinct executable."""

    family: str
    height: int
    width: int
    batch_size: int
    compute_dtype: str = None
    remat: str = None
    fused_modulation: str = None
    # "lanes": vmapped per-lane program with a stacked (B, 2) key array
    #   — each lane runs exactly the bs=1 computation with its own
    #   noise key, which is what makes padded batches bit-identical to
    #   unpadded singles (the queue path).
    # "batch": whole-batch program with one key — the legacy test-loop
    #   computation, jitted (the inference.py seam; byte-parity with a
    #   jitted legacy reference).
    # "stream": the vid2vid frame-recurrent _apply_G program.
    tag: str = "lanes"
    opts: tuple = ()  # frozen (name, repr(value)) inference_args

    @property
    def bucket_name(self):
        return f"{self.height}x{self.width}"

    @property
    def label(self):
        """The compile-ledger label: serve/<family>[/stream]/<HxW>/bs<N>
        (+ dtype/remat suffixes when a bucket overrides them)."""
        parts = ["serve", self.family]
        if self.tag != "lanes":
            parts.append(self.tag)
        parts.append(self.bucket_name)
        parts.append(f"bs{self.batch_size}")
        if self.compute_dtype:
            parts.append(str(self.compute_dtype))
        if self.remat:
            parts.append(f"remat-{self.remat}")
        return "/".join(parts)


def serving_settings(cfg):
    """Parse ``cfg.serving`` into engine settings (plain dict). Bucket
    entries are either ``[H, W]`` (inheriting the global knobs) or a
    mapping ``{hw: [H, W], batch_sizes: [...], compute_dtype: ...,
    remat: ..., fused_modulation: ...}`` for per-bucket overrides."""
    scfg = cfg_get(cfg or {}, "serving", None) or {}
    global_bs = tuple(int(b) for b in
                      (cfg_get(scfg, "batch_sizes", None) or (1, 4)))
    global_dtype = cfg_get(scfg, "compute_dtype", None)
    global_remat = cfg_get(scfg, "remat", None)
    global_fused = cfg_get(scfg, "fused_modulation", None)
    buckets = []
    for entry in (cfg_get(scfg, "buckets", None) or [[256, 256]]):
        if isinstance(entry, Mapping):
            hw = cfg_get(entry, "hw", None) or cfg_get(entry, "size", None)
            buckets.append(BucketCfg(
                int(hw[0]), int(hw[1]),
                tuple(int(b) for b in
                      (cfg_get(entry, "batch_sizes", None) or global_bs)),
                cfg_get(entry, "compute_dtype", global_dtype),
                cfg_get(entry, "remat", global_remat),
                cfg_get(entry, "fused_modulation", global_fused)))
        else:
            buckets.append(BucketCfg(int(entry[0]), int(entry[1]),
                                     global_bs, global_dtype,
                                     global_remat, global_fused))
    from imaginaire_tpu.serving.slo import slo_settings

    return {
        "families": list(cfg_get(scfg, "families", None) or ["spade"]),
        "buckets": buckets,
        "batch_sizes": global_bs,
        "queue_timeout_ms": float(cfg_get(scfg, "queue_timeout_ms", 5.0)),
        "max_queue": int(cfg_get(scfg, "max_queue", 64)),
        "compute_dtype": global_dtype,
        "remat": global_remat,
        "max_executables": int(cfg_get(scfg, "max_executables", 16)),
        "seed": int(cfg_get(scfg, "seed", 0)),
        "trace_sample_rate": float(cfg_get(scfg, "trace_sample_rate",
                                           1.0)),
        "slo": slo_settings(cfg),
    }


# ------------------------------------------------------ executable pool


class ExecutablePool:
    """LRU table of ledgered inference executables, keyed by
    :class:`ExecKey`. ``get`` builds (through
    ``xla_obs.compiled_program``) on miss and evicts the
    least-recently-used program past ``max_entries`` — eviction drops
    the AOT executable and its fingerprint table, so a re-admitted key
    pays one fresh (ledgered, un-tripwired) compile. ``warm`` compiles
    without executing, pinning the executable hot before the first
    request arrives."""

    def __init__(self, build_fn, max_entries=16):
        self._build = build_fn
        self.max_entries = max(int(max_entries), 1)
        self._programs = OrderedDict()
        self._lock = threading.RLock()
        self.builds = 0
        self.evictions = 0
        # labels that have EVER been evicted and not yet rebuilt: a
        # subsequent miss on one of these is an evict-then-recompile —
        # the expensive tail event request traces must attribute
        # (ISSUE 20), distinct from a plain first-seen cold compile
        self.evicted_labels = set()

    def __len__(self):
        return len(self._programs)

    def __contains__(self, key):
        return key in self._programs

    def keys(self):
        return list(self._programs)

    def get(self, key):
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                return prog
        # build outside the lock: compiles are slow and the builder may
        # recurse into telemetry
        fn = self._build(key)
        from imaginaire_tpu.telemetry import xla_obs

        prog = xla_obs.compiled_program(
            key.label, fn,
            # stream programs legitimately grow their conditioning
            # history over the first frames (the growth_only allowance)
            allow_shape_growth=(key.tag == "stream"))
        with self._lock:
            self._programs[key] = prog
            self.builds += 1
            self.evicted_labels.discard(key.label)
            while len(self._programs) > self.max_entries:
                old_key, _ = self._programs.popitem(last=False)
                self.evictions += 1
                self.evicted_labels.add(old_key.label)
                logger.info("serving pool: evicted %s (LRU, max %d)",
                            old_key.label, self.max_entries)
                from imaginaire_tpu import telemetry

                telemetry.get().meta("serve/evict", label=old_key.label,
                                     pool_size=len(self._programs))
        return prog

    def is_evict_recompile(self, key):
        """True when a ``get(key)`` now would pay a rebuild of a label
        this pool previously evicted (vs a first-seen cold compile)."""
        with self._lock:
            return (key not in self._programs
                    and key.label in self.evicted_labels)

    def warm(self, key, *example_args):
        """AOT-compile ``key`` for these example args without executing
        (``CompiledProgram.aot_compile``); returns the ledger's memory
        dict for the label."""
        return self.get(key).aot_compile(*example_args)


# -------------------------------------------------------- request queue


_REQUEST_IDS = iter(range(1, 1 << 62))


@dataclass
class ServeRequest:
    """One inference request: a data dict of numpy arrays with a lane
    dimension of 1 (``{"label": (1, H, W, C), ...}``)."""

    data: dict
    seed: int = 0
    stream_id: str = None
    id: int = field(default_factory=lambda: next(_REQUEST_IDS))
    t_submit: float = field(default_factory=time.perf_counter)

    @property
    def hw(self):
        for v in self.data.values():
            shape = getattr(v, "shape", ())
            if len(shape) == 4:
                return (int(shape[1]), int(shape[2]))
        raise ServingError("request carries no rank-4 (B,H,W,C) array")


class RequestQueue:
    """Pads and buckets pending requests into the nearest (bucket,
    batch-size) executable. Synchronous by design: ``submit`` enqueues,
    and the engine drains either when some resolution group can fill
    its largest configured batch size or when the oldest pending
    request has waited past ``queue_timeout_ms`` (``pump``), or
    unconditionally (``flush``). No background threads — determinism is
    what makes the pad-and-slice bit-parity testable."""

    def __init__(self, engine, max_depth=64, timeout_ms=5.0):
        self.engine = engine
        self.max_depth = int(max_depth)
        self.timeout_ms = float(timeout_ms)
        self._pending = []

    @property
    def depth(self):
        return len(self._pending)

    def submit(self, request):
        if len(self._pending) >= self.max_depth:
            raise ServingError(
                f"queue overflow: {len(self._pending)} pending >= "
                f"max_queue {self.max_depth} (backpressure, not OOM)")
        self._pending.append(request)
        return request.id

    def _groups(self):
        groups = OrderedDict()
        for req in self._pending:
            groups.setdefault(req.hw, []).append(req)
        return groups

    def due(self, now=None):
        """True when some group can fill its largest batch size or the
        oldest request is past the batching window."""
        if not self._pending:
            return False
        now = time.perf_counter() if now is None else now
        oldest = min(r.t_submit for r in self._pending)
        if (now - oldest) * 1e3 >= self.timeout_ms:
            return True
        for hw, reqs in self._groups().items():
            if len(reqs) >= self.engine.max_batch_for(hw):
                return True
        return False

    def drain(self):
        """Take every pending request, grouped by resolution."""
        groups = self._groups()
        self._pending = []
        return groups


# ------------------------------------------------------ stream sessions


class StreamSession:
    """Per-stream vid2vid conditioning state, device-resident across
    requests. Owns the ``prev_labels``/``prev_images`` ring buffers the
    trainer keeps as process-global attrs, so a server can interleave
    frames of many streams: ``step(frame)`` builds ``data_t`` from THIS
    stream's device-resident history (no host re-upload), runs the
    pooled stream executable, and rolls the rings forward with the
    device output. ``reset()`` starts a new shot."""

    def __init__(self, engine, stream_id, seed=None):
        self.engine = engine
        self.stream_id = stream_id
        self.seed = engine.settings["seed"] if seed is None else int(seed)
        trainer = engine.trainer
        if not hasattr(trainer, "_get_data_t"):
            raise ServingError(
                f"family {engine.family!r} has no frame-recurrent "
                f"trainer (_get_data_t); streaming sessions need the "
                f"vid2vid family")
        self.history = max(int(getattr(trainer, "num_frames_G", 2)) - 1, 1)
        self.prev_labels = None
        self.prev_images = None
        self.t = 0
        engine.tracer.lifecycle("open", stream_id, history=self.history)

    def reset(self):
        self.engine.tracer.lifecycle("reset", self.stream_id,
                                     frame=self.t)
        self.prev_labels = None
        self.prev_images = None
        self.t = 0

    def step(self, data, seed=None):
        """Generate the next frame from a single-frame data dict;
        returns the fake frame as a host numpy array while the ring
        buffers keep the device arrays.

        Each frame gets its own trace (trace_id
        ``<family>/<stream_id>/frame-N``): admit -> h2d_transfer (host
        frame upload) -> bucket/pad (conditioning assembly from the
        device-resident rings) -> execute -> d2h/slice (host copy +
        ring roll) -> respond. Stream traces carry ``stream_id`` so
        interleaved streams stay separable in the jsonl.
        """
        from imaginaire_tpu.model_utils.fs_vid2vid import concat_frames
        from imaginaire_tpu.utils.misc import numeric_only, to_device

        engine = self.engine
        trainer = engine.trainer
        t_submit = time.perf_counter()
        trace = engine.tracer.admit(next(_REQUEST_IDS),
                                    stream_id=self.stream_id,
                                    frame=self.t, t0=t_submit)
        trace.mark("h2d_transfer")
        data = to_device(trainer._start_of_iteration(
            numeric_only(dict(data)), -1))
        trace.mark("bucket/pad")
        data_t = trainer._get_data_t(data, 0, self.prev_labels,
                                     self.prev_images)
        call_data = {k: v for k, v in data_t.items()
                     if not k.startswith("_")}
        h, w = ServeRequest(data=call_data).hw
        seed = self.seed if seed is None else int(seed)
        rng = _prng(seed * 100003 + self.t)
        key = engine._exec_key(h, w, 1, tag="stream")
        hit = key in engine.pool
        evict_recompile = (not hit) and engine.pool.is_evict_recompile(
            key)
        trace.mark("execute")
        engine._maybe_chaos_delay(1)
        fake = engine._run(key, call_data, rng)
        trace.mark("d2h/slice")
        # rings advance with the DEVICE arrays: frame t+1 of this
        # stream conditions on buffers already resident on chip
        self.prev_labels = concat_frames(self.prev_labels,
                                         data_t["label"], self.history)
        self.prev_images = concat_frames(self.prev_images, fake,
                                         self.history)
        self.t += 1
        out = np.asarray(fake)
        trace.mark("respond")
        trace.annotate(executable=key.label, batch_size=1, lanes=1,
                       padded=0, warm_hit=bool(hit),
                       evict_recompile=bool(evict_recompile))
        engine._account(key, [t_submit], hit=hit, lanes=1, padded=0,
                        traces=[trace])
        return out


# -------------------------------------------------------------- engine


def _prng(seed):
    import jax

    return jax.random.PRNGKey(int(seed))


def _hbm_headroom_frac():
    """1 - peak/limit across local devices, or None where the backend
    exposes no memory_stats (CPU)."""
    try:
        import jax

        worst = None
        for dev in jax.local_devices():
            stats = dev.memory_stats()
            if not stats or not stats.get("bytes_limit"):
                continue
            frac = 1.0 - (stats.get("peak_bytes_in_use",
                                    stats.get("bytes_in_use", 0))
                          / float(stats["bytes_limit"]))
            worst = frac if worst is None else min(worst, frac)
        return worst
    except Exception:  # noqa: BLE001 — telemetry is best-effort
        return None


def _percentile(samples, q):
    """Linear-interpolated percentile, hardened for tiny samples
    (ISSUE 20 satellite): ``None`` on empty (the old rounding form
    raised IndexError), the sole element for n=1, and interpolation for
    n=2 — p50 of ``[10, 20]`` is 15, not 20 (nearest-rank rounding made
    every percentile of a 2-sample ring collapse to the max, so the
    first post-reset flush reported a wildly pessimistic p50)."""
    if not samples:
        return None
    ordered = sorted(samples)
    if len(ordered) == 1:
        return ordered[0]
    pos = q * (len(ordered) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(ordered) - 1)
    return ordered[lo] + (ordered[hi] - ordered[lo]) * (pos - lo)


class ServingEngine:
    """The serving frontend for one model family.

    Construction wires (but does not run) everything: a trainer for the
    family (reused when the entry point already built one), the
    executable pool, and the request queue. ``initialize`` builds model
    state from one example batch, ``load_weights`` restores through the
    verified path, ``warm`` AOT-compiles every configured (bucket,
    batch-size) executable, and ``serve``/``submit``+``pump`` run
    requests. Frozen feature extractors (VGG/Inception perceptual nets)
    stay off the hot path by construction: the pooled programs close
    over ``net_G.inference`` only — no loss params, no teachers (the
    ``flow/cache.py`` pattern generalized)."""

    def __init__(self, cfg, trainer=None, logdir=None, family=None):
        self.cfg = as_attrdict(cfg)
        self.settings = serving_settings(self.cfg)
        self.family = family or _family_of(self.cfg)
        if trainer is None:
            from imaginaire_tpu.registry import resolve

            trainer = resolve(self.cfg.trainer.type, "Trainer")(self.cfg)
        self.trainer = trainer
        self.logdir = logdir or cfg_get(self.cfg, "logdir", ".")
        self.pool = ExecutablePool(self._build_fn,
                                   self.settings["max_executables"])
        self.queue = RequestQueue(self, self.settings["max_queue"],
                                  self.settings["queue_timeout_ms"])
        self._nets = {}
        self._inference_args_by_opts = {(): dict(
            cfg_get(self.cfg, "inference_args", None) or {})}
        self._variables = None
        # latency rings are bounded SLIDING WINDOWS (maxlen, below) over
        # the most recent requests — telemetry flush does NOT clear them
        # (flush drains the event buffer, not engine state), so a post-
        # flush percentile still reflects the live window. The one
        # boundary that must not leak samples is a measurement boundary
        # (bench legs, loadgen load points): call ``reset_stats()``
        # there, or point N's p99 inherits point N-1's tail.
        self._latencies = deque(maxlen=2048)
        self._bucket_exec_ms = {}  # label -> deque of batch exec ms
        self._hits = 0
        self._misses = 0
        self._lane_total = 0
        self._lane_padded = 0
        self._batches = 0
        self._sessions = {}
        self._verified_restore = False
        # -- request-scoped observability (ISSUE 20) --
        from imaginaire_tpu.serving.slo import ErrorBudget
        from imaginaire_tpu.serving.tracing import Tracer

        self.tracer = Tracer(self.family,
                             self.settings["trace_sample_rate"])
        self.budget = ErrorBudget.from_settings(self.settings["slo"])
        self._traces = {}  # request_id -> in-flight RequestTrace
        self._served = 0   # request ordinal (chaos delay_serve site)
        self._slo_config_emitted = False

    # ------------------------------------------------------- lifecycle

    def initialize(self, example_batch=None, seed=None):
        """Build the trainer state from one example batch (no-op when
        the entry point already initialized the trainer)."""
        if self.trainer.state is None:
            if example_batch is None:
                raise ServingError(
                    "engine.initialize needs an example batch when the "
                    "trainer has no state yet")
            seed = self.settings["seed"] if seed is None else int(seed)
            data = self.trainer.start_of_iteration(example_batch, 0)
            self.trainer.init_state(_prng(seed), data)
        self.refresh_weights()
        return self

    def load_weights(self, checkpoint=None, require=True):
        """Restore ONLY through the verified path: discovery goes
        through ``load_latest_verified`` (quarantine + last-good
        fallback), an explicit path is integrity-verified and
        quarantined on mismatch with fallback to the newest verifiable
        sibling. ``require=True`` (the serving default) raises when
        nothing verifiable restored — a server must never run weights
        training would refuse."""
        if self.trainer.state is None:
            raise ServingError("initialize() before load_weights()")
        loaded = self.trainer.load_checkpoint(checkpoint or None,
                                              fallback=bool(checkpoint))
        if not loaded:
            if require:
                raise ServingError(
                    "no verifiable checkpoint to serve (refusing to "
                    "serve fresh/unverified weights; pass "
                    "require=False for smoke tests)")
            logger.warning("serving with FRESH weights (require=False)")
        self._verified_restore = bool(loaded)
        self.refresh_weights()
        from imaginaire_tpu import telemetry

        telemetry.get().meta("serve/weights", family=self.family,
                             verified=bool(loaded),
                             checkpoint=str(checkpoint or "latest"))
        return loaded

    def refresh_weights(self):
        """Re-pull inference variables (EMA params when model averaging
        is on) from the trainer state."""
        self._variables = self.trainer.inference_params()
        return self._variables

    # --------------------------------------------------------- keying

    def _bucket_for(self, hw):
        for b in self.settings["buckets"]:
            if b.hw == tuple(hw):
                return b
        return None

    def max_batch_for(self, hw):
        b = self._bucket_for(hw)
        return max(b.batch_sizes) if b else 1

    def _exec_key(self, h, w, bs, tag="lanes", opts=()):
        b = self._bucket_for((h, w))
        return ExecKey(
            family=self.family, height=int(h), width=int(w),
            batch_size=int(bs),
            compute_dtype=(b.compute_dtype if b
                           else self.settings["compute_dtype"]),
            remat=b.remat if b else self.settings["remat"],
            fused_modulation=b.fused_modulation if b else None,
            tag=tag, opts=tuple(opts))

    def _net_for(self, key):
        """The generator module for this key's knobs: the trainer's own
        net when nothing is overridden, else a rebuilt module with the
        bucket's remat/fused_modulation overlaid on ``cfg.gen``
        (module construction is cheap and the PR-9 policies keep the
        param tree checkpoint-invariant, so the same restored variables
        apply)."""
        overlay = {}
        if key.remat is not None:
            overlay["remat"] = key.remat
        if key.fused_modulation is not None:
            overlay["fused_modulation"] = key.fused_modulation
        if not overlay:
            return self.trainer.net_G
        cache_key = tuple(sorted(overlay.items()))
        net = self._nets.get(cache_key)
        if net is None:
            from imaginaire_tpu.registry import resolve

            gen_cfg = as_attrdict(copy.deepcopy(self.cfg.gen.to_dict()))
            recursive_update(gen_cfg, overlay)
            net = resolve(self.cfg.gen.type, "Generator")(
                gen_cfg, self.cfg.data)
            self._nets[cache_key] = net
        return net

    def _build_fn(self, key):
        """The pure function a pool key compiles: the same inference
        forward the trainer's test loop runs, with the bucket's
        compute-dtype cast (params-only — fp32 islands survive, the
        PR-9 contract) traced into the program."""
        import jax.numpy as jnp

        dt = jnp.dtype(key.compute_dtype) if key.compute_dtype else None

        def cast(variables):
            if dt is None or dt == jnp.float32:
                return variables
            import jax

            params = jax.tree_util.tree_map(
                lambda x: x.astype(dt)
                if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
                variables["params"])
            return dict(variables, params=params)

        if key.tag == "stream":
            trainer = self.trainer

            def stream_fn(variables, data_t, rng):
                out, _ = trainer._apply_G(cast(variables), data_t, rng,
                                          training=False)
                return out["fake_images"]

            return stream_fn
        net = self._net_for(key)
        inference_args = dict(self._inference_args_by_opts.get(
            key.opts, self._inference_args_by_opts[()]))

        if key.tag == "batch":
            # whole-batch, one noise key: the exact legacy test-loop
            # computation, jitted (byte-parity with jit(legacy))
            def fn(variables, data, rng):
                return net.apply(cast(variables), data, training=False,
                                 rngs={"noise": rng},
                                 method=net.inference, **inference_args)

            return fn

        # queue path: vmap the bs=1 computation over lanes, one noise
        # key per lane. Lane i's graph (and its noise draw) is then
        # independent of who else rode the batch — verified bit-
        # identical to the same request served unpadded, which a
        # whole-batch (B, style_dims) eps draw is not.
        import jax

        def one_lane(variables, lane, lane_key):
            lane = jax.tree_util.tree_map(lambda x: x[None], lane)
            out = net.apply(cast(variables), lane, training=False,
                            rngs={"noise": lane_key},
                            method=net.inference, **inference_args)
            return (out["fake_images"] if isinstance(out, dict)
                    else out)[0]

        def lanes_fn(variables, data, lane_keys):
            return jax.vmap(one_lane, in_axes=(None, 0, 0))(
                variables, data, lane_keys)

        return lanes_fn

    # -------------------------------------------------------- warming

    def warm(self, tags=("lanes",)):
        """AOT-compile every configured (bucket, batch-size) executable
        (``aot_compile`` — no execution, the compile lands in the
        ledger and the fingerprint pins the warm table). Returns
        {label: memory dict}. ``tags`` picks the program flavors to
        warm: ``lanes`` for queued traffic, ``batch`` for the
        entry-point forward() seam."""
        import jax.numpy as jnp

        if self._variables is None:
            raise ServingError("initialize() before warm()")
        report = {}
        for bucket in self.settings["buckets"]:
            for bs in bucket.batch_sizes:
                for tag in tags:
                    key = self._exec_key(bucket.height, bucket.width,
                                         bs, tag=tag)
                    example = self._zero_batch(bucket.height,
                                               bucket.width, bs)
                    rng = (jnp.zeros((bs, 2), jnp.uint32)
                           if tag == "lanes"
                           else _prng(self.settings["seed"]))
                    report[key.label] = self.pool.warm(
                        key, self._variables, example, rng)
        from imaginaire_tpu import telemetry

        telemetry.get().meta("serve/warm", family=self.family,
                             executables=sorted(report))
        return report

    def _example_lane(self):
        """One data lane shaped like what the trainer was initialized
        with — the template ``_zero_batch`` re-shapes per bucket."""
        if getattr(self, "_example", None) is None:
            raise ServingError(
                "no example lane registered; initialize() with an "
                "example batch or call register_example() first")
        return self._example

    def register_example(self, batch):
        """Remember one (preprocessed) batch as the shape template for
        warm(): rank-4 arrays re-shape to each bucket's (H, W), other
        arrays tile along the lane dim."""
        from imaginaire_tpu.utils.misc import numeric_only

        self._example = {k: np.asarray(v)[:1]
                         for k, v in numeric_only(dict(batch)).items()}
        return self

    def _zero_batch(self, h, w, bs):
        import jax.numpy as jnp

        lane = self._example_lane()
        out = {}
        for k, v in lane.items():
            shape = list(v.shape)
            if len(shape) == 4:
                shape[1], shape[2] = int(h), int(w)
            shape[0] = int(bs)
            out[k] = jnp.zeros(tuple(shape), dtype=v.dtype)
        return out

    # -------------------------------------------------------- serving

    def submit(self, request):
        """Enqueue one request; returns its ticket id. Call ``pump``
        (or ``flush``) to execute.

        This is where the request's trace is born: the admit span is
        anchored at ``request.t_submit`` (scheduled arrival under open-
        loop load), so queue-induced lateness lands in the trace, not
        outside it. A shed request (queue overflow) still gets a trace
        — rejected, budget-charged, always emitted.

        Note: ``serve/queue_depth`` is NOT emitted here. It used to be
        emitted both at enqueue and in ``_emit_slo``, interleaving two
        cadences into one series; ``_emit_slo`` (post-batch) is the one
        authoritative emission.
        """
        trace = self.tracer.admit(request.id, t0=request.t_submit)
        trace.annotate(queue_depth_at_admit=self.queue.depth)
        try:
            ticket = self.queue.submit(request)
        except ServingError:
            trace.annotate(rejected=True)
            trace.mark("respond").finish()
            self.budget.observe_rejected(trace=trace)
            self.tracer.emit(trace)
            raise
        # admit closes, queue_wait opens; it stays open until THIS
        # request's chunk starts executing (not its group's first chunk)
        trace.mark("queue_wait")
        self._traces[request.id] = trace
        return ticket

    def pump(self, now=None):
        """Execute pending requests if a batch is due; returns
        {request_id: image} for everything executed."""
        if not self.queue.due(now=now):
            return {}
        return self.flush()

    def flush(self):
        """Execute ALL pending requests now."""
        results = {}
        for hw, reqs in self.queue.drain().items():
            results.update(self._serve_group(hw, reqs))
        return results

    def serve(self, requests):
        """Synchronous convenience: submit + flush; returns images in
        request order."""
        for req in requests:
            self.submit(req)
        results = self.flush()
        return [results[req.id] for req in requests]

    def _serve_group(self, hw, reqs):
        """One resolution group: chunk to the nearest configured batch
        size, zero-pad the final partial chunk, slice padded lanes off
        before return."""
        bucket = self._bucket_for(hw)
        sizes = sorted(bucket.batch_sizes) if bucket \
            else [min(len(reqs), max(self.settings["batch_sizes"]))]
        results = {}
        i = 0
        while i < len(reqs):
            remaining = len(reqs) - i
            bs = next((s for s in sizes if s >= remaining), sizes[-1])
            chunk = reqs[i:i + bs]
            i += len(chunk)
            results.update(self._execute_chunk(hw, chunk, bs,
                                               hit=bucket is not None))
        return results

    def _execute_chunk(self, hw, chunk, bs, hit=True):
        import jax

        if self._variables is None:
            raise ServingError("initialize() before serving")
        key = self._exec_key(hw[0], hw[1], bs)
        hit = hit and key in self.pool
        evict_recompile = (not hit) and self.pool.is_evict_recompile(key)
        pad = bs - len(chunk)
        # each request's queue_wait span ends when ITS chunk starts —
        # not when the group's first chunk did — so a request stuck
        # behind an earlier chunk keeps that wait inside queue_wait and
        # spans stay contiguous (they must sum to e2e latency)
        traces = [self._traces.pop(r.id, None) for r in chunk]
        t_stage = time.perf_counter()
        for tr in traces:
            if tr is not None:
                tr.mark("bucket/pad", t=t_stage)
        host = {}
        for name in chunk[0].data:
            lanes = [np.asarray(r.data[name]) for r in chunk]
            stacked = np.concatenate(lanes, axis=0)
            if pad:
                # zero lanes AFTER the real ones; sliced off below.
                # Inference normalization runs on running statistics
                # (training=False), so real lanes never see the pads.
                stacked = np.concatenate(
                    [stacked, np.zeros((pad,) + stacked.shape[1:],
                                       stacked.dtype)], axis=0)
            host[name] = stacked
        # one noise key per lane, derived from the request's own seed —
        # pad lanes get a throwaway key (their output is sliced off)
        rng_host = np.stack([np.asarray(_prng(r.seed)) for r in chunk]
                            + [np.zeros(2, np.uint32)] * pad)
        t_stage = time.perf_counter()
        for tr in traces:
            if tr is not None:
                tr.mark("h2d_transfer", t=t_stage)
        # device_put so warm (jnp) and live (np) calls share one
        # fingerprint — a host/device mismatch would re-specialize
        data = {name: jax.device_put(arr) for name, arr in host.items()}
        rng = jax.device_put(rng_host)
        t_stage = time.perf_counter()
        for tr in traces:
            if tr is not None:
                tr.mark("execute", t=t_stage)
        self._maybe_chaos_delay(len(chunk))
        images = self._run(key, data, rng)
        t_stage = time.perf_counter()
        for tr in traces:
            if tr is not None:
                tr.mark("d2h/slice", t=t_stage)
        images = np.asarray(images)[:len(chunk)]
        t_stage = time.perf_counter()
        for tr in traces:
            if tr is not None:
                tr.mark("respond", t=t_stage)
                tr.annotate(executable=key.label, batch_size=bs,
                            lanes=len(chunk), padded=pad,
                            warm_hit=bool(hit),
                            evict_recompile=bool(evict_recompile))
        self._account(key, [r.t_submit for r in chunk], hit=hit,
                      lanes=bs, padded=pad, traces=traces)
        return {req.id: images[j] for j, req in enumerate(chunk)}

    def _maybe_chaos_delay(self, nreqs):
        """The ``delay_serve_at_request`` chaos site (ISSUE 20 dryrun
        red path): advance the served-request ordinal and let the chaos
        plane inject a latency spike inside the execute span."""
        from imaginaire_tpu.resilience import chaos

        monkey = chaos.get()
        for _ in range(max(int(nreqs), 1)):
            self._served += 1
            monkey.maybe_delay_serve(self._served)

    def _run(self, key, data, rng):
        """Dispatch one pooled executable and fence the result (serving
        latency is device-true by definition)."""
        import jax

        t0 = time.perf_counter()
        out = self.pool.get(key)(self._variables, data, rng)
        images = out["fake_images"] if isinstance(out, dict) else out
        images = jax.block_until_ready(images)
        exec_ms = (time.perf_counter() - t0) * 1e3
        ring = self._bucket_exec_ms.setdefault(
            key.label, deque(maxlen=512))
        ring.append(exec_ms)
        return images

    def forward(self, variables, data, rng, inference_args=None):
        """Drop-in for the trainer test loop's eager
        ``net_G.apply(..., method=inference)`` — the seam
        ``BaseTrainer.inference_forward`` routes through when an engine
        is attached, so one-shot ``inference.py`` runs inherit the
        ledgered warm executables + SLO telemetry for free."""
        from imaginaire_tpu.utils.misc import numeric_only

        t_submit = time.perf_counter()
        if variables is not None:
            self._variables = variables
        opts = ()
        if inference_args:
            opts = tuple(sorted((k, repr(v))
                                for k, v in dict(inference_args).items()))
            self._inference_args_by_opts.setdefault(
                opts, dict(inference_args))
        probe = ServeRequest(data=numeric_only(dict(data)))
        data = probe.data
        bs = None
        for v in data.values():
            if len(getattr(v, "shape", ())) == 4:
                bs = int(v.shape[0])
                break
        # one-shot seam: no queue, so the trace is the queue-path
        # subset admit -> bucket/pad -> h2d_transfer -> execute ->
        # respond (the caller keeps the device array; no d2h here)
        trace = self.tracer.admit(probe.id, t0=t_submit)
        trace.mark("bucket/pad")
        h, w = probe.hw
        key = self._exec_key(h, w, bs or 1, tag="batch", opts=opts)
        hit = key in self.pool
        evict_recompile = (not hit) and self.pool.is_evict_recompile(key)
        import jax

        trace.mark("h2d_transfer")
        data = jax.device_put(data)
        trace.mark("execute")
        self._maybe_chaos_delay(1)
        images = self._run(key, data, rng)
        trace.mark("respond")
        trace.annotate(executable=key.label, batch_size=bs or 1,
                       lanes=bs or 1, padded=0, warm_hit=bool(hit),
                       evict_recompile=bool(evict_recompile))
        self._account(key, [t_submit], hit=hit, lanes=bs or 1, padded=0,
                      traces=[trace])
        return images

    def attach(self):
        """Route the trainer's test loop through this engine
        (``BaseTrainer.inference_forward``)."""
        self.trainer._serving_engine = self
        return self

    # ------------------------------------------------------ telemetry

    def _account(self, key, submit_times, hit, lanes, padded,
                 traces=None):
        now = time.perf_counter()
        latencies = [(now - t) * 1e3 for t in submit_times]
        self._latencies.extend(latencies)
        if hit:
            self._hits += 1
        else:
            self._misses += 1
        self._lane_total += int(lanes)
        self._lane_padded += int(padded)
        self._batches += 1
        # budget verdict BEFORE emission: a breach flips the trace to
        # always-emit (and stamps dominant_span into the breach meta)
        # regardless of the sampling decision taken at admit
        traces = traces or []
        for j, latency_ms in enumerate(latencies):
            trace = traces[j] if j < len(traces) else None
            if trace is not None:
                trace.finish(t=now)
            self.budget.observe(latency_ms, trace=trace)
            if trace is not None:
                self.tracer.emit(trace)
        self._emit_slo(key)

    def _emit_slo(self, key=None):
        """The SLO counter surface, emitted after every executed batch
        (serving steps are requests, not training iterations)."""
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if not tm.enabled:
            return
        step = self._batches
        lat = list(self._latencies)
        if lat:
            tm.counter("serve/p50_ms", _percentile(lat, 0.50), step=step)
            tm.counter("serve/p99_ms", _percentile(lat, 0.99), step=step)
        tm.counter("serve/requests", len(lat), step=step)
        tm.counter("serve/queue_depth", self.queue.depth, step=step)
        total = self._hits + self._misses
        if total:
            tm.counter("serve/bucket_hit_rate", self._hits / total,
                       step=step)
        if self._lane_total:
            tm.counter("serve/pad_waste_frac",
                       self._lane_padded / self._lane_total, step=step)
        headroom = _hbm_headroom_frac()
        if headroom is not None:
            tm.counter("serve/hbm_headroom_frac", headroom, step=step)
        if key is not None:
            ring = self._bucket_exec_ms.get(key.label)
            if ring:
                # per-bucket series ride the executable's ledger label
                # (serve/<family>/<HxW>/bs<N>/p50_ms ...) so the report
                # can table them without a second naming scheme
                prefix = key.label
                tm.counter(f"{prefix}/p50_ms",
                           _percentile(list(ring), 0.50), step=step)
                tm.counter(f"{prefix}/p99_ms",
                           _percentile(list(ring), 0.99), step=step)
                tm.counter(f"{prefix}/count", len(ring), step=step)
        if self.budget.enabled:
            if not self._slo_config_emitted:
                self._slo_config_emitted = True
                tm.meta("serve/slo/config", family=self.family,
                        p99_ms=self.budget.p99_ms,
                        availability=self.budget.availability,
                        window=self.budget.window.maxlen)
            for name, value in self.budget.counters().items():
                tm.counter(name, value, step=step)

    # -------------------------------------------------------- streams

    def stream(self, stream_id, seed=None):
        """Get (or create) the :class:`StreamSession` for a stream id."""
        session = self._sessions.get(stream_id)
        if session is None:
            session = self._sessions[stream_id] = StreamSession(
                self, stream_id, seed=seed)
        return session

    def close_stream(self, stream_id):
        session = self._sessions.pop(stream_id, None)
        if session is not None:
            self.tracer.lifecycle("close", stream_id, frame=session.t)

    # ---------------------------------------------------------- stats

    def reset_stats(self):
        """Zero the sliding-window accounting at a measurement boundary
        (bench legs, loadgen load points): latency + per-executable
        exec-ms rings, hit/pad counters, and the SLO error-budget
        window. The ``_batches`` step axis is deliberately NOT reset —
        counter series must stay monotone in ``step`` across
        boundaries. Pool contents and in-flight traces are untouched
        (warm executables are the fixture, not the measurement)."""
        self._latencies.clear()
        for ring in self._bucket_exec_ms.values():
            ring.clear()
        self._hits = 0
        self._misses = 0
        self._lane_total = 0
        self._lane_padded = 0
        self.budget.reset()

    def stats(self):
        lat = list(self._latencies)
        return {
            "family": self.family,
            "batches": self._batches,
            "requests": len(lat),
            "p50_ms": _percentile(lat, 0.50) if lat else None,
            "p99_ms": _percentile(lat, 0.99) if lat else None,
            "bucket_hit_rate": (self._hits / (self._hits + self._misses)
                                if (self._hits + self._misses) else None),
            "pad_waste_frac": (self._lane_padded / self._lane_total
                               if self._lane_total else None),
            "queue_depth": self.queue.depth,
            "pool_size": len(self.pool),
            "pool_evictions": self.pool.evictions,
            "verified_restore": self._verified_restore,
            "hbm_headroom_frac": _hbm_headroom_frac(),
            "traces_started": self.tracer.started,
            "traces_emitted": self.tracer.emitted,
            "slo_burn_rate": (self.budget.burn_rate()
                              if self.budget.enabled else None),
            "slo_budget_remaining_frac": (
                self.budget.budget_remaining_frac()
                if self.budget.enabled else None),
            "slo_breaches": self.budget.breaches,
        }


def _family_of(cfg):
    """'imaginaire_tpu.trainers.spade' -> 'spade'."""
    return str(cfg_get(cfg_get(cfg, "trainer", {}) or {}, "type",
                       "unknown")).rsplit(".", 1)[-1]


def engine_from_config(cfg, trainer=None, logdir=None):
    """Build (without initializing) a :class:`ServingEngine`."""
    return ServingEngine(cfg, trainer=trainer, logdir=logdir)

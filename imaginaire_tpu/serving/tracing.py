"""Request-scoped serving traces (ISSUE 20).

The engine's aggregate SLO counters (serve/p50_ms, queue_depth rings)
answer "is serving healthy" but not "why was THIS request slow". Every
request therefore gets a trace id at admission and accumulates typed
spans across the serving pipeline:

    admit -> queue_wait -> bucket/pad -> h2d_transfer -> execute
          -> d2h/slice -> respond

Spans are contiguous by construction — each starts where the previous
ended — so a complete trace's span durations sum to its end-to-end
latency (the dryrun leg asserts within 10%). A trace also carries the
attribution the aggregate counters cannot: which pooled executable ran
it, how many pad lanes rode along, whether the executable was a warm
hit, and — the expensive case — whether a slow request paid an
ExecutablePool evict-then-recompile (``evict_recompile``).

Emission goes through the existing telemetry jsonl as ``kind="trace"``
records named ``trace/request`` (per-request) and ``trace/stream``
(StreamSession open/frame-N/reset/close lifecycle). Sampling is
deterministic per request id (``cfg.serving.trace_sample_rate``);
requests that breach the SLO (serving/slo.py) are ALWAYS emitted — the
traces you need most are the ones sampling would have dropped.
"""

from __future__ import annotations

import time

# The canonical span sequence of a queued request. ``forward`` (the
# one-shot inference.py seam) and stream frames use the subset that
# applies to them; the queue path emits every span exactly once.
REQUEST_SPANS = ("admit", "queue_wait", "bucket/pad", "h2d_transfer",
                 "execute", "d2h/slice", "respond")

# Knuth multiplicative hash: the sampling decision is a pure function
# of the request id, so a replayed request trace samples identically
# and tests need no RNG patching.
_HASH_MULT = 2654435761
_HASH_MOD = 1 << 32


def sampled(request_id, rate):
    """Deterministic sampling verdict for a request id at ``rate``
    (0.0 never, 1.0 always)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return ((int(request_id) * _HASH_MULT) % _HASH_MOD) / _HASH_MOD < rate


class RequestTrace:
    """One request's span accumulator.

    ``mark(name)`` closes the open span at ``now`` and opens the next —
    spans are contiguous and monotone by construction. ``annotate``
    attaches attribution fields (executable label, pad lanes, eviction
    verdicts). ``finish`` closes the final span and freezes ``e2e_ms``.
    """

    __slots__ = ("trace_id", "request_id", "kind", "stream_id", "frame",
                 "sampled", "t0", "spans", "fields", "_cursor", "_open",
                 "e2e_ms", "slo_breach")

    def __init__(self, trace_id, request_id, kind="request",
                 stream_id=None, frame=None, is_sampled=True, t0=None):
        self.trace_id = trace_id
        self.request_id = request_id
        self.kind = kind
        self.stream_id = stream_id
        self.frame = frame
        self.sampled = bool(is_sampled)
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self.spans = []
        self.fields = {}
        self._cursor = self.t0
        self._open = None
        self.e2e_ms = None
        self.slo_breach = False

    # ------------------------------------------------------------ spans

    def begin(self, name, t=None):
        """Open span ``name``; closes any currently open span first."""
        t = time.perf_counter() if t is None else float(t)
        if self._open is not None:
            self._close(t)
        self._open = name
        self._cursor = max(t, self._cursor)
        return self

    def _close(self, t):
        dur_ms = max(t - self._cursor, 0.0) * 1e3
        self.spans.append({"name": self._open,
                           "dur_ms": round(dur_ms, 4)})
        self._open = None
        self._cursor = t

    def mark(self, name, t=None):
        """Close the open span at ``t`` and immediately open ``name`` —
        the contiguous-span fast path the engine uses."""
        return self.begin(name, t=t)

    def annotate(self, **fields):
        self.fields.update(fields)
        return self

    def finish(self, t=None):
        """Close the final span and freeze the end-to-end latency."""
        t = time.perf_counter() if t is None else float(t)
        if self._open is not None:
            self._close(t)
        self.e2e_ms = round((t - self.t0) * 1e3, 4)
        return self

    # ----------------------------------------------------------- verdict

    def dominant_span(self):
        """(name, dur_ms) of the longest span — what an SLO breach meta
        names as the culprit."""
        if not self.spans:
            return None, None
        worst = max(self.spans, key=lambda s: s["dur_ms"])
        return worst["name"], worst["dur_ms"]

    def span_names(self):
        return [s["name"] for s in self.spans]

    def record(self):
        """The jsonl payload (everything but kind/name/t, which the
        telemetry plane stamps)."""
        rec = {
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "trace_kind": self.kind,
            "sampled": self.sampled,
            "slo_breach": self.slo_breach,
            "e2e_ms": self.e2e_ms,
            "spans": list(self.spans),
        }
        if self.stream_id is not None:
            rec["stream_id"] = self.stream_id
        if self.frame is not None:
            rec["frame"] = self.frame
        rec.update(self.fields)
        return rec


class Tracer:
    """The engine's trace factory + emitter.

    One per ServingEngine. ``admit`` mints the trace id (at admission —
    the request owns its id for its whole lifetime) and takes the
    deterministic sampling decision; ``emit`` writes the finished trace
    to the telemetry plane when it was sampled OR breached the SLO
    (breach traces are always kept). ``lifecycle`` emits the
    ``trace/stream`` open/reset/close records.
    """

    def __init__(self, family, sample_rate=1.0):
        self.family = str(family)
        self.sample_rate = float(sample_rate)
        self.started = 0
        self.emitted = 0
        self.dropped = 0

    def admit(self, request_id, stream_id=None, frame=None, t0=None):
        """Mint the trace for a freshly admitted request. ``t0``
        (defaults to now) anchors the admit span at the request's
        ``t_submit`` so span durations sum to the same end-to-end
        latency ``_account`` measures — including scheduling delay
        under open-loop load (no coordinated omission)."""
        self.started += 1
        if stream_id is not None:
            trace_id = f"{self.family}/{stream_id}/frame-{frame}"
        else:
            trace_id = f"{self.family}/r{int(request_id)}"
        trace = RequestTrace(
            trace_id, int(request_id),
            kind="stream" if stream_id is not None else "request",
            stream_id=stream_id, frame=frame,
            is_sampled=sampled(request_id, self.sample_rate), t0=t0)
        trace.begin("admit", t=trace.t0)
        return trace

    def emit(self, trace):
        """Write the finished trace (sampled or breaching); returns
        True when it actually landed in the plane."""
        if not (trace.sampled or trace.slo_breach):
            self.dropped += 1
            return False
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if not tm.enabled:
            return False
        tm.trace("trace/request", family=self.family, **trace.record())
        self.emitted += 1
        return True

    def lifecycle(self, event, stream_id, frame=None, **fields):
        """StreamSession lifecycle record: open / reset / close (frame
        traces go through admit/emit like any request)."""
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if not tm.enabled:
            return
        rec = {"family": self.family, "event": str(event),
               "stream_id": str(stream_id)}
        if frame is not None:
            rec["frame"] = int(frame)
        rec.update(fields)
        tm.trace("trace/stream", **rec)

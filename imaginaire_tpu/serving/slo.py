"""Serving SLO error budgets (ISSUE 20).

``cfg.serving.slo`` declares the contract the serving path is held to:

    slo:
      p99_ms: 250.0        # latency objective per request (None = off)
      availability: 0.999  # fraction of requests allowed to meet it
      window: 256          # rolling window (requests) for burn rate

``ErrorBudget`` keeps a rolling window of good/bad verdicts. A request
is *bad* when its end-to-end latency exceeds ``p99_ms`` or it was shed
at admission (queue overflow). The availability target implies an
allowed bad fraction (``1 - availability``); the burn rate is how fast
we spend it:

    burn_rate = bad_frac_in_window / allowed_bad_frac

burn_rate 1.0 means we are consuming budget exactly as fast as the SLO
permits; >1.0 means the budget will be exhausted before the window
turns over. ``budget_remaining_frac = max(0, 1 - burn_rate)`` is the
headline gauge check_run_health gates on.

Every breach immediately emits a ``serve/slo/breach`` meta naming the
dominant span of the breaching trace — the report and the gate can say
*which stage* ate the budget, not just that it was eaten.
"""

from __future__ import annotations

from collections import deque

from imaginaire_tpu.config import cfg_get


def slo_settings(cfg):
    """Parse ``cfg.serving.slo`` (missing / p99_ms=None → disabled)."""
    scfg = cfg_get(cfg or {}, "serving", None) or {}
    slo = cfg_get(scfg, "slo", None) or {}
    p99_ms = cfg_get(slo, "p99_ms", None)
    return {
        "p99_ms": None if p99_ms is None else float(p99_ms),
        "availability": float(cfg_get(slo, "availability", 0.999)),
        "window": max(int(cfg_get(slo, "window", 256)), 1),
    }


class ErrorBudget:
    """Rolling-window error budget for one serving engine.

    ``observe(latency_ms, trace=)`` files a verdict and returns whether
    the request breached; ``observe_rejected`` files a shed request
    (always bad). ``counters()`` yields the serve/slo/* gauge values
    the engine flushes alongside its latency percentiles.
    """

    def __init__(self, p99_ms=None, availability=0.999, window=256):
        self.p99_ms = None if p99_ms is None else float(p99_ms)
        self.availability = float(availability)
        # allowed bad fraction; floor avoids div-by-zero for
        # availability=1.0 (every breach is then an immediate burn).
        self.allowed_bad_frac = max(1.0 - self.availability, 1e-9)
        self.window = deque(maxlen=max(int(window), 1))
        self.breaches = 0
        self.rejected = 0
        self.observed = 0

    @classmethod
    def from_settings(cls, settings):
        return cls(p99_ms=settings["p99_ms"],
                   availability=settings["availability"],
                   window=settings["window"])

    @property
    def enabled(self):
        return self.p99_ms is not None

    # --------------------------------------------------------- verdicts

    def observe(self, latency_ms, trace=None):
        """File one served request; returns True when it breached the
        latency objective. Marks the trace (breach traces are always
        emitted regardless of sampling) and emits the breach meta."""
        self.observed += 1
        breached = self.enabled and latency_ms > self.p99_ms
        self.window.append(1 if breached else 0)
        if breached:
            self.breaches += 1
            if trace is not None:
                trace.slo_breach = True
            self._emit_breach(latency_ms, trace)
        return breached

    def observe_rejected(self, trace=None):
        """File a request shed at admission (queue overflow): counts
        against the budget whenever the SLO is enabled — a 503 is an
        availability failure no matter how fast it was."""
        self.observed += 1
        self.rejected += 1
        self.window.append(1 if self.enabled else 0)
        if self.enabled:
            self.breaches += 1
            if trace is not None:
                trace.slo_breach = True
            self._emit_breach(None, trace, rejected=True)
            return True
        return False

    def _emit_breach(self, latency_ms, trace, rejected=False):
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if not tm.enabled:
            return
        fields = {"target_ms": self.p99_ms, "rejected": bool(rejected)}
        if latency_ms is not None:
            fields["e2e_ms"] = round(float(latency_ms), 4)
        if trace is not None:
            fields["trace_id"] = trace.trace_id
            name, dur = trace.dominant_span()
            if name is not None:
                fields["dominant_span"] = name
                fields["dominant_span_ms"] = dur
            executable = trace.fields.get("executable")
            if executable:
                fields["executable"] = executable
        tm.meta("serve/slo/breach", **fields)

    # ----------------------------------------------------------- gauges

    def bad_frac(self):
        if not self.window:
            return 0.0
        return sum(self.window) / len(self.window)

    def burn_rate(self):
        return self.bad_frac() / self.allowed_bad_frac

    def budget_remaining_frac(self):
        return max(0.0, 1.0 - self.burn_rate())

    def counters(self):
        """serve/slo/* gauge values for the engine's flush block."""
        return {
            "serve/slo/burn_rate": round(self.burn_rate(), 6),
            "serve/slo/budget_remaining_frac":
                round(self.budget_remaining_frac(), 6),
            "serve/slo/breaches": self.breaches,
            "serve/slo/rejected": self.rejected,
        }

    def reset(self):
        """Clear the rolling window + counters (load-point boundary in
        the loadgen sweep; see ServingEngine.reset_stats)."""
        self.window.clear()
        self.breaches = 0
        self.rejected = 0
        self.observed = 0

"""Closed- and open-loop serving load generation (ISSUE 20).

The PR-19 SERVEBENCH numbers were measured one request at a time —
p99 under ZERO concurrent load, which is not a tail latency at all.
This module drives a :class:`ServingEngine` the way traffic actually
arrives and measures what the aggregate counters then mean:

- **open loop** (``run_open_loop``): Poisson arrivals at a configured
  offered rate. The generator never waits for responses, so queueing
  delay under overload is *measured, not hidden*: each request's
  ``t_submit`` is its SCHEDULED arrival time, which means a request
  submitted late because the engine was busy still accounts its full
  sojourn — the standard coordinated-omission fix.
- **closed loop** (``run_closed_loop``): a fixed concurrency of
  virtual users, each submitting its next request only after the
  previous answered. Measures best-case capacity; open loop measures
  overload behavior. Both are needed for an honest curve.
- **sweep** (``run_load_sweep``): open-loop points at increasing
  offered rates, ``engine.reset_stats()`` between points so point N's
  p99 cannot inherit point N-1's tail. This is what SERVEBENCH.json's
  offered-load-vs-latency curve comes from.
- **streams** (``run_stream_burst``): interleaved StreamSession frame
  loops, exercising the per-stream lifecycle traces under load.

Everything is deterministic under a fixed seed (numpy Generator;
arrivals, bucket mix, and request seeds all derive from it).
"""

from __future__ import annotations

import time

import numpy as np

from imaginaire_tpu.serving.engine import (ServeRequest, ServingError,
                                           _percentile)


def poisson_arrivals(rate_rps, duration_s, rng):
    """Arrival offsets (seconds from start) of a Poisson process at
    ``rate_rps`` over ``duration_s`` — exponential inter-arrivals."""
    out = []
    t = 0.0
    scale = 1.0 / max(float(rate_rps), 1e-9)
    while True:
        t += float(rng.exponential(scale))
        if t >= duration_s:
            return out
        out.append(t)


def _mixed_request(lanes, hws, rng):
    """One request over the configured resolution mix (uniform over
    buckets; each request gets its own noise seed)."""
    hw = hws[int(rng.integers(len(hws)))]
    return ServeRequest(data={k: np.asarray(v) for k, v in
                              lanes[hw].items()},
                        seed=int(rng.integers(1 << 31)))


def run_open_loop(engine, rate_rps, duration_s, lanes, seed=0):
    """Offer Poisson traffic at ``rate_rps`` for ``duration_s``;
    returns the point dict for the load curve.

    ``lanes`` maps ``(H, W) -> single-lane data dict`` (the resolution
    mix). The loop submits each request when the wall clock reaches its
    scheduled arrival — pumping the engine while waiting — and stamps
    ``t_submit`` with the SCHEDULED time, so a generator that falls
    behind charges the lateness to the engine (no coordinated
    omission). Queue overflow rejections are counted as shed load (and
    charged to the error budget by ``submit``), not retried.
    """
    rng = np.random.default_rng(seed)
    hws = sorted(lanes)
    arrivals = poisson_arrivals(rate_rps, duration_s, rng)
    depth_samples = []
    submitted = rejected = served = 0
    t0 = time.perf_counter()
    for offset in arrivals:
        target = t0 + offset
        while True:
            now = time.perf_counter()
            if now >= target:
                break
            out = engine.pump(now=now)
            if out:
                served += len(out)
            else:
                time.sleep(min(target - now, 5e-4))
        req = _mixed_request(lanes, hws, rng)
        req.t_submit = target
        try:
            engine.submit(req)
            submitted += 1
        except ServingError:
            rejected += 1
        depth_samples.append(engine.queue.depth)
        served += len(engine.pump())
    served += len(engine.flush())
    wall_s = time.perf_counter() - t0
    return _point(engine, "open", rate_rps, wall_s, submitted, rejected,
                  served, depth_samples)


def run_closed_loop(engine, concurrency, total_requests, lanes, seed=0):
    """``concurrency`` virtual users, each submitting its next request
    only once the previous answered; ``total_requests`` total. Returns
    the same point dict shape as ``run_open_loop`` with
    ``offered_rps=None`` (a closed loop offers whatever the engine
    sustains)."""
    rng = np.random.default_rng(seed)
    hws = sorted(lanes)
    depth_samples = []
    submitted = served = 0
    t0 = time.perf_counter()
    while submitted < total_requests:
        wave = min(int(concurrency), total_requests - submitted)
        for _ in range(wave):
            engine.submit(_mixed_request(lanes, hws, rng))
        submitted += wave
        depth_samples.append(engine.queue.depth)
        served += len(engine.flush())
    wall_s = time.perf_counter() - t0
    return _point(engine, "closed", None, wall_s, submitted, 0, served,
                  depth_samples)


def run_stream_burst(engine, stream_ids, frames, frame_data, seed=0):
    """Interleave ``frames`` frames across ``stream_ids`` streaming
    sessions (frame t of every stream before frame t+1 of any — the
    adversarial interleaving for per-stream state isolation), then
    close every stream. Returns {stream_id: [frame arrays]}."""
    outs = {sid: [] for sid in stream_ids}
    for sid in stream_ids:
        engine.stream(sid, seed=seed)
    for _ in range(int(frames)):
        for sid in stream_ids:
            outs[sid].append(engine.stream(sid).step(dict(frame_data)))
    for sid in stream_ids:
        engine.close_stream(sid)
    return outs


def _point(engine, mode, offered_rps, wall_s, submitted, rejected,
           served, depth_samples):
    # served is counted from the pump/flush results of THIS point; the
    # percentiles read the engine's latency ring, which covers only
    # this point when the caller reset_stats() at the boundary (the
    # sweep does) and the whole ring window otherwise.
    lat = list(engine._latencies)
    point = {
        "mode": mode,
        "offered_rps": (round(float(offered_rps), 3)
                        if offered_rps is not None else None),
        "achieved_rps": round(served / wall_s, 3) if wall_s > 0
        else None,
        "requests": submitted,
        "served": served,
        "rejected": rejected,
        "wall_s": round(wall_s, 3),
        "p50_ms": _round(_percentile(lat, 0.50)),
        "p99_ms": _round(_percentile(lat, 0.99)),
        "queue_depth_max": max(depth_samples) if depth_samples else 0,
        "queue_depth_mean": (round(sum(depth_samples)
                                   / len(depth_samples), 2)
                             if depth_samples else 0.0),
    }
    if engine.budget.enabled:
        point["slo_burn_rate"] = round(engine.budget.burn_rate(), 4)
        point["slo_breaches"] = engine.budget.breaches
    return point


def _round(value, digits=2):
    return None if value is None else round(float(value), digits)


def run_load_sweep(engine, rates, duration_s, lanes, seed=0):
    """One open-loop point per offered rate, lowest first,
    ``reset_stats()`` between points (the measurement-boundary
    contract: each point's percentiles cover only its own window).
    Returns the list of point dicts — the SERVEBENCH curve."""
    points = []
    for i, rate in enumerate(rates):
        engine.reset_stats()
        points.append(run_open_loop(engine, rate, duration_s, lanes,
                                    seed=seed + i))
    return points

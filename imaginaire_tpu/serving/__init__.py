"""Production serving (ISSUE 19): AOT-warmed executable pool, bucketed
micro-batching, and streaming vid2vid sessions. See ``engine.py``."""

from imaginaire_tpu.serving.engine import (  # noqa: F401
    BucketCfg,
    ExecKey,
    ExecutablePool,
    RequestQueue,
    ServeRequest,
    ServingEngine,
    ServingError,
    StreamSession,
    engine_from_config,
    serving_settings,
)

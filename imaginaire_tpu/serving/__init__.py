"""Production serving (ISSUE 19): AOT-warmed executable pool, bucketed
micro-batching, and streaming vid2vid sessions (``engine.py``) — plus
the request-scoped observability plane (ISSUE 20): per-request traces
(``tracing.py``), SLO error budgets (``slo.py``), and the closed/open-
loop load harness (``loadgen.py``)."""

from imaginaire_tpu.serving.engine import (  # noqa: F401
    BucketCfg,
    ExecKey,
    ExecutablePool,
    RequestQueue,
    ServeRequest,
    ServingEngine,
    ServingError,
    StreamSession,
    engine_from_config,
    serving_settings,
)
from imaginaire_tpu.serving.loadgen import (  # noqa: F401
    poisson_arrivals,
    run_closed_loop,
    run_load_sweep,
    run_open_loop,
    run_stream_burst,
)
from imaginaire_tpu.serving.slo import (  # noqa: F401
    ErrorBudget,
    slo_settings,
)
from imaginaire_tpu.serving.tracing import (  # noqa: F401
    REQUEST_SPANS,
    RequestTrace,
    Tracer,
)

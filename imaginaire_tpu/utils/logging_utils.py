"""Log-directory conventions (ref: imaginaire/utils/logging.py:21-51):
``logs/<date_uid>_<config_name>`` with a ``tensorboard/`` subdir,
master-only creation."""

from __future__ import annotations

import os
from datetime import datetime

from imaginaire_tpu.parallel.mesh import is_master
from imaginaire_tpu.utils.meters import set_summary_writer


def get_date_uid():
    return datetime.now().strftime("%Y_%m%d_%H%M_%S")


def init_logging(config_path, logdir=None, root="logs"):
    """(ref: logging.py:21-38)."""
    config_file = os.path.basename(config_path)
    date_uid = get_date_uid()
    if logdir is None:
        logdir = os.path.join(root, f"{date_uid}_{os.path.splitext(config_file)[0]}")
    return date_uid, logdir


def make_logging_dir(logdir):
    """(ref: logging.py:41-51)."""
    if is_master():
        os.makedirs(logdir, exist_ok=True)
        tb_dir = os.path.join(logdir, "tensorboard")
        os.makedirs(tb_dir, exist_ok=True)
        set_summary_writer(tb_dir)
    return logdir

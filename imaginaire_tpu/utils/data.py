"""Config-driven data-shape helpers (ref: imaginaire/utils/data.py:436-520).

These read the ``data:`` config section the same way the reference does,
so reference YAML configs port unchanged: ``input_types`` is a list of
single-key mappings ``{name: {num_channels: N, ...}}``; ``input_image`` /
``input_labels`` name which types feed the image / label tensors.
"""

from __future__ import annotations

from imaginaire_tpu.config import as_attrdict, cfg_get


def _iter_input_types(data_cfg):
    for data_type in as_attrdict(data_cfg).input_types:
        for name, props in data_type.items():
            yield name, props


def get_paired_input_image_channel_number(data_cfg):
    """Sum of channels over types listed in input_image
    (ref: utils/data.py:436-451)."""
    data_cfg = as_attrdict(data_cfg)
    num_channels = 0
    for name, props in _iter_input_types(data_cfg):
        if name in data_cfg.input_image:
            num_channels += props.num_channels
    return num_channels


def get_paired_input_label_channel_number(data_cfg, video=False):
    """Sum of channels over types listed in input_labels, +1 per type with
    use_dont_care; video mode multiplies by initial_sequence_length and
    adds prev-frame image channels (ref: utils/data.py:454-483)."""
    data_cfg = as_attrdict(data_cfg)
    num_labels = 0
    if not hasattr(data_cfg, "input_labels") or data_cfg.input_labels is None:
        return num_labels
    for name, props in _iter_input_types(data_cfg):
        if name in data_cfg.input_labels:
            num_labels += props.num_channels
            if cfg_get(props, "use_dont_care", False):
                num_labels += 1
    if video:
        num_time_steps = cfg_get(data_cfg.train, "initial_sequence_length", None)
        num_labels *= num_time_steps
        num_labels += get_paired_input_image_channel_number(data_cfg) * (num_time_steps - 1)
    return num_labels


def get_class_number(data_cfg):
    """(ref: utils/data.py:486-495)."""
    return data_cfg.num_classes


def get_crop_h_w(augmentation):
    """Find the '*crop_h_w' augmentation key, parse 'H,W'
    (ref: utils/data.py:498-520)."""
    augmentation = as_attrdict(augmentation)
    for k in augmentation.keys():
        if "crop_h_w" in k:
            crop_h, crop_w = str(augmentation[k]).split(",")
            return int(crop_h), int(crop_w)
    raise AttributeError("no *crop_h_w augmentation in config")


def get_crop_or_resize_h_w(augmentation):
    """Output size of the augmentation pipeline: the '*crop_h_w' key when
    one exists, else the fixed 'resize_h_w' (crop-free configs like the
    wc-mannequin hed stages). Raises an actionable ValueError when
    neither key can size the model."""
    augmentation = as_attrdict(augmentation)
    try:
        return get_crop_h_w(augmentation)
    except AttributeError:
        resize = cfg_get(augmentation, "resize_h_w", None)
        if resize is None:
            raise ValueError(
                "augmentations must carry a '*crop_h_w' or 'resize_h_w' "
                f"entry to size the model; got {sorted(augmentation)}"
            ) from None
        h, w = str(resize).split(",")
        return int(h), int(w)

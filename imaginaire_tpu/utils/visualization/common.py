"""Tensor -> image conversions (ref: imaginaire/utils/visualization/common.py).

NHWC numpy in, uint8 numpy / PIL out. ``tensor2im`` maps [-1,1] to uint8;
``tensor2label`` colorizes one-hot label maps with a stable palette;
``tensor2flow`` renders optical flow with the HSV wheel
(ref: visualization/common.py:156+).
"""

from __future__ import annotations

import colorsys

import numpy as np
from PIL import Image


def tensor2im(image, minus1to1_normalized=True):
    """(H,W,C) float in [-1,1] (or [0,1]) -> uint8 RGB."""
    img = np.asarray(image, dtype=np.float32)
    if minus1to1_normalized:
        img = (img + 1.0) / 2.0
    img = np.clip(img, 0.0, 1.0) * 255.0
    if img.shape[-1] == 1:
        img = np.repeat(img, 3, axis=-1)
    return img[..., :3].astype(np.uint8)


def _label_palette(n):
    # Stable golden-angle hue walk — deterministic, well-separated colors.
    colors = [(0, 0, 0)]
    for i in range(1, n):
        h = (i * 0.618033988749895) % 1.0
        r, g, b = colorsys.hsv_to_rgb(h, 0.75, 0.95)
        colors.append((int(r * 255), int(g * 255), int(b * 255)))
    return np.asarray(colors, dtype=np.uint8)


def tensor2label(label_map, num_labels=None):
    """One-hot (H,W,C) or index (H,W) label map -> colorized uint8 RGB
    (ref: visualization/common.py tensor2label)."""
    lab = np.asarray(label_map)
    if lab.ndim == 3 and lab.shape[-1] > 1:
        idx = lab.argmax(axis=-1)
        n = num_labels or lab.shape[-1]
    else:
        idx = lab.squeeze(-1).astype(np.int32) if lab.ndim == 3 else lab.astype(np.int32)
        n = num_labels or int(idx.max()) + 1
    return _label_palette(max(n, 1))[idx]


def tensor2flow(flow):
    """(H,W,2) flow -> HSV-wheel uint8 RGB (ref: visualization/common.py:156)."""
    flow = np.asarray(flow, dtype=np.float32)
    dx, dy = flow[..., 0], flow[..., 1]
    mag = np.sqrt(dx ** 2 + dy ** 2)
    ang = np.arctan2(dy, dx)
    h = (ang / (2 * np.pi) + 0.5) % 1.0
    s = np.ones_like(h)
    v = np.clip(mag / (mag.max() + 1e-6), 0, 1)
    hsv = np.stack([h, s, v], axis=-1)
    # vectorized hsv->rgb
    i = (hsv[..., 0] * 6).astype(np.int32) % 6
    f = hsv[..., 0] * 6 - np.floor(hsv[..., 0] * 6)
    p = hsv[..., 2] * (1 - hsv[..., 1])
    q = hsv[..., 2] * (1 - f * hsv[..., 1])
    t = hsv[..., 2] * (1 - (1 - f) * hsv[..., 1])
    vch = hsv[..., 2]
    rgb = np.select(
        [(i == k)[..., None] for k in range(6)],
        [np.stack([vch, t, p], -1), np.stack([q, vch, p], -1),
         np.stack([p, vch, t], -1), np.stack([p, q, vch], -1),
         np.stack([t, p, vch], -1), np.stack([vch, p, q], -1)])
    return (rgb * 255).astype(np.uint8)


def save_image_grid(images, path, cols=None):
    """Save a list of HWC uint8 images as one horizontal strip / grid."""
    images = [np.asarray(im) for im in images]
    h = max(im.shape[0] for im in images)
    w = max(im.shape[1] for im in images)
    cols = cols or len(images)
    rows = (len(images) + cols - 1) // cols
    canvas = np.zeros((rows * h, cols * w, 3), dtype=np.uint8)
    for i, im in enumerate(images):
        r, c = divmod(i, cols)
        canvas[r * h:r * h + im.shape[0], c * w:c * w + im.shape[1]] = im[..., :3]
    Image.fromarray(canvas).save(path, quality=95)
    return path


def save_tensor_strip(tensors, path):
    """Horizontally-concatenated (input, label, fake, ...) batch snapshot
    (ref: trainers/base.py:445-465): one row per batch element."""
    rows = []
    for batch in tensors:
        batch = np.asarray(batch)
        rows.append([tensor2im(batch[i]) for i in range(batch.shape[0])])
    images = [im for col in zip(*rows) for im in col]
    return save_image_grid(images, path, cols=len(tensors))

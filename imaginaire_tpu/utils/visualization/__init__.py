"""Visualization helpers (ref: imaginaire/utils/visualization/)."""

from imaginaire_tpu.utils.visualization.common import (
    save_image_grid,
    save_tensor_strip,
    tensor2flow,
    tensor2im,
    tensor2label,
)

__all__ = ["tensor2im", "tensor2label", "tensor2flow", "save_image_grid",
           "save_tensor_strip"]

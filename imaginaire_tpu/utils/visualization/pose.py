"""OpenPose skeleton label-map rendering
(ref: imaginaire/utils/visualization/pose.py:14-342).

Converts OpenPose JSON keypoints (body 25 + hands + face) into colored
or one-hot skeleton label maps, used as a ``vis::`` post-aug op by the
pose-driven vid2vid projects.
"""

from __future__ import annotations

import random

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.utils.visualization.face import draw_edge, interp_points


def define_edge_lists(basic_points_only=False):
    """Keypoint connectivity + stroke colors (ref: pose.py:281-339)."""
    pose_edge_list = [
        [17, 15], [15, 0], [0, 16], [16, 18],   # head
        [0, 1], [1, 8],                         # torso
        [1, 2], [2, 3], [3, 4],                 # right arm
        [1, 5], [5, 6], [6, 7],                 # left arm
        [8, 9], [9, 10], [10, 11],              # right leg
        [8, 12], [12, 13], [13, 14],            # left leg
    ]
    pose_color_list = [
        [153, 0, 153], [153, 0, 102], [102, 0, 153], [51, 0, 153],
        [153, 0, 51], [153, 0, 0],
        [153, 51, 0], [153, 102, 0], [153, 153, 0],
        [102, 153, 0], [51, 153, 0], [0, 153, 0],
        [0, 153, 51], [0, 153, 102], [0, 153, 153],
        [0, 102, 153], [0, 51, 153], [0, 0, 153],
    ]
    if not basic_points_only:
        pose_edge_list += [[11, 24], [11, 22], [22, 23],
                           [14, 21], [14, 19], [19, 20]]  # feet
        pose_color_list += [[0, 153, 153]] * 3 + [[0, 0, 153]] * 3
    hand_edge_list = [[0, 1, 2, 3, 4], [0, 5, 6, 7, 8], [0, 9, 10, 11, 12],
                      [0, 13, 14, 15, 16], [0, 17, 18, 19, 20]]
    hand_color_list = [[204, 0, 0], [163, 204, 0], [0, 204, 82],
                       [0, 82, 204], [163, 0, 204]]
    face_list = [
        [list(range(0, 17))],
        [list(range(17, 22))],
        [list(range(22, 27))],
        [[28, 31], list(range(31, 36)), [35, 28]],
        [[36, 37, 38, 39], [39, 40, 41, 36]],
        [[42, 43, 44, 45], [45, 46, 47, 42]],
        [list(range(48, 55)), [54, 55, 56, 57, 58, 59, 48]],
    ]
    return (pose_edge_list, pose_color_list, hand_edge_list, hand_color_list,
            face_list)


def extract_valid_keypoints(pts, edge_lists):
    """Zero out keypoints below the confidence threshold
    (ref: pose.py:144-174). pts: dict of 'pose'/'face'/'hand_l'/'hand_r'
    (N, 3) arrays."""
    thresholds = {"pose": 0.15, "face": 0.5, "hand_l": 0.3, "hand_r": 0.3}
    out = []
    for name in ("pose", "face", "hand_l", "hand_r"):
        p = np.asarray(pts.get(name, np.zeros((0, 3))), np.float32)
        if p.size:
            valid = p[:, 2] > thresholds[name]
            p = p[:, :2] * valid[:, None]
        else:
            p = np.zeros((0, 2), np.float32)
        out.append(p)
    return out


def draw_edges(canvas, keypoints, edges_list, bw, use_one_hot,
               random_drop_prob=0, edge_len=2, colors=None,
               draw_end_points=False):
    """(ref: pose.py:237-278)."""
    k = 0
    for edge_list in edges_list:
        for i, edge in enumerate(edge_list):
            for j in range(0, max(1, len(edge) - 1), edge_len - 1):
                if random.random() > random_drop_prob:
                    sub = list(edge)[j:j + edge_len]
                    x, y = keypoints[sub, 0], keypoints[sub, 1]
                    if 0 not in x:  # zeroed keypoints are invalid
                        cx, cy = interp_points(x, y)
                        if use_one_hot:
                            draw_edge(canvas[:, :, k], cx, cy, bw=bw,
                                      color=255,
                                      draw_end_points=draw_end_points)
                        else:
                            color = (colors[i] if colors is not None
                                     else (255, 255, 255))
                            draw_edge(canvas, cx, cy, bw=bw, color=color,
                                      draw_end_points=draw_end_points)
            k += 1
    return canvas


def connect_pose_keypoints(pts, edge_lists, size, basic_points_only=False,
                           remove_face_labels=False, random_drop_prob=0.0):
    """(ref: pose.py:177-234)."""
    pose_pts, face_pts, hand_pts_l, hand_pts_r = pts
    h, w, c = size
    canvas = np.zeros((h, w, c), np.uint8)
    use_one_hot = c > 3
    (pose_edge_list, pose_color_list, hand_edge_list, hand_color_list,
     face_list) = edge_lists

    span = int(pose_pts[:, 1].max() - pose_pts[:, 1].min()) \
        if pose_pts.size else h
    bw = max(1, span // 150)
    canvas = draw_edges(canvas, pose_pts, [pose_edge_list], bw, use_one_hot,
                        random_drop_prob, colors=pose_color_list,
                        draw_end_points=True)
    if not basic_points_only:
        bw = max(1, span // 450)
        for i, hand_pts in enumerate([hand_pts_l, hand_pts_r]):
            if hand_pts.size:
                if use_one_hot:
                    k = 24 + i
                    draw_edges(canvas[:, :, k], hand_pts, [hand_edge_list],
                               bw, False, random_drop_prob,
                               colors=[255] * len(hand_edge_list))
                else:
                    draw_edges(canvas, hand_pts, [hand_edge_list], bw, False,
                               random_drop_prob, colors=hand_color_list)
        if not remove_face_labels and face_pts.size:
            if use_one_hot:
                draw_edges(canvas[:, :, 26], face_pts, face_list, bw, False,
                           random_drop_prob)
            else:
                draw_edges(canvas, face_pts, face_list, bw, False,
                           random_drop_prob)
    return canvas


def openpose_to_npy(inputs, return_largest_only=False):
    """Decode OpenPose JSON dicts into per-person keypoint arrays
    (ref: pose.py:75-141). Returns the dict for the largest person when
    requested (multi-person frames pick the tallest skeleton). A list
    input (the data pipeline's frame list, ref convert:: op grammar)
    maps per frame."""
    if isinstance(inputs, list):
        if inputs and isinstance(inputs[0], dict) \
                and "pose_keypoints_2d" in inputs[0]:
            people = inputs  # bare people list: one frame
        else:  # frame list from the data pipeline
            return [openpose_to_npy(f, return_largest_only) for f in inputs]
    else:
        people = inputs.get("people", [])
    decoded = []
    for person in people:
        entry = {
            "pose": np.asarray(person.get("pose_keypoints_2d", []),
                               np.float32).reshape(-1, 3),
            "face": np.asarray(person.get("face_keypoints_2d", []),
                               np.float32).reshape(-1, 3),
            "hand_l": np.asarray(person.get("hand_left_keypoints_2d", []),
                                 np.float32).reshape(-1, 3),
            "hand_r": np.asarray(person.get("hand_right_keypoints_2d", []),
                                 np.float32).reshape(-1, 3),
        }
        decoded.append(entry)
    if not decoded:
        return None
    if return_largest_only:
        def height(e):
            valid = e["pose"][e["pose"][:, 2] > 0.1]
            return float(np.ptp(valid[:, 1])) if valid.size else 0.0

        return max(decoded, key=height)
    return decoded


def openpose_to_npy_largest_only(inputs):
    """(ref: pose.py:75-85)."""
    return openpose_to_npy(inputs, return_largest_only=True)


def draw_openpose_npy(resize_h, resize_w, crop_h, crop_w, original_h,
                      original_w, is_flipped, cfgdata, keypoints_npy):
    """Render decoded OpenPose keypoints to label maps per frame
    (ref: pose.py:14-72)."""
    pose_cfg = cfg_get(cfgdata, "for_pose_dataset", None)
    basic_points_only = cfg_get(pose_cfg, "basic_points_only", False) \
        if pose_cfg is not None else False
    remove_face_labels = cfg_get(pose_cfg, "remove_face_labels", False) \
        if pose_cfg is not None else False
    random_drop_prob = cfg_get(pose_cfg, "random_drop_prob", 0.0) \
        if pose_cfg is not None else 0.0
    use_one_hot = cfg_get(pose_cfg, "pose_one_hot", False) \
        if pose_cfg is not None else False

    edge_lists = define_edge_lists(basic_points_only)
    c = 27 if use_one_hot else 3
    outputs = []
    for frame in keypoints_npy:
        if frame is None:
            outputs.append(np.zeros((resize_h, resize_w, c), np.float32))
            continue
        # multi-person frames (openpose_to_npy without largest-only) are
        # lists of person dicts: render every person onto one canvas
        # (ref: pose.py draws per person and maxes the maps)
        people = frame if isinstance(frame, list) else [frame]
        label = np.zeros((resize_h, resize_w, c), np.float32)
        for person in people:
            pts = extract_valid_keypoints(person, edge_lists)
            # keypoints were already co-transformed (resize/crop/flip) by
            # the augmentor — they arrive in canvas coordinates; rescaling
            # again (as the reference does for raw keypoints) would
            # misalign them
            one = connect_pose_keypoints(
                pts, edge_lists, (resize_h, resize_w, c), basic_points_only,
                remove_face_labels, random_drop_prob)
            label = np.maximum(label, one.astype(np.float32) / 255.0)
        outputs.append(label)
    return outputs

"""Facial-landmark label-map rendering
(ref: imaginaire/utils/visualization/face.py:14-489).

Turns 68-point dlib landmarks into edge-sketch label maps (optionally
with per-part distance transforms and sinusoidal positional encodings),
plus keypoint normalization against a reference face. Host-side numpy —
this runs in the data pipeline as a ``vis::`` post-augmentation op.
"""

from __future__ import annotations

import numpy as np

from imaginaire_tpu.config import cfg_get

# 68-landmark facial part topology (ref: face.py:46-54); each part is a
# list of keypoint-index chains to connect.
FACE_PART_LIST = [
    [list(range(0, 17))],                                   # contour
    [list(range(17, 22))],                                  # right eyebrow
    [list(range(22, 27))],                                  # left eyebrow
    [[28, 31], list(range(31, 36)), [35, 28]],              # nose
    [[36, 37, 38, 39], [39, 40, 41, 36]],                   # right eye
    [[42, 43, 44, 45], [45, 46, 47, 42]],                   # left eye
    [list(range(48, 55)), [54, 55, 56, 57, 58, 59, 48],
     list(range(60, 65)), [64, 65, 66, 67, 60]],            # mouth + tongue
]


def _quad(x, a, b, c):
    return a * x ** 2 + b * x + c


def _linear(x, a, b):
    return a * x + b


def interp_points(x, y):
    """Fit a short curve through the keypoints and rasterize it
    (ref: face.py:445-481): quadratic fit along the dominant axis,
    linear for 2-point edges; returns integer coordinate arrays or
    (None, None) when the fit is degenerate."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    if np.abs(np.diff(x)).max(initial=0) < np.abs(np.diff(y)).max(initial=0):
        curve_y, curve_x = interp_points(y, x)
        if curve_y is None:
            return None, None
        return curve_x, curve_y
    try:
        from scipy.optimize import curve_fit

        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            if len(x) < 3:
                popt, _ = curve_fit(_linear, x, y)
                fit = _linear
            else:
                popt, _ = curve_fit(_quad, x, y)
                fit = _quad
                if abs(popt[0]) > 1:
                    return None, None
    except Exception:
        return None, None
    if x[0] > x[-1]:
        x = x[::-1]
        y = y[::-1]
    curve_x = np.linspace(x[0], x[-1], int(round(x[-1] - x[0])))
    curve_y = fit(curve_x, *popt)
    return curve_x.astype(int), curve_y.astype(int)


def set_color(im, yy, xx, color):
    """(ref: face.py:422-442): new strokes write, crossings average."""
    if not isinstance(color, (list, tuple)):
        color = [color] * 3
    if im.ndim == 3 and im.shape[2] == 3:
        untouched = (im[yy, xx] == 0).all()
        if untouched:
            im[yy, xx] = color
        else:
            im[yy, xx] = ((im[yy, xx].astype(float) + color) / 2).astype(
                np.uint8)
    else:
        im[yy, xx] = color[0]


def draw_edge(im, x, y, bw=1, color=(255, 255, 255), draw_end_points=False):
    """Rasterize a curve with a bw-wide stroke (ref: face.py:390-419)."""
    if x is None or np.size(x) == 0:
        return
    h, w = im.shape[:2]
    for i in range(-bw, bw):
        for j in range(-bw, bw):
            yy = np.clip(y + i, 0, h - 1)
            xx = np.clip(x + j, 0, w - 1)
            set_color(im, yy, xx, color)
    if draw_end_points:
        ends_y = np.array([y[0], y[-1]])
        ends_x = np.array([x[0], x[-1]])
        for i in range(-bw * 2, bw * 2):
            for j in range(-bw * 2, bw * 2):
                if i ** 2 + j ** 2 < 4 * bw ** 2:
                    yy = np.clip(ends_y + i, 0, h - 1)
                    xx = np.clip(ends_x + j, 0, w - 1)
                    set_color(im, yy, xx, color)


def connect_face_keypoints(resize_h, resize_w, crop_h, crop_w, original_h,
                           original_w, is_flipped, cfgdata, keypoints):
    """Draw (T, 68[+upper], 2) landmark sequences into per-frame edge
    label maps (ref: face.py:14-111)."""
    face_cfg = cfg_get(cfgdata, "for_face_dataset", None)
    add_upper_face = cfg_get(face_cfg, "add_upper_face", False) \
        if face_cfg is not None else False
    add_dist_map = cfg_get(face_cfg, "add_distance_transform", False) \
        if face_cfg is not None else False
    add_pos_encode = add_dist_map and cfg_get(
        face_cfg, "add_positional_encode", False) if face_cfg is not None \
        else False

    part_list = [list(p) for p in FACE_PART_LIST]
    keypoints = np.asarray(keypoints, np.float32)
    if add_upper_face:
        # mirror the jaw contour above the brow line (ref: face.py:57-63)
        part_list[0] = [list(range(0, 17)) + list(range(68, 83)) + [0]]
        pts = keypoints[:, :17].astype(np.int32)
        baseline_y = (pts[:, 0:1, 1] + pts[:, -1:, 1]) / 2
        upper = pts[:, 1:-1].copy()
        upper[:, :, 1] = baseline_y + (baseline_y - upper[:, :, 1]) * 2 // 3
        keypoints = np.concatenate([keypoints, upper[:, ::-1]], axis=1)

    edge_len = 3
    bw = max(1, resize_h // 256)
    outputs = []
    for t in range(keypoints.shape[0]):
        im_edges = np.zeros((resize_h, resize_w, 1), np.uint8)
        im_dists = np.zeros((resize_h, resize_w, 0), np.float32)
        im_pos = np.zeros((resize_h, resize_w, 0), np.float32)
        for part in part_list:
            for e, edge in enumerate(part):
                edge = list(edge)
                im_edge = np.zeros((resize_h, resize_w, 1), np.uint8)
                for i in range(0, max(1, len(edge) - 1), edge_len - 1):
                    sub = edge[i:i + edge_len]
                    cx, cy = interp_points(keypoints[t, sub, 0],
                                           keypoints[t, sub, 1])
                    draw_edge(im_edges, cx, cy, bw=bw)
                    if add_dist_map:
                        draw_edge(im_edge, cx, cy, bw=bw)
                if add_dist_map:
                    im_dist = _distance_transform(255 - im_edge[..., 0])
                    im_dist = np.clip(im_dist / 3, 0, 255)
                    im_dists = np.dstack([im_dists, im_dist])
                    if add_pos_encode and e == 0:
                        im_pos = np.zeros((resize_h, resize_w, 0), np.float32)
                        dist = (im_dist - 127.5) / 127.5
                        for level in range(10):
                            phase = np.pi * (2 ** level) * dist
                            im_pos = np.dstack([im_pos, np.sin(phase),
                                                np.cos(phase)])
        label = im_edges.astype(np.float32)
        if add_dist_map:
            label = np.dstack([label, im_dists])
        label = label / 255.0
        if add_pos_encode:
            label = np.dstack([label, im_pos])
        outputs.append(label)
    return outputs


def _distance_transform(binary):
    """L1 distance to the nearest zero pixel; cv2 when present, else a
    two-pass chamfer sweep (same metric, pure numpy)."""
    try:
        import cv2

        return cv2.distanceTransform(binary.astype(np.uint8), cv2.DIST_L1, 3)
    except ImportError:
        h, w = binary.shape
        inf = h + w
        d = np.where(binary == 0, 0, inf).astype(np.int32)
        for i in range(h):
            for j in range(w):
                if i > 0:
                    d[i, j] = min(d[i, j], d[i - 1, j] + 1)
                if j > 0:
                    d[i, j] = min(d[i, j], d[i, j - 1] + 1)
        for i in range(h - 1, -1, -1):
            for j in range(w - 1, -1, -1):
                if i < h - 1:
                    d[i, j] = min(d[i, j], d[i + 1, j] + 1)
                if j < w - 1:
                    d[i, j] = min(d[i, j], d[i, j + 1] + 1)
        return d.astype(np.float32)


def normalize_face_keypoints(keypoints, ref_keypoints, dist_scales=None,
                             momentum=0.9):
    """Scale each facial part of ``keypoints`` toward the reference
    face's part proportions (ref: face.py:197-268, simplified to the
    part-centroid scaling that drives few-shot face reenactment)."""
    keypoints = np.asarray(keypoints, np.float32).copy()
    ref_keypoints = np.asarray(ref_keypoints, np.float32)
    new_scales = []
    for part in FACE_PART_LIST:
        idx = sorted({i for chain in part for i in chain if i < 68})
        pts = keypoints[idx]
        ref = ref_keypoints[idx]
        center = pts.mean(axis=0, keepdims=True)
        ref_center = ref.mean(axis=0, keepdims=True)
        spread = np.linalg.norm(pts - center, axis=1).mean() + 1e-6
        ref_spread = np.linalg.norm(ref - ref_center, axis=1).mean() + 1e-6
        scale = ref_spread / spread
        new_scales.append(scale)
        keypoints[idx] = center + (pts - center) * scale
    if dist_scales is not None:
        new_scales = [momentum * o + (1 - momentum) * n
                      for o, n in zip(dist_scales, new_scales)]
    return keypoints, new_scales

"""Exponential moving average of generator params with spectral-norm
collapse (ref: imaginaire/utils/model_average.py:35-197).

Functional version: the EMA is just another params pytree in the train
state. The reference's ``remove_sn`` mode materializes the
sigma-normalized weight into the averaged copy (sn_compute_weight,
ref: model_average.py:183-197) so the EMA model needs no power-iteration
state at inference; ``collapse_spectral_norm`` does the same by walking
the 'spectral' variable collection alongside 'params'.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _normalize(v, eps=1e-12):
    return v / (jnp.linalg.norm(v) + eps)


def collapse_spectral_norm(params, spectral):
    """Return params with every spectrally-normalized kernel divided by its
    current sigma (estimated from the stored power-iteration ``u``).

    ``spectral`` mirrors the module tree with ``{'u': vec}`` leaves at the
    scopes that own a ``kernel`` param (see layers/weight_norm.py).
    """
    if spectral is None:
        return params

    def walk(p_node, s_node):
        if not isinstance(p_node, dict):
            return p_node
        out = {}
        for k, v in p_node.items():
            s_child = s_node.get(k) if isinstance(s_node, dict) else None
            if isinstance(v, dict):
                out[k] = walk(v, s_child or {})
            else:
                out[k] = v
        if isinstance(s_node, dict) and "u" in s_node and "kernel" in out:
            kernel = out["kernel"]
            u = s_node["u"]
            w_mat = kernel.reshape(-1, kernel.shape[-1]).T  # (out, rest)
            v = _normalize(w_mat.T @ u)
            u2 = _normalize(w_mat @ v)
            sigma = jnp.einsum("o,or,r->", u2, w_mat, v)
            out["kernel"] = kernel / sigma
        return out

    return walk(dict(params), dict(spectral))


def ema_init(params, spectral=None, remove_sn=True):
    """Initialize the averaged copy (ref: model_average.py:48-81).

    Every leaf is a fresh buffer: leaves that pass through
    ``collapse_spectral_norm`` unchanged must NOT alias ``params``, or a
    jitted step that donates the state pytree would donate the same buffer
    twice and crash.
    """
    src = collapse_spectral_norm(params, spectral) if remove_sn else params
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), src)


def ema_update(avg_params, params, num_updates, beta=0.9999,
               start_iteration=1000, spectral=None, remove_sn=True):
    """One EMA step (ref: model_average.py:87-130): beta=0 (pure copy)
    until start_iteration, then exponential averaging. With remove_sn the
    source weights are sigma-collapsed first, so ``avg_params`` always
    holds inference-ready weights.

    num_updates is the post-increment counter (reference increments before
    comparing).
    """
    src = collapse_spectral_norm(params, spectral) if remove_sn else params
    b = jnp.where(num_updates <= start_iteration, 0.0, beta)
    return jax.tree_util.tree_map(
        lambda a, p: a * b + p * (1.0 - b), avg_params, src)

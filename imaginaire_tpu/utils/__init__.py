"""Runtime utilities: init, meters, logging, EMA, misc tensor helpers."""

"""Checkpoint save/load with the reference's pointer-file contract
(ref: imaginaire/trainers/base.py:199-265, 790-829; SURVEY.md §5.4).

orbax handles the array serialization; the surrounding protocol is kept
bit-compatible in spirit:
  - checkpoints at ``<logdir>/epoch_EEEEE_iteration_IIIIIIIII_checkpoint``
  - ``<logdir>/latest_checkpoint.txt`` holds the latest checkpoint name
  - resume mode restores everything; weights-only mode restores params

Multi-host contract (the reference master-gates torch.save,
ref: trainers/base.py:790-829): ``save_checkpoint`` must be called by
EVERY process with the (possibly non-fully-addressable) sharded state —
it hands the live ``jax.Array`` pytree to orbax, whose save is a
collective: each host serializes only the shards it owns and the
coordinator commits the checkpoint atomically. The pointer file is
written by the master process only, after the commit. ``device_get`` is
deliberately NOT used here: it would materialize the full state on every
host (and raises for non-addressable arrays on real multi-host slices).

``async_save=True`` uses ``ocp.AsyncCheckpointer``: serialization runs
in a background thread after a device barrier, so training resumes
immediately (preemption-safe: an interrupted async save leaves only a
tmp dir, never a half-committed checkpoint — the pointer still names the
previous complete one). Call ``wait_for_pending_checkpoint()`` before
reading the checkpoint back or exiting the process.

Fault tolerance (ISSUE 7, ``resilience/``):
  - per-leaf crc32 checksums are computed at save time and ride the
    checkpoint's sidecar — the existing ``.partition.json`` when a
    partition descriptor is saved, ``.integrity.json`` otherwise;
  - ``load_checkpoint`` verifies the restored bytes against them and
    raises ``CheckpointIntegrityError`` on mismatch;
  - ``load_latest_verified`` implements the resume path: a corrupt /
    truncated / missing pointed checkpoint is quarantined (``*.corrupt``
    rename + ``ckpt/quarantined`` meta event) and the newest checkpoint
    that DOES verify is restored instead (``ckpt/fallback`` +
    ``resilience/ckpt_fallbacks``);
  - ``latest_checkpoint_path`` falls back to a logdir scan when the
    pointer names a dead path (a crash between quarantine/deletion and
    the next pointer write must not strand the run);
  - ``max_to_keep`` retention GC runs after each pointer write and never
    deletes the pointer target or the newest verifiable checkpoint;
  - pointer/sidecar writes retry transient IO with bounded backoff
    (``resilience/retry.py``).
"""

from __future__ import annotations

import contextlib
import os
import re

import orbax.checkpoint as ocp

from imaginaire_tpu import telemetry
from imaginaire_tpu.parallel.mesh import is_master

_POINTER = "latest_checkpoint.txt"
_CKPT_RE = re.compile(r"^epoch_(\d+)_iteration_(\d+)_checkpoint$")

# Lazily-built singleton: AsyncCheckpointer owns a thread pool + barrier
# state, so one per process, reused across saves.
_ASYNC_CKPT = None
# The one in-flight pointer-writer thread (see save_checkpoint): joined
# by wait_for_pending_checkpoint so pointer writes can never interleave
# across saves or be lost at process exit.
_POINTER_THREAD = None


def _async_checkpointer():
    global _ASYNC_CKPT
    if _ASYNC_CKPT is None:
        _ASYNC_CKPT = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _ASYNC_CKPT


def _align_orbax_barrier_counters():
    """Pin orbax's per-process barrier counters before a collective save.

    Orbax suffixes its internal barrier keys (``create_tmp_directory:…``,
    async-save finalization, …) with PER-PROCESS ``itertools.count()``
    values from ``orbax.checkpoint.multihost.counters`` and asserts via
    ``sync_global_devices`` that every process computed the same key.
    That assumes uniform save history — which elastic membership breaks
    (ISSUE 13): a host that rejoined mid-run has saved fewer checkpoints
    than the survivors, so at the next collective save its counter (say
    ``.1``) disagrees with theirs (``.4``) and the whole pod dies with
    ``sync_global_devices name mismatch``.

    The counters carry no information for us: saves are already
    serialized pod-wide by the named ``ckpt_enter``/``ckpt_commit``
    timed barriers, each save targets a unique directory name, and
    ``wait_for_pending_checkpoint`` drains any in-flight async commit
    before the next dispatch — so resetting the counters between saves
    cannot collide two concurrent barriers. Resetting (rather than
    patching the accessors) keeps orbax's own uniqueness-within-a-save
    behavior intact while making the sequence identical everywhere."""
    import itertools

    try:
        from orbax.checkpoint.multihost import counters as _counters
    except Exception:  # pragma: no cover — older orbax layouts
        return
    for attr in ("_tmp_directory_counter", "_async_save_counter",
                 "_composite_save_counter"):
        if hasattr(_counters, attr):
            setattr(_counters, attr, itertools.count())


def checkpoint_name(epoch, iteration):
    return f"epoch_{epoch:05d}_iteration_{iteration:09d}_checkpoint"


def parse_checkpoint_name(name):
    m = re.search(r"epoch_(\d+)_iteration_(\d+)", os.path.basename(name))
    if not m:
        return 0, 0
    return int(m.group(1)), int(m.group(2))


def scan_checkpoints(logdir):
    """Committed checkpoints under ``logdir``, oldest first, as
    ``[(epoch, iteration, path), ...]``. Only exact
    ``epoch_*_iteration_*_checkpoint`` directory names count —
    quarantined ``*.corrupt`` renames and tmp dirs never match."""
    try:
        names = os.listdir(logdir)
    except OSError:
        return []
    out = []
    for name in names:
        m = _CKPT_RE.match(name)
        path = os.path.join(logdir, name)
        if m and os.path.isdir(path):
            out.append((int(m.group(1)), int(m.group(2)), path))
    out.sort()
    return out


def save_checkpoint(logdir, state, epoch, iteration, max_to_keep=None,
                    async_save=False, partition_descriptor=None,
                    checksum=True):
    """Collective save of the sharded state + master-only pointer write.

    Every process passes its live state pytree; orbax writes each array
    shard from the host that owns it (ref contract: base.py:790-829).
    With ``async_save`` the call returns as soon as device arrays are
    snapshotted; the pointer is then written by a completion callback so
    it never names an uncommitted checkpoint.

    ``checksum`` computes per-leaf crc32 checksums of the state at
    dispatch time (one device_get of the addressable leaves — see
    PROFILE.md for the cost) and writes them into the checkpoint's
    sidecar after the commit; ``partition_descriptor`` (the active
    partition plan's ``describe()``) makes that sidecar the existing
    ``.partition.json``, otherwise checksums land in
    ``.integrity.json``. ``max_to_keep`` enables retention GC after the
    pointer write (never deletes the pointer target or the newest
    verifiable checkpoint).
    """
    from imaginaire_tpu.resilience import chaos

    name = checkpoint_name(epoch, iteration)
    path = os.path.abspath(os.path.join(logdir, name))
    # commit any in-flight async save first: back-to-back saves would
    # otherwise race the existence check below (orbax also serializes
    # saves internally, so this costs nothing extra)
    wait_for_pending_checkpoint()
    # Multi-process entry barrier (ISSUE 8): orbax's collective save
    # blocks untimed on every host — a peer that never arrives (dead or
    # stalled) used to hang the pod here forever. The timed rendezvous
    # raises ClusterDesyncError NAMING the absent process instead; once
    # everyone has passed it, the collective itself is entered together.
    from imaginaire_tpu.resilience import cluster

    cluster.timed_barrier("ckpt_enter", tag=name)
    # Everyone is now entering THIS save together — align orbax's
    # per-process barrier counters so elastic members with different
    # save histories derive identical collective keys (ISSUE 13).
    _align_orbax_barrier_counters()

    def _write_pointer():
        if is_master():
            from imaginaire_tpu.resilience.retry import retry_call

            def _write():
                with open(os.path.join(logdir, _POINTER), "w") as f:
                    f.write(name + "\n")

            retry_call(_write, label="ckpt_pointer")

    def _after_commit():
        """Sidecar + pointer + GC + chaos hook — runs strictly after
        the array data is committed, in commit order. The committed
        files' raw-byte digests join the integrity record here (they
        only exist post-commit): restore verifies THEM before the
        deserializer touches the data — feeding corrupt bytes to a
        native decoder is a heap hazard, not just a wrong answer."""
        full = integrity
        if full is not None:
            try:
                from imaginaire_tpu.resilience.integrity import (
                    file_digests,
                )

                full = dict(full, files=file_digests(path))
            except Exception as e:  # noqa: BLE001 — never fail a save
                import logging

                logging.getLogger(__name__).warning(
                    "checkpoint file-digest pass failed: %s", e)
        _write_sidecars(path, partition_descriptor, full)
        # All-host commit barrier BEFORE the pointer moves (ISSUE 8):
        # the pointer must never name a checkpoint some host has not
        # finished committing — a restart racing that window would
        # resume half the pod from the new checkpoint and half from
        # the old one. Timed, so a host that died mid-commit surfaces
        # as a named ClusterDesyncError, not a wedged pointer thread.
        from imaginaire_tpu.resilience import cluster

        cluster.timed_barrier("ckpt_commit", tag=name)
        _write_pointer()
        gc_checkpoints(logdir, max_to_keep, protect=(path,))
        chaos.get().maybe_corrupt_checkpoint(path, iteration)

    if os.path.exists(path):
        # idempotent per (epoch, iteration): the final-iteration save and
        # a coinciding snapshot_save_iter save name the same state; orbax
        # refuses to overwrite a committed checkpoint, and the reference's
        # torch.save overwrite would be a no-op here anyway. Still (re)write
        # the pointer — a crash between a past commit and its pointer write
        # must not leave the newer checkpoint unnamed forever.
        print(f"Checkpoint {name} already exists; skipping duplicate save")
        _write_pointer()
        return path

    # checksums are computed from the live arrays BEFORE dispatch: after
    # an async save returns, the caller's buffers may be donated to the
    # next step, so the commit thread must never touch ``state`` again
    integrity = None
    if checksum and is_master():
        from imaginaire_tpu.resilience.integrity import tree_checksums

        with telemetry.span("ckpt_checksum"):
            try:
                integrity = tree_checksums(state)
            except Exception as e:  # noqa: BLE001 — never fail a save
                import logging

                logging.getLogger(__name__).warning(
                    "checkpoint checksum computation failed: %s", e)

    if async_save:
        global _POINTER_THREAD
        ckpt = _async_checkpointer()
        with telemetry.span("ckpt"):
            # async path: the span covers only the device snapshot +
            # save dispatch (what the step loop actually pays); the
            # background commit gets its own ckpt_commit span
            ckpt.save(path, state)
        # orbax finalizes the save (tmp-dir rename) on its background
        # thread; queue the pointer write behind that commit so readers
        # never observe pointer-before-commit. The thread handle is kept
        # so wait_for_pending_checkpoint can join it — otherwise a later
        # save's pointer could be overwritten by this older thread, or
        # the write lost at process exit. Both a commit failure and a
        # pointer-write failure are stashed on the thread and re-raised
        # at the join, never swallowed — and the pointer is only written
        # when the commit actually succeeded, so it can never name a
        # checkpoint that failed to finalize.
        import threading

        def _commit_then_point():
            try:
                with telemetry.span("ckpt_commit"):
                    ckpt.wait_until_finished()
                _after_commit()
            except BaseException as e:  # re-raised by the joiner
                _commit_then_point.error = e

        _commit_then_point.error = None
        # named so watchdog stack dumps identify a wedged commit
        _POINTER_THREAD = threading.Thread(target=_commit_then_point,
                                           daemon=True, name="ckpt-pointer")
        _POINTER_THREAD._pointer_fn = _commit_then_point
        _POINTER_THREAD.start()
    else:
        with telemetry.span("ckpt"):
            with ocp.PyTreeCheckpointer() as ckpt:
                ckpt.save(path, state)
        _after_commit()
        telemetry.get().heartbeat()
    return path


def wait_for_pending_checkpoint():
    """Block until any in-flight async save has committed AND its
    pointer write has landed."""
    global _POINTER_THREAD
    if _ASYNC_CKPT is not None:
        with telemetry.span("ckpt_wait"):
            _ASYNC_CKPT.wait_until_finished()
        telemetry.get().heartbeat()
    if _POINTER_THREAD is not None:
        thread = _POINTER_THREAD
        _POINTER_THREAD = None
        thread.join()
        err = getattr(thread._pointer_fn, "error", None)
        if err is not None:
            raise RuntimeError(
                "async checkpoint commit or pointer write failed; "
                "latest_checkpoint.txt still names the previous complete "
                "checkpoint") from err


def latest_checkpoint_path(logdir):
    """The pointed checkpoint (ref: base.py:225-233) — falling back to
    the newest parseable checkpoint in ``logdir`` when the pointer names
    a missing/unreadable path (quarantined, GC'd by an older policy, or
    torn by a crash). No pointer file at all still returns None: only
    the master ever writes it, and a fresh logdir must not resume from
    stray directories."""
    pointer = os.path.join(logdir, _POINTER)
    if not os.path.exists(pointer):
        return None
    try:
        with open(pointer) as f:
            name = f.read().strip()
    except OSError:
        name = ""
    path = os.path.join(logdir, name) if name else None
    if path and os.path.exists(path):
        return path
    entries = scan_checkpoints(logdir)
    if not entries:
        return None
    fallback = entries[-1][2]
    telemetry.get().meta("ckpt/pointer_fallback", pointer=name or None,
                         fallback=fallback)
    import logging

    logging.getLogger(__name__).warning(
        "latest_checkpoint.txt names %r which does not exist; falling "
        "back to newest checkpoint in logdir: %s", name, fallback)
    return fallback


# ------------------------------------------------------------- sidecars


def _write_sidecars(path, partition_descriptor, integrity):
    """Write the checkpoint's sidecar(s): checksums ride the partition
    sidecar when a descriptor is saved, ``.integrity.json`` otherwise
    (replicated checkpoints carry no ``.partition.json`` — legacy
    readers treat its absence as 'saved replicated')."""
    if partition_descriptor is not None:
        write_partition_sidecar(path, partition_descriptor,
                                integrity=integrity)
    elif integrity is not None:
        write_integrity_sidecar(path, integrity)


def write_partition_sidecar(path, descriptor, integrity=None):
    """Persist the saving run's partition-plan descriptor (mesh axes/
    shape + update-state sharding knobs, see
    ``PartitionPlan.describe``) as a ``<ckpt>.partition.json`` sibling —
    like the ``.ema_bn.pkl`` sibling, a sidecar keeps the state tree's
    structure stable across checkpoint versions. Master-only; a missing
    sidecar means 'saved replicated' (pre-ISSUE-6 checkpoints). The
    per-leaf ``integrity`` checksums ride the same file under the
    reserved ``integrity`` key (``read_partition_sidecar`` strips it)."""
    import json

    if not is_master():
        return
    payload = dict(descriptor or {})
    if integrity is not None:
        payload["integrity"] = integrity
    try:
        from imaginaire_tpu.resilience.retry import retry_call

        def _write():
            tmp = str(path) + ".partition.json.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, default=str)
            os.replace(tmp, str(path) + ".partition.json")

        retry_call(_write, label="partition_sidecar")
    except Exception as e:  # noqa: BLE001 — a sidecar must never fail a save
        import logging

        logging.getLogger(__name__).warning(
            "partition sidecar write failed: %s", e)


def write_integrity_sidecar(path, integrity):
    """``<ckpt>.integrity.json`` for checkpoints without a partition
    descriptor. Master-only; never fails a save."""
    import json

    if not is_master():
        return
    try:
        from imaginaire_tpu.resilience.retry import retry_call

        def _write():
            tmp = str(path) + ".integrity.json.tmp"
            with open(tmp, "w") as f:
                json.dump(integrity, f, indent=1, default=str)
            os.replace(tmp, str(path) + ".integrity.json")

        retry_call(_write, label="integrity_sidecar")
    except Exception as e:  # noqa: BLE001
        import logging

        logging.getLogger(__name__).warning(
            "integrity sidecar write failed: %s", e)


def read_partition_sidecar(path):
    """The saved partition descriptor, or None (replicated / legacy).
    The ``integrity`` key (ISSUE 7 checksums sharing the file) is
    stripped — descriptor comparisons stay byte-compatible with
    pre-ISSUE-7 sidecars."""
    import json
    import os as _os

    sidecar = str(path) + ".partition.json"
    if not _os.path.exists(sidecar):
        return None
    try:
        with open(sidecar) as f:
            payload = json.load(f)
    except Exception:  # noqa: BLE001
        return None
    if isinstance(payload, dict):
        payload = {k: v for k, v in payload.items() if k != "integrity"}
        return payload or None
    return payload


def read_integrity_sidecar(path):
    """The saved per-leaf checksums, or None (legacy checkpoint):
    ``.partition.json``'s ``integrity`` key when present, else the
    standalone ``.integrity.json``."""
    import json
    import os as _os

    sidecar = str(path) + ".partition.json"
    if _os.path.exists(sidecar):
        try:
            with open(sidecar) as f:
                payload = json.load(f)
            if isinstance(payload, dict) and payload.get("integrity"):
                return payload["integrity"]
        except Exception:  # noqa: BLE001
            pass
    sidecar = str(path) + ".integrity.json"
    if not _os.path.exists(sidecar):
        return None
    try:
        with open(sidecar) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001
        return None


# ------------------------------------------------------------ retention


def gc_checkpoints(logdir, max_to_keep, protect=()):
    """Retention GC: keep the newest ``max_to_keep`` checkpoints.

    Never deletes the pointer target, anything in ``protect``, or the
    newest checkpoint that carries integrity checksums (the last
    *verifiable* one — fallback must always have somewhere to land).
    Master-only; emits a ``ckpt/gc`` telemetry meta event naming what
    was deleted."""
    if not max_to_keep or int(max_to_keep) <= 0 or not is_master():
        return []
    entries = scan_checkpoints(logdir)
    if len(entries) <= int(max_to_keep):
        return []
    protected = {os.path.abspath(str(p)) for p in protect}
    pointer = os.path.join(logdir, _POINTER)
    if os.path.exists(pointer):
        try:
            with open(pointer) as f:
                pointed = f.read().strip()
            if pointed:
                protected.add(os.path.abspath(
                    os.path.join(logdir, pointed)))
        except OSError:
            pass
    # the newest verifiable checkpoint stays: it is where a corrupt
    # pointer target falls back to
    for _, _, path in reversed(entries):
        if read_integrity_sidecar(path) is not None:
            protected.add(os.path.abspath(path))
            break
    doomed = [path for _, _, path in entries[:-int(max_to_keep)]
              if os.path.abspath(path) not in protected]
    if not doomed:
        return []
    import logging
    import shutil

    from imaginaire_tpu.resilience.integrity import sidecar_files

    deleted = []
    for path in doomed:
        try:
            shutil.rmtree(path)
        except OSError as e:
            logging.getLogger(__name__).warning(
                "checkpoint GC failed to delete %s: %s", path, e)
            continue
        for sidecar in sidecar_files(path):
            try:
                os.remove(sidecar)
            except OSError:
                pass
        deleted.append(path)
    if deleted:
        tm = telemetry.get()
        if tm.enabled:
            tm.meta("ckpt/gc", deleted=[os.path.basename(p)
                                        for p in deleted],
                    kept=len(entries) - len(deleted),
                    max_to_keep=int(max_to_keep))
            tm.counter("resilience/ckpt_gc_deleted", len(deleted))
        logging.getLogger(__name__).info(
            "checkpoint GC deleted %d checkpoint(s) (max_to_keep=%d): %s",
            len(deleted), int(max_to_keep),
            [os.path.basename(p) for p in deleted])
    return deleted


# -------------------------------------------------------------- restore


@contextlib.contextmanager
def _no_restore_barrier():
    """Suppress orbax's end-of-restore process sync for the duration.

    ``Checkpointer.restore`` closes with ``sync_global_processes`` — an
    UNTIMED ``sync_global_devices`` psum over every global device
    through the CPU gloo layer. In an elastic pod (ISSUE 13) restores
    are legitimately asymmetric: a joiner restores the published
    checkpoint at startup while the survivors re-commit their live
    state and never touch orbax, so the joiner's barrier waits 30s for
    gloo contexts no peer will ever create and the restore dies with
    ``DEADLINE_EXCEEDED`` — and even when every member restores, a
    fallback scan that walks a different number of candidates on one
    host leaves that host's collective sequence offset from its peers,
    which surfaces later as a wedged/aborted all-device sync at the
    next checkpoint save. Restore is read-only, so the barrier guards
    nothing; pod-wide resume agreement is the KV-store consensus vote
    (timed, and it NAMES the absent process). Saves keep their sync:
    the pre-finalize barrier is what stops the primary from renaming
    the tmp directory while peers are still writing."""
    from orbax.checkpoint import checkpointer as _ocp_checkpointer

    mh = _ocp_checkpointer.multihost
    orig = mh.sync_global_processes

    def _skip(name, **kwargs):
        return None

    mh.sync_global_processes = _skip
    try:
        yield
    finally:
        mh.sync_global_processes = orig


def _host_template(target):
    """A host-numpy zeros pytree with ``target``'s structure: what
    orbax needs from ``item`` is the tree structure (optimizer
    namedtuples survive the round-trip) and per-leaf dtypes/shapes —
    not the values. Building zeros instead of ``jax.device_get(target)``
    skips a full state materialization per restore and works when the
    live state is a non-addressable pod-sharded tree (ISSUE 8), where
    ``device_get`` raises."""
    import jax
    import numpy as np

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return np.zeros(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, target)


def load_checkpoint(path, target=None, verify=True):
    """Restore a state pytree; ``target`` gives structure/dtypes.

    Arrays come back as host numpy; callers ``device_put`` them with
    their own shardings (trainers re-shard on resume). This keeps
    restore layout-agnostic — a checkpoint written on one mesh shape
    loads on another.

    ``verify`` is two-layered: the sidecar's raw-file digests are
    checked with plain Python reads BEFORE orbax deserializes anything
    (corrupt compressed chunks fed to a native decoder are a heap
    hazard, not just a wrong answer), then the per-leaf checksums are
    replayed against the restored arrays. Either mismatch raises
    ``CheckpointIntegrityError``; checkpoints saved without checksums
    restore unverified, as before.
    """
    import jax

    integrity = read_integrity_sidecar(path) if verify else None
    if verify:
        from imaginaire_tpu.resilience.integrity import verify_files

        verify_files(os.path.abspath(path),
                     (integrity or {}).get("files"), context=str(path))
    with telemetry.span("ckpt_load"), _no_restore_barrier(), \
            ocp.PyTreeCheckpointer() as ckpt:
        if target is not None:
            # force host-numpy restore here too (ISSUE 11): without
            # restore args orbax replays the SAVED shardings from the
            # sharding file — fine when the topology matches, a
            # ``ValueError: sharding ... Got None`` when it does not
            # (an elastic pod restoring a checkpoint written by a
            # world whose devices no longer exist). The item keeps the
            # tree structure (optimizer namedtuples) and true shapes.
            import numpy as np

            item = _host_template(target)
            restore_args = jax.tree_util.tree_map(
                lambda x: (ocp.RestoreArgs(restore_type=np.ndarray)
                           if hasattr(x, "shape") else ocp.RestoreArgs()),
                item)
            payload = ckpt.restore(os.path.abspath(path), item=item,
                                   restore_args=restore_args)

            def _item_shape(v, t):
                # scalar zarr arrays come back shape-(1,) on the numpy
                # restore path; the template remembers the true shape
                if hasattr(t, "shape") and hasattr(v, "shape") \
                        and tuple(v.shape) != tuple(t.shape):
                    return np.asarray(v).reshape(tuple(t.shape))
                return v

            payload = jax.tree_util.tree_map(_item_shape, payload, item)
        else:
            # no target: force every array leaf to restore as host
            # numpy (ISSUE 8). Without restore args orbax replays the
            # SAVED shardings — a checkpoint written by an N-process
            # pod then refuses to restore in any other topology (the
            # mesh in the sharding file names devices this process
            # does not have). numpy restore keeps the documented
            # contract: restores are layout-agnostic, callers commit
            # under their own shardings.
            import numpy as np

            meta = ckpt.metadata(os.path.abspath(path))
            restore_args = jax.tree_util.tree_map(
                lambda m: (ocp.RestoreArgs(restore_type=np.ndarray)
                           if hasattr(m, "shape") else ocp.RestoreArgs()),
                meta)
            payload = ckpt.restore(os.path.abspath(path),
                                   restore_args=restore_args)

            def _true_shape(v, m):
                # orbax hands scalar zarr arrays back as shape (1,)
                # ndarrays on the numpy restore path; the metadata
                # remembers the saved shape
                if hasattr(m, "shape") and hasattr(v, "shape") \
                        and tuple(v.shape) != tuple(m.shape):
                    return np.asarray(v).reshape(tuple(m.shape))
                return v

            payload = jax.tree_util.tree_map(_true_shape, payload, meta)
    if verify:
        from imaginaire_tpu.resilience.integrity import verify_tree

        verify_tree(payload, integrity, context=str(path))
        tm = telemetry.get()
        if tm.enabled:
            tm.meta("ckpt/verified", checkpoint=str(path),
                    verified=integrity is not None,
                    n_leaves=(integrity or {}).get("n_leaves"))
    return payload


def load_latest_verified(logdir, target=None, verify=True):
    """The resume path with last-good fallback: restore the pointed
    checkpoint, quarantining any candidate that is corrupt / truncated
    / unrestorable and falling back to the next-newest until one
    verifies.

    Returns ``(payload, path, fallbacks)`` — ``payload`` None when the
    logdir has no pointer (fresh run). Raises when a pointer exists but
    EVERY candidate failed: resuming from scratch over a logdir full of
    corrupt checkpoints must be an explicit operator decision, not a
    silent restart."""
    from imaginaire_tpu.resilience.integrity import (
        CheckpointIntegrityError,
        quarantine_checkpoint,
    )

    pointer = os.path.join(logdir, _POINTER)
    if not os.path.exists(pointer):
        return None, None, 0
    try:
        with open(pointer) as f:
            pointed_name = f.read().strip()
    except OSError:
        pointed_name = ""
    pointed = (os.path.abspath(os.path.join(logdir, pointed_name))
               if pointed_name else None)
    candidates = []
    if pointed and os.path.exists(pointed):
        candidates.append(pointed)
    for _, _, path in reversed(scan_checkpoints(logdir)):
        if os.path.abspath(path) != pointed:
            candidates.append(os.path.abspath(path))
    if not candidates:
        import logging

        logging.getLogger(__name__).warning(
            "latest_checkpoint.txt names %r but no checkpoint exists in "
            "%s", pointed_name, logdir)
        return None, None, 0
    tm = telemetry.get()
    fallbacks = 0
    errors = []
    for cand in candidates:
        try:
            payload = load_checkpoint(cand, target=target, verify=verify)
        except CheckpointIntegrityError as e:
            errors.append(f"{cand}: {e}")
            quarantine_checkpoint(cand, reason="integrity mismatch")
            fallbacks += 1
            _note_fallback(tm, cand, fallbacks, str(e))
            continue
        except Exception as e:  # noqa: BLE001 — truncated/unrestorable
            if type(e).__name__ in ("XlaRuntimeError",
                                    "JaxRuntimeError"):
                # runtime/collective infrastructure failure, not
                # evidence about THIS checkpoint's bytes: quarantining
                # here would walk the fallback scan through every
                # candidate and condemn a healthy logdir (ISSUE 13:
                # seen as gloo context timeouts when a resize left the
                # pod's collective layer wedged). Fail the restore
                # loudly and leave the checkpoints alone.
                raise
            errors.append(f"{cand}: {type(e).__name__}: {e}")
            quarantine_checkpoint(cand,
                                  reason=f"restore failed: "
                                         f"{type(e).__name__}")
            fallbacks += 1
            _note_fallback(tm, cand, fallbacks, str(e))
            continue
        if fallbacks and tm.enabled:
            tm.counter("resilience/ckpt_fallbacks", fallbacks)
        return payload, cand, fallbacks
    raise RuntimeError(
        f"no verifiable checkpoint in {logdir}: every candidate failed "
        f"to restore ({len(errors)} quarantined). Delete or repair the "
        f"logdir to restart from scratch. Errors: "
        + " | ".join(errors[:3]))


def _note_fallback(tm, path, fallbacks, error):
    import logging

    if tm.enabled:
        tm.meta("ckpt/fallback", skipped=str(path), fallbacks=fallbacks,
                error=error[:500])
    logging.getLogger(__name__).error(
        "checkpoint %s failed to restore (%s); falling back to the "
        "next-newest checkpoint", path, error[:500])

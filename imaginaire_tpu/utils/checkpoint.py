"""Checkpoint save/load with the reference's pointer-file contract
(ref: imaginaire/trainers/base.py:199-265, 790-829; SURVEY.md §5.4).

orbax handles the array serialization (async-capable, preemption-safe —
the idiomatic TPU upgrade over torch.save); the surrounding protocol is
kept bit-compatible in spirit:
  - checkpoints at ``<logdir>/epoch_EEEEE_iteration_IIIIIIIII_checkpoint``
  - ``<logdir>/latest_checkpoint.txt`` holds the latest checkpoint name
  - resume mode restores everything; weights-only mode restores params
"""

from __future__ import annotations

import os
import re

import jax
import orbax.checkpoint as ocp

from imaginaire_tpu.parallel.mesh import is_master

_POINTER = "latest_checkpoint.txt"


def checkpoint_name(epoch, iteration):
    return f"epoch_{epoch:05d}_iteration_{iteration:09d}_checkpoint"


def parse_checkpoint_name(name):
    m = re.search(r"epoch_(\d+)_iteration_(\d+)", os.path.basename(name))
    if not m:
        return 0, 0
    return int(m.group(1)), int(m.group(2))


def save_checkpoint(logdir, state, epoch, iteration, max_to_keep=None):
    """Master-writes state pytree + pointer file (ref: base.py:790-829)."""
    name = checkpoint_name(epoch, iteration)
    path = os.path.abspath(os.path.join(logdir, name))
    with ocp.PyTreeCheckpointer() as ckpt:
        ckpt.save(path, jax.device_get(state))
    if is_master():
        with open(os.path.join(logdir, _POINTER), "w") as f:
            f.write(name + "\n")
    return path


def latest_checkpoint_path(logdir):
    """(ref: base.py:225-233)."""
    pointer = os.path.join(logdir, _POINTER)
    if not os.path.exists(pointer):
        return None
    with open(pointer) as f:
        name = f.read().strip()
    path = os.path.join(logdir, name)
    return path if os.path.exists(path) else None


def load_checkpoint(path, target=None):
    """Restore a state pytree; ``target`` gives structure/dtypes."""
    with ocp.PyTreeCheckpointer() as ckpt:
        if target is not None:
            return ckpt.restore(os.path.abspath(path), item=jax.device_get(target))
        return ckpt.restore(os.path.abspath(path))

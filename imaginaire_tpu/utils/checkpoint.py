"""Checkpoint save/load with the reference's pointer-file contract
(ref: imaginaire/trainers/base.py:199-265, 790-829; SURVEY.md §5.4).

orbax handles the array serialization; the surrounding protocol is kept
bit-compatible in spirit:
  - checkpoints at ``<logdir>/epoch_EEEEE_iteration_IIIIIIIII_checkpoint``
  - ``<logdir>/latest_checkpoint.txt`` holds the latest checkpoint name
  - resume mode restores everything; weights-only mode restores params

Multi-host contract (the reference master-gates torch.save,
ref: trainers/base.py:790-829): ``save_checkpoint`` must be called by
EVERY process with the (possibly non-fully-addressable) sharded state —
it hands the live ``jax.Array`` pytree to orbax, whose save is a
collective: each host serializes only the shards it owns and the
coordinator commits the checkpoint atomically. The pointer file is
written by the master process only, after the commit. ``device_get`` is
deliberately NOT used here: it would materialize the full state on every
host (and raises for non-addressable arrays on real multi-host slices).

``async_save=True`` uses ``ocp.AsyncCheckpointer``: serialization runs
in a background thread after a device barrier, so training resumes
immediately (preemption-safe: an interrupted async save leaves only a
tmp dir, never a half-committed checkpoint — the pointer still names the
previous complete one). Call ``wait_for_pending_checkpoint()`` before
reading the checkpoint back or exiting the process.
"""

from __future__ import annotations

import os
import re

import orbax.checkpoint as ocp

from imaginaire_tpu import telemetry
from imaginaire_tpu.parallel.mesh import is_master

_POINTER = "latest_checkpoint.txt"

# Lazily-built singleton: AsyncCheckpointer owns a thread pool + barrier
# state, so one per process, reused across saves.
_ASYNC_CKPT = None
# The one in-flight pointer-writer thread (see save_checkpoint): joined
# by wait_for_pending_checkpoint so pointer writes can never interleave
# across saves or be lost at process exit.
_POINTER_THREAD = None


def _async_checkpointer():
    global _ASYNC_CKPT
    if _ASYNC_CKPT is None:
        _ASYNC_CKPT = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _ASYNC_CKPT


def checkpoint_name(epoch, iteration):
    return f"epoch_{epoch:05d}_iteration_{iteration:09d}_checkpoint"


def parse_checkpoint_name(name):
    m = re.search(r"epoch_(\d+)_iteration_(\d+)", os.path.basename(name))
    if not m:
        return 0, 0
    return int(m.group(1)), int(m.group(2))


def save_checkpoint(logdir, state, epoch, iteration, max_to_keep=None,
                    async_save=False):
    """Collective save of the sharded state + master-only pointer write.

    Every process passes its live state pytree; orbax writes each array
    shard from the host that owns it (ref contract: base.py:790-829).
    With ``async_save`` the call returns as soon as device arrays are
    snapshotted; the pointer is then written by a completion callback so
    it never names an uncommitted checkpoint.
    """
    name = checkpoint_name(epoch, iteration)
    path = os.path.abspath(os.path.join(logdir, name))
    # commit any in-flight async save first: back-to-back saves would
    # otherwise race the existence check below (orbax also serializes
    # saves internally, so this costs nothing extra)
    wait_for_pending_checkpoint()

    def _write_pointer():
        if is_master():
            with open(os.path.join(logdir, _POINTER), "w") as f:
                f.write(name + "\n")

    if os.path.exists(path):
        # idempotent per (epoch, iteration): the final-iteration save and
        # a coinciding snapshot_save_iter save name the same state; orbax
        # refuses to overwrite a committed checkpoint, and the reference's
        # torch.save overwrite would be a no-op here anyway. Still (re)write
        # the pointer — a crash between a past commit and its pointer write
        # must not leave the newer checkpoint unnamed forever.
        print(f"Checkpoint {name} already exists; skipping duplicate save")
        _write_pointer()
        return path

    if async_save:
        global _POINTER_THREAD
        ckpt = _async_checkpointer()
        with telemetry.span("ckpt"):
            # async path: the span covers only the device snapshot +
            # save dispatch (what the step loop actually pays); the
            # background commit gets its own ckpt_commit span
            ckpt.save(path, state)
        # orbax finalizes the save (tmp-dir rename) on its background
        # thread; queue the pointer write behind that commit so readers
        # never observe pointer-before-commit. The thread handle is kept
        # so wait_for_pending_checkpoint can join it — otherwise a later
        # save's pointer could be overwritten by this older thread, or
        # the write lost at process exit. Both a commit failure and a
        # pointer-write failure are stashed on the thread and re-raised
        # at the join, never swallowed — and the pointer is only written
        # when the commit actually succeeded, so it can never name a
        # checkpoint that failed to finalize.
        import threading

        def _commit_then_point():
            try:
                with telemetry.span("ckpt_commit"):
                    ckpt.wait_until_finished()
                _write_pointer()
            except BaseException as e:  # re-raised by the joiner
                _commit_then_point.error = e

        _commit_then_point.error = None
        # named so watchdog stack dumps identify a wedged commit
        _POINTER_THREAD = threading.Thread(target=_commit_then_point,
                                           daemon=True, name="ckpt-pointer")
        _POINTER_THREAD._pointer_fn = _commit_then_point
        _POINTER_THREAD.start()
    else:
        with telemetry.span("ckpt"):
            with ocp.PyTreeCheckpointer() as ckpt:
                ckpt.save(path, state)
        _write_pointer()
        telemetry.get().heartbeat()
    return path


def wait_for_pending_checkpoint():
    """Block until any in-flight async save has committed AND its
    pointer write has landed."""
    global _POINTER_THREAD
    if _ASYNC_CKPT is not None:
        with telemetry.span("ckpt_wait"):
            _ASYNC_CKPT.wait_until_finished()
        telemetry.get().heartbeat()
    if _POINTER_THREAD is not None:
        thread = _POINTER_THREAD
        _POINTER_THREAD = None
        thread.join()
        err = getattr(thread._pointer_fn, "error", None)
        if err is not None:
            raise RuntimeError(
                "async checkpoint commit or pointer write failed; "
                "latest_checkpoint.txt still names the previous complete "
                "checkpoint") from err


def latest_checkpoint_path(logdir):
    """(ref: base.py:225-233)."""
    pointer = os.path.join(logdir, _POINTER)
    if not os.path.exists(pointer):
        return None
    with open(pointer) as f:
        name = f.read().strip()
    path = os.path.join(logdir, name)
    return path if os.path.exists(path) else None


def write_partition_sidecar(path, descriptor):
    """Persist the saving run's partition-plan descriptor (mesh axes/
    shape + update-state sharding knobs, see
    ``PartitionPlan.describe``) as a ``<ckpt>.partition.json`` sibling —
    like the ``.ema_bn.pkl`` sibling, a sidecar keeps the state tree's
    structure stable across checkpoint versions. Master-only; a missing
    sidecar means 'saved replicated' (pre-ISSUE-6 checkpoints)."""
    import json

    if not is_master():
        return
    try:
        with open(str(path) + ".partition.json", "w") as f:
            json.dump(descriptor, f, indent=1, default=str)
    except Exception as e:  # noqa: BLE001 — a sidecar must never fail a save
        import logging

        logging.getLogger(__name__).warning(
            "partition sidecar write failed: %s", e)


def read_partition_sidecar(path):
    """The saved partition descriptor, or None (replicated / legacy)."""
    import json
    import os as _os

    sidecar = str(path) + ".partition.json"
    if not _os.path.exists(sidecar):
        return None
    try:
        with open(sidecar) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001
        return None


def load_checkpoint(path, target=None):
    """Restore a state pytree; ``target`` gives structure/dtypes.

    Arrays come back as host numpy; callers ``device_put`` them with
    their own shardings (trainers re-shard on resume). This keeps
    restore layout-agnostic — a checkpoint written on one mesh shape
    loads on another.
    """
    import jax

    with telemetry.span("ckpt_load"), ocp.PyTreeCheckpointer() as ckpt:
        if target is not None:
            return ckpt.restore(os.path.abspath(path),
                                item=jax.device_get(target))
        return ckpt.restore(os.path.abspath(path))

"""Weight-init factory (ref: imaginaire/utils/init_weight.py:8-61).

The reference applies ``weights_init(type, gain)`` to every module after
construction; here the equivalent is a process-global default initializer
that blocks read at ``param(...)`` creation time. The trainer factory sets
it from ``cfg.trainer.init`` before calling ``model.init`` (same config
surface: xavier / xavier_uniform / normal / kaiming / orthogonal / none).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from flax import linen as nn
from jax import random

_DEFAULT = {"type": "xavier", "gain": 0.02}


def set_default_init(init_type="xavier", gain=0.02):
    _DEFAULT["type"] = init_type or "none"
    _DEFAULT["gain"] = gain


def get_default_init():
    return dict(_DEFAULT)


def make_kernel_init(init_type=None, gain=None):
    """Return a flax initializer fn for conv/dense kernels.

    Fan computation follows torch's (kernel layout here is
    (spatial..., in, out)): fan_in = in * prod(spatial), fan_out =
    out * prod(spatial).
    """
    init_type = init_type if init_type is not None else _DEFAULT["type"]
    gain = gain if gain is not None else _DEFAULT["gain"]

    def init(key, shape, dtype=jnp.float32):
        fan_in = math.prod(shape[:-1])
        fan_out = shape[-1] * math.prod(shape[:-2]) if len(shape) > 1 else shape[-1]
        if init_type in ("none", "", None):
            # torch default: kaiming_uniform(a=sqrt(5)) == U(-1/sqrt(fan_in), +)
            bound = 1.0 / math.sqrt(fan_in) if fan_in > 0 else 0.0
            return random.uniform(key, shape, dtype, -bound, bound)
        if init_type == "normal":
            return gain * random.normal(key, shape, dtype)
        if init_type == "xavier":
            std = gain * math.sqrt(2.0 / (fan_in + fan_out))
            return std * random.normal(key, shape, dtype)
        if init_type == "xavier_uniform":
            a = gain * math.sqrt(6.0 / (fan_in + fan_out))
            return random.uniform(key, shape, dtype, -a, a)
        if init_type == "kaiming":
            std = gain * math.sqrt(2.0 / fan_in)
            return std * random.normal(key, shape, dtype)
        if init_type == "orthogonal":
            return gain * nn.initializers.orthogonal()(key, shape, dtype)
        raise ValueError(f"unknown init type {init_type!r}")

    return init


def default_kernel_init(key, shape, dtype=jnp.float32):
    return make_kernel_init()(key, shape, dtype)

"""Meters -> TensorBoard (ref: imaginaire/utils/meters.py).

Same contract as the reference: ``Meter.write`` buffers values,
``flush`` averages them, filters non-finite with a console warning, and
writes a scalar per meter (ref: meters.py:107-145). Master-process-only,
like every reference writer.
"""

from __future__ import annotations

import math

from imaginaire_tpu.parallel.mesh import is_master, master_only

_WRITER = None


@master_only
def set_summary_writer(log_dir):
    """(ref: meters.py:55-60)."""
    global _WRITER
    from torch.utils.tensorboard import SummaryWriter

    _WRITER = SummaryWriter(log_dir=log_dir)


def get_summary_writer():
    return _WRITER


@master_only
def add_hparams(hparam_dict=None, metric_dict=None):
    """Hyper-parameter dashboard entry (ref: meters.py:81-105): logs the
    hparams alongside their metrics so TensorBoard's hparams plugin can
    compare runs."""
    if _WRITER is None:
        return
    if not isinstance(hparam_dict, dict) or not isinstance(metric_dict, dict):
        raise TypeError("hparam_dict and metric_dict should be dictionaries.")
    from torch.utils.tensorboard.summary import hparams

    exp, ssi, sei = hparams(hparam_dict, metric_dict)
    writer = _WRITER._get_file_writer()
    writer.add_summary(exp)
    writer.add_summary(ssi)
    writer.add_summary(sei)
    for key, value in metric_dict.items():
        _WRITER.add_scalar(key, value)


@master_only
def write_summary(name, data, step, hist=False):
    """(ref: meters.py:63-78)."""
    if _WRITER is None:
        return
    if hist:
        _WRITER.add_histogram(name, data, step)
    else:
        _WRITER.add_scalar(name, data, step)


class Meter:
    """(ref: meters.py:107-159).

    ``write`` accepts plain floats OR device arrays; device values are
    kept as-is and only materialized at ``flush`` time. This keeps the
    training loop free of per-step host syncs (a device_get per loss per
    step would serialize XLA dispatch — the TPU analogue of the
    reference detaching losses post-step, ref: base.py:716-721).
    """

    def __init__(self, name):
        self.name = name
        self.values = []

    def reset(self):
        self.values = []

    def write(self, value):
        if value is not None:
            self.values.append(value)

    def write_image(self, img_grid, step):
        if is_master() and _WRITER is not None:
            _WRITER.add_image(self.name, img_grid, step, dataformats="HWC")

    def flush(self, step):
        values = [float(v) for v in self.values]  # device sync happens here
        finite = [v for v in values if math.isfinite(v)]
        if len(finite) != len(values):
            print(f"meter {self.name} has non-finite values")
        if finite:
            write_summary(self.name, sum(finite) / len(finite), step)
        self.reset()

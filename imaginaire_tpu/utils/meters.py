"""Meters -> TensorBoard (ref: imaginaire/utils/meters.py).

Same contract as the reference: ``Meter.write`` buffers values,
``flush`` averages them, filters non-finite with a console warning, and
writes a scalar per meter (ref: meters.py:107-145). Master-process-only,
like every reference writer.
"""

from __future__ import annotations

import logging
import math

from imaginaire_tpu.parallel.mesh import is_master, master_only

logger = logging.getLogger(__name__)

_WRITER = None


@master_only
def set_summary_writer(log_dir):
    """(ref: meters.py:55-60). A missing torch degrades to a logged
    warning + no writer: scalar history still lands in the telemetry
    sinks (telemetry/sinks.py), so torch-free hosts train fine."""
    global _WRITER
    try:
        from torch.utils.tensorboard import SummaryWriter
    except ImportError as e:
        logger.warning(
            "torch.utils.tensorboard unavailable (%s); TensorBoard "
            "summaries disabled — scalars still flow to the telemetry "
            "sinks (telemetry.jsonl)", e)
        _WRITER = None
        return
    _WRITER = SummaryWriter(log_dir=log_dir)


def get_summary_writer():
    return _WRITER


@master_only
def add_hparams(hparam_dict=None, metric_dict=None):
    """Hyper-parameter dashboard entry (ref: meters.py:81-105): logs the
    hparams alongside their metrics so TensorBoard's hparams plugin can
    compare runs."""
    if _WRITER is None:
        return
    if not isinstance(hparam_dict, dict) or not isinstance(metric_dict, dict):
        raise TypeError("hparam_dict and metric_dict should be dictionaries.")
    from torch.utils.tensorboard.summary import hparams

    exp, ssi, sei = hparams(hparam_dict, metric_dict)
    writer = _WRITER._get_file_writer()
    writer.add_summary(exp)
    writer.add_summary(ssi)
    writer.add_summary(sei)
    for key, value in metric_dict.items():
        _WRITER.add_scalar(key, value)


@master_only
def write_summary(name, data, step, hist=False):
    """(ref: meters.py:63-78). Scalars fan out through the telemetry
    sinks (jsonl/console/tensorboard); when a TensorBoardSink is
    configured it owns the TB write, otherwise the direct writer path
    keeps the original behavior bit-for-bit."""
    if hist:
        if _WRITER is not None:
            _WRITER.add_histogram(name, data, step)
        return
    from imaginaire_tpu import telemetry

    tb_handled = telemetry.get().counter(name, float(data), step=step)
    if not tb_handled and _WRITER is not None:
        _WRITER.add_scalar(name, data, step)


class Meter:
    """(ref: meters.py:107-159).

    ``write`` accepts plain floats OR device arrays; device values are
    kept as-is and only materialized at ``flush`` time. This keeps the
    training loop free of per-step host syncs (a device_get per loss per
    step would serialize XLA dispatch — the TPU analogue of the
    reference detaching losses post-step, ref: base.py:716-721).
    """

    def __init__(self, name):
        self.name = name
        self.values = []

    def reset(self):
        self.values = []

    def write(self, value):
        if value is not None:
            self.values.append(value)

    def write_image(self, img_grid, step):
        if is_master() and _WRITER is not None:
            _WRITER.add_image(self.name, img_grid, step, dataformats="HWC")

    def flush(self, step):
        values = [float(v) for v in self.values]  # device sync happens here
        finite = [v for v in values if math.isfinite(v)]
        dropped = len(values) - len(finite)
        if dropped:
            # a nonfinite_count scalar makes NaN onset visible on
            # dashboards instead of only in scrollback
            logger.warning("meter %s has %d non-finite value(s) at step "
                           "%s", self.name, dropped, step)
            write_summary(f"{self.name}/nonfinite_count", dropped, step)
        if finite:
            write_summary(self.name, sum(finite) / len(finite), step)
        self.reset()


def get_weight_stats(params, spectral, grads=None, eps=1e-12):
    """Spectral-norm weight statistics (ref: imaginaire/utils/meters.py:19-51).

    The reference computes, per spectrally-normalized layer, the raw
    weight norm, the gradient norm, and the power-iteration sigma
    estimate ``u^T W v`` (it ships this helper unwired; here it is also
    reachable from the trainer via ``trainer.log_weight_stats``).

    Args:
        params: a 'params' pytree (dicts of arrays).
        spectral: the matching 'spectral' collection (dicts holding 'u'
            leaves at the layer paths that carry spectral norm).
        grads: optional gradient pytree with params' structure.
    Returns:
        dict mapping 'path/to/layer' -> {'weight_norm', 'sigma',
        'grad_norm' (0.0 when grads is None)}.
    """
    import numpy as np

    stats = {}

    def walk(spec_node, path):
        if not isinstance(spec_node, dict):
            return
        if "u" in spec_node and not isinstance(spec_node["u"], dict):
            pnode = params
            gnode = grads
            for k in path:
                pnode = pnode.get(k, {}) if isinstance(pnode, dict) else {}
                if gnode is not None:
                    gnode = gnode.get(k, {}) if isinstance(gnode, dict) else {}
            kernel = pnode.get("kernel") if isinstance(pnode, dict) else None
            if kernel is None:
                return
            # host numpy throughout: callers pass device_get'd trees and
            # a per-layer device round-trip per logging interval would be
            # pure waste
            u = np.asarray(spec_node["u"])
            w = np.asarray(kernel)
            # same matrix view as layers/weight_norm.py: (out, rest)
            w_mat = w.reshape(-1, w.shape[-1]).T
            v = w_mat.T @ u
            v = v / (np.linalg.norm(v) + eps)
            sigma = u @ (w_mat @ v)
            entry = {
                "weight_norm": float(np.linalg.norm(w)),
                "sigma": float(sigma),
                "grad_norm": 0.0,
            }
            gk = gnode.get("kernel") if isinstance(gnode, dict) else None
            if gk is not None:
                entry["grad_norm"] = float(np.linalg.norm(np.asarray(gk)))
            stats["/".join(path)] = entry
        for k, v in spec_node.items():
            if isinstance(v, dict):
                walk(v, path + [k])

    walk(spectral, [])
    return stats


@master_only
def write_weight_stats(prefix, params, spectral, step, grads=None):
    """Log per-layer spectral stats as TB scalars (ref: meters.py:31-51)."""
    for layer, entry in get_weight_stats(params, spectral, grads).items():
        for stat, value in entry.items():
            write_summary(f"{prefix}/{layer}/{stat}", value, step)

"""Checkpoint retrieval + misc IO (ref: imaginaire/utils/io.py).

The reference fetches pretrained checkpoints from Google Drive
(``get_checkpoint(path, drive_id)``). TPU pods usually run with no
general egress, so resolution order here is: existing local file ->
$IMAGINAIRE_CHECKPOINT_ROOT mirror -> optional download via
``gdown``/``urllib`` when the environment allows it -> a loud error
explaining how to provision the file offline.
"""

from __future__ import annotations

import os

CHECKPOINT_ROOT_ENV = "IMAGINAIRE_CHECKPOINT_ROOT"


def get_checkpoint(checkpoint_path, url_or_id=""):
    """(ref: io.py get_checkpoint). Returns a local path to the file."""
    if os.path.exists(checkpoint_path):
        return checkpoint_path
    mirror_root = os.environ.get(CHECKPOINT_ROOT_ENV)
    if mirror_root:
        mirrored = os.path.join(mirror_root,
                                os.path.basename(checkpoint_path))
        if os.path.exists(mirrored):
            return mirrored
    if url_or_id:
        os.makedirs(os.path.dirname(checkpoint_path) or ".", exist_ok=True)
        try:
            if url_or_id.startswith("http"):
                import urllib.request

                urllib.request.urlretrieve(url_or_id, checkpoint_path)
            else:  # Google Drive file id (the reference's convention)
                import gdown

                gdown.download(id=url_or_id, output=checkpoint_path,
                               quiet=False)
            if os.path.exists(checkpoint_path):
                return checkpoint_path
        except Exception as e:  # no egress / missing gdown
            raise FileNotFoundError(
                f"Could not download {checkpoint_path!r} ({e}). This "
                "environment likely has no network egress: provision the "
                "file manually and either place it at that path or set "
                f"${CHECKPOINT_ROOT_ENV} to a directory containing it."
            ) from e
    raise FileNotFoundError(
        f"Checkpoint {checkpoint_path!r} not found and no source given; "
        f"place the file there or set ${CHECKPOINT_ROOT_ENV}.")


def save_pilimage_in_jpeg(fullname, output_img):
    """(ref: io.py save_pilimage_in_jpeg)."""
    os.makedirs(os.path.dirname(fullname), exist_ok=True)
    output_img.save(fullname, "JPEG", quality=99)

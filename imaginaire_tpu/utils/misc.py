"""Small tensor utilities (ref: imaginaire/utils/misc.py).

NHWC throughout. The reference's to_cuda/to_half family is replaced by
dtype casts + device placement handled by jit; what remains useful on TPU
is imagenet normalization, label splitting, and resize wrappers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# torchvision ImageNet statistics (ref: utils/misc.py apply_imagenet_normalization).
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def apply_imagenet_normalization(x):
    """Map [-1, 1] images to imagenet-normalized (ref: utils/misc.py:~200).

    Args:
        x: (..., H, W, C>=3) in [-1, 1]. Only the first 3 channels are kept
           (the fork's 4-channel RGBA hack, ref: losses/perceptual.py:97).
    """
    x = x[..., :3]
    x = (x + 1.0) * 0.5
    mean = jnp.asarray(IMAGENET_MEAN, dtype=x.dtype)
    std = jnp.asarray(IMAGENET_STD, dtype=x.dtype)
    return (x - mean) / std


def resize_bilinear(x, hw):
    """Bilinear resize of NHWC batch to (H, W)."""
    n, _, _, c = x.shape
    return jax.image.resize(x, (n, hw[0], hw[1], c), method="bilinear")


def resize_nearest(x, hw):
    n, _, _, c = x.shape
    return jax.image.resize(x, (n, hw[0], hw[1], c), method="nearest")


def upsample_2x(x, method="nearest"):
    """2x spatial upsample for NHWC tensors."""
    b, h, w, c = x.shape
    return jax.image.resize(x, (b, 2 * h, 2 * w, c), method=method)


def downsample_2x(x, method="bilinear"):
    n, h, w, c = x.shape
    return jax.image.resize(x, (n, h // 2, w // 2, c), method=method)


def split_labels(labels, label_lengths):
    """Split a concatenated one-hot label tensor back into named parts
    (ref: utils/misc.py:17-41). labels: (..., C) channel-last."""
    out = {}
    start = 0
    for name, length in label_lengths.items():
        out[name] = labels[..., start:start + length]
        start += length
    return out


def to_device(tree):
    """Move numeric leaves to device arrays, passing strings/bytes (e.g.
    the dataset's per-sample 'key' field) through untouched — the jnp
    analogue of the reference's recursive ``to_cuda``
    (ref: utils/misc.py:56-83)."""
    import numpy as np

    def leaf(x):
        if isinstance(x, (str, bytes)):
            return x
        if isinstance(x, (list, tuple)) and x and isinstance(x[0], (str, bytes)):
            return x
        try:
            return jnp.asarray(x)
        except TypeError:
            return x

    return jax.tree_util.tree_map(
        leaf, tree, is_leaf=lambda x: isinstance(x, (str, bytes, list, tuple))
        and not isinstance(x, np.ndarray))


def split_host_leaves(tree):
    """Split a batch dict into (numeric, host) halves with the
    ``numeric_only`` key semantics: the numeric half is safe to
    ``jax.device_put`` (arrays/scalars), the host half carries everything
    that must stay on the host — strings/bytes, per-sample 'key' lists,
    '_'-prefixed host-object entries (wc-vid2vid point-cloud payloads),
    object-dtype arrays. ``merge_host_leaves`` re-zips the halves.

    Used by the device-prefetch pipeline: the numeric half ships to
    device as committed sharded arrays in the producer thread while the
    host half rides alongside untouched.
    """
    import numpy as np

    if not isinstance(tree, dict):
        return tree, None
    numeric, host = {}, {}
    for k, v in tree.items():
        if isinstance(k, str) and k.startswith("_"):
            host[k] = v
        elif isinstance(v, dict):
            sub_num, sub_host = split_host_leaves(v)
            if sub_num:
                numeric[k] = sub_num
            if sub_host:
                host[k] = sub_host
        elif isinstance(v, (str, bytes)):
            host[k] = v
        elif isinstance(v, (list, tuple)):
            host[k] = v
        elif isinstance(v, np.ndarray) and v.dtype == object:
            host[k] = v
        elif isinstance(v, (np.ndarray, int, float, np.number)) \
                or hasattr(v, "dtype"):
            numeric[k] = v
        else:
            host[k] = v
    return numeric, host


def merge_host_leaves(numeric, host):
    """Inverse of ``split_host_leaves``: overlay the host half back onto
    the (device-placed) numeric half. Returns a plain dict tree."""
    if not host:
        return numeric
    out = dict(numeric or {})
    for k, v in host.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = merge_host_leaves(out[k], v)
        else:
            out[k] = v
    return out


def numeric_only(tree):
    """Drop non-array entries (sample keys, filenames) from a data dict so
    the remainder is a valid jit argument. Recurses into dicts only —
    lists are treated as leaves (a batch's 'key' field is a list of str)."""
    if not isinstance(tree, dict):
        return tree
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out[k] = numeric_only(v)
        elif isinstance(v, (str, bytes)):
            continue
        elif isinstance(v, (list, tuple)) and v and isinstance(v[0], (str, bytes)):
            continue
        else:
            out[k] = v
    return out


def to_float(tree):
    return jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), tree)


def to_bf16(tree):
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16) if jnp.issubdtype(a.dtype, jnp.floating) else a,
        tree,
    )


def random_shift(x, key, offset=0.05):
    """Randomly translate each image, reflect-padded
    (ref: utils/misc.py:183-203, a bilinear grid_sample; here integer-pixel
    shifts via reflect pad + per-sample dynamic_slice — jit/vmap friendly,
    no gather grid)."""
    b, h, w, c = x.shape
    mh, mw = max(1, int(offset * h)), max(1, int(offset * w))
    pad = jnp.pad(x, ((0, 0), (mh, mh), (mw, mw), (0, 0)), mode="reflect")
    ky, kx = jax.random.split(key)
    oy = jax.random.randint(ky, (b,), 0, 2 * mh + 1)
    ox = jax.random.randint(kx, (b,), 0, 2 * mw + 1)

    def one(img, y0, x0):
        return jax.lax.dynamic_slice(img, (y0, x0, 0), (h, w, c))

    return jax.vmap(one)(pad, oy, ox)


def gradient_penalty(d_apply, params, images, key):
    """R1-style gradient penalty helper used by MUNIT's optional GP
    (ref: trainers/munit.py gp loss): E[||∇_x D(x)||²]."""

    def d_sum(x):
        out = d_apply(params, x)
        if isinstance(out, (list, tuple)):
            out = sum(jnp.sum(o) for o in out)
        else:
            out = jnp.sum(out)
        return out

    grads = jax.grad(d_sum)(images)
    return jnp.mean(jnp.sum(grads ** 2, axis=tuple(range(1, grads.ndim))))

"""imaginaire_tpu: a TPU-native (JAX/XLA/Pallas) framework for GAN-based
image and video synthesis, with the capabilities of NVIDIA Imaginaire.

Layer map (mirrors SURVEY.md section 1, re-designed TPU-first):

- ``config``/``registry``  : YAML-over-defaults config; string-keyed component registry.
- ``parallel``             : device mesh, sharding rules, collectives (replaces
                             torch.distributed / DDP; ref: imaginaire/utils/distributed.py).
- ``ops``                  : Pallas kernels + jnp reference implementations for the
                             reference's CUDA extensions (resample2d, channelnorm,
                             correlation; ref: imaginaire/third_party/*).
- ``layers``               : conv/residual block family with the ``order`` micro-DSL,
                             activation norms (SPADE/AdaIN/...), weight norms
                             (ref: imaginaire/layers/*).
- ``models``               : generators + discriminators for the 9 algorithms
                             (ref: imaginaire/generators, imaginaire/discriminators).
- ``losses``               : GAN/perceptual/feature-matching/KL/flow losses
                             (ref: imaginaire/losses/*).
- ``optim``                : optax-based optimizer factory incl. Fromage/Madam and
                             lr schedules (ref: imaginaire/optimizers, utils/trainer.py).
- ``data``                 : config-driven multi-type datasets, folder/shard backends,
                             augmentation (ref: imaginaire/datasets, utils/data.py).
- ``trainers``             : functional GAN training harness; jit-compiled sharded
                             train steps (ref: imaginaire/trainers/*).
- ``evaluation``           : FID/KID/PRDC (ref: imaginaire/evaluation/*).

All array layouts are NHWC (TPU-native), not the reference's NCHW.
"""

__version__ = "0.1.0"

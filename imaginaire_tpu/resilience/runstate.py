"""Run-state sidecar: the host-side training state a bit-exact resume
needs beyond the device pytree (ISSUE 7).

The checkpoint's device state already carries the RNG key chain and the
step counters; what the pointer-file restart used to *silently reset*
was everything host-side: the mid-epoch data position (the epoch's
shuffle is seeded, but the batches-consumed offset was lost — a resumed
run replayed the epoch from batch 0), the HealthMonitor's EWMA/breach
history, and the telemetry ring. ``<ckpt>.runstate.json`` captures them
at save time; ``BaseTrainer.load_checkpoint`` replays them on resume
and the train loop fast-forwards the loader by ``batch_in_epoch``.

JSON (not orbax) on purpose: the payload is a few KB of host floats,
must stay readable when the array data is corrupt (the fallback scan
reads candidates' run state), and a schema change must never invalidate
the array tree.
"""

from __future__ import annotations

import json
import logging
import os

logger = logging.getLogger(__name__)

RUNSTATE_VERSION = 1
_SUFFIX = ".runstate.json"


def runstate_path(checkpoint_path, process_index=0, epoch=None):
    """Process 0's sidecar keeps the legacy ``.runstate.json`` name
    (single-host checkpoints stay byte-compatible); other hosts get
    ``.runstate.p<i>.json`` (ISSUE 8: the monitor/telemetry halves of
    the run state are per-host — restoring process 3 with process 0's
    EWMA history would be wrong, and before this every non-master
    host silently lost its half).

    After an elastic resize (ISSUE 13) process indices are REMAPPED —
    the process now called p1 may be the host that was p2 when the
    previous sidecar was written. Sidecars from a resized pod
    (membership epoch > 0) are therefore keyed by epoch AND rank:
    ``.runstate.e<E>.p<i>.json`` — an (epoch, rank) pair is stable
    where a bare rank is not."""
    if epoch is None:
        from imaginaire_tpu.resilience.cluster import membership_epoch

        epoch = membership_epoch()
    if epoch:
        return (f"{checkpoint_path}.runstate.e{int(epoch)}"
                f".p{int(process_index)}.json")
    if process_index:
        return f"{checkpoint_path}.runstate.p{int(process_index)}.json"
    return str(checkpoint_path) + _SUFFIX


def build_runstate(epoch, iteration, batch_in_epoch, monitor=None,
                   telemetry_state=None):
    return {
        "version": RUNSTATE_VERSION,
        "epoch": int(epoch),
        "iteration": int(iteration),
        "batch_in_epoch": int(max(batch_in_epoch, 0)),
        "monitor": monitor or {},
        "telemetry": telemetry_state or {},
    }


def write_runstate(checkpoint_path, runstate):
    """Per-host sidecar write (ISSUE 8: every process persists its OWN
    host-side state — process 0 under the legacy name, process i under
    ``.runstate.p<i>.json``, epoch-keyed after a resize); failures
    degrade to a warning (a missing runstate means a coarse resume,
    never a failed save).

    In a resized pod (epoch > 0) the master ALSO writes the legacy
    ``.runstate.json``: its epoch/iteration/batch position is
    cluster-wide truth, and keeping the legacy name current means any
    future membership — whatever epoch it runs at — can fall back to
    it when its own (epoch, rank) sidecar does not exist."""
    from imaginaire_tpu.parallel.mesh import get_rank
    from imaginaire_tpu.resilience.cluster import membership_epoch

    rank = get_rank()
    epoch = membership_epoch()
    path = runstate_path(checkpoint_path, rank, epoch=epoch)
    targets = [path]
    if epoch and rank == 0:
        targets.append(runstate_path(checkpoint_path, 0, epoch=0))
    try:
        from imaginaire_tpu.resilience.retry import retry_call

        def _write():
            for target in targets:
                tmp = target + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(runstate, f, indent=1, default=str)
                os.replace(tmp, target)

        retry_call(_write, label="runstate_write")
        return path
    except Exception as e:  # noqa: BLE001 — never fail a save over this
        logger.warning("runstate sidecar write failed for %s: %s",
                       checkpoint_path, e)
        return None


def read_runstate(checkpoint_path, process_index=None):
    """The saved run state for this host, or None (legacy checkpoint /
    unreadable). A non-zero process whose own sidecar is missing (a
    checkpoint written before the pod grew, or by fewer hosts) falls
    back to the master sidecar — the epoch/iteration/batch position in
    it is cluster-wide truth; only the monitor/telemetry halves are
    per-host color."""
    if process_index is None:
        from imaginaire_tpu.parallel.mesh import get_rank

        process_index = get_rank()
    try:
        # elastic shrink leftovers (ISSUE 11): sidecars for process
        # indices the pod no longer has are expected after a resize —
        # name them once and ignore them (never crash, never restore
        # another world's host-side state)
        from imaginaire_tpu.resilience.integrity import orphan_sidecars

        orphans = orphan_sidecars(checkpoint_path)
        if orphans:
            logger.warning(
                "ignoring %d orphan runstate sidecar(s) from a larger "
                "world (elastic shrink): %s", len(orphans),
                ", ".join(os.path.basename(p) for p in orphans))
    except Exception:  # noqa: BLE001 — advisory only
        pass
    from imaginaire_tpu.resilience.cluster import membership_epoch

    epoch = membership_epoch()
    # read order (ISSUE 13): this membership's own (epoch, rank)
    # sidecar first; then — a checkpoint written by a DIFFERENT
    # membership (pre-resize, or a world this rank wasn't part of) —
    # the legacy master sidecar, whose epoch/iteration/batch position
    # is cluster-wide truth. The remap fallback is observable:
    # ``resilience/runstate_remap`` names what was wanted and what was
    # used, so a resumed-after-resize run carries the evidence.
    own = runstate_path(checkpoint_path, int(process_index), epoch=epoch)
    candidates = [own, runstate_path(checkpoint_path, 0, epoch=0)]
    for path in dict.fromkeys(candidates):
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("unreadable runstate sidecar %s: %s (resuming "
                           "with a coarse epoch restart)", path, e)
            return None
        if path != own:
            _emit_runstate_remap(own, path, epoch, int(process_index))
        return payload
    return None


def _emit_runstate_remap(wanted, used, epoch, process_index):
    """Meta event for a cross-membership runstate fallback: this rank's
    own (epoch, rank) sidecar was absent and the master's cluster-wide
    record stood in — expected right after a resize, worth flagging if
    it persists."""
    logger.info("runstate remap: %s absent, using %s (membership epoch "
                "%d, process %d)", os.path.basename(wanted),
                os.path.basename(used), epoch, process_index)
    try:
        from imaginaire_tpu import telemetry

        telemetry.get().meta(
            "resilience/runstate_remap",
            wanted=os.path.basename(wanted),
            used=os.path.basename(used),
            membership_epoch=int(epoch),
            process_index=int(process_index))
    except Exception:  # noqa: BLE001 — advisory only
        pass

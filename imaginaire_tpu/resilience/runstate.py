"""Run-state sidecar: the host-side training state a bit-exact resume
needs beyond the device pytree (ISSUE 7).

The checkpoint's device state already carries the RNG key chain and the
step counters; what the pointer-file restart used to *silently reset*
was everything host-side: the mid-epoch data position (the epoch's
shuffle is seeded, but the batches-consumed offset was lost — a resumed
run replayed the epoch from batch 0), the HealthMonitor's EWMA/breach
history, and the telemetry ring. ``<ckpt>.runstate.json`` captures them
at save time; ``BaseTrainer.load_checkpoint`` replays them on resume
and the train loop fast-forwards the loader by ``batch_in_epoch``.

JSON (not orbax) on purpose: the payload is a few KB of host floats,
must stay readable when the array data is corrupt (the fallback scan
reads candidates' run state), and a schema change must never invalidate
the array tree.
"""

from __future__ import annotations

import json
import logging
import os

logger = logging.getLogger(__name__)

RUNSTATE_VERSION = 1
_SUFFIX = ".runstate.json"


def runstate_path(checkpoint_path, process_index=0):
    """Process 0's sidecar keeps the legacy ``.runstate.json`` name
    (single-host checkpoints stay byte-compatible); other hosts get
    ``.runstate.p<i>.json`` (ISSUE 8: the monitor/telemetry halves of
    the run state are per-host — restoring process 3 with process 0's
    EWMA history would be wrong, and before this every non-master
    host silently lost its half)."""
    if process_index:
        return f"{checkpoint_path}.runstate.p{int(process_index)}.json"
    return str(checkpoint_path) + _SUFFIX


def build_runstate(epoch, iteration, batch_in_epoch, monitor=None,
                   telemetry_state=None):
    return {
        "version": RUNSTATE_VERSION,
        "epoch": int(epoch),
        "iteration": int(iteration),
        "batch_in_epoch": int(max(batch_in_epoch, 0)),
        "monitor": monitor or {},
        "telemetry": telemetry_state or {},
    }


def write_runstate(checkpoint_path, runstate):
    """Per-host sidecar write (ISSUE 8: every process persists its OWN
    host-side state — process 0 under the legacy name, process i under
    ``.runstate.p<i>.json``); failures degrade to a warning (a missing
    runstate means a coarse resume, never a failed save)."""
    from imaginaire_tpu.parallel.mesh import get_rank

    path = runstate_path(checkpoint_path, get_rank())
    try:
        from imaginaire_tpu.resilience.retry import retry_call

        def _write():
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(runstate, f, indent=1, default=str)
            os.replace(tmp, path)

        retry_call(_write, label="runstate_write")
        return path
    except Exception as e:  # noqa: BLE001 — never fail a save over this
        logger.warning("runstate sidecar write failed for %s: %s",
                       checkpoint_path, e)
        return None


def read_runstate(checkpoint_path, process_index=None):
    """The saved run state for this host, or None (legacy checkpoint /
    unreadable). A non-zero process whose own sidecar is missing (a
    checkpoint written before the pod grew, or by fewer hosts) falls
    back to the master sidecar — the epoch/iteration/batch position in
    it is cluster-wide truth; only the monitor/telemetry halves are
    per-host color."""
    if process_index is None:
        from imaginaire_tpu.parallel.mesh import get_rank

        process_index = get_rank()
    for idx in dict.fromkeys((int(process_index), 0)):
        path = runstate_path(checkpoint_path, idx)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("unreadable runstate sidecar %s: %s (resuming "
                           "with a coarse epoch restart)", path, e)
            return None
    return None

"""Fault-tolerance layer (ISSUE 7): bit-exact resume, checkpoint
integrity + last-good fallback, bounded retries, preemption handling,
and the deterministic chaos harness that keeps those paths tested.

Three pillars (see the sibling modules):

- ``runstate`` / ``preemption`` — checkpoints capture the *full* run
  state (device pytree + host-side monitor/telemetry/data-position
  sidecar), and SIGTERM drains the in-flight step into an emergency
  checkpoint within a deadline before a clean exit (``EXIT_PREEMPTED``).
- ``integrity`` / ``retry`` — per-leaf checksums verified on restore,
  corrupt checkpoints quarantined with automatic fallback to the newest
  verifiable one (``utils/checkpoint.py``), and transient IO retried
  with bounded backoff under ``resilience/*`` telemetry counters.
- ``chaos`` — ``cfg.chaos`` injects SIGTERM / checkpoint corruption /
  IO errors / NaN batches at configured steps, so the recovery paths
  above are exercised by the dryrun ``spade_chaos`` leg and
  ``tests/test_resilience.py``, not just by outages.

``configure(cfg)`` is the single entry point (train.py calls it next to
``telemetry.configure``): it installs the retry policy and the chaos
singleton. ``install_preemption_guard(cfg)`` is separate because only
the training entry point owns signal handlers.
"""

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.resilience import chaos, cluster, elastic
from imaginaire_tpu.resilience.cluster import ClusterDesyncError
from imaginaire_tpu.resilience.elastic import (
    ElasticCoordinator,
    ElasticResize,
    elastic_settings,
)
from imaginaire_tpu.resilience.integrity import (
    CheckpointIntegrityError,
    quarantine_checkpoint,
    tree_checksums,
    verify_tree,
)
from imaginaire_tpu.resilience.preemption import (
    EXIT_ELASTIC_RESTART,
    EXIT_PREEMPTED,
    PreemptionGuard,
    install_preemption_guard,
)
from imaginaire_tpu.resilience.retry import (
    retry_call,
    retry_settings,
    set_default_policy,
)
from imaginaire_tpu.resilience.runstate import (
    build_runstate,
    read_runstate,
    write_runstate,
)

__all__ = [
    "CheckpointIntegrityError",
    "ClusterDesyncError",
    "EXIT_ELASTIC_RESTART",
    "EXIT_PREEMPTED",
    "ElasticCoordinator",
    "ElasticResize",
    "PreemptionGuard",
    "build_runstate",
    "chaos",
    "cluster",
    "configure",
    "elastic",
    "elastic_settings",
    "install_preemption_guard",
    "quarantine_checkpoint",
    "read_runstate",
    "resilience_settings",
    "retry_call",
    "retry_settings",
    "set_default_policy",
    "tree_checksums",
    "verify_tree",
    "write_runstate",
]


def resilience_settings(cfg):
    """Parse the ``cfg.resilience`` group (see config.py defaults)."""
    rcfg = cfg_get(cfg or {}, "resilience", None) or {}
    enabled = bool(cfg_get(rcfg, "enabled", True))
    return {
        "enabled": enabled,
        "checksum": enabled and bool(cfg_get(rcfg, "checksum", True)),
        "verify_on_load": enabled and bool(cfg_get(rcfg,
                                                   "verify_on_load",
                                                   True)),
        "emergency_checkpoint": enabled and bool(
            cfg_get(rcfg, "emergency_checkpoint", True)),
        "emergency_deadline_s": float(
            cfg_get(rcfg, "emergency_deadline_s", 60.0) or 0.0),
        "retry": retry_settings(cfg),
    }


def configure(cfg):
    """Install the process-wide resilience policy: retry defaults from
    ``cfg.resilience.retry``, the chaos singleton from ``cfg.chaos``,
    and the cluster coordination policy from ``cfg.resilience.cluster``
    (timed barriers + preemption voting, ISSUE 8). Returns the parsed
    settings."""
    settings = resilience_settings(cfg)
    set_default_policy(settings["retry"])
    chaos.configure(cfg)
    settings["cluster"] = cluster.configure(cfg)
    return settings

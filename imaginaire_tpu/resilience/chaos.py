"""Deterministic fault injection (chaos harness, ISSUE 7).

Recovery paths that only run during real outages are untested product
code. The ``cfg.chaos`` group injects faults at *configured steps* so
the dryrun chaos leg and the resilience tests exercise the exact
machinery production relies on:

- ``sigterm_at_step``            — deliver SIGTERM to this process after
  that iteration completes (exercises the preemption guard + emergency
  checkpoint + resume).
- ``corrupt_checkpoint_at_step`` — flip bytes inside the checkpoint
  committed at that iteration (exercises integrity verification,
  quarantine, and last-good fallback on the next resume).
- ``nan_batch_at_step``          — poison the batch's images with NaN at
  that iteration (exercises the in-graph non-finite guard + triage).
- ``io_error_at_step``           — raise a one-shot ``ChaosIOError``
  from the configured site (``io_error_site``: ``flow_store`` |
  ``loader`` | ``feature_store``) on that site's Nth access (exercises
  the bounded-retry wrapper).
- ``degrade_eval_at_sweep``      — inflate measured FID from that eval
  sweep onward (exercises the ISSUE-18 regression sentinel and the
  ``--max-quality-regressions`` gate). Persistent rather than one-shot:
  the sentinel requires K consecutive bad sweeps.

Every injection is one-shot per (kind, step) and emits a
``chaos/<kind>`` telemetry meta event, so a chaos run's jsonl records
exactly which faults fired where. Disabled (the default) the singleton
is inert — every ``maybe_*`` is an attribute check and a return.
"""

from __future__ import annotations

import logging
import os
import signal

from imaginaire_tpu.config import cfg_get

logger = logging.getLogger(__name__)


class ChaosIOError(IOError):
    """The injected transient IO failure (retry wrappers recover it)."""


def chaos_settings(cfg):
    ccfg = cfg_get(cfg or {}, "chaos", None) or {}

    def step(key):
        value = cfg_get(ccfg, key, None)
        return None if value is None else int(value)

    return {
        "enabled": bool(cfg_get(ccfg, "enabled", False)),
        "sigterm_at_step": step("sigterm_at_step"),
        "corrupt_checkpoint_at_step": step("corrupt_checkpoint_at_step"),
        "nan_batch_at_step": step("nan_batch_at_step"),
        "io_error_at_step": step("io_error_at_step"),
        "io_error_site": str(cfg_get(ccfg, "io_error_site",
                                     "flow_store")),
        # distributed chaos (ISSUE 8): one-of-N injections gated on the
        # process index, driving the coordinated-drain and timed-barrier
        # recovery paths in multi-process runs
        "kill_at_step": step("kill_at_step"),
        "kill_process_index": int(cfg_get(ccfg, "kill_process_index", 0)
                                  or 0),
        "stall_at_step": step("stall_at_step"),
        "stall_process_index": int(cfg_get(ccfg, "stall_process_index",
                                           0) or 0),
        "stall_duration_s": float(cfg_get(ccfg, "stall_duration_s",
                                          30.0) or 0.0),
        # divergence injection (ISSUE 17): perturb one process's
        # OBSERVED loss stream at the podview digest boundary — the
        # measurable signature of a desynced SPMD replica (an in-graph
        # perturbation would be homogenized by the healthy pod's
        # cross-host all-reduce before the loss scalar exists)
        "diverge_loss_at_step": step("diverge_loss_at_step"),
        "diverge_process_index": int(
            cfg_get(ccfg, "diverge_process_index", 0) or 0),
        "diverge_scale": float(cfg_get(ccfg, "diverge_scale", 1e-3)
                               or 1e-3),
        # quality degradation (ISSUE 18): inflate measured FID from the
        # Nth eval sweep (1-based) onward — persistent, because the
        # regression sentinel needs K consecutive bad sweeps
        "degrade_eval_at_sweep": step("degrade_eval_at_sweep"),
        "degrade_eval_scale": float(cfg_get(ccfg, "degrade_eval_scale",
                                            1.0) or 1.0),
        # serving latency spike (ISSUE 20): sleep inside the execute
        # span of the Nth served request (1-based ordinal) onward for
        # ``delay_serve_count`` requests — drives the SLO burn-rate
        # red path of the serving dryrun leg
        "delay_serve_at_request": step("delay_serve_at_request"),
        "delay_serve_ms": float(cfg_get(ccfg, "delay_serve_ms", 50.0)
                                or 0.0),
        "delay_serve_count": int(cfg_get(ccfg, "delay_serve_count", 1)
                                 or 1),
    }


def corrupt_checkpoint_bytes(path, n_bytes=64):
    """Flip ``n_bytes`` in the middle of the largest file under a
    checkpoint directory (or the file itself) — the byte-corruption
    primitive the harness injects and the integrity layer must catch.
    Returns the corrupted file path, or None when nothing was found."""
    path = str(path)
    target = path
    if os.path.isdir(path):
        largest, size = None, -1
        for dirpath, _, files in os.walk(path):
            for name in files:
                p = os.path.join(dirpath, name)
                try:
                    s = os.path.getsize(p)
                except OSError:
                    continue
                if s > size:
                    largest, size = p, s
        target = largest
    if target is None or not os.path.isfile(target):
        return None
    size = os.path.getsize(target)
    if size == 0:
        return None
    n = min(int(n_bytes), size)
    offset = max((size - n) // 2, 0)
    with open(target, "r+b") as f:
        f.seek(offset)
        chunk = f.read(n)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))
    logger.warning("chaos: corrupted %d bytes at offset %d of %s", n,
                   offset, target)
    return target


class ChaosMonkey:
    def __init__(self, settings=None):
        self.settings = settings or chaos_settings({})
        self.enabled = bool(self.settings["enabled"])
        self._fired = set()
        self._site_calls = {}

    # ------------------------------------------------------------ firing

    def _should(self, kind, at_step, step):
        if not self.enabled or at_step is None or step != at_step:
            return False
        token = (kind, int(step))
        if token in self._fired:
            return False
        self._fired.add(token)
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if tm.enabled:
            tm.meta(f"chaos/{kind}", step=int(step))
        logger.warning("chaos: injecting %s at step %s", kind, step)
        return True

    # ------------------------------------------------------- injection API

    def maybe_sigterm(self, step):
        """Deliver SIGTERM to this process at the configured step."""
        if self._should("sigterm", self.settings["sigterm_at_step"],
                        step):
            os.kill(os.getpid(), signal.SIGTERM)

    @staticmethod
    def _my_process_index():
        try:
            from imaginaire_tpu.resilience import cluster

            return cluster.process_index()
        except Exception:  # noqa: BLE001 — no backend yet
            return 0

    def maybe_kill(self, step):
        """Kill-one-of-N: deliver SIGTERM to THIS process only when its
        index matches ``kill_process_index`` (ISSUE 8). The surviving
        hosts must learn of the drain through the per-step preemption
        vote and ALL exit ``EXIT_PREEMPTED`` behind one coordinated
        emergency checkpoint — the recovery path this injection
        exists to keep tested."""
        if self.settings["kill_at_step"] is None \
                or self._my_process_index() \
                != self.settings["kill_process_index"]:
            return
        if self._should("kill", self.settings["kill_at_step"], step):
            os.kill(os.getpid(), signal.SIGTERM)

    def maybe_stall(self, step):
        """Stall-one-of-N: freeze THIS process for ``stall_duration_s``
        when its index matches (ISSUE 8). The other hosts' next timed
        rendezvous (per-step preemption vote, checkpoint entry barrier)
        must raise ``ClusterDesyncError`` naming this process instead
        of hanging the pod."""
        if self.settings["stall_at_step"] is None \
                or self._my_process_index() \
                != self.settings["stall_process_index"]:
            return
        if self._should("stall", self.settings["stall_at_step"], step):
            import time

            dur = self.settings["stall_duration_s"]
            logger.warning("chaos: stalling process %d for %.1fs",
                           self._my_process_index(), dur)
            time.sleep(dur)

    def maybe_nan_batch(self, data, step):
        """Return ``data`` with its ``images`` leaf poisoned to NaN at
        the configured step (shallow copy; other leaves untouched)."""
        if not self._should("nan_batch",
                            self.settings["nan_batch_at_step"], step):
            return data
        if not isinstance(data, dict) or "images" not in data:
            return data
        import jax.numpy as jnp

        images = data["images"]
        poisoned = type(data)(data)
        poisoned["images"] = jnp.full(images.shape, jnp.nan,
                                      dtype=images.dtype)
        return poisoned

    def maybe_corrupt_checkpoint(self, path, step):
        """Corrupt the checkpoint committed at the configured step."""
        if self._should("corrupt_checkpoint",
                        self.settings["corrupt_checkpoint_at_step"],
                        step):
            corrupt_checkpoint_bytes(path)

    def maybe_perturb_losses(self, losses, step):
        """Diverge-one-of-N: return a perturbed copy of THIS process's
        observed loss scalars when its index matches (ISSUE 17). The
        podview divergence sentinel must trip on the resulting crc
        mismatch within ``digest_every_n_steps`` steps."""
        at = self.settings["diverge_loss_at_step"]
        if at is None or self._my_process_index() \
                != self.settings["diverge_process_index"]:
            return losses
        if not self._should("diverge_loss", at, step):
            return losses
        scale = self.settings["diverge_scale"]
        return {k: float(v) * (1.0 + scale) + scale
                for k, v in (losses or {}).items()}

    def maybe_degrade_eval(self, fid, sweep_index):
        """Quality degradation (ISSUE 18): return ``fid`` inflated by
        ``degrade_eval_scale`` (relative) from sweep
        ``degrade_eval_at_sweep`` onward — NOT one-shot, because the
        regression sentinel only fires on K consecutive bad sweeps; a
        single degraded point models measurement noise, a persistent
        one models a regressed model. The ``chaos/degrade_eval`` meta
        is still emitted exactly once, at the first degraded sweep."""
        at = self.settings["degrade_eval_at_sweep"]
        if not self.enabled or at is None or sweep_index < at:
            return fid
        self._should("degrade_eval", at, at)  # one-shot meta marker
        return float(fid) * (1.0 + self.settings["degrade_eval_scale"])

    def maybe_delay_serve(self, ordinal):
        """Serving latency spike (ISSUE 20): sleep ``delay_serve_ms``
        inside the engine's execute span for requests
        ``[delay_serve_at_request, delay_serve_at_request +
        delay_serve_count)`` (1-based served-request ordinal). A run of
        consecutive slow requests — not a single outlier — is what an
        SLO burn-rate gate must go red on; the ``chaos/delay_serve``
        meta is emitted once per delayed request so the jsonl names
        exactly which requests were poisoned."""
        at = self.settings["delay_serve_at_request"]
        if not self.enabled or at is None \
                or not at <= ordinal < at + self.settings[
                    "delay_serve_count"]:
            return
        if self._should("delay_serve", ordinal, ordinal):
            import time

            time.sleep(self.settings["delay_serve_ms"] / 1e3)

    def maybe_io_error(self, site):
        """Raise a one-shot ``ChaosIOError`` on the configured site's
        Nth access (sites count their own calls — loader/flow-store
        reads have no global step)."""
        if not self.enabled or self.settings["io_error_at_step"] is None \
                or site != self.settings["io_error_site"]:
            return
        call = self._site_calls.get(site, 0)
        self._site_calls[site] = call + 1
        if call == self.settings["io_error_at_step"] \
                and self._should(f"io_error/{site}", call, call):
            raise ChaosIOError(
                f"chaos-injected transient IO failure at {site} access "
                f"#{call}")


class _NullChaos:
    """Inert default: every ``maybe_*`` returns immediately."""

    enabled = False

    def maybe_sigterm(self, step):
        pass

    def maybe_kill(self, step):
        pass

    def maybe_stall(self, step):
        pass

    def maybe_nan_batch(self, data, step):
        return data

    def maybe_corrupt_checkpoint(self, path, step):
        pass

    def maybe_perturb_losses(self, losses, step):
        return losses

    def maybe_degrade_eval(self, fid, sweep_index):
        return fid

    def maybe_delay_serve(self, ordinal):
        pass

    def maybe_io_error(self, site):
        pass


_NULL = _NullChaos()
_CHAOS = _NULL


def get():
    """The process chaos singleton (inert until ``configure`` opts in)."""
    return _CHAOS


def configure(cfg):
    """Install the chaos singleton from ``cfg.chaos``; disabled configs
    install the inert null object."""
    global _CHAOS
    settings = chaos_settings(cfg)
    _CHAOS = ChaosMonkey(settings) if settings["enabled"] else _NULL
    if settings["enabled"]:
        logger.warning("chaos harness ENABLED: %s",
                       {k: v for k, v in settings.items()
                        if v not in (None, False)})
    return _CHAOS

"""Checkpoint integrity: per-leaf checksums, restore-time verification,
and quarantine of corrupt checkpoints (ISSUE 7).

A preempted/killed run must never come back up on silently-corrupted
state: a half-written array shard restores as garbage that trains for
hours before the loss explodes. ``tree_checksums`` fingerprints every
leaf of the saved payload (crc32 over the raw bytes + shape + dtype);
the record rides the checkpoint's sidecar (``.partition.json`` when a
partition plan is active, ``.integrity.json`` otherwise — see
``utils/checkpoint.py``) and ``verify_tree`` replays it against the
restored arrays. A mismatch raises ``CheckpointIntegrityError``; the
caller quarantines the checkpoint (``quarantine_checkpoint`` renames it
``*.corrupt`` so scans skip it forever) and falls back to the newest
checkpoint that does verify.

Leaf matching is by pytree key path; when the restored structure names
leaves differently (orbax restores namedtuple optimizer states as plain
containers when no target is given), verification falls back to
comparing the multiset of (dtype, shape, crc) records — byte corruption
still cannot hide, only a swap of two bit-identical leaves could.
"""

from __future__ import annotations

import logging
import os
import zlib

import numpy as np

logger = logging.getLogger(__name__)

INTEGRITY_VERSION = 1
# sidecar files that ride a checkpoint directory and must follow it
# through quarantine (and die with it in GC)
SIDECAR_SUFFIXES = (".partition.json", ".integrity.json",
                    ".runstate.json", ".ema_bn.pkl")


def sidecar_files(path):
    """Existing sidecar paths for a checkpoint: the fixed suffixes plus
    the per-host ``.runstate.p<i>.json`` family (ISSUE 8) and its
    epoch-keyed ``.runstate.e<E>.p<i>.json`` variant from resized pods
    (ISSUE 13) — quarantine and GC must move/delete the whole set,
    discovered by glob so a pod of any size is covered. After an
    elastic shrink the family can name MORE processes than the pod now
    has; those orphans still die with the checkpoint in GC, but
    quarantine leaves them in place (see ``orphan_sidecars``)."""
    import glob as _glob

    path = str(path)
    out = [path + s for s in SIDECAR_SUFFIXES
           if os.path.exists(path + s)]
    out.extend(sorted(_glob.glob(_glob.escape(path)
                                 + ".runstate.p*.json")))
    out.extend(sorted(_glob.glob(_glob.escape(path)
                                 + ".runstate.e*.p*.json")))
    return out


def runstate_index(sidecar_path):
    """Process index of a per-host ``.runstate.p<i>.json`` (or
    epoch-keyed ``.runstate.e<E>.p<i>.json``, ISSUE 13) sidecar path,
    or None for every other sidecar kind."""
    import re

    m = re.search(r"\.runstate\.(?:e\d+\.)?p(\d+)\.json$",
                  str(sidecar_path))
    return int(m.group(1)) if m else None


def runstate_epoch(sidecar_path):
    """Membership epoch of an epoch-keyed runstate sidecar; 0 for the
    legacy unkeyed family, None for non-runstate sidecars."""
    import re

    s = str(sidecar_path)
    m = re.search(r"\.runstate\.e(\d+)\.p\d+\.json$", s)
    if m:
        return int(m.group(1))
    if re.search(r"\.runstate(?:\.p\d+)?\.json$", s):
        return 0
    return None


def orphan_sidecars(path, world_size=None):
    """Per-host runstate sidecars whose process index no longer exists
    (``i >= world_size``): an elastic shrink (ISSUE 11) leaves the dead
    hosts' sidecars behind on checkpoints written by the larger world.
    They are harmless — resume never reads them (each live process
    reads its own index, falling back to p0) — so readers warn and
    ignore; only checkpoint GC retires them, together with the
    checkpoint they ride."""
    if world_size is None:
        try:
            from imaginaire_tpu.parallel.mesh import get_world_size

            world_size = get_world_size()
        except Exception:  # noqa: BLE001 — no backend: nothing orphan
            return []
    out = []
    for sidecar in sidecar_files(path):
        idx = runstate_index(sidecar)
        if idx is not None and idx >= int(world_size):
            out.append(sidecar)
    return out


class CheckpointIntegrityError(RuntimeError):
    """A restored checkpoint's bytes do not match its saved checksums."""


def _leaf_record(leaf):
    """(record dict, skip reason). Non-addressable / object leaves are
    skipped with a reason instead of forcing a gather — EXCEPT fully
    replicated multi-process arrays (the pod DP steady state, ISSUE 8):
    the local replica IS the global value, so per-leaf checksums keep
    covering pod checkpoints instead of degrading to file digests
    only."""
    if not getattr(leaf, "is_fully_addressable", True):
        if getattr(leaf, "is_fully_replicated", False):
            try:
                arr = np.asarray(leaf.addressable_data(0))
                if arr.dtype == object:
                    return None, "object_dtype"
                # ascontiguousarray promotes 0-d to (1,) — record the
                # promoted shape, matching what the addressable path
                # (and restore-time verification) computes
                arr = np.ascontiguousarray(arr)
                return {
                    "crc": int(zlib.crc32(arr.tobytes())),
                    "shape": [int(s) for s in arr.shape],
                    "dtype": str(arr.dtype),
                }, None
            except Exception:  # noqa: BLE001
                return None, "not_fully_addressable"
        return None, "not_fully_addressable"
    try:
        import jax

        arr = np.asarray(jax.device_get(leaf))
    except Exception:  # noqa: BLE001 — fall back to a plain asarray
        try:
            arr = np.asarray(leaf)
        except Exception:  # noqa: BLE001
            return None, "not_array"
    if arr.dtype == object:
        return None, "object_dtype"
    arr = np.ascontiguousarray(arr)
    return {
        "crc": int(zlib.crc32(arr.tobytes())),
        "shape": [int(s) for s in arr.shape],
        "dtype": str(arr.dtype),
    }, None


def tree_checksums(tree):
    """Per-leaf crc32 record for a state pytree.

    Returns ``{"version", "algo", "leaves": {keypath: record},
    "skipped": {keypath: reason}}``. The whole-tree crc (``tree_crc``,
    order-independent) gives run logs a one-number state identity.
    """
    import jax

    leaves, skipped = {}, {}
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        record, reason = _leaf_record(leaf)
        if record is None:
            skipped[key] = reason
        else:
            leaves[key] = record
    tree_crc = 0
    for rec in sorted((r["crc"] for r in leaves.values())):
        tree_crc = zlib.crc32(str(rec).encode(), tree_crc)
    return {"version": INTEGRITY_VERSION, "algo": "crc32",
            "leaves": leaves, "skipped": skipped,
            "tree_crc": int(tree_crc), "n_leaves": len(leaves)}


def verify_tree(tree, integrity, context=""):
    """Raise ``CheckpointIntegrityError`` when ``tree``'s bytes diverge
    from a ``tree_checksums`` record; no-op for None/empty records
    (legacy checkpoints saved before ISSUE 7)."""
    if not integrity or not integrity.get("leaves"):
        return None
    got = tree_checksums(tree)
    want_leaves = integrity["leaves"]
    mismatches = []
    if set(got["leaves"]) == set(want_leaves):
        for key, want in want_leaves.items():
            have = got["leaves"][key]
            for field in ("crc", "shape", "dtype"):
                if have[field] != want[field]:
                    mismatches.append(
                        f"{key}: {field} {want[field]} -> {have[field]}")
                    break
    else:
        # structure renamed (e.g. no-target restore flattens optimizer
        # namedtuples): byte corruption still cannot hide from the
        # (dtype, shape, crc) multiset
        def multiset(leaves):
            return sorted((r["dtype"], tuple(r["shape"]), r["crc"])
                          for r in leaves.values())

        if multiset(got["leaves"]) != multiset(want_leaves):
            want_set = multiset(want_leaves)
            got_set = multiset(got["leaves"])
            missing = [r for r in want_set if r not in got_set]
            mismatches.append(
                f"leaf multiset differs ({len(missing)} saved leaf "
                f"record(s) unmatched, e.g. {missing[:3]})")
    if mismatches:
        raise CheckpointIntegrityError(
            f"checkpoint integrity verification failed"
            f"{' for ' + context if context else ''}: "
            + "; ".join(mismatches[:8])
            + (f" (+{len(mismatches) - 8} more)"
               if len(mismatches) > 8 else ""))
    return got


def file_digests(root):
    """Raw-byte (size, crc32) records for every file under a committed
    checkpoint directory, keyed by relative path.

    This is the FIRST verification layer: restoring a byte-corrupted
    checkpoint is not merely wrong, it is *dangerous* — the serializer
    decodes compressed chunks, and feeding corrupt bytes to a native
    decoder can corrupt the heap before any leaf checksum gets a chance
    to run (observed: NaN params + delayed SIGSEGV after restoring a
    chaos-corrupted checkpoint). ``verify_files`` replays these records
    with plain Python reads, so corruption is caught before the
    deserializer touches a single byte."""
    out = {}
    root = str(root)
    for dirpath, _, files in os.walk(root):
        for name in files:
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            crc, size = 0, 0
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
                    size += len(chunk)
            out[rel] = {"size": size, "crc": int(crc)}
    return out


def verify_files(root, records, context=""):
    """Raise ``CheckpointIntegrityError`` when the on-disk files diverge
    from a ``file_digests`` record; no-op for None/empty (legacy)."""
    if not records:
        return
    mismatches = []
    for rel, want in records.items():
        path = os.path.join(str(root), rel)
        if not os.path.isfile(path):
            mismatches.append(f"{rel}: missing")
            continue
        crc, size = 0, 0
        try:
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
                    size += len(chunk)
        except OSError as e:
            mismatches.append(f"{rel}: unreadable ({e})")
            continue
        if size != want.get("size"):
            mismatches.append(
                f"{rel}: size {want.get('size')} -> {size}")
        elif int(crc) != want.get("crc"):
            mismatches.append(
                f"{rel}: file crc {want.get('crc')} -> {int(crc)}")
    if mismatches:
        raise CheckpointIntegrityError(
            f"checkpoint file verification failed"
            f"{' for ' + context if context else ''} (refusing to "
            f"deserialize corrupt bytes): " + "; ".join(mismatches[:8])
            + (f" (+{len(mismatches) - 8} more)"
               if len(mismatches) > 8 else ""))


def quarantine_checkpoint(path, reason="corrupt"):
    """Rename a corrupt checkpoint (and its sidecars) out of the resume
    scan: ``<ckpt>`` -> ``<ckpt>.corrupt`` (numbered on collision).
    Returns the quarantine path, or None when nothing was moved.

    Multi-process (ISSUE 8): only process 0 renames — on a shared
    checkpoint directory a non-master rename would yank the files out
    from under peers mid-verification; the master's quarantine is
    cluster-wide truth and the resume consensus handles any host that
    raced past it."""
    from imaginaire_tpu import telemetry
    from imaginaire_tpu.parallel.mesh import is_master

    path = str(path)
    if not os.path.exists(path):
        return None
    if not is_master():
        logger.error("corrupt checkpoint %s detected on process >0 "
                     "(%s); master owns the quarantine rename", path,
                     reason)
        tm = telemetry.get()
        if tm.enabled:
            tm.meta("ckpt/quarantine_deferred", checkpoint=path,
                    reason=str(reason))
        return None
    target = path + ".corrupt"
    n = 0
    while os.path.exists(target):
        n += 1
        target = f"{path}.corrupt{n}"
    suffix = target[len(path):]
    try:
        os.replace(path, target)
    except OSError as e:
        logger.error("failed to quarantine corrupt checkpoint %s: %s",
                     path, e)
        return None
    orphans = set(orphan_sidecars(path))
    for sidecar in sidecar_files(path):
        if sidecar in orphans:
            # elastic shrink leftovers (ISSUE 11): a sidecar for a
            # process index the pod no longer has must NOT follow the
            # rename — the numbered-collision suffix of a later
            # quarantine at the same path would disagree with where its
            # checkpoint went. Resume ignores it; GC retires it.
            logger.warning(
                "quarantine: leaving orphan runstate sidecar %s in "
                "place (process index >= current world size — an "
                "elastic shrink left it behind)", sidecar)
            continue
        try:
            os.replace(sidecar, path + suffix + sidecar[len(path):])
        except OSError:  # the data dir moved; sidecars best-effort
            pass
    tm = telemetry.get()
    if tm.enabled:
        tm.meta("ckpt/quarantined", checkpoint=path, quarantine=target,
                reason=str(reason))
        tm.counter("resilience/ckpt_quarantined", 1)
    logger.error("quarantined corrupt checkpoint %s -> %s (%s)", path,
                 target, reason)
    return target

"""SIGTERM/preemption handling: drain the in-flight step, write an
emergency checkpoint within a deadline, exit cleanly (ISSUE 7).

Preemptible TPU slices deliver SIGTERM with a short grace period before
SIGKILL. The guard converts that signal into a cooperative flag the
train loop polls once per iteration: the loop finishes the step it
already dispatched (orbax blocks on the live arrays, so the save *is*
the drain), writes a synchronous emergency checkpoint + run-state
sidecar, shuts the prefetcher producer down, and exits with
``EXIT_PREEMPTED`` so the supervisor knows the run is resumable rather
than failed.

The deadline (``cfg.resilience.emergency_deadline_s``) starts at signal
delivery: if the drain + save has not committed by then, the process
force-exits (``os._exit``) with the same code — the supervisor's
SIGKILL was coming anyway, and a forced exit at least leaves the
previous complete checkpoint and the telemetry trail intact instead of
dying mid-write *after* the pointer moved.
"""

from __future__ import annotations

import logging
import os
import signal
import threading

from imaginaire_tpu.config import cfg_get

logger = logging.getLogger(__name__)

# EX_TEMPFAIL: the conventional "retry me" exit status — distinguishes a
# preempted-but-checkpointed run from a real failure
EXIT_PREEMPTED = 75
# An elastic resize (ISSUE 13) that could not complete in-process
# (re-init failure, shrink below min_world_size, coordinator loss): the
# checkpointed state is intact and the supervisor should relaunch the
# pod at whatever world size it can muster — distinct from 75 so the
# launcher can tell "host preempted, done" from "pod wants a restart".
EXIT_ELASTIC_RESTART = 76


class PreemptionGuard:
    """Cooperative SIGTERM-to-checkpoint bridge for the train loop."""

    def __init__(self, deadline_s=60.0, signals=(signal.SIGTERM,),
                 exit_on_deadline=True):
        self.deadline_s = float(deadline_s or 0.0)
        self.signals = tuple(signals)
        self.exit_on_deadline = bool(exit_on_deadline)
        self._triggered = threading.Event()
        self._timer = None
        self._prev_handlers = {}
        self.signum = None

    # ------------------------------------------------------------ install

    def install(self):
        """Register the handlers (main thread only — signal.signal
        raises elsewhere, in which case the guard stays inert)."""
        try:
            for sig in self.signals:
                self._prev_handlers[sig] = signal.signal(sig,
                                                         self._handler)
        except ValueError:
            logger.warning(
                "preemption guard not installed (not the main thread); "
                "SIGTERM will kill the run without an emergency "
                "checkpoint")
            self._prev_handlers = {}
        return self

    def uninstall(self):
        self.disarm()
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev_handlers = {}

    # ------------------------------------------------------------ handler

    def _handler(self, signum, frame):
        first = not self._triggered.is_set()
        self._triggered.set()
        self.signum = signum
        if not first:
            return  # repeated signals: the drain is already running
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if tm.enabled:
            tm.meta("resilience/preempt_signal", signum=int(signum),
                    deadline_s=self.deadline_s)
            tm.counter("resilience/preemptions", 1)
        logger.warning(
            "signal %d received: draining the in-flight step and "
            "writing an emergency checkpoint (deadline %.1fs)",
            signum, self.deadline_s)
        if self.deadline_s > 0:
            self._timer = threading.Timer(self.deadline_s,
                                          self._deadline_expired)
            self._timer.daemon = True
            self._timer.start()

    @property
    def triggered(self):
        return self._triggered.is_set()

    def trigger_remote(self, flagged=()):
        """Join a drain another host initiated (ISSUE 8): the per-step
        preemption vote observed a peer's SIGTERM flag. Sets the local
        flag and arms the same deadline timer the signal handler would
        — the collective emergency save must not be allowed to wedge
        past the grace period on ANY host."""
        first = not self._triggered.is_set()
        self._triggered.set()
        if not first:
            return
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if tm.enabled:
            tm.meta("resilience/preempt_remote_trigger",
                    flagged=list(flagged), deadline_s=self.deadline_s)
            tm.counter("resilience/preemptions", 1)
        logger.warning(
            "peer process(es) %s flagged preemption: joining the "
            "coordinated drain (deadline %.1fs)",
            list(flagged), self.deadline_s)
        if self.deadline_s > 0:
            self._timer = threading.Timer(self.deadline_s,
                                          self._deadline_expired)
            self._timer.daemon = True
            self._timer.start()

    def disarm(self):
        """Cancel the deadline timer — call once the emergency
        checkpoint has committed."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def reset(self):
        """Clear the drain flag after a drain that did NOT exit (the
        elastic shrink, ISSUE 11): the survivors committed the flagged
        host's collective emergency checkpoint and keep training — a
        sticky flag would re-enter the drain at every later vote."""
        self.disarm()
        self._triggered.clear()
        self.signum = None

    # ----------------------------------------------------------- deadline

    def _deadline_expired(self):
        from imaginaire_tpu import telemetry

        logger.error(
            "emergency-checkpoint deadline (%.1fs) expired before the "
            "drain finished; force-exiting — the pointer still names "
            "the previous complete checkpoint", self.deadline_s)
        tm = telemetry.get()
        try:
            if tm.enabled:
                tm.meta("resilience/preempt_deadline_expired",
                        deadline_s=self.deadline_s)
                tm.flush()
        except Exception:  # noqa: BLE001 — exiting either way
            pass
        if self.exit_on_deadline:
            os._exit(EXIT_PREEMPTED)


def preemption_settings(cfg):
    rcfg = cfg_get(cfg or {}, "resilience", None) or {}
    return {
        "enabled": bool(cfg_get(rcfg, "emergency_checkpoint", True))
        and bool(cfg_get(rcfg, "enabled", True)),
        "deadline_s": float(cfg_get(rcfg, "emergency_deadline_s", 60.0)
                            or 0.0),
    }


def install_preemption_guard(cfg):
    """Build + install a guard from ``cfg.resilience``; None when the
    emergency-checkpoint machinery is disabled."""
    s = preemption_settings(cfg)
    if not s["enabled"]:
        return None
    return PreemptionGuard(deadline_s=s["deadline_s"]).install()

"""Elastic pods: dynamic mesh resize with live state redistribution
(ISSUE 11).

PRs 7-8 made preemption survivable but stop-the-world: one lost host
idles the whole pod until the SAME world size comes back. This module
lets the survivors keep training. On a peer-loss signal (a drain vote
whose flagged host won't return, heartbeat staleness, or a
``ClusterDesyncError`` from a timed collective) the surviving processes
run a consensus round over the coordination-service KV store they
already share, agree on the new topology + resume iteration, tear the
jax distributed runtime down IN-PROCESS, re-initialize it with the
shrunken world on a fresh port, rebuild the mesh/partition plan, and
restore the emergency checkpoint through the existing layout-agnostic
no-target path — optimizer/EMA shards land redistributed under the new
NamedShardings (the portable-collective reshard of arXiv:2112.01075,
reusing PR-6's reshard-on-load instead of inventing a second path).
Scale-up on rejoin is the same flow in reverse, rendezvoused through
``<logdir>/elastic/``.

Three hard-won mechanics (validated against jax 0.4.37 on the CPU pod
harness; see tests/test_elastic.py):

- ``jax.distributed.shutdown()`` HANGS when a peer died abruptly (the
  shutdown barrier waits for everyone) and a second ``initialize``
  refuses to run. ``force_teardown`` instead detaches the old
  client/service from ``distributed.global_state``, shuts the old
  client down on a daemon thread bounded by its ``shutdown_timeout``,
  and deliberately LEAKS the old coordination service — a dead-peer
  error poll on a leaked service is noise; a blocked main thread is an
  outage.
- jax's default missed-heartbeat callback terminates the process —
  exactly wrong for a survivor. Elastic runs init through the raw
  distributed-runtime client with a benign callback, so peer loss is
  an event we *observe*, not one that kills us.
- ``xla_bridge.process_count`` (and friends) are ``lru_cache``'d:
  after re-init the pod would keep reporting the OLD world size.
  Teardown clears the backend table AND those caches.

The per-process virtual device count is fixed at launch
(``--xla_force_host_platform_device_count`` parses once, in C++), so
elastic pods OVER-PROVISION devices per process and keep the *logical*
mesh constant across resizes where possible: a 6-device data mesh is 3
procs x 2 devices before the kill and 2 procs x 3 devices after, and
because the global batch is composed block-contiguously (data/loader
block split) the training math is bit-identical across the transition.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from imaginaire_tpu.config import cfg_get

logger = logging.getLogger(__name__)

TOPOLOGY_FILE = "topology.json"
JOIN_DIR = "join"


class ElasticResize(Exception):
    """Raised out of the train loop to unwind into the supervise loop
    with an agreed ``ResizePlan`` (train.py catches it, applies the
    plan, and re-enters the loop — nothing about it is an error)."""

    def __init__(self, plan):
        super().__init__(f"elastic resize -> world {plan.world_size} "
                         f"(generation {plan.generation})")
        self.plan = plan


def elastic_settings(cfg):
    """Parse ``cfg.resilience.elastic`` (see config.py defaults)."""
    rcfg = cfg_get(cfg or {}, "resilience", None) or {}
    ecfg = cfg_get(rcfg, "elastic", None) or {}
    return {
        "enabled": bool(cfg_get(ecfg, "enabled", False)),
        "min_world_size": int(cfg_get(ecfg, "min_world_size", 2)),
        "grow_back": bool(cfg_get(ecfg, "grow_back", True)),
        "resize_timeout_s": float(
            cfg_get(ecfg, "resize_timeout_s", 60.0) or 0.0),
        "join_poll_s": float(cfg_get(ecfg, "join_poll_s", 0.25) or 0.25),
        "join_timeout_s": float(
            cfg_get(ecfg, "join_timeout_s", 600.0) or 0.0),
        "port_stride": int(cfg_get(ecfg, "port_stride", 17) or 1),
        "heartbeat_interval_s": float(
            cfg_get(ecfg, "heartbeat_interval_s", 1.0) or 1.0),
        "max_missing_heartbeats": int(
            cfg_get(ecfg, "max_missing_heartbeats", 5) or 5),
        "init_timeout_s": float(
            cfg_get(ecfg, "init_timeout_s", 120.0) or 120.0),
        "shutdown_timeout_s": float(
            cfg_get(ecfg, "shutdown_timeout_s", 5.0) or 5.0),
    }


# --------------------------------------------------- raw init / teardown

# old (client, service) pairs kept alive on purpose: destroying a
# service whose registered peers died abruptly can block; a leaked one
# only logs "tasks unhealthy" on its error poll until process exit
_LEAKED = []
_PEER_LOSS_EVENTS = []


def _benign_missed_heartbeat(status):
    """jax's default callback terminates the process on peer loss; a
    survivor must treat it as a *signal* instead."""
    _PEER_LOSS_EVENTS.append(str(status))
    logger.warning("elastic: coordination-service heartbeat reports a "
                   "lost peer: %s", status)


def raw_init(coordinator_address, num_processes, process_id,
             settings=None):
    """Initialize ``jax.distributed`` through the raw runtime client.

    Equivalent to ``jax.distributed.initialize`` except: the
    missed-heartbeat callback is benign (peer loss must not kill a
    survivor), ``shutdown_on_destruction`` is off (an elastic process's
    exit must never block in the collective shutdown barrier of a world
    that no longer exists), and the client heartbeat is fast so peer
    loss is *detected* within seconds, not minutes. Populates
    ``distributed.global_state`` exactly like the stock initializer so
    every downstream consumer (gloo collectives, ``cluster.client()``)
    is untouched.
    """
    from jax._src import distributed
    from jax._src.lib import xla_extension as xe

    s = settings or elastic_settings({})
    gs = distributed.global_state
    if gs.client is not None:
        raise RuntimeError("elastic raw_init: a distributed client is "
                           "already live — force_teardown() first")
    hb = max(int(round(s["heartbeat_interval_s"])), 1)
    miss = max(int(s["max_missing_heartbeats"]), 2)
    if process_id == 0:
        bind = "[::]:" + str(coordinator_address).rsplit(":", 1)[1]
        gs.service = xe.get_distributed_runtime_service(
            bind, num_processes, heartbeat_interval=hb,
            max_missing_heartbeats=miss)
    gs.client = xe.get_distributed_runtime_client(
        coordinator_address, process_id,
        init_timeout=int(s["init_timeout_s"]),
        shutdown_timeout=int(s["shutdown_timeout_s"]),
        heartbeat_interval=hb, max_missing_heartbeats=miss,
        missed_heartbeat_callback=_benign_missed_heartbeat,
        shutdown_on_destruction=False, use_compression=True)
    gs.client.connect()
    gs.process_id = int(process_id)
    gs.num_processes = int(num_processes)
    gs.coordinator_address = str(coordinator_address)


def force_teardown():
    """Detach the live distributed runtime so a new one can start.

    The cooperative ``jax.distributed.shutdown`` is a collective — it
    waits for peers that may be dead. This path never blocks: detach
    the client/service from ``global_state``, shut the old client down
    on a daemon thread (bounded by its own ``shutdown_timeout``), leak
    the old service, drop every backend, and clear the lru-cached
    process topology (``jax.process_count`` would otherwise keep
    reporting the dead world)."""
    import jax
    from jax._src import distributed
    from jax._src import xla_bridge

    gs = distributed.global_state
    old_client, old_service = gs.client, gs.service
    gs.client = None
    gs.service = None
    gs.preemption_sync_manager = None
    gs.coordinator_address = None
    gs.process_id = 0
    gs.num_processes = None
    if old_client is not None:
        def _shutdown():
            try:
                old_client.shutdown()
            except Exception as e:  # noqa: BLE001 — leaked world noise
                logger.debug("elastic: old client shutdown: %s", e)

        threading.Thread(target=_shutdown, daemon=True,
                         name="elastic-old-client-shutdown").start()
    if old_client is not None or old_service is not None:
        _LEAKED.append((old_client, old_service))
    xla_bridge._clear_backends()
    for fn in (jax.process_count, jax.process_index, jax.device_count,
               jax.local_device_count):
        cache_clear = getattr(fn, "cache_clear", None)
        if cache_clear is not None:
            cache_clear()
    # jitted executables baked device ids of the dead world into their
    # bindings — anything cached at the jax level must go too
    try:
        jax.clear_caches()
    except Exception as e:  # noqa: BLE001 — best-effort on older jax
        logger.debug("elastic: jax.clear_caches failed: %s", e)


# ------------------------------------------------------------ the plan

class ResizePlan:
    """The agreed post-resize topology — everything a member needs to
    tear down, re-init, and resume, JSON-able so it can ride the KV
    store (shrink consensus) or ``topology.json`` (rejoin).

    ``members`` is an ordered list of member tokens; a member's NEW
    process id is its index. Survivors are ``"p<old_id>"`` (sorted, so
    surviving ids stay stable where possible — the old master stays
    master); joiners are their join-request nonces, appended last."""

    def __init__(self, generation, members, coordinator, iteration=-1,
                 epoch=0, mesh_axes=None, mesh_shape=None,
                 barrier_epochs=None, reason="shrink", old_world=None,
                 old_mesh_shape=None):
        self.generation = int(generation)
        self.members = list(members)
        self.coordinator = str(coordinator)
        self.iteration = int(iteration)
        self.epoch = int(epoch)
        self.mesh_axes = list(mesh_axes) if mesh_axes else None
        self.mesh_shape = (list(mesh_shape)
                           if mesh_shape is not None else None)
        self.barrier_epochs = dict(barrier_epochs or {})
        self.reason = str(reason)
        self.old_world = old_world
        self.old_mesh_shape = (list(old_mesh_shape)
                               if old_mesh_shape is not None else None)

    @property
    def world_size(self):
        return len(self.members)

    def process_id_of(self, token):
        try:
            return self.members.index(str(token))
        except ValueError:
            return None

    def to_json(self):
        return json.dumps({
            "version": 1, "generation": self.generation,
            "members": self.members, "coordinator": self.coordinator,
            "iteration": self.iteration, "epoch": self.epoch,
            "mesh_axes": self.mesh_axes, "mesh_shape": self.mesh_shape,
            "barrier_epochs": self.barrier_epochs,
            "reason": self.reason, "old_world": self.old_world,
            "old_mesh_shape": self.old_mesh_shape,
        })

    @classmethod
    def from_json(cls, text):
        rec = json.loads(text)
        return cls(rec["generation"], rec["members"],
                   rec["coordinator"], rec.get("iteration", -1),
                   rec.get("epoch", 0), rec.get("mesh_axes"),
                   rec.get("mesh_shape"), rec.get("barrier_epochs"),
                   rec.get("reason", "shrink"), rec.get("old_world"),
                   rec.get("old_mesh_shape"))


# ------------------------------------------------- state redistribution

class RedistributionPlanner:
    """Per-leaf routing for the state move a resize implies (ISSUE 13).

    Two routes exist:

    - ``"gather"``: the live leaf is pulled to host memory BEFORE the
      old runtime is torn down and re-committed directly under the new
      world's shardings — no checkpoint round-trip. Only sound when the
      leaf's full value is locally present (replicated / single-device
      sharding) AND the live iteration equals the plan's consensus
      iteration, so the carried bytes are bit-identical to what the
      rest of the pod restores.
    - ``"checkpoint"``: the leaf rides the emergency checkpoint through
      the layout-agnostic reshard-on-load path (PR-6) — the only route
      for cross-process shards (survivors hold partial data) and for
      joiners (no live state at all).

    Byte totals mirror ``partition.state_bytes_report`` (same
    size*itemsize accounting via ``tree_bytes``), so the telemetry the
    resize emits is directly comparable to the partition ledger.

    When EVERY leaf routes ``"gather"`` the executor (train.py +
    ``trainer.elastic_recommit``) skips the orbax restore entirely —
    the big downtime win for replicated pods. A mixed plan restores the
    full tree and overwrites the gather-routed leaves with the carried
    live values.
    """

    def __init__(self, plan, live_iteration, state):
        self.plan = plan
        self.live_iteration = int(live_iteration)
        self.routes = {}          # path-key -> "gather" | "checkpoint"
        self.gather_bytes = 0
        self.checkpoint_bytes = 0
        self._build(state)

    # ----------------------------------------------------------- build

    @staticmethod
    def _leaf_key(path):
        import jax

        return jax.tree_util.keystr(path)

    @staticmethod
    def _leaf_bytes(leaf):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            return 0
        try:
            return int(size) * int(dtype.itemsize)
        except Exception:  # noqa: BLE001 — extension dtypes
            return 0

    @staticmethod
    def _locally_complete(leaf):
        """Whether this process holds the leaf's FULL value: replicated
        shardings and plain host/single-device arrays qualify; a leaf
        sharded across processes does not (a survivor only owns its
        shard — carrying it would truncate the tensor)."""
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            return True  # host numpy / python scalar
        rep = getattr(sharding, "is_fully_replicated", None)
        if rep is not None:
            return bool(rep)
        try:
            return len(sharding.device_set) <= 1
        except Exception:  # noqa: BLE001 — exotic sharding
            return False

    def _build(self, state):
        import jax

        live_matches = (self.live_iteration >= 0
                        and self.plan.iteration == self.live_iteration)
        leaves = (jax.tree_util.tree_flatten_with_path(state)[0]
                  if state is not None else [])
        for path, leaf in leaves:
            nbytes = self._leaf_bytes(leaf)
            if live_matches and self._locally_complete(leaf):
                self.routes[self._leaf_key(path)] = "gather"
                self.gather_bytes += nbytes
            else:
                self.routes[self._leaf_key(path)] = "checkpoint"
                self.checkpoint_bytes += nbytes

    # --------------------------------------------------------- queries

    @property
    def total_bytes(self):
        return self.gather_bytes + self.checkpoint_bytes

    @property
    def all_gather(self):
        """True when every leaf can skip the checkpoint round-trip."""
        return bool(self.routes) and all(
            r == "gather" for r in self.routes.values())

    def route_counts(self):
        gather = sum(1 for r in self.routes.values() if r == "gather")
        return {"gather": gather,
                "checkpoint": len(self.routes) - gather}

    def summary(self):
        """The redistribution record ``record_resize`` folds into the
        ``elastic/resize`` meta event (and PROFILE.md's cost table)."""
        counts = self.route_counts()
        return {
            "redistributed_bytes": int(self.total_bytes),
            "gather_bytes": int(self.gather_bytes),
            "checkpoint_bytes": int(self.checkpoint_bytes),
            "gather_leaves": counts["gather"],
            "checkpoint_leaves": counts["checkpoint"],
        }

    # -------------------------------------------------------- snapshot

    def snapshot(self, state):
        """Pull every gather-routed leaf to an OWNED host copy. Must
        run while the old backend is still alive — after
        ``force_teardown`` the arrays' buffers are gone. The copy is
        deliberate: a zero-copy view into a device buffer would dangle
        once the backend table is cleared."""
        import jax
        import numpy as np

        carry = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
            key = self._leaf_key(path)
            if self.routes.get(key) != "gather":
                continue
            try:
                carry[key] = np.array(leaf)  # copy=True by default
            except Exception as e:  # noqa: BLE001 — fall back to ckpt
                logger.warning(
                    "elastic: gather snapshot failed for %s (%s) — "
                    "leaf demoted to the checkpoint route", key, e)
                self.routes[key] = "checkpoint"
                nbytes = self._leaf_bytes(leaf)
                self.gather_bytes -= nbytes
                self.checkpoint_bytes += nbytes
        return carry


# ----------------------------------------------------- the coordinator

class ElasticCoordinator:
    """Owns the resize lifecycle for one training process.

    Shrink: ``plan_shrink(dead, ...)`` runs the survivor consensus over
    the OLD KV store (a poll-based rendezvous — the service barrier
    would wait on the dead) and returns the agreed ``ResizePlan``.
    Grow: the master polls ``<logdir>/elastic/join/`` for join-request
    nonces, announces a strictly-future target step through the KV
    store (``announce_grow``/``poll_grow``), and at the target step
    every survivor derives the identical ``plan_grow``. ``apply(plan)``
    performs the actual teardown/re-init and barrier-epoch adoption;
    the caller (train.py) rebuilds mesh/plan/state around it.
    """

    def __init__(self, cfg, logdir=None):
        self.cfg = cfg
        self.settings = elastic_settings(cfg)
        self.logdir = str(logdir) if logdir else None
        self.generation = int(os.environ.get(
            "IMAGINAIRE_ELASTIC_GENERATION", "0"))
        # the generation-0 coordinator anchors the port schedule: every
        # later generation lives at base_port + gen * port_stride, so
        # each resize rendezvouses on a fresh service while remaining
        # deterministic for every member
        self._base_coordinator = os.environ.get(
            "IMAGINAIRE_ELASTIC_BASE_COORDINATOR",
            os.environ.get("IMAGINAIRE_DIST_COORDINATOR", ""))
        self._announced_grow = None
        self.resizes = 0
        self.downtime_ms = 0.0
        self.redistributed_bytes = 0

    @property
    def enabled(self):
        return bool(self.settings["enabled"])

    # ------------------------------------------------------------ paths

    def elastic_dir(self):
        if not self.logdir:
            return None
        return os.path.join(self.logdir, "elastic")

    def topology_path(self):
        d = self.elastic_dir()
        return os.path.join(d, TOPOLOGY_FILE) if d else None

    # ----------------------------------------------------------- shrink

    def coordinator_for(self, generation):
        """Deterministic coordinator address of a generation."""
        base = self._base_coordinator
        if not base or ":" not in base:
            raise RuntimeError(
                "elastic: no base coordinator address (set "
                "IMAGINAIRE_DIST_COORDINATOR)")
        host, port = base.rsplit(":", 1)
        return f"{host}:{int(port) + int(generation) * self.settings['port_stride']}"

    def can_shrink(self, dead, world=None):
        """Whether the survivors can reshape instead of exiting: the
        master (KV host) must survive, and the surviving world must
        stay at or above ``min_world_size``."""
        from imaginaire_tpu.resilience import cluster

        if not self.enabled:
            return False
        n = int(world if world is not None else cluster.process_count())
        dead = set(int(d) for d in dead)
        if not dead or 0 in dead:
            return False  # the coordinator died with the KV store
        return (n - len(dead)) >= max(self.settings["min_world_size"], 1)

    def plan_shrink(self, dead, iteration=-1, epoch=0):
        """Survivor consensus over the OLD KV store. Returns the agreed
        ``ResizePlan`` or raises ``ClusterDesyncError`` when a survivor
        never votes within ``resize_timeout_s``."""
        from imaginaire_tpu.resilience import cluster

        n = cluster.process_count()
        i = cluster.process_index()
        dead = sorted(set(int(d) for d in dead))
        survivors = [p for p in range(n) if p not in dead]
        gen = self.generation + 1
        payload = {"it": int(iteration), "ep": int(epoch),
                   "tok": f"p{i}"}
        votes = cluster.agree_survivors(
            "shrink", gen, payload, survivors,
            timeout_s=self.settings["resize_timeout_s"])
        its = [int(v.get("it", -1)) for v in votes.values()]
        valid = [v for v in its if v >= 0]
        agreed_it = min(valid) if valid else -1
        agreed_ep = min(int(v.get("ep", 0)) for v in votes.values())
        mesh_axes, mesh_shape = self._fit_shape(len(survivors))
        plan = ResizePlan(
            gen, [f"p{p}" for p in survivors],
            self.coordinator_for(gen), iteration=agreed_it,
            epoch=agreed_ep, mesh_axes=mesh_axes, mesh_shape=mesh_shape,
            barrier_epochs=cluster.export_barrier_epochs(),
            reason="shrink", old_world=n,
            old_mesh_shape=self._current_mesh_shape())
        if i == min(survivors):
            # consensus done; the master's plan is identical to every
            # other survivor's (same votes, same derivation) — publish
            # the topology file for observers and future joiners
            self.publish_topology(plan)
        return plan

    def _fit_shape(self, new_world):
        """(axes, dims) the new world's mesh will use — the constant
        logical mesh when the surviving devices still cover it, else
        the re-derived shape from the divisibility rules."""
        import jax

        from imaginaire_tpu.parallel import mesh as mesh_lib

        try:
            per_proc = jax.local_device_count()
        except Exception:  # noqa: BLE001 — backend already torn down
            per_proc = 1
        total = per_proc * int(new_world)
        axes, dims = mesh_lib.fit_mesh_shape(self.cfg, total)
        return list(axes), (list(dims) if dims is not None else None)

    def _current_mesh_shape(self):
        from imaginaire_tpu.parallel.mesh import peek_mesh

        mesh = peek_mesh()
        if mesh is None:
            return None
        return [int(s) for s in mesh.devices.shape]

    # ------------------------------------------------------------- grow

    def check_join_requests(self):
        """Sorted join-request nonces present in the join dir minus the
        ones already part of the current membership (master-side poll;
        cheap: one listdir)."""
        d = self.elastic_dir()
        if not d:
            return []
        join_dir = os.path.join(d, JOIN_DIR)
        try:
            names = os.listdir(join_dir)
        except OSError:
            return []
        return sorted(os.path.splitext(name)[0] for name in names
                      if name.endswith(".json"))

    def announce_grow(self, target_step, joiners):
        """Master: publish the grow decision through the KV store. Every
        member reads it at a barrier-synced step strictly BEFORE
        ``target_step`` (the write happens-before the next barrier
        release), so the whole pod acts at the same iteration."""
        from imaginaire_tpu.resilience import cluster

        c = cluster.client()
        if c is None:
            return None
        rec = {"target": int(target_step),
               "joiners": sorted(str(j) for j in joiners),
               "generation": self.generation + 1}
        if self._announced_grow == rec["joiners"]:
            return None
        try:
            c.key_value_set(f"elastic/grow/g{self.generation}",
                            json.dumps(rec), allow_overwrite=True)
            self._announced_grow = rec["joiners"]
        except Exception as e:  # noqa: BLE001 — retried next sync step
            logger.warning("elastic: grow announce failed: %s", e)
            return None
        logger.info("elastic: grow announced — joiner(s) %s attach at "
                    "step %d", rec["joiners"], rec["target"])
        return rec

    def poll_grow(self):
        """The pending grow record ``{"target", "joiners",
        "generation"}`` for this generation, or None."""
        from imaginaire_tpu.resilience import cluster

        c = cluster.client()
        if c is None:
            return None
        prefix = "elastic/grow/"
        try:
            entries = c.key_value_dir_get(prefix)
        except Exception:  # noqa: BLE001 — no announcement yet
            return None
        for key, value in entries:
            if key.rsplit("/", 1)[-1] == f"g{self.generation}":
                try:
                    return json.loads(value)
                except ValueError:
                    return None
        return None

    def plan_grow(self, joiners, iteration, epoch):
        """Deterministic grow plan every survivor derives identically
        from the announced grow record — no extra consensus round."""
        from imaginaire_tpu.resilience import cluster

        n = cluster.process_count()
        gen = self.generation + 1
        members = [f"p{p}" for p in range(n)]
        members.extend(sorted(str(j) for j in joiners))
        mesh_axes, mesh_shape = self._fit_shape(len(members))
        return ResizePlan(
            gen, members, self.coordinator_for(gen),
            iteration=int(iteration), epoch=int(epoch),
            mesh_axes=mesh_axes, mesh_shape=mesh_shape,
            barrier_epochs=cluster.export_barrier_epochs(),
            reason="grow", old_world=n,
            old_mesh_shape=self._current_mesh_shape())

    # -------------------------------------------------------- topology

    def publish_topology(self, plan):
        """Write ``<logdir>/elastic/topology.json`` atomically — the
        rendezvous document joiners poll (and the operator's view of
        the live topology)."""
        path = self.topology_path()
        if not path:
            return None
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(plan.to_json())
        os.replace(tmp, path)
        return path

    def consume_join_requests(self, joiners):
        """Retire the join-request files a grow plan absorbed (master,
        post-publish) so the next poll doesn't re-admit them."""
        d = self.elastic_dir()
        if not d:
            return
        for nonce in joiners:
            try:
                os.remove(os.path.join(d, JOIN_DIR, f"{nonce}.json"))
            except OSError:
                pass

    # ------------------------------------------------------------ apply

    def apply(self, plan, my_token=None):
        """Execute the resize on this process: tear the old runtime
        down, point the ``IMAGINAIRE_DIST_*`` contract at the new
        topology, re-init through ``mesh.maybe_init_distributed_from_env``
        (routed back here via ``IMAGINAIRE_ELASTIC``), and re-align the
        barrier epochs (a fresh member would otherwise desync every
        counter-tagged rendezvous). Returns phase timings in ms."""
        from imaginaire_tpu.parallel import mesh as mesh_lib
        from imaginaire_tpu.resilience import cluster

        if my_token is None:
            my_token = f"p{cluster.process_index()}"
        new_id = plan.process_id_of(my_token)
        if new_id is None:
            raise RuntimeError(
                f"elastic: this process ({my_token}) is not a member of "
                f"generation {plan.generation}")
        timings = {}
        t0 = time.perf_counter()
        cluster.stop_heartbeat()
        force_teardown()
        timings["teardown_ms"] = round(
            (time.perf_counter() - t0) * 1000.0, 3)
        os.environ["IMAGINAIRE_DIST_COORDINATOR"] = plan.coordinator
        os.environ["IMAGINAIRE_DIST_NUM_PROCESSES"] = str(
            plan.world_size)
        os.environ["IMAGINAIRE_DIST_PROCESS_ID"] = str(new_id)
        os.environ["IMAGINAIRE_ELASTIC"] = "1"
        os.environ["IMAGINAIRE_ELASTIC_GENERATION"] = str(
            plan.generation)
        if self._base_coordinator:
            os.environ["IMAGINAIRE_ELASTIC_BASE_COORDINATOR"] = \
                self._base_coordinator
        t1 = time.perf_counter()
        mesh_lib.maybe_init_distributed_from_env()
        timings["reinit_ms"] = round(
            (time.perf_counter() - t1) * 1000.0, 3)
        cluster.adopt_barrier_epochs(plan.barrier_epochs)
        cluster.start_heartbeat()
        self.generation = plan.generation
        self._announced_grow = None
        self.resizes += 1
        logger.info(
            "elastic: generation %d live — world %d -> %d, process %s "
            "-> %d, coordinator %s (teardown %.0fms, re-init %.0fms)",
            plan.generation, plan.old_world or -1, plan.world_size,
            my_token, new_id, plan.coordinator,
            timings["teardown_ms"], timings["reinit_ms"])
        return timings

    def record_resize(self, plan, downtime_ms, phases=None,
                      redistribution=None):
        """Emit the ``elastic/resize`` meta event + counters every
        downstream reader keys on (check_run_health's changed-process-
        count acceptance, report.py's elasticity section, bench's leg
        summary). ``redistribution`` is
        ``RedistributionPlanner.summary()`` — the per-route byte
        accounting of the state move this resize performed."""
        from imaginaire_tpu import telemetry

        self.downtime_ms += float(downtime_ms)
        redist = dict(redistribution or {})
        self.redistributed_bytes += int(
            redist.get("redistributed_bytes", 0) or 0)
        tm = telemetry.get()
        if tm.enabled:
            tm.meta("elastic/resize", generation=plan.generation,
                    reason=plan.reason, old_world=plan.old_world,
                    new_world=plan.world_size,
                    old_shape=plan.old_mesh_shape,
                    new_shape=plan.mesh_shape,
                    iteration=plan.iteration,
                    downtime_ms=round(float(downtime_ms), 3),
                    phases=dict(phases or {}),
                    redistribution=redist)
            # counters are read latest-value-as-total (report.py), so
            # emit the cumulative figures, not the per-event deltas
            tm.counter("elastic/resizes", self.resizes)
            tm.counter("elastic/downtime_ms",
                       round(self.downtime_ms, 3))
            tm.counter("elastic/redistributed_bytes",
                       self.redistributed_bytes)
            tm.flush()


def maybe_elastic_init_from_env():
    """The ``IMAGINAIRE_ELASTIC=1`` branch of
    ``mesh.maybe_init_distributed_from_env``: same ``IMAGINAIRE_DIST_*``
    contract, but the runtime comes up through ``raw_init`` (benign
    heartbeat callback, non-blocking teardown) so the process can
    survive — and perform — later resizes. Returns True when it ran."""
    n = os.environ.get("IMAGINAIRE_DIST_NUM_PROCESSES")
    if not n or int(n) <= 1:
        return False
    raw_init(os.environ.get("IMAGINAIRE_DIST_COORDINATOR"), int(n),
             int(os.environ.get("IMAGINAIRE_DIST_PROCESS_ID", "0")),
             settings=env_settings())
    return True


def env_settings():
    """Init-time knobs can't come from cfg (the runtime boots before
    the config loads on re-exec'd joiners) — the launcher forwards them
    through the environment, defaults otherwise."""
    s = elastic_settings({})
    for env, key, cast in (
            ("IMAGINAIRE_ELASTIC_HEARTBEAT_S", "heartbeat_interval_s",
             float),
            ("IMAGINAIRE_ELASTIC_MAX_MISSING", "max_missing_heartbeats",
             int),
            ("IMAGINAIRE_ELASTIC_INIT_TIMEOUT_S", "init_timeout_s",
             float)):
        raw = os.environ.get(env)
        if raw:
            try:
                s[key] = cast(raw)
            except ValueError:
                pass
    return s


# ------------------------------------------------------------- joiners

def request_join(logdir, nonce):
    """Joiner: announce this process wants in. Returns the request
    path. The master absorbs the nonce into the next grow plan."""
    join_dir = os.path.join(str(logdir), "elastic", JOIN_DIR)
    os.makedirs(join_dir, exist_ok=True)
    path = os.path.join(join_dir, f"{nonce}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"nonce": str(nonce), "time": time.time(),
                   "pid": os.getpid()}, f)
    os.replace(tmp, path)
    return path


def wait_for_join(logdir, nonce, timeout_s=600.0, poll_s=0.25):
    """Joiner: block until ``topology.json`` names this nonce a member,
    then point the ``IMAGINAIRE_DIST_*`` env contract at the agreed
    topology and return the plan (the caller inits through
    ``mesh.maybe_init_distributed_from_env`` exactly like a launch-time
    member, then adopts ``plan.barrier_epochs``)."""
    topo = os.path.join(str(logdir), "elastic", TOPOLOGY_FILE)
    deadline = time.time() + float(timeout_s)
    nonce = str(nonce)
    while True:
        plan = None
        try:
            with open(topo) as f:
                plan = ResizePlan.from_json(f.read())
        except (OSError, ValueError, KeyError):
            plan = None
        if plan is not None:
            my_id = plan.process_id_of(nonce)
            if my_id is not None:
                os.environ["IMAGINAIRE_DIST_COORDINATOR"] = \
                    plan.coordinator
                os.environ["IMAGINAIRE_DIST_NUM_PROCESSES"] = str(
                    plan.world_size)
                os.environ["IMAGINAIRE_DIST_PROCESS_ID"] = str(my_id)
                os.environ["IMAGINAIRE_ELASTIC"] = "1"
                os.environ["IMAGINAIRE_ELASTIC_GENERATION"] = str(
                    plan.generation)
                logger.info("elastic: join granted — process %d of %d, "
                            "generation %d, coordinator %s", my_id,
                            plan.world_size, plan.generation,
                            plan.coordinator)
                return plan
        if time.time() >= deadline:
            raise TimeoutError(
                f"elastic: join request {nonce!r} not granted within "
                f"{timeout_s:g}s (topology: {topo})")
        time.sleep(float(poll_s))

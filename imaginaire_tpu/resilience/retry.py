"""Bounded retry-with-backoff for transient IO (ISSUE 7).

A preemptible-slice run's checkpoint commits and flow-cache shard IO
cross network filesystems that throw transient ``OSError``s under load;
one flaky write must not kill a multi-hour run. ``retry_call`` retries a
callable a bounded number of times with exponential backoff, counting
every retry into the ``resilience/retry/<label>`` telemetry counter and
emitting a ``resilience/retry_exhausted`` meta event before the final
exception propagates — so retried IO is *visible*, never silent.

The default policy comes from ``cfg.resilience.retry`` via
``resilience.configure`` (train.py calls it); library call sites that
predate configuration fall back to the module defaults below.
"""

from __future__ import annotations

import logging
import time

from imaginaire_tpu.config import cfg_get

logger = logging.getLogger(__name__)

# module defaults; resilience.configure overlays cfg.resilience.retry
_POLICY = {
    "retries": 3,       # total attempts = retries (1 first try + retries-1)
    "backoff_s": 0.1,   # first sleep; doubles per attempt
    "max_backoff_s": 2.0,
}


def retry_settings(cfg):
    """Parse ``cfg.resilience.retry`` over the module defaults."""
    rcfg = cfg_get(cfg_get(cfg or {}, "resilience", {}) or {}, "retry",
                   None) or {}
    return {
        "retries": max(int(cfg_get(rcfg, "retries", _POLICY["retries"])), 1),
        "backoff_s": float(cfg_get(rcfg, "backoff_s",
                                   _POLICY["backoff_s"])),
        "max_backoff_s": float(cfg_get(rcfg, "max_backoff_s",
                                       _POLICY["max_backoff_s"])),
    }


def set_default_policy(policy):
    """Install the process-wide retry policy (``resilience.configure``)."""
    _POLICY.update({k: policy[k] for k in ("retries", "backoff_s",
                                           "max_backoff_s") if k in policy})


def retry_call(fn, *args, label="io", retries=None, backoff_s=None,
               max_backoff_s=None, retry_on=(OSError,), _sleep=time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; retry on ``retry_on`` exceptions.

    Retries ``retries`` total attempts with exponential backoff
    (``backoff_s * 2^attempt``, capped at ``max_backoff_s``). Each retry
    bumps ``resilience/retry/<label>``; exhausting the budget emits a
    ``resilience/retry_exhausted`` meta event and re-raises the last
    exception. Exceptions outside ``retry_on`` propagate immediately
    (corruption is not transient — the caller quarantines instead).
    """
    from imaginaire_tpu import telemetry

    attempts = max(int(retries if retries is not None
                       else _POLICY["retries"]), 1)
    base = float(backoff_s if backoff_s is not None
                 else _POLICY["backoff_s"])
    cap = float(max_backoff_s if max_backoff_s is not None
                else _POLICY["max_backoff_s"])
    last = None
    for attempt in range(attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            last = e
            if attempt + 1 >= attempts:
                break
            delay = min(base * (2 ** attempt), cap)
            tm = telemetry.get()
            if tm.enabled:
                tm.counter(f"resilience/retry/{label}", attempt + 1)
            logger.warning(
                "transient %s failure (attempt %d/%d), retrying in "
                "%.2fs: %s", label, attempt + 1, attempts, delay, e)
            _sleep(delay)
    tm = telemetry.get()
    if tm.enabled:
        tm.meta("resilience/retry_exhausted", label=label,
                attempts=attempts, error=str(last))
    logger.error("%s failed after %d attempt(s): %s", label, attempts,
                 last)
    raise last

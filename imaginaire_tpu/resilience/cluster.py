"""Multi-process cluster coordination: timed collectives, cross-host
heartbeats, coordinated preemption, and resume consensus (ISSUE 8).

The dominant real-world failure mode at multi-host scale is not a crash
— it is a *hang*: one dead or stalled process parks every surviving
host inside an untimed collective forever (characterized in PAPERS.md,
arXiv:1810.11112). Everything here converts that silent hang into a
loud, named, bounded failure:

- ``timed_barrier(name, timeout_s)`` — a cluster rendezvous built on
  the jax coordination service. Each process announces its arrival in
  the service's KV store before waiting, so a timeout can read the
  arrival record and raise ``ClusterDesyncError`` naming exactly which
  process index(es) never showed up, instead of ``DEADLINE_EXCEEDED``
  pointing at nobody.
- ``ClusterHeartbeat`` — a daemon thread stamping ``hb/p<i>`` (wall
  time + last step) every ``heartbeat_interval_s``. ``peer_status``
  reads all stamps; the PR-2 hang watchdog folds it into its dump so a
  distributed stall names the stalled process index, not just "no step
  completed here". Stamps are scoped to the pod's *membership epoch*
  (the elastic generation, ISSUE 13): after a resize the survivors
  stamp ``hb/e<E>/p<i>`` and ``peer_status`` only reads the current
  epoch's scope, so a departed host's final stamps never report it as
  a stalled peer of a membership it is no longer part of.
- ``coordinate_preemption(step, local_flag)`` — the per-step vote that
  makes the PR-7 SIGTERM drain *collective*: a signal lands on ONE
  host, but the emergency checkpoint is a collective orbax save, so
  every host must enter it at the same iteration or the pod deadlocks
  (the signaled host waits in the save barrier while the others wait
  in the next step's psum). Each host writes its local flag for the
  iteration, everyone rendezvouses, everyone reads the full vote set —
  all hosts observe the same OR at the same step. The vote doubles as
  a per-iteration liveness probe: a stalled peer trips the barrier
  timeout and gets named.
- ``agree_min(name, value)`` — resume consensus: every host publishes
  the newest checkpoint iteration IT verified; the cluster restores
  the min. A host whose local copy of a newer checkpoint failed
  integrity follows the consensus instead of silently training from
  different weights than its peers.

Single-process (or uninitialized ``jax.distributed``) every entry
point degrades to the trivial local answer — no RPC, no thread.

The KV client is the coordination service jax.distributed already
runs for device bootstrapping; no extra infrastructure. Tests inject a
fake client via ``set_client_for_testing``.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from imaginaire_tpu.config import cfg_get

logger = logging.getLogger(__name__)


class ClusterDesyncError(RuntimeError):
    """A timed cluster rendezvous expired: one or more processes never
    arrived (dead, stalled, or partitioned). Carries the absent process
    indices in ``.absent``."""

    def __init__(self, message, absent=(), barrier=None):
        super().__init__(message)
        self.absent = tuple(absent)
        self.barrier = barrier


# test seam: a fake client (and fake process topology) installed by
# tests/test_cluster.py so the protocol logic runs without spawning a
# real 2-process jax.distributed cluster
_CLIENT_OVERRIDE = None
_TOPOLOGY_OVERRIDE = None  # (process_index, process_count)


def set_client_for_testing(client, process_index=None, process_count=None):
    global _CLIENT_OVERRIDE, _TOPOLOGY_OVERRIDE
    _CLIENT_OVERRIDE = client
    _TOPOLOGY_OVERRIDE = (None if process_index is None
                          else (int(process_index), int(process_count)))


def process_index():
    if _TOPOLOGY_OVERRIDE is not None:
        return _TOPOLOGY_OVERRIDE[0]
    import jax

    return jax.process_index()


def process_count():
    if _TOPOLOGY_OVERRIDE is not None:
        return _TOPOLOGY_OVERRIDE[1]
    import jax

    return jax.process_count()


def client():
    """The coordination-service KV client, or None (single process /
    distributed runtime not initialized)."""
    if _CLIENT_OVERRIDE is not None:
        return _CLIENT_OVERRIDE
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # noqa: BLE001 — no distributed runtime
        return None


def is_active():
    return process_count() > 1 and client() is not None


def cluster_settings(cfg):
    """Parse ``cfg.resilience.cluster`` (see config.py defaults)."""
    rcfg = cfg_get(cfg or {}, "resilience", None) or {}
    ccfg = cfg_get(rcfg, "cluster", None) or {}
    enabled = cfg_get(ccfg, "enabled", "auto")
    if enabled == "auto":
        enabled = process_count() > 1
    return {
        "enabled": bool(enabled),
        "barrier_timeout_s": float(cfg_get(ccfg, "barrier_timeout_s",
                                           300.0) or 0.0),
        "sync_every_n_steps": int(cfg_get(ccfg, "sync_every_n_steps", 1)
                                  or 0),
        "heartbeat_interval_s": float(cfg_get(ccfg,
                                              "heartbeat_interval_s",
                                              10.0) or 0.0),
        "heartbeat_timeout_s": float(cfg_get(ccfg, "heartbeat_timeout_s",
                                             60.0) or 0.0),
    }


# process-wide settings installed by configure(); barrier calls that
# don't pass an explicit timeout read it
_SETTINGS = None


def configure(cfg):
    """Install the cluster policy (``resilience.configure`` calls this
    alongside the retry/chaos setup). Returns the parsed settings."""
    global _SETTINGS
    _SETTINGS = cluster_settings(cfg)
    if _SETTINGS["enabled"] and is_active():
        logger.info("cluster coordination active: process %d/%d, "
                    "barrier timeout %.1fs, preempt sync every %d "
                    "step(s)", process_index(), process_count(),
                    _SETTINGS["barrier_timeout_s"],
                    _SETTINGS["sync_every_n_steps"])
    return _SETTINGS


def settings():
    return _SETTINGS if _SETTINGS is not None else cluster_settings({})


def default_timeout_s():
    return settings()["barrier_timeout_s"]


# ------------------------------------------------------ timed barrier

# per-name invocation counters: barrier ids must be unique per
# rendezvous, and a timed-out id must never be reused (the coordination
# service considers it failed)
_BARRIER_EPOCH = {}
_BARRIER_LOCK = threading.Lock()


def _next_epoch(name):
    with _BARRIER_LOCK:
        k = _BARRIER_EPOCH.get(name, 0)
        _BARRIER_EPOCH[name] = k + 1
    return k


def export_barrier_epochs():
    """Snapshot of the per-name barrier counters (ISSUE 11): rides the
    elastic ``ResizePlan``/``topology.json`` so every post-resize
    member resumes the SAME counter values."""
    with _BARRIER_LOCK:
        return dict(_BARRIER_EPOCH)


def adopt_barrier_epochs(epochs):
    """Fast-forward the local barrier counters to a cluster snapshot
    (ISSUE 11). A process that (re)joins an elastic pod starts its
    counters at zero while the survivors carry theirs forward — its
    next counter-tagged rendezvous would wait at ``name:0`` against
    peers at ``name:k`` and trip a spurious ``ClusterDesyncError``.
    Max-merge (never rewind: a reused barrier id is poison to the
    coordination service) keeps everyone aligned."""
    if not epochs:
        return
    with _BARRIER_LOCK:
        for name, value in dict(epochs).items():
            try:
                value = int(value)
            except (TypeError, ValueError):
                continue
            if value > _BARRIER_EPOCH.get(name, 0):
                _BARRIER_EPOCH[name] = value


def timed_barrier(name, timeout_s=None, tag=None):
    """Cluster rendezvous that raises instead of hanging.

    Every process announces itself under ``arrive/<id>/p<i>`` and then
    waits at the service barrier. On ``DEADLINE_EXCEEDED`` the arrival
    record names the process(es) that never made it — the difference
    between "the pod hung" and "process 3 is dead, restart it".

    ``tag`` pins the barrier id (callers with a natural unique key, e.g.
    the checkpoint iteration); otherwise a per-name counter keeps
    repeated rendezvous distinct. No-op when single-process.
    """
    c = client()
    n = process_count()
    if n <= 1 or c is None:
        return
    timeout_s = default_timeout_s() if timeout_s is None else float(
        timeout_s)
    bid = f"{name}:{tag if tag is not None else _next_epoch(name)}"
    i = process_index()
    try:
        c.key_value_set(f"arrive/{bid}/p{i}", f"{time.time():.3f}",
                        allow_overwrite=True)
    except Exception as e:  # noqa: BLE001 — arrival record best-effort
        logger.warning("cluster: arrival record for %s failed: %s", bid,
                       e)
    try:
        c.wait_at_barrier(f"barrier/{bid}", int(max(timeout_s, 0.001)
                                                * 1000))
    except Exception as e:
        arrived = _arrivals(c, bid)
        absent = sorted(set(range(n)) - set(arrived))
        _desync_event(bid, absent, arrived, timeout_s, str(e))
        raise ClusterDesyncError(
            f"cluster barrier {name!r} timed out after {timeout_s:g}s: "
            f"process(es) {absent or '<unknown>'} absent "
            f"(arrived: {sorted(arrived)} of {n}; this is process {i}). "
            f"One process is dead or stalled — every host should exit "
            f"and the supervisor restart the pod.",
            absent=absent, barrier=name) from e
    # collective-wait attribution (ISSUE 17): the arrival records give
    # it for free — this process's wait is the spread between its own
    # arrival stamp and the last one. Read BEFORE deleting our key.
    try:
        times = _arrival_times(c, bid)
        if i in times and times:
            wait_ms = (max(times.values()) - times[i]) * 1e3
            from imaginaire_tpu.telemetry import podview

            podview.get().note_collective_wait(wait_ms)
    except Exception:  # noqa: BLE001 — attribution is best-effort
        pass
    # rendezvous done on every process: each cleans its own arrival key
    try:
        c.key_value_delete(f"arrive/{bid}/p{i}")
    except Exception:  # noqa: BLE001
        pass


def _arrivals(c, bid):
    return sorted(_arrival_times(c, bid))


def _arrival_times(c, bid):
    """{process_index: arrival wall time} from the barrier's arrival
    records."""
    try:
        entries = c.key_value_dir_get(f"arrive/{bid}/")
    except Exception:  # noqa: BLE001
        return {}
    out = {}
    for key, value in entries:
        base = key.rsplit("/", 1)[-1]
        if base.startswith("p"):
            try:
                out[int(base[1:])] = float(value)
            except ValueError:
                continue
    return out


def _desync_event(bid, absent, arrived, timeout_s, error):
    from imaginaire_tpu import telemetry

    tm = telemetry.get()
    if tm.enabled:
        tm.meta("resilience/cluster_desync", barrier=bid,
                absent=list(absent), arrived=sorted(arrived),
                timeout_s=timeout_s, process=process_index(),
                error=error[:300])
        tm.counter("resilience/cluster_desyncs", 1)
        # straggler attribution (ISSUE 17) BEFORE the flush: the absent
        # process(es) get pod/straggler/* counters + the "stalled" span
        # meta in the same desync flush, so the evidence lands before
        # ClusterDesyncError unwinds the run
        try:
            from imaginaire_tpu.telemetry import podview

            podview.get().note_desync(absent)
        except Exception:  # noqa: BLE001 — attribution is best-effort
            pass
        tm.flush()  # the evidence must land before the process exits
    logger.error("cluster barrier %s timed out (%.1fs): absent %s, "
                 "arrived %s", bid, timeout_s, absent, sorted(arrived))


# ------------------------------------------------- preemption voting

def coordinate_preemption(step, local_flag, timeout_s=None,
                          return_flagged=False):
    """Collective OR of per-host preemption flags at iteration ``step``.

    The SIGTERM drain (PR 7) must be entered by EVERY host at the same
    iteration: the emergency save is a collective, so a host draining
    alone deadlocks against peers running the next step. Protocol:
    write the local flag for this step, rendezvous, read the complete
    vote set — the barrier guarantees every vote is visible to every
    reader, so all hosts compute the same OR for the same step.

    ``return_flagged=True`` returns ``(or, flagged_indices)`` instead
    of the bare OR — the elastic drain split (ISSUE 11) needs to know
    WHICH host(s) are leaving to decide whether the survivors can
    reshape in-process rather than the whole pod exiting.

    Single-process: returns ``local_flag`` unchanged, no RPC.
    Raises ``ClusterDesyncError`` when a peer never votes (stalled) —
    the per-step vote doubles as the pod's liveness probe.
    """
    c = client()
    n = process_count()
    if n <= 1 or c is None:
        if return_flagged:
            return bool(local_flag), ([process_index()] if local_flag
                                      else [])
        return bool(local_flag)
    i = process_index()
    step = int(step)
    try:
        c.key_value_set(f"psync/{step}/p{i}", "1" if local_flag else "0",
                        allow_overwrite=True)
    except Exception as e:  # noqa: BLE001
        logger.warning("cluster: preemption vote write failed: %s", e)
    try:
        timed_barrier("psync", timeout_s=timeout_s, tag=step)
    except ClusterDesyncError:
        raise
    votes = {}
    try:
        for key, value in c.key_value_dir_get(f"psync/{step}/"):
            base = key.rsplit("/", 1)[-1]
            if base.startswith("p"):
                votes[int(base[1:])] = value.strip() == "1"
    except Exception as e:  # noqa: BLE001 — the local flag still counts
        logger.warning("cluster: preemption vote read failed: %s", e)
    # bounded KV footprint: each process retires its own vote from two
    # steps ago (the current step's keys must survive slow readers)
    try:
        c.key_value_delete(f"psync/{step - 2}/p{i}")
    except Exception:  # noqa: BLE001
        pass
    flagged = sorted(p for p, v in votes.items() if v)
    if flagged and not local_flag:
        from imaginaire_tpu import telemetry

        tm = telemetry.get()
        if tm.enabled:
            tm.meta("resilience/preempt_remote", step=step,
                    flagged=flagged, process=i)
        logger.warning("cluster: process(es) %s flagged preemption at "
                       "step %d — joining the coordinated drain",
                       flagged, step)
    result = bool(local_flag) or bool(flagged)
    if return_flagged:
        if local_flag and i not in flagged:
            flagged = sorted(flagged + [i])
        return result, flagged
    return result


# ---------------------------------------------------- resume consensus

def agree_min(name, value, extra=None, timeout_s=None):
    """Publish ``value`` (an int; -1 = "nothing local") and return
    ``(consensus, votes)`` where consensus is the min over processes
    that published >= 0 and votes maps process index -> (value, extra).

    The resume path uses this with the newest checkpoint iteration each
    host *verified*: min-over-verified is the newest state EVERY host
    can restore, so a host whose local copy of a newer checkpoint fails
    integrity follows the consensus instead of silently diverging.

    Single-process: ``(value, {0: (value, extra)})``.
    """
    c = client()
    n = process_count()
    if n <= 1 or c is None:
        return int(value), {0: (int(value), extra)}
    i = process_index()
    epoch = _next_epoch(f"agree/{name}")
    payload = json.dumps({"v": int(value), "x": extra})
    try:
        c.key_value_set(f"agree/{name}/{epoch}/p{i}", payload,
                        allow_overwrite=True)
    except Exception as e:  # noqa: BLE001
        logger.warning("cluster: agree(%s) publish failed: %s", name, e)
    timed_barrier(f"agree_{name}", timeout_s=timeout_s, tag=epoch)
    votes = {}
    try:
        for key, val in c.key_value_dir_get(f"agree/{name}/{epoch}/"):
            base = key.rsplit("/", 1)[-1]
            if base.startswith("p"):
                rec = json.loads(val)
                votes[int(base[1:])] = (int(rec["v"]), rec.get("x"))
    except Exception as e:  # noqa: BLE001
        logger.warning("cluster: agree(%s) read failed: %s", name, e)
        votes[i] = (int(value), extra)
    try:
        c.key_value_delete(f"agree/{name}/{epoch}/p{i}")
    except Exception:  # noqa: BLE001
        pass
    valid = [v for v, _ in votes.values() if v >= 0]
    consensus = min(valid) if valid else -1
    return consensus, votes


# ------------------------------------------------- survivor consensus

def agree_survivors(name, generation, payload, survivors, timeout_s=None,
                    poll_s=0.05):
    """KV-poll rendezvous among an explicit survivor set (ISSUE 11).

    The service barrier (``timed_barrier``) counts EVERY registered
    process — after a peer dies it can only time out. The elastic
    shrink consensus instead publishes each survivor's vote under
    ``elastic/<name>/<generation>/p<i>`` and POLLS the directory until
    every survivor's vote is visible: dead processes are simply not
    waited on. Returns ``{process_index: payload}`` for the survivor
    set; raises ``ClusterDesyncError`` naming the survivors that never
    voted within ``timeout_s`` (a second loss during the consensus).

    Single-process (or no client): ``{process_index(): payload}``.
    """
    c = client()
    i = process_index()
    survivors = sorted(int(p) for p in survivors)
    if c is None or len(survivors) <= 1:
        return {i: payload}
    timeout_s = default_timeout_s() if timeout_s is None else float(
        timeout_s)
    prefix = f"elastic/{name}/{int(generation)}/"
    try:
        c.key_value_set(prefix + f"p{i}", json.dumps(payload),
                        allow_overwrite=True)
    except Exception as e:  # noqa: BLE001
        logger.warning("cluster: agree_survivors(%s) publish failed: %s",
                       name, e)
    deadline = time.time() + max(timeout_s, 0.001)
    votes = {}
    while True:
        try:
            entries = c.key_value_dir_get(prefix)
        except Exception:  # noqa: BLE001 — nobody published yet
            entries = []
        for key, value in entries:
            base = key.rsplit("/", 1)[-1]
            if base.startswith("p"):
                try:
                    votes[int(base[1:])] = json.loads(value)
                except (ValueError, TypeError):
                    continue
        if all(p in votes for p in survivors):
            return {p: votes[p] for p in survivors}
        if time.time() >= deadline:
            absent = sorted(set(survivors) - set(votes))
            _desync_event(f"{name}:{generation}", absent,
                          sorted(votes), timeout_s,
                          "survivor consensus timed out")
            raise ClusterDesyncError(
                f"elastic consensus {name!r} (generation {generation}) "
                f"timed out after {timeout_s:g}s: survivor(s) {absent} "
                f"never voted (voted: {sorted(votes)}; this is process "
                f"{i}). A second host was lost mid-consensus — exit "
                f"and let the supervisor restart the pod.",
                absent=absent, barrier=name)
        time.sleep(poll_s)


# --------------------------------------------------------- heartbeats

_MEMBERSHIP_EPOCH = None  # test override (set_membership_epoch)


def membership_epoch():
    """The pod's current membership epoch — the elastic generation
    (ISSUE 13). Heartbeat stamps are scoped to it: a host that departed
    in an earlier membership left its stamps under the OLD epoch's
    scope, so it never shows up as a ``stalled_peers`` entry of the
    membership it is no longer part of. Epoch 0 (a never-resized pod)
    keeps the legacy unscoped ``hb/p<i>`` keys."""
    if _MEMBERSHIP_EPOCH is not None:
        return int(_MEMBERSHIP_EPOCH)
    import os

    try:
        return int(os.environ.get("IMAGINAIRE_ELASTIC_GENERATION", "0"))
    except ValueError:
        return 0


def set_membership_epoch(epoch):
    """Test seam: pin the membership epoch (None restores the
    environment-derived value)."""
    global _MEMBERSHIP_EPOCH
    _MEMBERSHIP_EPOCH = epoch


def heartbeat_key(process_idx, epoch=None):
    """The KV key this process's heartbeat stamps under — epoch-scoped
    for resized pods, the legacy flat key for epoch 0."""
    e = membership_epoch() if epoch is None else int(epoch)
    if e == 0:
        return f"hb/p{process_idx}"
    return f"hb/e{e}/p{process_idx}"


class ClusterHeartbeat(threading.Thread):
    """Daemon thread stamping this process's liveness into the KV store
    so *other* hosts' watchdog dumps can name a stalled peer."""

    def __init__(self, interval_s=10.0):
        super().__init__(daemon=True, name="cluster-heartbeat")
        self.interval_s = max(float(interval_s), 0.5)
        self._stop_event = threading.Event()

    def run(self):
        c = client()
        if c is None:
            return
        i = process_index()
        while not self._stop_event.wait(self.interval_s):
            from imaginaire_tpu import telemetry

            stamp = json.dumps({"t": round(time.time(), 3),
                                "step": telemetry.get().last_step})
            try:
                # key re-derived per stamp: the epoch is cheap to read
                # and a long-lived thread must follow a membership
                # change even if the restart raced it
                c.key_value_set(heartbeat_key(i), stamp,
                                allow_overwrite=True)
            except Exception as e:  # noqa: BLE001 — liveness best-effort
                logger.debug("cluster heartbeat write failed: %s", e)

    def stop(self):
        self._stop_event.set()


_HEARTBEAT = None


def start_heartbeat(cfg=None):
    """Start (once) the heartbeat thread; no-op single-process."""
    global _HEARTBEAT
    s = cluster_settings(cfg) if cfg is not None else settings()
    if not s["enabled"] or not is_active() \
            or s["heartbeat_interval_s"] <= 0:
        return None
    if _HEARTBEAT is None or not _HEARTBEAT.is_alive():
        _HEARTBEAT = ClusterHeartbeat(s["heartbeat_interval_s"])
        _HEARTBEAT.start()
    return _HEARTBEAT


def stop_heartbeat():
    """Stop the heartbeat thread (elastic teardown, ISSUE 11): the
    running thread captured the OLD world's KV client; a fresh
    ``start_heartbeat`` after re-init binds the new one."""
    global _HEARTBEAT
    if _HEARTBEAT is not None:
        _HEARTBEAT.stop()
        _HEARTBEAT = None


def peer_status(stale_after_s=None):
    """{process_index: {"t", "step", "age_s", "stalled"}} from the
    heartbeat record, or None when not a multi-process run. Processes
    with NO stamp at all are reported with ``t None, stalled True`` —
    a host that never heartbeated is the prime suspect."""
    c = client()
    n = process_count()
    if n <= 1 or c is None:
        return None
    stale_after_s = (settings()["heartbeat_timeout_s"]
                     if stale_after_s is None else float(stale_after_s))
    now = time.time()
    out = {}
    epoch = membership_epoch()
    try:
        entries = c.key_value_dir_get("hb/")
    except Exception:  # noqa: BLE001
        entries = []
    for key, value in entries:
        # membership-epoch scoping (ISSUE 13): only THIS epoch's stamps
        # count. Epoch 0 reads the legacy flat ``hb/p<i>`` keys (and
        # skips any ``hb/e*/`` scope); epoch E reads ``hb/e<E>/p<i>``.
        parts = [p for p in key.split("/") if p]
        if "hb" in parts:
            parts = parts[parts.index("hb") + 1:]
        if epoch == 0:
            if len(parts) != 1:
                continue
        elif len(parts) != 2 or parts[0] != f"e{epoch}":
            continue
        base = parts[-1]
        if not base.startswith("p"):
            continue
        try:
            idx = int(base[1:])
            rec = json.loads(value)
        except ValueError:
            continue
        age = now - float(rec.get("t", 0))
        out[idx] = {"t": rec.get("t"), "step": rec.get("step"),
                    "age_s": round(age, 1),
                    "stalled": age > stale_after_s}
    for idx in range(n):
        if idx not in out:
            out[idx] = {"t": None, "step": None, "age_s": None,
                        "stalled": True}
    return out


def stalled_peers(stale_after_s=None):
    """Sorted indices of peers whose heartbeat is stale (excluding this
    process); [] single-process."""
    status = peer_status(stale_after_s)
    if not status:
        return []
    me = process_index()
    return sorted(i for i, rec in status.items()
                  if i != me and rec["stalled"])

"""Optimizers + schedules (ref: imaginaire/optimizers/{fromage,madam}.py,
imaginaire/utils/trainer.py:219-306).

optax GradientTransformations. 'fused' variants in the reference are a
CUDA concern — under XLA every optimizer is fused into the train step, so
``fused_opt`` is accepted and ignored.
"""

from imaginaire_tpu.optim.optimizers import (
    fromage,
    get_optimizer_for_params,
    get_scheduler,
    init_optimizer_state,
    madam,
)
from imaginaire_tpu.optim.remat import (
    POLICIES as REMAT_POLICIES,
    call_block,
    call_hyper_block,
    remat_block,
    remat_block_cls,
    remat_hyper_block_cls,
    resolve_policy,
)

__all__ = ["fromage", "madam", "get_optimizer_for_params", "get_scheduler",
           "init_optimizer_state", "REMAT_POLICIES", "resolve_policy",
           "remat_block", "remat_block_cls", "remat_hyper_block_cls",
           "call_block", "call_hyper_block"]

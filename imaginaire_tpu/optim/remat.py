"""Named rematerialization policies — ONE registry for every family.

ISSUE 10: the SPADE-only ``gen.remat`` knob becomes a uniform per-block
``jax.checkpoint`` policy surface across every generator and
discriminator (spade/vid2vid/fs_vid2vid, pix2pixHD, UNIT/MUNIT,
FUNIT/COCO-FUNIT). Configs name a policy; models resolve it here —
one error message, one registry:

  ``none``           no remat: every block activation stays live for the
                     backward pass (the fp32 seed behavior).
  ``blocks``         ``jax.checkpoint`` around each block with the
                     default policy (save nothing inside the block;
                     recompute the block forward during backward). The
                     historical spade knob value.
  ``dots_saveable``  checkpoint each block but let XLA keep matmul/conv
                     outputs (``jax.checkpoint_policies.dots_saveable``)
                     — recompute only the cheap elementwise tail, the
                     middle ground on MXU-heavy blocks.
  ``save_nothing``   explicit ``nothing_saveable`` — the offload-style
                     maximally-frugal policy (same residency as
                     ``blocks`` today; named separately so configs can
                     pin the aggressive end of the ladder explicitly).

``training`` must be a STATIC positional argument under remat: a traced
kwarg bool breaks the blocks' Python control flow (norm mode switches,
dropout). The wrappers here put ``training`` FIRST — ``__call__(self,
training, x, *cond)`` with ``static_argnums=(1,)`` — so one fixed index
covers blocks with any conditional-input arity (vid2vid's up blocks take
one or two cond maps depending on the flow curriculum). The wrapped
block keeps the same flax ``name``, so the parameter tree is IDENTICAL
across policies and the knob can toggle mid-training.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
from flax import linen as nn


class RematPolicy(NamedTuple):
    """A resolved registry entry. ``enabled`` False means no checkpoint
    wrapping at all; ``policy`` is the jax.checkpoint saveable-filter
    (None = the checkpoint default: save nothing)."""

    name: str
    enabled: bool
    policy: Any


POLICIES = {
    "none": RematPolicy("none", False, None),
    "blocks": RematPolicy("blocks", True, None),
    "dots_saveable": RematPolicy(
        "dots_saveable", True, jax.checkpoint_policies.dots_saveable),
    "save_nothing": RematPolicy(
        "save_nothing", True, jax.checkpoint_policies.nothing_saveable),
}


def resolve_policy(name, where="remat"):
    """Resolve a policy name (or pass through a ``RematPolicy``); the
    single validation point for every family's remat knob. Raises at
    trace/init time so a bad config fails loudly before any step runs."""
    if isinstance(name, RematPolicy):
        return name
    key = "none" if name is None else str(name)
    try:
        return POLICIES[key]
    except KeyError:
        raise ValueError(
            f"{where}={name!r} is not a known remat policy; use one of "
            + ", ".join(repr(k) for k in POLICIES)) from None


# wrapped-class cache: nn.remat creates a new class; reusing it keeps
# repeated block construction cheap and class identities stable
_WRAPPED = {}


def remat_block_cls(block_cls, policy, where="remat"):
    """The Module class implementing ``policy`` over ``block_cls``.

    ``none`` returns ``block_cls`` unchanged (kwarg calling convention);
    enabled policies return an ``nn.remat``-lifted subclass whose
    ``__call__(training, x, *cond)`` is all-positional with ``training``
    static. Use :func:`call_block` to call either uniformly, or
    :func:`remat_block` for a closure with the uniform kwarg signature.
    """
    pol = resolve_policy(policy, where=where)
    if not pol.enabled:
        return block_cls
    key = (block_cls, pol.name)
    if key not in _WRAPPED:
        class _Positional(block_cls):
            _remat_positional = True

            def __call__(self, training, x, *cond):  # noqa: D102
                return block_cls.__call__(self, x, *cond, training=training)

        _Positional.__name__ = block_cls.__name__
        _Positional.__qualname__ = block_cls.__qualname__
        _WRAPPED[key] = nn.remat(_Positional, static_argnums=(1,),
                                 policy=pol.policy)
    return _WRAPPED[key]


def remat_hyper_block_cls(block_cls, policy, where="remat"):
    """Variant for hyper blocks (fs_vid2vid's ``HyperRes2dBlock``) whose
    per-sample predicted ``conv_weights``/``norm_weights`` ride the call
    as traced pytrees: ``__call__(training, conv_weights, norm_weights,
    x, *cond)``, everything but ``training`` traced."""
    pol = resolve_policy(policy, where=where)
    if not pol.enabled:
        return block_cls
    key = (block_cls, pol.name, "hyper")
    if key not in _WRAPPED:
        class _PositionalHyper(block_cls):
            _remat_positional = True
            _remat_hyper = True

            def __call__(self, training, conv_weights, norm_weights,
                         x, *cond):  # noqa: D102
                return block_cls.__call__(
                    self, x, *cond, conv_weights=conv_weights,
                    norm_weights=norm_weights, training=training)

        _PositionalHyper.__name__ = block_cls.__name__
        _PositionalHyper.__qualname__ = block_cls.__qualname__
        _WRAPPED[key] = nn.remat(_PositionalHyper, static_argnums=(1,),
                                 policy=pol.policy)
    return _WRAPPED[key]


def is_positional(blk):
    """True when ``blk`` came out of an enabled-policy wrapper and uses
    the training-first positional convention."""
    return bool(getattr(blk, "_remat_positional", False))


def call_block(blk, x, *cond, training=False):
    """Call a block built from :func:`remat_block_cls` with the uniform
    ``(x, *cond, training=...)`` convention, whatever the policy."""
    if is_positional(blk):
        return blk(training, x, *cond)
    return blk(x, *cond, training=training)


def call_hyper_block(blk, x, *cond, conv_weights=None, norm_weights=None,
                     training=False):
    """:func:`call_block` for :func:`remat_hyper_block_cls` blocks."""
    if is_positional(blk):
        return blk(training, conv_weights, norm_weights, x, *cond)
    return blk(x, *cond, conv_weights=conv_weights,
               norm_weights=norm_weights, training=training)


def remat_block(block_cls, policy, where="remat", **block_kw):
    """Compact-style convenience: build the block under ``policy`` and
    return a callable with the uniform ``(x, *cond, training=...)``
    signature. ``block_kw`` must carry ``name=`` so the parameter tree
    is policy-invariant."""
    cls = remat_block_cls(block_cls, policy, where=where)
    blk = cls(**block_kw)
    return lambda x, *cond, training=False: call_block(
        blk, x, *cond, training=training)

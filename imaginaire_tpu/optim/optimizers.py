"""Fromage / Madam optimizers and the config-driven factory.

ref: imaginaire/optimizers/fromage.py:11-44, madam.py:9-62,
imaginaire/utils/trainer.py:219-306 (factory + lr policies).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from imaginaire_tpu.config import cfg_get


def fromage(lr: float):
    """Fromage (arXiv:2002.03432): norm-rescaled step + 1/sqrt(1+lr^2)
    shrink (ref: fromage.py:20-44). Stateless."""

    shrink = 1.0 / math.sqrt(1.0 + lr ** 2)

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fromage requires params")

        def upd(g, p):
            g_norm = jnp.linalg.norm(g)
            p_norm = jnp.linalg.norm(p)
            scaled = jnp.where((p_norm > 0.0) & (g_norm > 0.0),
                               g * (p_norm / jnp.maximum(g_norm, 1e-30)), g)
            new_p = (p - lr * scaled) * shrink
            return new_p - p

        return jax.tree_util.tree_map(upd, grads, params), state

    return optax.GradientTransformation(init_fn, update_fn)


class MadamState(NamedTuple):
    step: jnp.ndarray
    exp_avg_sq: optax.Updates
    p_max: optax.Updates


def madam(lr: float, scale: float = 3.0, g_bound: Optional[float] = None):
    """Madam (arXiv:2006.14560): multiplicative update clamped to a
    scale-of-init bound (ref: madam.py:20-62)."""

    def init_fn(params):
        return MadamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg_sq=jax.tree_util.tree_map(jnp.zeros_like, params),
            p_max=jax.tree_util.tree_map(
                lambda p: scale * jnp.sqrt(jnp.mean(p * p)), params),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("madam requires params")
        step = state.step + 1
        bias_correction = 1.0 - 0.999 ** step.astype(jnp.float32)

        def upd(g, p, avg_sq, p_max):
            new_avg = 0.999 * avg_sq + 0.001 * g * g
            g_normed = g / jnp.sqrt(new_avg / bias_correction)
            g_normed = jnp.nan_to_num(g_normed, nan=0.0)
            if g_bound is not None:
                g_normed = jnp.clip(g_normed, -g_bound, g_bound)
            new_p = p * jnp.exp(-lr * g_normed * jnp.sign(p))
            new_p = jnp.clip(new_p, -p_max, p_max)
            return new_p - p, new_avg

        flat = jax.tree_util.tree_map(upd, grads, params, state.exp_avg_sq, state.p_max)
        updates = jax.tree_util.tree_map(lambda t: t[0], flat,
                                         is_leaf=lambda t: isinstance(t, tuple))
        new_avg_sq = jax.tree_util.tree_map(lambda t: t[1], flat,
                                            is_leaf=lambda t: isinstance(t, tuple))
        return updates, MadamState(step=step, exp_avg_sq=new_avg_sq, p_max=state.p_max)

    return optax.GradientTransformation(init_fn, update_fn)


def get_scheduler(cfg_opt, iters_per_epoch: int = 1) -> Callable[[int], float]:
    """lr-policy -> multiplier(step). 'step' decays by gamma every
    step_size EPOCHS like torch StepLR (ref: utils/trainer.py:219-240);
    steps are converted via iters_per_epoch. 'constant' -> 1.0."""
    policy = cfg_get(cfg_opt, "lr_policy", None) or {}
    ptype = cfg_get(policy, "type", "constant")
    if ptype == "constant":
        return lambda step: 1.0
    # iteration_mode counts optimizer steps directly; epoch mode converts
    # via iters_per_epoch (ref: utils/trainer.py:219-258)
    iteration_mode = cfg_get(policy, "iteration_mode", False)
    if ptype == "step":
        step_size = policy["step_size"]
        gamma = policy["gamma"]

        def sched(step):
            unit = step if iteration_mode else step // max(iters_per_epoch, 1)
            return gamma ** (unit // step_size)

        return sched
    if ptype == "linear":
        # constant until decay_start, then linear to 0 at decay_end
        # (ref scheduler family)
        decay_start = cfg_get(policy, "decay_start", 0)
        decay_end = cfg_get(policy, "decay_end", decay_start + 1)

        def sched(step):
            # trace-safe: called with a traced step inside the jitted update
            import jax.numpy as jnp

            unit = step if iteration_mode else step // max(iters_per_epoch, 1)
            frac = (unit - decay_start) / max(decay_end - decay_start, 1)
            return jnp.clip(1.0 - frac, 0.0, 1.0)

        return sched
    raise NotImplementedError(f"Learning rate policy {ptype} not implemented.")


def init_optimizer_state(tx, params, plan=None):
    """``tx.init(params)``, materialized under a partition plan.

    With an active ``PartitionPlan`` (parallel/partition.py) the init
    runs as a jitted program whose ``out_shardings`` are the plan's
    cross-replica update-state specs (arXiv:2004.13336): every moment
    leaf is *born* as its 1/N data-axis shard (+ model-axis channel
    shard where the rules match), so the full replicated moment tree —
    2x param bytes for adam, the single biggest state entry in
    PROFILE.md's budget — never exists on any chip, not even
    transiently at init. Scalar bookkeeping leaves (adam ``count``,
    madam ``step``/``p_max``) resolve to replicated. Without a plan
    this is exactly ``tx.init(params)``.
    """
    if plan is None or not getattr(plan, "active", False):
        return tx.init(params)
    import jax
    from jax.sharding import NamedSharding

    shapes = jax.eval_shape(tx.init, params)
    specs = plan.update_state_specs(shapes)
    mesh = plan.mesh
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: type(s).__name__ == "PartitionSpec")
    # lint: allow(bare-jit) -- one-shot sharded optimizer-state init at t=0; out_shardings placement, never re-dispatched
    return jax.jit(tx.init, out_shardings=shardings)(params)


def get_optimizer_for_params(cfg_opt, sched: Optional[Callable[[int], float]] = None):
    """Build the optax chain for one network (ref: utils/trainer.py:261-306).

    Returns GradientTransformation; lr schedule (if any) multiplies the
    base lr per step.
    """
    opt_type = cfg_get(cfg_opt, "type", "adam")
    lr = cfg_get(cfg_opt, "lr", 1e-4)
    if sched is not None:
        lr_sched = lambda step: lr * sched(step)  # noqa: E731
    else:
        lr_sched = lr

    if opt_type == "adam":
        return optax.adam(
            learning_rate=lr_sched,
            b1=cfg_get(cfg_opt, "adam_beta1", 0.9),
            b2=cfg_get(cfg_opt, "adam_beta2", 0.999),
            eps=cfg_get(cfg_opt, "eps", 1e-8),
        )
    if opt_type == "rmsprop":
        base = optax.rmsprop(
            learning_rate=lr_sched,
            eps=cfg_get(cfg_opt, "eps", 1e-8),
        )
        wd = cfg_get(cfg_opt, "weight_decay", 0)
        if wd:
            return optax.chain(optax.add_decayed_weights(wd), base)
        return base
    if opt_type == "sgd":
        return optax.sgd(
            learning_rate=lr_sched,
            momentum=cfg_get(cfg_opt, "momentum", 0) or None,
        )
    if opt_type == "fromage":
        # fromage's shrink couples lr into the update; schedules would
        # change the contraction factor — keep static lr like the reference.
        return fromage(lr)
    if opt_type == "madam":
        return madam(lr, scale=cfg_get(cfg_opt, "scale", 3.0),
                     g_bound=cfg_get(cfg_opt, "g_bound", None))
    raise NotImplementedError(f"Optimizer {opt_type} is not yet implemented.")

"""Async device-prefetch layer: overlap host batch prep, host->device
transfer, and XLA step dispatch.

The host ``DataLoader`` already overlaps decode/augment with compute
(``num_workers`` thread pool), but its batches land on the host — the
trainer then paid a synchronous, uncommitted ``jnp.asarray`` transfer at
the top of every iteration (``to_device``), stalling the step dispatch
for the full H2D latency. ``DevicePrefetcher`` closes that gap, the
jax analogue of the reference's ``pin_memory=True`` +
``.cuda(non_blocking=True)`` pair: a producer thread pulls host
batches, runs the trainer's host-side ``_start_of_iteration`` hook,
splits numeric leaves from host-only entries (``numeric_only``
semantics — strings, per-sample key lists, '_'-prefixed host payloads
stay put), and issues ``jax.device_put`` with committed
``NamedSharding(mesh, P('data', ...))`` specs so arrays arrive already
laid out for the SPMD step program — no post-hoc redistribution inside
jit. A bounded queue keeps up to ``depth`` batches resident on device
ahead of the consumer.

Observability: per-batch ``data/host_wait_ms`` (producer blocked on the
host loader), ``data/transfer_ms`` (device_put dispatch) and
``data/queue_depth`` (ready batches at consume time) accumulate in a
lock-guarded buffer; ``drain_stats()`` hands them to the trainer's
meters, flushed on ``logging_iter`` with the loss meters — nothing here
ever blocks the step loop on a device sync.

Lifecycle contract (mirrors ``DataLoader._iter_prefetch``): the wrapper
is re-iterable — each ``__iter__`` spawns a fresh producer; worker
exceptions travel through the queue and re-raise in the consumer;
abandoning the iterator early (``break`` / GeneratorExit) sets a stop
flag and drains the queue so a blocked producer put always unwinds.

Config: the ``data.device_prefetch`` knob ({enabled, depth}, defaults
on / depth 2) is honored by every family config via the defaults tree;
with it off, consumers keep the synchronous ``to_device`` path.

The producer thread is also where the vid2vid family's amortized
FlowNet2 teacher executes (``flow/cache.py``): the trainer's
``_start_of_iteration`` hook — run here as ``host_preprocess`` —
attaches the teacher's ``(flow, conf)`` ground truth to the batch, so
the 52.2 ms/frame teacher forward overlaps the running step and its
outputs ship through the same committed-sharding transfer as the rest
of the batch (the ``flow_teacher`` span nests under
``prefetch_preprocess`` in the phase table).
"""

from __future__ import annotations

import queue
import threading
import time

from imaginaire_tpu.config import cfg_get


class PrefetchedBatch(dict):
    """Marker type for batches a ``DevicePrefetcher`` produced: the
    host-side ``_start_of_iteration`` hook already ran and numeric
    leaves are committed device arrays — consumers must skip their own
    preprocess + transfer (``BaseTrainer.start_of_iteration`` does)."""


def prefetch_settings(cfg):
    """(enabled, depth) from the ``data.device_prefetch`` config knob.

    Accepts a missing knob (defaults on, depth 2), a bare bool, or the
    {enabled, depth} mapping the defaults tree carries.
    """
    pcfg = cfg_get(cfg_get(cfg, "data", {}) or {}, "device_prefetch", None)
    if pcfg is None:
        return True, 2
    if isinstance(pcfg, bool):
        return pcfg, 2
    return (bool(cfg_get(pcfg, "enabled", True)),
            max(int(cfg_get(pcfg, "depth", 2)), 1))


class DevicePrefetcher:
    """Wrap a host batch iterable; keep ``depth`` batches on device
    ahead of the consumer.

    Args:
        loader: host batch iterable (``DataLoader`` or any iterable of
            dict batches). ``set_epoch``/``__len__``/``dataset`` pass
            through when present.
        host_preprocess: optional ``fn(batch, index) -> batch`` run in
            the producer thread BEFORE transfer — the trainer's
            host-side ``_start_of_iteration`` hook. ``index`` counts
            batches within the current iteration pass, so callers can
            derive the consuming iteration number.
        depth: number of batches kept resident on device ahead of the
            consumer (the queue bound).
        mesh: mesh for the committed batch sharding; defaults to the
            process mesh (``peek_mesh``), degrading to uncommitted
            ``to_device`` placement when none is configured.
    """

    def __init__(self, loader, host_preprocess=None, depth=2, mesh=None,
                 axis="data"):
        self.loader = loader
        self.host_preprocess = host_preprocess
        self.depth = max(int(depth), 1)
        self.mesh = mesh
        self.axis = axis
        self._stats_lock = threading.Lock()
        self._stats = {}
        self._drop_batches = 0  # fast_forward fallback (one-shot)

    # ------------------------------------------------- loader passthrough

    def set_epoch(self, epoch):
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def fast_forward(self, n_batches):
        """Mid-epoch resume: skip the first ``n_batches`` of the next
        iteration pass. Delegates to the wrapped loader (no item is
        loaded or transferred for the skipped prefix); loaders without
        the knob fall back to a producer-side drop counter — batches
        are produced then discarded before preprocess/transfer."""
        if hasattr(self.loader, "fast_forward"):
            self.loader.fast_forward(n_batches)
        else:
            self._drop_batches = max(int(n_batches), 0)

    def __len__(self):
        return len(self.loader)

    @property
    def dataset(self):
        return getattr(self.loader, "dataset", None)

    # ------------------------------------------------------ observability

    def _record(self, name, value):
        with self._stats_lock:
            self._stats.setdefault(name, []).append(float(value))

    def drain_stats(self):
        """Pop accumulated {meter_name: [values]} — plain host floats,
        safe to write into meters without a device sync."""
        with self._stats_lock:
            out, self._stats = self._stats, {}
        return out

    # ------------------------------------------------------------ pipeline

    def _transfer(self, batch):
        """Split host-only leaves out, commit the numeric remainder as
        sharded device arrays, re-merge. Non-dict batches place whole."""
        from imaginaire_tpu.parallel.sharding import place_committed_batch
        from imaginaire_tpu.utils.misc import merge_host_leaves, \
            split_host_leaves

        if not isinstance(batch, dict):
            return place_committed_batch(batch, mesh=self.mesh,
                                         axis=self.axis)
        numeric, host = split_host_leaves(batch)
        placed = place_committed_batch(numeric, mesh=self.mesh,
                                       axis=self.axis)
        return PrefetchedBatch(merge_host_leaves(placed, host))

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        sentinel = object()

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def produce():
            # producer-side telemetry spans (prefetch_host / _preprocess
            # / _transfer) are tagged with this thread's name — the hang
            # watchdog's stack dump and the phase table both show where
            # the pipeline actually spends its time, off the step path
            from imaginaire_tpu import telemetry

            tm = telemetry.get()
            try:
                source = iter(self.loader)
                drop, self._drop_batches = self._drop_batches, 0
                for _ in range(drop):
                    try:
                        next(source)
                    except StopIteration:
                        return
                index = 0
                while not stop.is_set():
                    t0 = time.perf_counter()
                    with tm.span("prefetch_host"):
                        try:
                            batch = next(source)
                        except StopIteration:
                            return
                    self._record("data/host_wait_ms",
                                 (time.perf_counter() - t0) * 1e3)
                    if self.host_preprocess is not None:
                        with tm.span("prefetch_preprocess"):
                            batch = self.host_preprocess(batch, index)
                    t1 = time.perf_counter()
                    with tm.span("prefetch_transfer"):
                        batch = self._transfer(batch)
                    self._record("data/transfer_ms",
                                 (time.perf_counter() - t1) * 1e3)
                    put(batch)
                    index += 1
            except BaseException as e:  # forwarded to the consumer
                put(e)
            finally:
                put(sentinel)

        producer = threading.Thread(target=produce, daemon=True,
                                    name="device-prefetch")
        producer.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                # depth actually in use: this batch + what is still queued
                self._record("data/queue_depth", q.qsize() + 1)
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            producer.join(timeout=10)

"""Unpaired two-domain image dataset — UNIT / MUNIT
(ref: imaginaire/datasets/unpaired_images.py:10-119).

Each data type (images_a, images_b) has its own independent file pool;
training samples each domain independently at random, inference walks
both pools with modulo indexing so differing domain sizes stay valid
(ref: unpaired_images.py:48-70). Augmentation is per-domain (unpaired):
each domain draws its own crop/flip (ref: unpaired_images.py:100-104).
"""

from __future__ import annotations

import random

import numpy as np

from imaginaire_tpu.data.base import BaseDataset


def type_sequences(dataset, root_idx, root, data_type):
    """Per-type {sequence: [stems]} metadata for unpaired domains.

    Folder backends walk <root>/<type>/; lmdb/packed backends read the
    per-type <root>/<type>/all_filenames.json when present, else fall
    back to a per-type key in the shared root manifest.
    """
    import json
    import os

    from imaginaire_tpu.data.backends import create_folder_metadata

    if dataset.backend_kind == "folder":
        return create_folder_metadata(root, [data_type])
    per_type = os.path.join(root, data_type, "all_filenames.json")
    if os.path.exists(per_type):
        with open(per_type) as f:
            return json.load(f)
    seqs = dataset.sequence_lists[root_idx]
    if isinstance(seqs, dict) and data_type in seqs:
        return seqs[data_type]
    raise ValueError(
        f"unpaired dataset: no per-type file list for {data_type!r} under "
        f"{root!r} (need {per_type} or a {data_type!r} key in the root "
        "all_filenames.json — a shared sequence list would silently pair "
        "the domains)")


def load_unpaired_type(dataset, data_type, root_idx, seq, stem):
    """Load + independently augment + normalize one domain's image.

    Shared by the unpaired and few-shot datasets. Returns
    (HWC float32 array, is_flipped bool for this domain's own draw).
    """
    arr = dataset.backends[data_type][root_idx].getitem(f"{seq}/{stem}")
    was_uint8 = getattr(arr, "dtype", None) == np.uint8
    data = {data_type: [arr]}
    data = dataset._apply_ops(data, {data_type: dataset.pre_aug_ops[data_type]})
    data, is_flipped = dataset.augmentor.perform_augmentation(
        data, paired=False)
    data = dataset._apply_ops(data,
                              {data_type: dataset.post_aug_ops[data_type]})
    arr = data[data_type][0].astype(np.float32)
    if was_uint8:  # rescale keyed off the SOURCE dtype, like base.py
        arr = arr / 255.0
    if dataset.normalize[data_type]:
        arr = arr * 2.0 - 1.0
    return arr, is_flipped


class Dataset(BaseDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        # Per-type flattened (root, sequence, stem) pools: each domain has
        # its own file set, so walk each type's metadata independently
        # (base.sequence_lists only indexes the first type)
        # (ref: unpaired_images.py:21-46).
        self.items = {t: [] for t in self.data_types}
        for root_idx, root in enumerate(self.roots):
            for t in self.data_types:
                for seq, stems in type_sequences(self, root_idx, root, t).items():
                    for stem in stems:
                        self.items[t].append((root_idx, seq, stem))
        self.epoch_length = max(len(v) for v in self.items.values())

    def __len__(self):
        return self.epoch_length

    def _sample_keys(self, index):
        """(ref: unpaired_images.py:48-70)."""
        keys = {}
        for t in self.data_types:
            pool = self.items[t]
            if self.is_inference:
                keys[t] = pool[index % len(pool)]
            else:
                keys[t] = random.choice(pool)
        return keys

    def __getitem__(self, index):
        keys = self._sample_keys(index)
        out = {}
        flips = []
        for t in self.data_types:
            root_idx, seq, stem = keys[t]
            out[t], flipped = load_unpaired_type(self, t, root_idx, seq, stem)
            flips.append(flipped)
        # per-domain flags: each domain draws its own flip
        out["is_flipped"] = np.asarray(flips)
        out["key"] = "|".join(f"{keys[t][1]}/{keys[t][2]}"
                              for t in self.data_types)
        return out

"""Paired few-shot video dataset — fs-vid2vid
(ref: imaginaire/datasets/paired_few_shot_videos.py:33-300).

Like paired_videos, but each sample also carries K reference frames
(ref_images / ref_labels) drawn from the same sequence, disjoint from
the training window (ref: paired_few_shot_videos.py:120-200).
Inference mode pins the content sequence and the k-shot frame
(``set_inference_sequence_idx``).
"""

from __future__ import annotations

import random

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.data.paired_videos import Dataset as VideoDataset


class Dataset(VideoDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        self.few_shot_K = cfg_get(self.cfgdata, "initial_few_shot_K", 1)
        self.inference_sequence_idx = 0
        self.inference_k_shot_sequence_index = 0
        self.inference_k_shot_frame_index = 0
        if is_inference:
            # the default sequence (idx 0) is pinned without any
            # set_inference_sequence_idx call — it needs the first-frame
            # crop barrier too
            import threading

            self._first_item_event = threading.Event()
        self._rebuild()

    def set_inference_sequence_idx(self, index, k_shot_index=None,
                                   k_shot_frame_index=0):
        """(ref: paired_few_shot_videos.py:92-107)."""
        self.inference_sequence_idx = index % len(self.sequences)
        self.inference_k_shot_sequence_index = (
            self.inference_sequence_idx if k_shot_index is None
            else k_shot_index % len(self.sequences))
        self.inference_k_shot_frame_index = k_shot_frame_index
        self.epoch_length = len(
            self.sequences[self.inference_sequence_idx][2])
        # a new sequence must not inherit the previous one's
        # threaded common attributes (e.g. the person-crop bbox)
        self._common_attr = None
        import threading

        self._first_item_event = threading.Event()

    def set_few_shot_K(self, k):
        self.few_shot_K = int(k)
        self._rebuild()

    def _rebuild(self):
        few_shot_K = getattr(self, "few_shot_K", 1)
        self.valid = [s for s in self.sequences
                      if len(s[2]) >= self.sequence_length + few_shot_K]
        self.epoch_length = max(len(self.valid), 1)

    def __getitem__(self, index):
        frame_idx = None
        if self.is_inference:
            root_idx, seq, stems = self.sequences[self.inference_sequence_idx]
            frame_idx = index % len(stems)
            frames = [stems[frame_idx]]
            self._await_first_frame(frame_idx)
            ref_root, ref_seq, ref_stems = self.sequences[
                self.inference_k_shot_sequence_index]
            ref_frames = [ref_stems[self.inference_k_shot_frame_index
                                    % len(ref_stems)]]
        else:
            # strided window; the K refs must fit outside it
            # (ref: paired_few_shot_videos.py:150-179)
            required, time_step = self._sample_time_step(
                extra=self.few_shot_K)
            candidates = (self.valid if time_step == 1 else
                          [s for s in self.valid
                           if len(s[2]) >= required + self.few_shot_K])
            root_idx, seq, stems = candidates[index % len(candidates)]
            max_start = len(stems) - required - self.few_shot_K
            start = random.randint(0, max(max_start, 0))
            end = start + required
            frames = stems[start:end:time_step]
            assert len(frames) == self.sequence_length
            # K reference frames disjoint from the window
            pool = list(range(0, start)) + list(range(end, len(stems)))
            ref_frames = [stems[i] for i in
                          sorted(random.sample(pool, self.few_shot_K))]
            ref_root, ref_seq = root_idx, seq

        try:
            raw = self.load_item(root_idx, seq, frames)
            out = self.process_item(raw)
        finally:
            self._signal_first_frame(frame_idx)
        out = self.concat_labels(out)
        ref_raw = self.load_item(ref_root, ref_seq, ref_frames)
        # the reference window computes its OWN person bbox — it must not
        # inherit (or overwrite) the driving window's stashed crop
        ref = self.process_item(ref_raw, thread_common_attr=False)
        ref = self.concat_labels(ref)
        out["ref_images"] = ref["images"]  # (K, H, W, C)
        if "label" in ref:
            out["ref_labels"] = ref["label"]
        out["key"] = f"{seq}/{frames[-1]}"
        return out

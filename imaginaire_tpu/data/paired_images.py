"""Paired image dataset — SPADE / pix2pixHD
(ref: imaginaire/datasets/paired_images.py:9-86, a seq_len=1
specialization of paired_videos).
"""

from __future__ import annotations

import numpy as np

from imaginaire_tpu.data.base import BaseDataset


class Dataset(BaseDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        # Flatten (root, sequence, frame) into a global index.
        self.items = []
        for root_idx, seqs in enumerate(self.sequence_lists):
            for seq, stems in seqs.items():
                for stem in stems:
                    self.items.append((root_idx, seq, stem))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, index):
        root_idx, seq, stem = self.items[index % len(self.items)]
        raw = self.load_item(root_idx, seq, [stem])
        out = self.process_item(raw)
        out = self.concat_labels(out, squeeze_time=True)
        out["key"] = f"{seq}/{stem}"
        return out

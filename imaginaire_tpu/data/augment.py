"""Config-driven augmentation pipeline
(ref: imaginaire/utils/data.py:26-250 Augmentor on albumentations).

cv2-based reimplementation of the reference's augmentation keys, applied
jointly to all augmentable data types (paired mode). Label-like types
(NEAREST interpolator) are resized with nearest-neighbor; images with the
configured interpolator. Augmentations are ordered as given in the
config, matching the reference's ``_build_augmentation_ops``.

Supported keys: resize_smallest_side, resize_h_w, random_resize_h_w_aspect,
rotate, random_rotate_90, random_scale_limit, random_crop_h_w,
center_crop_h_w, horizontal_flip, max_time_step.

Keypoint data types ((N,2)/(N,3) coordinate arrays) are co-transformed
with the same parameters instead of pixel-resampled
(ref: utils/data.py keypoint_params on the albumentations Compose), and
the post-augmentation geometry (resize_h/w, crop_h/w, is_flipped) stays
readable for ``vis::`` ops (ref: datasets/base.py:489-503).
"""

from __future__ import annotations

import random
import threading

import cv2
import numpy as np

_INTERP = {
    "NEAREST": cv2.INTER_NEAREST,
    "BILINEAR": cv2.INTER_LINEAR,
    "BICUBIC": cv2.INTER_CUBIC,
    None: cv2.INTER_LINEAR,
}


def _parse_hw(value):
    h, w = str(value).split(",")
    return int(h), int(w)


def deterministic_resize_chain(aug_cfg, hw):
    """The resize ops a sample of original size ``hw`` deterministically
    receives from ``aug_cfg`` — shared between the Augmentor and the
    flow-cache precompute CLI so both produce bit-identical canonical
    frames. Returns (ops, (h, w), deterministic): ``deterministic`` is
    False when the config carries randomized resize keys
    (random_resize_h_w_aspect / random_scale_limit), in which case the
    caller must fall back to the Augmentor's own per-sample draw."""
    cfg = dict(aug_cfg or {})
    h, w = hw
    ops = []
    if "resize_smallest_side" in cfg:
        s = int(cfg["resize_smallest_side"])
        scale = s / min(h, w)
        h, w = int(round(h * scale)), int(round(w * scale))
        ops.append(("resize", (h, w)))
    if "resize_h_w" in cfg:
        h, w = _parse_hw(cfg["resize_h_w"])
        ops.append(("resize", (h, w)))
    deterministic = not ("random_resize_h_w_aspect" in cfg
                         or ("random_scale_limit" in cfg
                             and "resize_smallest_side" in cfg))
    return ops, (h, w), deterministic


class Augmentor:
    def __init__(self, aug_cfg, interpolators=None, keypoint_data_types=None):
        self.cfg = dict(aug_cfg or {})
        self.interpolators = interpolators or {}
        self.keypoint_data_types = list(keypoint_data_types or [])
        self.max_time_step = int(self.cfg.get("max_time_step", 1))
        self.original_h = 0
        self.original_w = 0
        self.resize_h = 0
        self.resize_w = 0
        self.crop_h = 0
        self.crop_w = 0
        self.is_flipped = False
        # Flow-cache support: data types whose CANONICAL frames (after
        # the resize ops, before crop/flip) are stashed per call, plus a
        # per-call record of the spatial params. Thread-local — the
        # loader's prefetch workers share one Augmentor instance.
        self.capture_canonical_types = set()
        self._tls = threading.local()

    @property
    def last_record(self):
        """Spatial-augmentation record of this thread's last
        ``perform_augmentation`` call (see _make_record)."""
        return getattr(self._tls, "record", None)

    @property
    def last_canonical(self):
        """{data_type: [canonical HWC frames]} captured for
        ``capture_canonical_types`` on this thread's last call (only
        when the record's ``canonical_ok``)."""
        return getattr(self._tls, "canonical", {})

    def _interp(self, data_type):
        return _INTERP.get(self.interpolators.get(data_type), cv2.INTER_LINEAR)

    def perform_augmentation(self, inputs, paired=True):
        """inputs: {data_type: [HWC np.ndarray, ...]}. Returns (outputs,
        is_flipped). Same random draw applied across types and frames."""
        first = next(iter(inputs.values()))[0]
        self.original_h, self.original_w = first.shape[:2]
        h, w = first.shape[:2]

        cfg = self.cfg
        ops, (h, w), resize_deterministic = deterministic_resize_chain(
            cfg, (h, w))
        ops = list(ops)
        if "random_resize_h_w_aspect" in cfg:
            # 'H,W' base with aspect jitter from random_scale_limit.
            bh, bw = _parse_hw(cfg["random_resize_h_w_aspect"])
            limit = float(cfg.get("random_scale_limit", 0.2))
            aspect = 1.0 + random.uniform(0, limit)
            h, w = int(round(bh * aspect)), int(round(bw * aspect))
            ops.append(("resize", (h, w)))
        elif "random_scale_limit" in cfg and "resize_smallest_side" in cfg:
            limit = float(cfg["random_scale_limit"])
            scale = 1.0 + random.uniform(0, limit)
            h, w = int(round(h * scale)), int(round(w * scale))
            ops.append(("resize", (h, w)))
        rotate = float(cfg.get("rotate", 0) or 0)
        if rotate:
            ops.append(("rotate", random.uniform(-rotate, rotate)))
        if cfg.get("random_rotate_90", False):
            ops.append(("rot90", random.randint(0, 3)))
        crop = None
        if "random_crop_h_w" in cfg:
            ch, cw = _parse_hw(cfg["random_crop_h_w"])
            top = random.randint(0, max(h - ch, 0))
            left = random.randint(0, max(w - cw, 0))
            crop = (top, left, ch, cw)
        elif "center_crop_h_w" in cfg:
            ch, cw = _parse_hw(cfg["center_crop_h_w"])
            crop = (max(h - ch, 0) // 2, max(w - cw, 0) // 2, ch, cw)
        if crop:
            ops.append(("crop", crop))
        is_flipped = bool(cfg.get("horizontal_flip", False)) and random.random() < 0.5
        if is_flipped:
            ops.append(("hflip", None))

        # expose the post-augmentation geometry for vis:: ops
        self.resize_h, self.resize_w = h, w
        if crop:
            self.crop_h, self.crop_w = crop[2], crop[3]
            self.resize_h, self.resize_w = crop[2], crop[3]
        else:
            self.crop_h, self.crop_w = h, w
        self.is_flipped = is_flipped

        # canonical split for the flow cache: everything up to the first
        # non-resize op is "canonical" (the resolution flow is computed
        # and cached at); the remainder must be pure crop/hflip for the
        # equivariant flow transform to be valid
        cut = len(ops)
        for i, (op, _) in enumerate(ops):
            if op != "resize":
                cut = i
                break
        canonical_ok = all(op in ("crop", "hflip") for op, _ in ops[cut:])
        record = {
            "original_hw": (self.original_h, self.original_w),
            "canonical_hw": (h, w),
            "crop": crop,  # (top, left, ch, cw) in canonical coords
            "hflip": is_flipped,
            "canonical_ok": canonical_ok,
            "resize_deterministic": resize_deterministic,
        }
        self._tls.record = record
        canonical = {}

        out = {}
        for data_type, frames in inputs.items():
            if data_type in self.keypoint_data_types:
                out[data_type] = [self._apply_keypoints(f, ops) for f in frames]
                continue
            if frames and not hasattr(frames[0], "shape"):
                # non-spatial payloads (e.g. pickled unprojection mappings,
                # ext: pkl) pass through untouched — their convert:: op
                # decodes them after augmentation (ref: the reference's
                # augmentable-type split in datasets/base.py)
                out[data_type] = frames
                continue
            interp = self._interp(data_type)
            if canonical_ok and data_type in self.capture_canonical_types:
                # run the chain in two halves through the SAME _apply so
                # the augmented output stays bit-identical: canonical is
                # the mid-chain state, not a recomputation
                pre = [self._apply(f, ops[:cut], interp) for f in frames]
                canonical[data_type] = pre
                out[data_type] = [self._apply(f, ops[cut:], interp)
                                  for f in pre]
            else:
                out[data_type] = [self._apply(f, ops, interp) for f in frames]
        self._tls.canonical = canonical
        return out, is_flipped

    def _apply_keypoints(self, pts, ops):
        """Co-transform (N, 2[+extra]) xy coordinates with the image ops.

        OpenPose frames arrive as dicts of keypoint groups
        ({pose, face, hand_l, hand_r}, see visualization.pose
        openpose_to_npy) — or, multi-person (openpose_to_npy without
        largest-only), as a LIST of such dicts — each group of each
        person is co-transformed."""
        if isinstance(pts, dict):
            return {k: self._apply_keypoints(v, ops) for k, v in pts.items()}
        if isinstance(pts, list):
            return [self._apply_keypoints(p, ops) for p in pts]
        if pts is None:
            return None
        pts = np.asarray(pts, np.float32).copy()
        if pts.ndim != 2 or pts.shape[-1] < 2:
            return pts
        h, w = self.original_h, self.original_w
        for op, arg in ops:
            if op == "resize":
                nh, nw = arg
                pts[:, 0] *= nw / max(w, 1)
                pts[:, 1] *= nh / max(h, 1)
                h, w = nh, nw
            elif op == "rotate":
                m = cv2.getRotationMatrix2D((w / 2, h / 2), arg, 1.0)
                xy1 = np.concatenate([pts[:, :2], np.ones((len(pts), 1))], 1)
                pts[:, :2] = xy1 @ m.T
            elif op == "rot90":
                for _ in range(arg):
                    x, y = pts[:, 0].copy(), pts[:, 1].copy()
                    pts[:, 0], pts[:, 1] = y, w - 1 - x
                    h, w = w, h
            elif op == "crop":
                top, left, ch, cw = arg
                pts[:, 0] -= left
                pts[:, 1] -= top
                h, w = ch, cw
            elif op == "hflip":
                pts[:, 0] = w - 1 - pts[:, 0]
        return pts

    @staticmethod
    def _apply(img, ops, interp):
        for op, arg in ops:
            if op == "resize":
                img = cv2.resize(img, (arg[1], arg[0]), interpolation=interp)
            elif op == "rotate":
                hh, ww = img.shape[:2]
                m = cv2.getRotationMatrix2D((ww / 2, hh / 2), arg, 1.0)
                img = cv2.warpAffine(img, m, (ww, hh), flags=interp)
            elif op == "rot90":
                img = np.rot90(img, arg)
            elif op == "crop":
                top, left, ch, cw = arg
                img = img[top:top + ch, left:left + cw]
            elif op == "hflip":
                img = img[:, ::-1]
            if img.ndim == 2:
                img = img[:, :, None]
        return np.ascontiguousarray(img)

"""Few-shot unpaired class dataset — FUNIT / COCO-FUNIT
(ref: imaginaire/datasets/unpaired_few_shot_images.py:10-212).

Folder layout: <root>/<data_type>/<class_name>/<files>. The first path
segment of each sequence is its class; training samples a random
content image and a random style image (each with its class index);
evaluation walks one style class at a time via ``set_sample_class_idx``
(ref: unpaired_few_shot_images.py:26-38, 96-120).

Emits: images_content, images_style, labels_content, labels_style.
"""

from __future__ import annotations

import random

import numpy as np

from imaginaire_tpu.data.base import BaseDataset
from imaginaire_tpu.data.unpaired_images import type_sequences


class Dataset(BaseDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        # Per-type pools with class labels derived from the first path
        # segment (ref: unpaired_few_shot_images.py:40-95).
        self.items = {t: [] for t in self.data_types}
        class_names = {t: set() for t in self.data_types}
        for root_idx, root in enumerate(self.roots):
            for t in self.data_types:
                seqs = type_sequences(self, root_idx, root, t)
                for seq, stems in seqs.items():
                    cls = seq.split("/")[0]
                    class_names[t].add(cls)
                    for stem in stems:
                        self.items[t].append((root_idx, seq, stem, cls))
        self.class_name_to_idx = {
            t: {c: i for i, c in enumerate(sorted(class_names[t]))}
            for t in self.data_types}
        self.items_by_class = {t: {} for t in self.data_types}
        for t in self.data_types:
            for item in self.items[t]:
                idx = self.class_name_to_idx[t][item[3]]
                self.items_by_class[t].setdefault(idx, []).append(item)
        self.num_content_classes = len(self.class_name_to_idx["images_content"])
        self.num_style_classes = len(self.class_name_to_idx["images_style"])
        self.sample_class_idx = None
        self.epoch_length = max(len(v) for v in self.items.values())

    def set_sample_class_idx(self, class_idx=None):
        """(ref: unpaired_few_shot_images.py:26-38)."""
        self.sample_class_idx = class_idx
        if class_idx is None:
            self.epoch_length = max(len(v) for v in self.items.values())
        else:
            self.epoch_length = len(
                self.items_by_class["images_style"][class_idx])

    def __len__(self):
        return self.epoch_length

    def _sample_keys(self, index):
        """(ref: unpaired_few_shot_images.py:96-133)."""
        keys = {}
        if self.is_inference and self.sample_class_idx is not None:
            content_pool = self.items["images_content"]
            keys["images_content"] = content_pool[index % len(content_pool)]
            style_pool = self.items_by_class["images_style"][
                self.sample_class_idx]
            keys["images_style"] = style_pool[index % len(style_pool)]
        else:
            for t in self.data_types:
                keys[t] = random.choice(self.items[t])
        return keys

    def __getitem__(self, index):
        from imaginaire_tpu.data.unpaired_images import load_unpaired_type

        keys = self._sample_keys(index)
        out = {}
        flips = []
        for t in self.data_types:
            root_idx, seq, stem, cls = keys[t]
            out[t], flipped = load_unpaired_type(self, t, root_idx, seq, stem)
            flips.append(flipped)
            label_key = "labels_" + t.split("_", 1)[1]
            out[label_key] = np.asarray(self.class_name_to_idx[t][cls],
                                        np.int32)
        out["is_flipped"] = np.asarray(flips)
        out["key"] = "|".join(f"{keys[t][1]}/{keys[t][2]}"
                              for t in self.data_types)
        return out

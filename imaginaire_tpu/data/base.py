"""Config-driven multi-type dataset base (ref: imaginaire/datasets/base.py).

Per data type the config declares ext / num_channels / normalize /
interpolator / use_dont_care / is_mask / pre+post aug ops
(ref: base.py:92-150). Items come out as channel-last float32 numpy with:
  - images normalized to [-1, 1] when ``normalize`` (ref: base.py:203-237),
  - 1-channel label maps one-hot expanded to num_channels (+1 dont-care
    channel kept when use_dont_care, ref: base.py:272-298),
  - all ``input_labels`` types concatenated into ``data['label']``
    (ref: paired_videos.py:276-283).
"""

from __future__ import annotations

import importlib
import os

import numpy as np

from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.data.augment import Augmentor
from imaginaire_tpu.data.backends import (
    FolderBackend,
    LMDBBackend,
    PackedBackend,
    create_folder_metadata,
)


class BaseDataset:
    def __init__(self, cfg, is_inference=False, is_test=False):
        cfg = as_attrdict(cfg)
        self.cfg = cfg
        self.is_inference = is_inference
        self.is_test = is_test
        self.cfgdata = cfg.test_data if is_test else cfg.data
        data_info = (self.cfgdata.test if is_test
                     else (self.cfgdata.val if is_inference else self.cfgdata.train))
        self.data_info = data_info
        self.name = cfg_get(self.cfgdata, "name", "dataset")
        self.roots = list(data_info.roots)
        self.batch_size = cfg_get(data_info, "batch_size", 1)

        backend = "folder"
        if cfg_get(data_info, "is_lmdb", False):
            backend = "lmdb"
        elif cfg_get(data_info, "is_packed", False):
            backend = "packed"
        self.backend_kind = backend

        # Per-type properties (ref: base.py:92-150).
        self.data_types = []
        self.image_data_types = []
        self.extensions = {}
        self.normalize = {}
        self.interpolators = {}
        self.num_channels = {}
        self.use_dont_care = {}
        self.is_mask = {}
        self.pre_aug_ops = {}
        self.post_aug_ops = {}
        for data_type in self.cfgdata.input_types:
            (name, info), = data_type.items()
            self.data_types.append(name)
            self.image_data_types.append(name)
            self.extensions[name] = cfg_get(info, "ext", None)
            self.normalize[name] = cfg_get(info, "normalize", False)
            self.interpolators[name] = cfg_get(info, "interpolator", None)
            self.num_channels[name] = cfg_get(info, "num_channels", None)
            self.use_dont_care[name] = cfg_get(info, "use_dont_care", False)
            self.is_mask[name] = cfg_get(info, "is_mask", False)
            self.pre_aug_ops[name] = _parse_ops(cfg_get(info, "pre_aug_ops", "None"))
            self.post_aug_ops[name] = _parse_ops(cfg_get(info, "post_aug_ops", "None"))
        self.input_labels = list(cfg_get(self.cfgdata, "input_labels", None) or [])
        self.input_image = list(cfg_get(self.cfgdata, "input_image", None) or [])

        # Backends + sequence lists per root.
        self.backends = {t: [] for t in self.data_types}
        self.sequence_lists = []
        for root in self.roots:
            if backend == "folder":
                self.sequence_lists.append(
                    create_folder_metadata(root, self.data_types))
            else:
                import json

                with open(os.path.join(root, "all_filenames.json")) as f:
                    self.sequence_lists.append(json.load(f))
            for t in self.data_types:
                path = os.path.join(root, t)
                if backend == "folder":
                    self.backends[t].append(FolderBackend(path, self.extensions[t]))
                elif backend == "lmdb":
                    self.backends[t].append(LMDBBackend(path, self.extensions[t]))
                else:
                    self.backends[t].append(PackedBackend(path, self.extensions[t]))

        aug_cfg = cfg_get(data_info, "augmentations", None) or {}
        self.augmentor = Augmentor(aug_cfg, self.interpolators)

    # ------------------------------------------------------------------ api

    def __len__(self):
        raise NotImplementedError

    def __getitem__(self, index):
        raise NotImplementedError

    def get_label_lengths(self):
        """{label type: channel count incl. dont-care} (ref: base.py:204-218)."""
        lengths = {}
        for t in self.input_labels:
            n = self.num_channels[t]
            if self.use_dont_care[t]:
                n += 1
            lengths[t] = n
        return lengths

    # ------------------------------------------------------------- loading

    def load_item(self, lmdb_idx, sequence_name, filenames):
        """Load all data types for the given frames -> {type: [HWC arrays]}."""
        data = {}
        for t in self.data_types:
            frames = []
            for fname in filenames:
                key = f"{sequence_name}/{fname}"
                frames.append(self.backends[t][lmdb_idx].getitem(key))
            data[t] = frames
        return data

    def process_item(self, data):
        """pre-ops -> joint augmentation -> post-ops -> normalize/one-hot ->
        concat labels. Returns dict of (T,H,W,C) or (H,W,C) float arrays."""
        data = self._apply_ops(data, self.pre_aug_ops)
        data, is_flipped = self.augmentor.perform_augmentation(
            data, paired=True)
        data = self._apply_ops(data, self.post_aug_ops)

        out = {}
        for t in self.data_types:
            frames = []
            for arr in data[t]:
                arr = arr.astype(np.float32)
                if arr.dtype != np.float32:
                    arr = arr.astype(np.float32)
                if self.is_mask[t] or (self.num_channels[t] and
                                       arr.shape[-1] == 1 and self.num_channels[t] > 1):
                    arr = self._encode_onehot(
                        arr, self.num_channels[t], self.use_dont_care[t])
                else:
                    if arr.max() > 1.5:  # uint8-range input
                        arr = arr / 255.0
                    if self.normalize[t]:
                        arr = arr * 2.0 - 1.0
                frames.append(arr)
            out[t] = np.stack(frames, axis=0)
        out["is_flipped"] = np.asarray(is_flipped)
        return out

    @staticmethod
    def _encode_onehot(label_map, num_labels, use_dont_care):
        """(H,W,1) index map -> (H,W,num_labels[+1]) one-hot
        (ref: base.py:272-298): out-of-range and negative indices become
        the dont-care index; channel kept only when use_dont_care."""
        idx = label_map[..., 0].astype(np.int64)
        idx[(idx < 0) | (idx >= num_labels)] = num_labels
        out = np.zeros(idx.shape + (num_labels + 1,), dtype=np.float32)
        np.put_along_axis(out, idx[..., None], 1.0, axis=-1)
        if not use_dont_care:
            out = out[..., :num_labels]
        return out

    def concat_labels(self, out, squeeze_time=False):
        """(ref: paired_videos.py:276-283)."""
        if self.input_labels:
            labels = [out.pop(t) for t in self.input_labels]
            out["label"] = np.concatenate(labels, axis=-1)
        if squeeze_time:
            for k in list(out.keys()):
                v = out[k]
                if isinstance(v, np.ndarray) and v.ndim >= 4:
                    out[k] = v[0] if v.shape[0] == 1 else v
        return out

    def _apply_ops(self, data, op_dict):
        """'module::function' plugin ops (ref: base.py:386-460)."""
        for t, ops in op_dict.items():
            for op in ops:
                data[t] = op(data[t])
        return data


def _parse_ops(spec):
    if not spec or spec == "None":
        return []
    ops = []
    for item in str(spec).split(","):
        item = item.strip()
        if "::" in item:
            module, fn = item.split("::")
            ops.append(getattr(importlib.import_module(module), fn))
    return ops

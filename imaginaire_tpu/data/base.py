"""Config-driven multi-type dataset base (ref: imaginaire/datasets/base.py).

Per data type the config declares ext / num_channels / normalize /
interpolator / use_dont_care / is_mask / pre+post aug ops
(ref: base.py:92-150). Items come out as channel-last float32 numpy with:
  - images normalized to [-1, 1] when ``normalize`` (ref: base.py:203-237),
  - 1-channel label maps one-hot expanded to num_channels (+1 dont-care
    channel kept when use_dont_care, ref: base.py:272-298),
  - all ``input_labels`` types concatenated into ``data['label']``
    (ref: paired_videos.py:276-283).
"""

from __future__ import annotations

import importlib
import os
import threading

import numpy as np

from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.data.augment import Augmentor
from imaginaire_tpu.data.backends import (
    FolderBackend,
    LMDBBackend,
    PackedBackend,
    create_folder_metadata,
)


class BaseDataset:
    def __init__(self, cfg, is_inference=False, is_test=False):
        cfg = as_attrdict(cfg)
        self.cfg = cfg
        self.is_inference = is_inference
        self.is_test = is_test
        self._common_attr = None
        self._common_attr_lock = threading.Lock()
        self.cfgdata = cfg.test_data if is_test else cfg.data
        data_info = (self.cfgdata.test if is_test
                     else (self.cfgdata.val if is_inference else self.cfgdata.train))
        self.data_info = data_info
        self.name = cfg_get(self.cfgdata, "name", "dataset")
        self.roots = list(data_info.roots)
        self.batch_size = cfg_get(data_info, "batch_size", 1)

        backend = "folder"
        if cfg_get(data_info, "is_lmdb", False):
            backend = "lmdb"
        elif cfg_get(data_info, "is_packed", False):
            backend = "packed"
        self.backend_kind = backend

        # Per-type properties (ref: base.py:92-150).
        self.data_types = []
        self.image_data_types = []
        self.extensions = {}
        self.normalize = {}
        self.interpolators = {}
        self.num_channels = {}
        self.use_dont_care = {}
        self.is_mask = {}
        self.pre_aug_ops = {}
        self.post_aug_ops = {}
        for data_type in self.cfgdata.input_types:
            (name, info), = data_type.items()
            self.data_types.append(name)
            self.image_data_types.append(name)
            self.extensions[name] = cfg_get(info, "ext", None)
            self.normalize[name] = cfg_get(info, "normalize", False)
            self.interpolators[name] = cfg_get(info, "interpolator", None)
            self.num_channels[name] = cfg_get(info, "num_channels", None)
            self.use_dont_care[name] = cfg_get(info, "use_dont_care", False)
            self.is_mask[name] = cfg_get(info, "is_mask", False)
            self.pre_aug_ops[name] = _parse_ops(cfg_get(info, "pre_aug_ops", "None"))
            self.post_aug_ops[name] = _parse_ops(cfg_get(info, "post_aug_ops", "None"))
        # TPU-native label path: ship (H,W) int index maps to the device
        # and one-hot there (trainers/base._expand_labels) instead of
        # building ~num_channels x float32 one-hot tensors on the host —
        # for COCO-Stuff's 183 classes that is a 0.3MB vs 48MB per-image
        # host->device transfer (SURVEY.md §7 hard-part #6).
        self.one_hot_on_device = bool(
            cfg_get(self.cfgdata, "one_hot_on_device", False))
        if self.one_hot_on_device and (
                self.supports_temporal_stride
                or "video" in str(cfg_get(self.cfgdata, "type", ""))):
            # video trainers fold past labels into channels on the host
            # (trainers/vid2vid._start_of_iteration) — int maps would
            # silently skip that path, so refuse rather than mis-train.
            # The type-name check also catches video datasets that don't
            # implement temporal striding (paired_few_shot_videos_native).
            raise ValueError(
                "one_hot_on_device is implemented for image datasets "
                "only; drop the knob for video dataset types")
        self.input_labels = list(cfg_get(self.cfgdata, "input_labels", None) or [])
        self.input_image = list(cfg_get(self.cfgdata, "input_image", None) or [])
        self.keypoint_data_types = list(
            cfg_get(self.cfgdata, "keypoint_data_types", None) or [])
        self.full_data_ops = _parse_ops(
            cfg_get(self.cfgdata, "full_data_ops", "None"))

        # Backends + sequence lists per root.
        self.backends = {t: [] for t in self.data_types}
        self.sequence_lists = []
        for root in self.roots:
            if backend == "folder":
                self.sequence_lists.append(
                    create_folder_metadata(root, self.data_types))
            else:
                import json

                with open(os.path.join(root, "all_filenames.json")) as f:
                    self.sequence_lists.append(json.load(f))
            for t in self.data_types:
                path = os.path.join(root, t)
                if backend == "folder":
                    self.backends[t].append(FolderBackend(path, self.extensions[t]))
                elif backend == "lmdb":
                    self.backends[t].append(LMDBBackend(path, self.extensions[t]))
                else:
                    self.backends[t].append(PackedBackend(path, self.extensions[t]))

        aug_cfg = cfg_get(data_info, "augmentations", None) or {}
        self.augmentor = Augmentor(aug_cfg, self.interpolators,
                                   keypoint_data_types=self.keypoint_data_types)
        if self.augmentor.max_time_step > 1 and not self.supports_temporal_stride:
            # the knob must never parse without effect: silently accepting
            # it would change training semantics vs the reference
            # (ref: datasets/paired_videos.py:167-191)
            raise ValueError(
                f"augmentations.max_time_step={self.augmentor.max_time_step} "
                f"is configured, but {type(self).__module__} does not "
                "implement strided temporal sampling; use a video dataset "
                "type or drop the knob")

    # video subclasses honoring augmentations.max_time_step set this True
    supports_temporal_stride = False

    # ------------------------------------------------------------------ api

    def __len__(self):
        raise NotImplementedError

    def __getitem__(self, index):
        raise NotImplementedError

    def get_label_lengths(self):
        """{label type: channel count incl. dont-care} (ref: base.py:204-218)."""
        lengths = {}
        for t in self.input_labels:
            n = self.num_channels[t]
            if self.use_dont_care[t]:
                n += 1
            lengths[t] = n
        return lengths

    # ------------------------------------------------------------- loading

    def load_item(self, lmdb_idx, sequence_name, filenames):
        """Load all data types for the given frames -> {type: [HWC arrays]}.

        Backends exposing ``getitems`` (the packed shard's native
        thread-pool reader) fetch a whole frame window in one concurrent
        batched read — the hot path for video datasets."""
        data = {}
        for t in self.data_types:
            backend = self.backends[t][lmdb_idx]
            keys = [f"{sequence_name}/{fname}" for fname in filenames]
            if len(keys) > 1 and hasattr(backend, "getitems"):
                data[t] = backend.getitems(keys)
            else:
                data[t] = [backend.getitem(k) for k in keys]
        return data

    def process_item(self, data, thread_common_attr=True):
        """pre-ops -> joint augmentation -> post-ops -> normalize/one-hot ->
        concat labels. Returns dict of (T,H,W,C) or (H,W,C) float arrays.

        ``thread_common_attr=False`` processes the item WITHOUT reading or
        writing the sequence-level common-attribute stash — the few-shot
        reference window must compute its own person bbox, not inherit
        the driving window's (ref: fs_vid2vid.py:242-256 computes
        ref_crop_coords separately)."""
        # Key the 0-255 -> 0-1 rescale off the SOURCE dtype, not a value
        # heuristic (float-valued data like .npy flow fields can exceed
        # 1.5 and must not be divided by 255).
        was_uint8 = {t: (len(data[t]) > 0 and
                         getattr(data[t][0], "dtype", None) == np.uint8)
                     for t in self.data_types}
        data = self._apply_ops(data, self.pre_aug_ops)
        data, is_flipped = self.augmentor.perform_augmentation(
            data, paired=True)
        # Keep the co-transformed keypoint coordinates as '<type>_xy'
        # before the vis:: op rasterizes them into label maps
        # (ref: paired_few_shot_videos.py:241-246); full-data ops like
        # crop_face_from_data consume these.
        kp_copies = {}
        for t in self.keypoint_data_types:
            frames = data.get(t)
            if frames and not isinstance(frames[0], dict):
                try:
                    kp_copies[t + "_xy"] = np.stack(
                        [np.asarray(f, np.float32) for f in frames])
                except (ValueError, TypeError):
                    # ragged per-frame keypoint counts, or structured
                    # multi-person lists (openpose_to_npy without
                    # largest-only): no flat stash
                    pass
        data = self._apply_ops(data, self.post_aug_ops)
        data.update(kp_copies)
        # thread common attributes (e.g. crop_person_from_data's inference
        # crop bbox) from the first processed window into later windows of
        # the same sequence (ref: paired_few_shot_videos.py:296-312;
        # cleared by set_inference_sequence_idx). The loader's prefetch
        # workers are THREADS over this shared dataset, so the stash is
        # lock-protected; windows that started before the first stash
        # landed still compute their own bbox (same first-windows caveat
        # as the reference's worker-index dance). The sequential eval
        # frame loaders (video FID / test loops) are unaffected.
        if thread_common_attr and self.is_inference:
            with self._common_attr_lock:
                if getattr(self, "_common_attr", None):
                    data.setdefault("common_attr", self._common_attr)
        data = self._apply_full_data_ops(data)
        if "common_attr" in data:
            stashed = data.pop("common_attr")
            if thread_common_attr and self.is_inference:
                with self._common_attr_lock:
                    self._common_attr = stashed

        out = {}
        for k in kp_copies:
            if k in data:
                out[k] = data[k]
        for t in self.data_types:
            if t not in data:
                continue  # consumed by a full-data op (e.g. instance maps)
            if not isinstance(data[t], (list, tuple)):
                # a convert:: op replaced the frame list with a structured
                # payload (e.g. decode_unprojections' {resolution: array}
                # dict) — pass it through; consumers read it directly
                out[t] = data[t]
                continue
            frames = []
            for arr in data[t]:
                arr = np.asarray(arr)
                vis_output = arr.ndim == 3 and t in self.keypoint_data_types
                arr = arr.astype(np.float32)
                if self.is_mask[t] or (self.num_channels[t] and arr.ndim == 3
                                       and arr.shape[-1] == 1
                                       and self.num_channels[t] > 1
                                       and not vis_output):
                    if self.one_hot_on_device and self.is_mask[t] \
                            and t in self.input_labels:
                        arr = self._encode_index_map(
                            arr, self.num_channels[t])
                    else:
                        arr = self._encode_onehot(
                            arr, self.num_channels[t], self.use_dont_care[t])
                else:
                    if was_uint8[t]:
                        arr = arr / 255.0
                    if self.normalize[t]:
                        arr = arr * 2.0 - 1.0
                frames.append(arr)
            out[t] = np.stack(frames, axis=0)
        out["is_flipped"] = np.asarray(is_flipped)
        return out

    @staticmethod
    def _encode_index_map(label_map, num_labels):
        """(H,W,1) -> (H,W,1) int32 with the same out-of-range mapping as
        ``_encode_onehot`` (OOR/negative -> dont-care index num_labels);
        the device-side ``jax.nn.one_hot`` then reproduces the host
        encoding exactly (a dropped dont-care channel falls out as the
        all-zero row one_hot gives out-of-range indices)."""
        idx = label_map[..., :1].astype(np.int32)
        idx[(idx < 0) | (idx >= num_labels)] = num_labels
        return idx

    @staticmethod
    def _encode_onehot(label_map, num_labels, use_dont_care):
        """(H,W,1) index map -> (H,W,num_labels[+1]) one-hot
        (ref: base.py:272-298): out-of-range and negative indices become
        the dont-care index; channel kept only when use_dont_care."""
        idx = label_map[..., 0].astype(np.int64)
        idx[(idx < 0) | (idx >= num_labels)] = num_labels
        out = np.zeros(idx.shape + (num_labels + 1,), dtype=np.float32)
        np.put_along_axis(out, idx[..., None], 1.0, axis=-1)
        if not use_dont_care:
            out = out[..., :num_labels]
        return out

    def concat_labels(self, out, squeeze_time=False):
        """(ref: paired_videos.py:276-283).

        With ``one_hot_on_device`` the single mask label type stays an
        int index map under ``label`` (channel dim dropped; the trainer
        one-hot expands it on device) and any remaining float label
        types concatenate under ``label_float`` — the trainer appends
        them after the device-side one-hot, preserving the reference's
        label channel order (mask channels first)."""
        if self.input_labels and self.one_hot_on_device:
            mask_types = [t for t in self.input_labels if self.is_mask[t]]
            if len(mask_types) != 1:
                raise ValueError(
                    "one_hot_on_device needs exactly one mask label type, "
                    f"got {mask_types} — disable the knob for this config")
            if mask_types[0] != self.input_labels[0]:
                raise ValueError(
                    "one_hot_on_device requires the mask label type first "
                    "in input_labels (channel-order contract)")
            idx = out.pop(mask_types[0])
            out["label"] = idx[..., 0]  # (T,H,W) int32
            floats = [out.pop(t) for t in self.input_labels
                      if t != mask_types[0]]
            if floats:
                out["label_float"] = np.concatenate(floats, axis=-1)
        elif self.input_labels:
            labels = [out.pop(t) for t in self.input_labels]
            out["label"] = np.concatenate(labels, axis=-1)
        if squeeze_time:
            for k in list(out.keys()):
                v = out[k]
                min_ndim = 3 if (k == "label" and self.one_hot_on_device) \
                    else 4  # int index maps carry no channel dim
                if isinstance(v, np.ndarray) and v.ndim >= min_ndim:
                    out[k] = v[0] if v.shape[0] == 1 else v
        return out

    def _apply_ops(self, data, op_dict):
        """Plugin ops with the reference's spec grammar
        (ref: base.py:386-515): builtins (decode_json/decode_pkl/
        to_numpy), 'module::function' per-type ops, and the prefixed
        'vis::module::function' (receives the augmentation geometry and
        turns keypoints into rendered label maps) / 
        'convert::module::function' forms."""
        for t, ops in op_dict.items():
            if t not in data:
                continue
            for spec in ops:
                fn, op_type = self._resolve_op(spec)
                data[t] = fn(data[t])
        return data

    def _apply_full_data_ops(self, data):
        """Ops over the whole data dict (ref: base.py:399-406)."""
        for spec in self.full_data_ops:
            module, fn_name = spec.split("::")
            fn = getattr(importlib.import_module(module), fn_name)
            data = fn(self.cfgdata, self.is_inference, data)
        return data

    def _resolve_op(self, spec):
        """(ref: base.py:434-515)."""
        import json
        import pickle
        from functools import partial

        if spec == "decode_json":
            return (lambda frames: [json.loads(f) if isinstance(f, (str, bytes))
                                    else f for f in frames]), None
        if spec == "decode_pkl":
            return (lambda frames: [pickle.loads(f) for f in frames]), None
        if spec == "to_numpy":
            return (lambda frames: [np.asarray(f) for f in frames]), None
        parts = str(spec).split("::")
        if len(parts) == 2:
            module, fn_name = parts
            return getattr(importlib.import_module(module), fn_name), None
        if len(parts) == 3:
            op_type, module, fn_name = parts
            fn = getattr(importlib.import_module(module), fn_name)
            if op_type == "vis":
                aug = self.augmentor
                return partial(fn, aug.resize_h, aug.resize_w, aug.crop_h,
                               aug.crop_w, aug.original_h, aug.original_w,
                               aug.is_flipped, self.cfgdata), "vis"
            if op_type == "convert":
                return fn, "convert"
        raise ValueError(f"Unknown op spec {spec!r}")


def _parse_ops(spec):
    if not spec or spec == "None":
        return []
    return [item.strip() for item in str(spec).split(",")
            if item.strip() and item.strip() != "None"]

"""Paired video dataset — vid2vid family
(ref: imaginaire/datasets/paired_videos.py:24-316).

Sequences of aligned frames per data type. ``sequence_length`` is
mutable: the trainer's curriculum doubles it as temporal training
progresses (``set_sequence_length``, ref: paired_videos.py:74-89);
sampling picks a sequence with at least that many frames and a random
start offset. Output tensors are (T, H, W, C); the loader collates to
(B, T, H, W, C).
"""

from __future__ import annotations

import random
import threading

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.data.base import BaseDataset


class Dataset(BaseDataset):
    supports_temporal_stride = True

    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        self.sequence_length = int(
            cfg_get(self.data_info, "initial_sequence_length", 1)
            if not is_inference else 1)
        # Flatten (root, sequence) with frame lists.
        self.sequences = []
        self.sequence_length_max = 0
        for root_idx, seqs in enumerate(self.sequence_lists):
            for seq, stems in seqs.items():
                self.sequences.append((root_idx, seq, list(stems)))
                self.sequence_length_max = max(self.sequence_length_max,
                                               len(stems))
        # clamp here too: the first batch is fetched before the trainer's
        # curriculum ever calls set_sequence_length
        self.sequence_length = min(self.sequence_length,
                                   max(self.sequence_length_max, 1))
        self._rebuild()
        # Teacher flow cache, dataset half (flow/cache.py): on training
        # items, look the canonical-resolution (flow, conf) pairs up in
        # the on-disk store from the loader worker threads (hits load
        # here, in parallel; misses ship canonical frames for the
        # producer-thread teacher). Inference items never carry flow
        # supervision.
        self._flow_hook = None
        if not is_inference and not is_test and self.input_image:
            from imaginaire_tpu.flow.cache import (
                DatasetFlowCacheHook,
                flow_cache_settings,
            )

            if flow_cache_settings(cfg).enabled \
                    and cfg_get(cfg, "flow_network", None) is not None:
                image_type = self.input_image[0]
                hook = DatasetFlowCacheHook(
                    cfg, self.name, image_type,
                    self.normalize.get(image_type, False),
                    weights_path=cfg_get(cfg.flow_network, "weights_path",
                                         None))
                if hook.active:
                    self._flow_hook = hook
                    self.augmentor.capture_canonical_types.add(image_type)

    def set_sequence_length(self, sequence_length):
        """(ref: paired_videos.py:74-89)."""
        sequence_length = min(int(sequence_length), self.sequence_length_max)
        self.sequence_length = sequence_length
        self._rebuild()

    def num_inference_sequences(self):
        """(ref: paired_videos.py:91-97)."""
        assert self.is_inference
        return len(self.sequences)

    def set_inference_sequence_idx(self, index):
        """Pin one sequence; items become its frames one by one
        (ref: paired_videos.py:99-112). The video FID/eval harness and
        the per-frame test loop iterate this way."""
        assert self.is_inference
        self.inference_sequence_idx = index % len(self.sequences)
        self.epoch_length = len(
            self.sequences[self.inference_sequence_idx][2])
        # a new sequence must not inherit the previous one's
        # threaded common attributes (e.g. the person-crop bbox)
        self._common_attr = None
        # prefetch workers processing frames >0 block on this until
        # frame 0 has stashed the sequence's common attrs — otherwise
        # the first prefetched window computes its own crop and the
        # rollout's first frames jitter
        self._first_item_event = threading.Event()

    def _rebuild(self):
        self.valid = [s for s in self.sequences
                      if len(s[2]) >= self.sequence_length]
        self.epoch_length = max(len(self.valid), 1)

    def __len__(self):
        return self.epoch_length

    def _sample_time_step(self, extra=0):
        """Temporal-stride augmentation: a random frame stride in
        [1, max_time_step], falling back to 1 when the strided window
        (plus ``extra`` frames, e.g. few-shot refs) exceeds even the
        longest sequence (ref: paired_videos.py:167-177,
        utils/data.py:111-114)."""
        time_step = random.randint(1, self.augmentor.max_time_step)
        required = 1 + (self.sequence_length - 1) * time_step
        if required + extra > self.sequence_length_max:
            required, time_step = self.sequence_length, 1
        return required, time_step

    def __getitem__(self, index):
        seq_idx = getattr(self, "inference_sequence_idx", None)
        if self.is_inference and seq_idx is not None:
            # pinned sequence: item = one frame (ref: paired_videos.py:150+)
            root_idx, seq, stems = self.sequences[seq_idx]
            frame_idx = index % len(stems)
            frames = [stems[frame_idx]]
            self._await_first_frame(frame_idx)
        else:
            if self.is_inference:
                required, time_step = self.sequence_length, 1
            else:
                required, time_step = self._sample_time_step()
            # stride > 1 needs a longer raw window than self.valid
            # guarantees (ref: paired_videos.py:178-182)
            candidates = (self.valid if time_step == 1 else
                          [s for s in self.valid if len(s[2]) >= required])
            root_idx, seq, stems = candidates[index % len(candidates)]
            max_start = len(stems) - required
            start = (0 if self.is_inference
                     else random.randint(0, max_start) if max_start > 0
                     else 0)
            frames = stems[start:start + required:time_step]
            assert len(frames) == self.sequence_length
            frame_idx = None
        try:
            raw = self.load_item(root_idx, seq, frames)
            out = self.process_item(raw)
        finally:
            self._signal_first_frame(frame_idx)
        out = self.concat_labels(out)  # keeps (T, H, W, C)
        if self._flow_hook is not None and frame_idx is None \
                and len(frames) >= 2:
            out = self._flow_hook.attach_item(
                out, root_idx, seq, list(frames),
                self.augmentor.last_record,
                (self.augmentor.last_canonical or {}).get(
                    self._flow_hook.image_type))
        out["key"] = f"{seq}/{frames[-1]}"
        return out

    # -------------------------------------------- first-frame crop barrier

    def _await_first_frame(self, frame_idx):
        """Pinned-sequence prefetch barrier: frames >0 wait until frame 0
        has processed (and stashed the sequence common attrs, e.g. the
        person-crop bbox) so every frame of the window uses ONE crop.
        Frame 0 is always submitted to the pool first, so this cannot
        self-deadlock; the timeout guards a wedged first frame (waiters
        then fall back to computing their own crop, as before)."""
        ev = getattr(self, "_first_item_event", None)
        if ev is None or frame_idx is None or frame_idx == 0:
            return
        ev.wait(timeout=30.0)

    def _signal_first_frame(self, frame_idx):
        """Release the barrier once frame 0 finished (even on failure —
        the exception surfaces in the consumer either way)."""
        ev = getattr(self, "_first_item_event", None)
        if ev is not None and frame_idx == 0:
            ev.set()

"""Paired video dataset — vid2vid family
(ref: imaginaire/datasets/paired_videos.py:24-316).

Sequences of aligned frames per data type. ``sequence_length`` is
mutable: the trainer's curriculum doubles it as temporal training
progresses (``set_sequence_length``, ref: paired_videos.py:74-89);
sampling picks a sequence with at least that many frames and a random
start offset. Output tensors are (T, H, W, C); the loader collates to
(B, T, H, W, C).
"""

from __future__ import annotations

import random

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.data.base import BaseDataset


class Dataset(BaseDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        self.sequence_length = int(
            cfg_get(self.data_info, "initial_sequence_length", 1)
            if not is_inference else 1)
        # Flatten (root, sequence) with frame lists.
        self.sequences = []
        self.sequence_length_max = 0
        for root_idx, seqs in enumerate(self.sequence_lists):
            for seq, stems in seqs.items():
                self.sequences.append((root_idx, seq, list(stems)))
                self.sequence_length_max = max(self.sequence_length_max,
                                               len(stems))
        # clamp here too: the first batch is fetched before the trainer's
        # curriculum ever calls set_sequence_length
        self.sequence_length = min(self.sequence_length,
                                   max(self.sequence_length_max, 1))
        self._rebuild()

    def set_sequence_length(self, sequence_length):
        """(ref: paired_videos.py:74-89)."""
        sequence_length = min(int(sequence_length), self.sequence_length_max)
        self.sequence_length = sequence_length
        self._rebuild()

    def num_inference_sequences(self):
        """(ref: paired_videos.py:91-97)."""
        assert self.is_inference
        return len(self.sequences)

    def set_inference_sequence_idx(self, index):
        """Pin one sequence; items become its frames one by one
        (ref: paired_videos.py:99-112). The video FID/eval harness and
        the per-frame test loop iterate this way."""
        assert self.is_inference
        self.inference_sequence_idx = index % len(self.sequences)
        self.epoch_length = len(
            self.sequences[self.inference_sequence_idx][2])
        # a new sequence must not inherit the previous one's
        # threaded common attributes (e.g. the person-crop bbox)
        self._common_attr = None

    def _rebuild(self):
        self.valid = [s for s in self.sequences
                      if len(s[2]) >= self.sequence_length]
        self.epoch_length = max(len(self.valid), 1)

    def __len__(self):
        return self.epoch_length

    def __getitem__(self, index):
        seq_idx = getattr(self, "inference_sequence_idx", None)
        if self.is_inference and seq_idx is not None:
            # pinned sequence: item = one frame (ref: paired_videos.py:150+)
            root_idx, seq, stems = self.sequences[seq_idx]
            frames = [stems[index % len(stems)]]
        else:
            root_idx, seq, stems = self.valid[index % len(self.valid)]
            max_start = len(stems) - self.sequence_length
            start = (0 if self.is_inference
                     else random.randint(0, max_start) if max_start > 0
                     else 0)
            frames = stems[start:start + self.sequence_length]
        raw = self.load_item(root_idx, seq, frames)
        out = self.process_item(raw)
        out = self.concat_labels(out)  # keeps (T, H, W, C)
        out["key"] = f"{seq}/{frames[-1]}"
        return out

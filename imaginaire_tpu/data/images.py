"""Class-conditional image dataset (ref: imaginaire/datasets/images.py:10-197).

Folder layout: <root>/images/<class_name>/<files>; the class index comes
from the first path segment. Training samples a random image (optionally
restricted to one class via ``set_sample_class_idx``); emits
``images`` + integer ``labels``.
"""

from __future__ import annotations

import random

import numpy as np

from imaginaire_tpu.data.base import BaseDataset
from imaginaire_tpu.data.unpaired_images import (
    load_unpaired_type,
    type_sequences,
)


class Dataset(BaseDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        t = self.data_types[0]
        self.image_type = t
        self.items = []
        class_names = set()
        for root_idx, root in enumerate(self.roots):
            for seq, stems in type_sequences(self, root_idx, root, t).items():
                cls = seq.split("/")[0]
                class_names.add(cls)
                for stem in stems:
                    self.items.append((root_idx, seq, stem, cls))
        self.class_name_to_idx = {c: i for i, c
                                  in enumerate(sorted(class_names))}
        self.num_classes = len(self.class_name_to_idx)
        self.items_by_class = {}
        for item in self.items:
            idx = self.class_name_to_idx[item[3]]
            self.items_by_class.setdefault(idx, []).append(item)
        self.sample_class_idx = None
        self.epoch_length = len(self.items)

    def set_sample_class_idx(self, class_idx=None):
        """(ref: images.py:23-31)."""
        self.sample_class_idx = class_idx
        self.epoch_length = (len(self.items) if class_idx is None
                             else len(self.items_by_class[class_idx]))

    def __len__(self):
        return self.epoch_length

    def __getitem__(self, index):
        if self.sample_class_idx is not None:
            pool = self.items_by_class[self.sample_class_idx]
        else:
            pool = self.items
        item = (pool[index % len(pool)] if self.is_inference
                else random.choice(pool))
        root_idx, seq, stem, cls = item
        image, flipped = load_unpaired_type(self, self.image_type, root_idx,
                                            seq, stem)
        return {
            self.image_type: image,
            "labels": np.asarray(self.class_name_to_idx[cls], np.int32),
            "is_flipped": np.asarray(flipped),
            "key": f"{seq}/{stem}",
        }

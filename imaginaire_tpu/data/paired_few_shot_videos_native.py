"""Few-shot paired dataset over *encoded video clips*
(ref: imaginaire/datasets/paired_few_shot_videos_native.py:18-229).

Where ``paired_few_shot_videos`` reads per-frame image files, this
variant stores whole encoded clips (one ``.mp4``/``.avi`` blob per
sequence entry) and decodes two frames per sample on the host:
a *driving* frame and a *source* (few-shot reference) frame — the
reference decodes with torchvision.io (``_getitem``, ref:
paired_few_shot_videos_native.py:117-222) and emits
``driving_images`` / ``source_images``.

TPU-native design notes:
  - decoding uses cv2.VideoCapture (no av/decord/torchvision in the
    image); blobs come through any backend (folder / packed shard), so
    clips can live in the native packed format and be fetched by the
    C++ thread-pool reader.
  - ``first_last_only`` pins the two frames to the clip's endpoints
    (ref: paired_few_shot_videos_native.py:29-33,151-154).
  - corrupt clips degrade to blank frames with a console warning, like
    the reference's try/except (ref: 157-161) — a bad shard must not
    kill a 10k-step TPU training job.
"""

from __future__ import annotations

import os
import random
import tempfile

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.data.base import BaseDataset

_VIDEO_EXTS = ("mp4", "avi", "mov", "webm", "mkv")


def decode_video_frames(buf_or_path, frame_indices=None, num_random=2,
                        first_last_only=False, rng=None):
    """Decode chosen frames from an encoded video.

    Returns a list of HWC uint8 RGB arrays. ``frame_indices`` wins;
    otherwise picks ``num_random`` distinct random frames (or the first
    and last when ``first_last_only``)."""
    import cv2

    rng = rng or random
    tmp = None
    path = buf_or_path
    if isinstance(buf_or_path, (bytes, bytearray)):
        tmp = tempfile.NamedTemporaryFile(suffix=".mp4", delete=False)
        tmp.write(buf_or_path)
        tmp.flush()
        tmp.close()
        path = tmp.name
    try:
        cap = cv2.VideoCapture(path)
        if not cap.isOpened():
            raise ValueError("cv2.VideoCapture failed to open clip")
        n = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
        out = None
        if n > 0:
            idxs = _choose_indices(n, frame_indices, num_random,
                                   first_last_only, rng)
            out = []
            for i in idxs:
                cap.set(cv2.CAP_PROP_POS_FRAMES, i)
                ok, frame = cap.read()
                if not ok:
                    # container over-reported its frame count (VFR /
                    # truncated GOP): fall back to sequential decode
                    out = None
                    break
                out.append(frame)
        if out is None:
            # No (reliable) frame count. Stream instead of buffering the
            # whole clip (a long 1080p clip decoded wholesale is tens of
            # GB): first/last keeps 2 frames; random/indexed counts in a
            # first pass, then keeps only the chosen frames.
            cap.release()
            cap = cv2.VideoCapture(path)
            if first_last_only and frame_indices is None:
                first = last = None
                while True:
                    ok, frame = cap.read()
                    if not ok:
                        break
                    if first is None:
                        first = frame
                    last = frame
                if first is None:
                    raise ValueError("empty video clip")
                out = [first, last]
            else:
                n = 0
                while cap.grab():
                    n += 1
                if n == 0:
                    raise ValueError("empty video clip")
                idxs = _choose_indices(n, frame_indices, num_random,
                                       first_last_only, rng)
                wanted = {}
                cap.release()
                cap = cv2.VideoCapture(path)
                for i in range(max(idxs) + 1):
                    ok, frame = cap.read()
                    if not ok:
                        break
                    if i in idxs:
                        wanted[i] = frame
                missing = [i for i in idxs if i not in wanted]
                if missing:
                    raise ValueError(f"failed to decode frames {missing}")
                out = [wanted[i] for i in idxs]
        cap.release()
        return [cv2.cvtColor(f, cv2.COLOR_BGR2RGB) for f in out]
    finally:
        if tmp is not None:
            os.unlink(tmp.name)


def _choose_indices(n, frame_indices, num_random, first_last_only, rng):
    if frame_indices is not None:
        return [i % n for i in frame_indices]
    if first_last_only:
        return [0, max(n - 1, 0)]
    k = min(num_random, n)
    idxs = rng.sample(range(n), k)
    while len(idxs) < num_random:  # clip shorter than requested draws
        idxs.append(idxs[-1])
    return idxs


def _resize_target(augmentor):
    """The augmentation pipeline's output (h, w), if a resize key pins
    one; used to size blank fallback frames consistently."""
    cfg = getattr(augmentor, "cfg", {}) or {}
    for key in ("random_crop_h_w", "center_crop_h_w", "resize_h_w"):
        if key in cfg:
            h, w = str(cfg[key]).split(",")
            return int(h), int(w)
    return None


class Dataset(BaseDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        self.is_video_dataset = True
        self.first_last_only = cfg_get(self.cfgdata, "first_last_only", False)
        self.video_data_type = cfg_get(self.cfgdata, "video_data_type",
                                       "videos")
        # flat clip mapping (ref: paired_few_shot_videos_native.py:54-80)
        self.mapping = []
        for root_idx, sequence_list in enumerate(self.sequence_lists):
            for sequence_name, filenames in sequence_list.items():
                for filename in filenames:
                    self.mapping.append((root_idx, sequence_name, filename))
        self.epoch_length = len(self.mapping)

    def __len__(self):
        return self.epoch_length

    def _probe_clip_hw(self):
        """Frame size from another clip's container header (no full
        decode), so blank fallbacks match healthy items' shape even when
        the very first item of the run is the corrupt one."""
        import cv2

        for root_idx, seq, fname in self.mapping[:8]:
            try:
                blob = self.load_item(root_idx, seq, [fname])[
                    self.video_data_type][0]
                frames = decode_video_frames(blob, frame_indices=[0])
                self._last_good_hw = frames[0].shape[:2]
                return self._last_good_hw
            except Exception:  # noqa: BLE001
                continue
        return None

    def num_inference_sequences(self):
        return len(self.mapping)

    def __getitem__(self, index):
        root_idx, sequence_name, filename = self.mapping[
            index % max(len(self.mapping), 1)]
        raw = self.load_item(root_idx, sequence_name, [filename])

        vt = self.video_data_type
        blob = raw[vt][0]
        try:
            frames = decode_video_frames(
                blob, first_last_only=self.first_last_only)
            self._last_good_hw = frames[0].shape[:2]
        except Exception as e:  # noqa: BLE001 — degrade, don't kill the run
            print(f"paired_few_shot_videos_native: bad clip "
                  f"{sequence_name}/{filename}: {e}")
            # Match healthy items' shape so batch collation survives:
            # prefer the last decoded clip's size, else the config's
            # resize target, else probe another clip's header, else the
            # reference's 512 default
            # (ref: paired_few_shot_videos_native.py:157-161).
            h, w = getattr(self, "_last_good_hw", None) \
                or _resize_target(self.augmentor) \
                or self._probe_clip_hw() or (512, 512)
            blank = np.zeros((h, w, 3), dtype=np.uint8)
            frames = [blank, blank.copy()]
        raw[vt] = frames
        # non-video data types carry one entry per clip; replicate across
        # the two decoded frames so joint augmentation stays paired
        for t in self.data_types:
            if t != vt and len(raw[t]) == 1:
                raw[t] = [raw[t][0], raw[t][0]]

        out = self.process_item(raw)
        out = self.concat_labels(out)
        videos = out.pop(vt)
        out["driving_images"] = videos[0]
        out["source_images"] = videos[1]
        out["key"] = f"{sequence_name}/{filename}"
        out["original_h_w"] = np.array(
            [self.augmentor.original_h, self.augmentor.original_w],
            dtype=np.int32)
        return out

"""Few-shot paired dataset over *encoded video clips*
(ref: imaginaire/datasets/paired_few_shot_videos_native.py:18-229).

Where ``paired_few_shot_videos`` reads per-frame image files, this
variant stores whole encoded clips (one ``.mp4``/``.avi`` blob per
sequence entry) and decodes two frames per sample on the host:
a *driving* frame and a *source* (few-shot reference) frame — the
reference decodes with torchvision.io (``_getitem``, ref:
paired_few_shot_videos_native.py:117-222) and emits
``driving_images`` / ``source_images``.

TPU-native design notes:
  - decoding uses cv2.VideoCapture (no av/decord/torchvision in the
    image); blobs come through any backend (folder / packed shard), so
    clips can live in the native packed format and be fetched by the
    C++ thread-pool reader.
  - ``first_last_only`` pins the two frames to the clip's endpoints
    (ref: paired_few_shot_videos_native.py:29-33,151-154).
  - corrupt clips degrade to blank frames with a console warning, like
    the reference's try/except (ref: 157-161) — a bad shard must not
    kill a 10k-step TPU training job.
"""

from __future__ import annotations

import os
import random
import tempfile

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.data.base import BaseDataset

_VIDEO_EXTS = ("mp4", "avi", "mov", "webm", "mkv")


def decode_video_frames(buf_or_path, frame_indices=None, num_random=2,
                        first_last_only=False, rng=None):
    """Decode chosen frames from an encoded video.

    Returns a list of HWC uint8 RGB arrays. ``frame_indices`` wins;
    otherwise picks ``num_random`` distinct random frames (or the first
    and last when ``first_last_only``)."""
    import cv2

    rng = rng or random
    tmp = None
    path = buf_or_path
    if isinstance(buf_or_path, (bytes, bytearray)):
        tmp = tempfile.NamedTemporaryFile(suffix=".mp4", delete=False)
        tmp.write(buf_or_path)
        tmp.flush()
        tmp.close()
        path = tmp.name
    try:
        cap = cv2.VideoCapture(path)
        if not cap.isOpened():
            raise ValueError("cv2.VideoCapture failed to open clip")
        n = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
        if n <= 0:
            # some containers don't report frame count; count by decoding
            frames_all = []
            while True:
                ok, frame = cap.read()
                if not ok:
                    break
                frames_all.append(frame)
            n = len(frames_all)
            if n == 0:
                raise ValueError("empty video clip")
            idxs = _choose_indices(n, frame_indices, num_random,
                                   first_last_only, rng)
            out = [frames_all[i] for i in idxs]
        else:
            idxs = _choose_indices(n, frame_indices, num_random,
                                   first_last_only, rng)
            out = []
            for i in idxs:
                cap.set(cv2.CAP_PROP_POS_FRAMES, i)
                ok, frame = cap.read()
                if not ok:
                    raise ValueError(f"failed to decode frame {i}/{n}")
                out.append(frame)
        cap.release()
        return [cv2.cvtColor(f, cv2.COLOR_BGR2RGB) for f in out]
    finally:
        if tmp is not None:
            os.unlink(tmp.name)


def _choose_indices(n, frame_indices, num_random, first_last_only, rng):
    if frame_indices is not None:
        return [i % n for i in frame_indices]
    if first_last_only:
        return [0, max(n - 1, 0)]
    k = min(num_random, n)
    idxs = rng.sample(range(n), k)
    while len(idxs) < num_random:  # clip shorter than requested draws
        idxs.append(idxs[-1])
    return idxs


class Dataset(BaseDataset):
    def __init__(self, cfg, is_inference=False, is_test=False):
        super().__init__(cfg, is_inference, is_test)
        self.is_video_dataset = True
        self.first_last_only = cfg_get(self.cfgdata, "first_last_only", False)
        self.video_data_type = cfg_get(self.cfgdata, "video_data_type",
                                       "videos")
        # flat clip mapping (ref: paired_few_shot_videos_native.py:54-80)
        self.mapping = []
        for root_idx, sequence_list in enumerate(self.sequence_lists):
            for sequence_name, filenames in sequence_list.items():
                for filename in filenames:
                    self.mapping.append((root_idx, sequence_name, filename))
        self.epoch_length = len(self.mapping)

    def __len__(self):
        return self.epoch_length

    def num_inference_sequences(self):
        return len(self.mapping)

    def __getitem__(self, index):
        root_idx, sequence_name, filename = self.mapping[
            index % max(len(self.mapping), 1)]
        raw = self.load_item(root_idx, sequence_name, [filename])

        vt = self.video_data_type
        blob = raw[vt][0]
        try:
            frames = decode_video_frames(
                blob, first_last_only=self.first_last_only)
        except Exception as e:  # noqa: BLE001 — degrade, don't kill the run
            print(f"paired_few_shot_videos_native: bad clip "
                  f"{sequence_name}/{filename}: {e}")
            blank = np.zeros((512, 512, 3), dtype=np.uint8)
            frames = [blank, blank.copy()]
        raw[vt] = frames
        # non-video data types carry one entry per clip; replicate across
        # the two decoded frames so joint augmentation stays paired
        for t in self.data_types:
            if t != vt and len(raw[t]) == 1:
                raw[t] = [raw[t][0], raw[t][0]]

        out = self.process_item(raw)
        out = self.concat_labels(out)
        videos = out.pop(vt)
        out["driving_images"] = videos[0]
        out["source_images"] = videos[1]
        out["key"] = f"{sequence_name}/{filename}"
        out["original_h_w"] = np.array(
            [self.augmentor.original_h, self.augmentor.original_w],
            dtype=np.int32)
        return out

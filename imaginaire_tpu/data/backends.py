"""Storage backends (ref: imaginaire/datasets/{lmdb,folder}.py,
imaginaire/utils/lmdb.py).

Three backends with one interface — ``getitem(key) -> np.ndarray (HWC)``:

  FolderBackend  : raw files under ``root/<data_type>/<sequence>/<file>.<ext>``
                   (ref: datasets/folder.py:15-86).
  LMDBBackend    : readonly LMDB, cv2.imdecode, BGR->RGB
                   (ref: datasets/lmdb.py:17-79) — gated on the ``lmdb``
                   package being installed.
  PackedBackend  : TPU-native equivalent of the LMDB shard: one
                   ``.bin`` blob + ``.idx.json`` offsets per data type,
                   written by ``build_packed_dataset``. Same role (large
                   sequential reads off network storage feeding TPU-VM
                   hosts) with zero external dependencies.
"""

from __future__ import annotations

import json
import os

import cv2
import numpy as np


def _decode_image(buf, ext):
    if ext in ("npy",):
        from io import BytesIO

        return np.load(BytesIO(buf))
    if ext in ("json", "txt"):
        # raw text payloads (keypoint JSON etc.) decode via data-pipeline
        # ops like decode_json (ref: datasets/base.py:446-452)
        return buf.decode("utf-8")
    if ext in ("pkl", "pickle"):
        return buf
    if ext in ("mp4", "avi", "mov", "webm", "mkv"):
        # raw encoded video blob; decoded by the video datasets
        # (paired_few_shot_videos_native) via cv2.VideoCapture
        return buf
    arr = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), cv2.IMREAD_UNCHANGED)
    if arr is None:
        raise ValueError("failed to decode image buffer")
    if arr.ndim == 2:
        arr = arr[:, :, None]
    elif arr.shape[2] == 3:
        arr = cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)
    elif arr.shape[2] == 4:
        arr = cv2.cvtColor(arr, cv2.COLOR_BGRA2RGBA)
    return arr


class FolderBackend:
    """(ref: datasets/folder.py:15-86)."""

    def __init__(self, root, ext=None):
        self.root = root
        self.ext = ext

    def getitem(self, key):
        path = os.path.join(self.root, key)
        if self.ext:
            path = f"{path}.{self.ext}"
        if path.endswith(".npy"):
            return np.load(path)
        with open(path, "rb") as f:
            buf = f.read()
        return _decode_image(buf, path.rsplit(".", 1)[-1])


class LMDBBackend:
    """(ref: datasets/lmdb.py:17-79). Requires the ``lmdb`` package."""

    def __init__(self, root, ext=None):
        try:
            import lmdb
        except ImportError as e:
            raise ImportError(
                "The 'lmdb' package is not installed in this environment; "
                "use the folder backend (is_lmdb: False) or PackedBackend "
                "(is_packed: True) instead.") from e
        self.env = lmdb.open(root, readonly=True, lock=False, readahead=False,
                             meminit=False)
        meta = os.path.join(root, "metadata.json")
        self.ext = ext
        if os.path.exists(meta):
            with open(meta) as f:
                self.ext = json.load(f).get("ext", ext)

    def getitem(self, key):
        with self.env.begin(write=False) as txn:
            buf = txn.get(key.encode())
        if buf is None:
            raise KeyError(key)
        return _decode_image(buf, self.ext)


class PackedBackend:
    """Packed binary shard: ``data.bin`` + ``index.json`` ({key: [off, len,
    ext]}). Reads are a single positioned read — the property LMDB
    provided — served by the native C++ thread-pool reader when the
    toolchain is available (imaginaire_tpu/native), else Python IO."""

    def __init__(self, root, ext=None):
        import threading

        with open(os.path.join(root, "index.json")) as f:
            self.index = json.load(f)
        self.bin_path = os.path.join(root, "data.bin")
        self._f = None
        self._native = None
        self._native_tried = False
        self._lock = threading.Lock()  # prefetch workers share the backend
        self.ext = ext

    def _reader(self):
        with self._lock:
            if not self._native_tried:
                self._native_tried = True
                try:
                    from imaginaire_tpu.native import NativeBlobReader

                    self._native = NativeBlobReader(self.bin_path)
                except Exception:
                    self._native = None
        return self._native

    def _fd(self):
        with self._lock:
            if self._f is None:
                self._f = os.open(self.bin_path, os.O_RDONLY)
        return self._f

    def close(self):
        """Release the fd/native reader. Call only after all reads have
        quiesced (an in-flight pread on the closed fd could hit a
        recycled descriptor); a closed backend stays closed — getitem
        after close reopens the plain fd but never resurrects the
        native reader."""
        with self._lock:
            if self._f is not None:
                os.close(self._f)
                self._f = None
            if self._native is not None:
                self._native.close()
                self._native = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def getitem(self, key):
        off, length, ext = self.index[key]
        native = self._reader()
        if native is not None:
            buf = native.read(off, length)
        else:
            # os.pread is atomic per call — safe under the prefetch
            # thread pool (a shared seek+read handle is not)
            buf = os.pread(self._fd(), length, off)
        return _decode_image(buf, ext or self.ext)

    def getitems(self, keys):
        """Batch fetch: one concurrent native read per extent."""
        native = self._reader()
        entries = [self.index[k] for k in keys]
        if native is not None:
            bufs = native.read_batch([(off, length)
                                      for off, length, _ in entries])
        else:
            bufs = [self.getitem(k) for k in keys]
            return bufs
        return [_decode_image(buf, ext or self.ext)
                for buf, (_, _, ext) in zip(bufs, entries)]


def _walk_dataset_files(data_root, data_types, sequence_files):
    """Yield (data_type, seq, stem, ext, raw bytes) over the
    ``data_root/<data_type>/<sequence>/<file>`` tree in sorted order,
    recording {seq: [stems]} into ``sequence_files`` — the shared walk
    of both builder formats (ref: utils/lmdb.py:56-129)."""
    seen = {}
    for data_type in data_types:
        type_root = os.path.join(data_root, data_type)
        for seq in sorted(os.listdir(type_root)):
            seq_dir = os.path.join(type_root, seq)
            if not os.path.isdir(seq_dir):
                continue
            for fname in sorted(os.listdir(seq_dir)):
                stem, ext = os.path.splitext(fname)
                with open(os.path.join(seq_dir, fname), "rb") as f:
                    buf = f.read()
                if stem not in seen.setdefault(seq, set()):
                    seen[seq].add(stem)
                    sequence_files.setdefault(seq, []).append(stem)
                yield data_type, seq, stem, ext.lstrip("."), buf


def build_packed_dataset(data_root, out_root, data_types):
    """Pack ``data_root/<data_type>/<sequence>/<file>`` trees into one
    blob per data type + all_filenames.json (the builder contract of
    ref: utils/lmdb.py:56-129 / scripts/build_lmdb.py:40-125)."""
    os.makedirs(out_root, exist_ok=True)
    sequence_files = {}
    outs, indices = {}, {}
    for data_type in data_types:
        type_out = os.path.join(out_root, data_type)
        os.makedirs(type_out, exist_ok=True)
        outs[data_type] = open(os.path.join(type_out, "data.bin"), "wb")
        indices[data_type] = {}
    try:
        for data_type, seq, stem, ext, buf in _walk_dataset_files(
                data_root, data_types, sequence_files):
            out = outs[data_type]
            indices[data_type][f"{seq}/{stem}"] = [out.tell(), len(buf),
                                                   ext]
            out.write(buf)
    finally:
        for f in outs.values():
            f.close()
    for data_type in data_types:
        with open(os.path.join(out_root, data_type, "index.json"),
                  "w") as f:
            json.dump(indices[data_type], f)
    with open(os.path.join(out_root, "all_filenames.json"), "w") as f:
        json.dump(sequence_files, f)
    return out_root


def build_lmdb_dataset(data_root, out_root, data_types, map_size=1 << 40):
    """Write the reference's LMDB layout: one readonly LMDB per data
    type (key = 'sequence/stem', value = raw encoded bytes) plus
    metadata.json (extension) and all_filenames.json
    (ref: utils/lmdb.py:56-129, scripts/build_lmdb.py:40-125). Gated on
    the ``lmdb`` package; PackedBackend is the dependency-free
    equivalent."""
    try:
        import lmdb
    except ImportError as e:
        raise ImportError(
            "The 'lmdb' package is not installed; use --format packed "
            "(build_packed_dataset) instead.") from e
    os.makedirs(out_root, exist_ok=True)
    sequence_files = {}
    envs, txns, ext_seen = {}, {}, {}
    for data_type in data_types:
        type_out = os.path.join(out_root, data_type)
        os.makedirs(type_out, exist_ok=True)
        envs[data_type] = lmdb.open(type_out, map_size=map_size)
        txns[data_type] = envs[data_type].begin(write=True)
    try:
        for data_type, seq, stem, ext, buf in _walk_dataset_files(
                data_root, data_types, sequence_files):
            txns[data_type].put(f"{seq}/{stem}".encode(), buf)
            ext_seen[data_type] = ext or ext_seen.get(data_type)
        for txn in txns.values():
            txn.commit()
    finally:
        for env in envs.values():
            env.close()
    for data_type in data_types:
        meta = os.path.join(out_root, data_type, "metadata.json")
        with open(meta, "w") as f:
            json.dump({"ext": ext_seen.get(data_type)}, f)
    with open(os.path.join(out_root, "all_filenames.json"), "w") as f:
        json.dump(sequence_files, f)
    return out_root


def create_folder_metadata(data_root, data_types):
    """Walk a raw folder tree -> {sequence: [stems]} (runtime version of
    the builder's metadata, ref: utils/lmdb.py:132-215)."""
    first_type = data_types[0]
    type_root = os.path.join(data_root, first_type)
    sequences = {}
    for seq in sorted(os.listdir(type_root)):
        seq_dir = os.path.join(type_root, seq)
        if not os.path.isdir(seq_dir):
            continue
        stems = [os.path.splitext(f)[0] for f in sorted(os.listdir(seq_dir))]
        sequences[seq] = stems
    return sequences

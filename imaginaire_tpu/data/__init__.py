"""Data subsystem (ref: imaginaire/datasets/, utils/lmdb.py, utils/data.py).

Host-side numpy pipeline feeding NHWC batches to the jitted train step.
Per-host sharding replaces DistributedSampler (SURVEY.md §2.2): each JAX
process reads its own slice of the global batch; inside jit the batch is
already sharded over the 'data' mesh axis.
"""

from imaginaire_tpu.data.loader import (
    get_test_dataloader,
    get_train_and_val_dataloader,
)

__all__ = ["get_train_and_val_dataloader", "get_test_dataloader"]

"""Batching + per-host sharding loader
(ref: imaginaire/utils/dataset.py:24-83).

Replaces DataLoader + DistributedSampler: each JAX process takes the
index slice ``process_index::process_count`` of the shuffled epoch
(ref sharding: utils/dataset.py:46-50), batches on the host, and yields
dicts of stacked NHWC arrays. ``set_epoch`` reseeds the shuffle like
``DistributedSampler.set_epoch`` (ref: train.py:70).

Elastic pods (ISSUE 11) add a second split mode: with
``global_batch_size`` set, the loader fixes the GLOBAL batch and splits
each global batch block-contiguously — host ``i`` takes rows
``[i*share, (i+1)*share)`` of every batch, and the per-host batch size
is derived from the LIVE world size at iteration time. The strided
split permutes the sample -> mesh-position assignment whenever the
world size changes (different hosts, different rows — a float reduction
over a different operand order is not bit-stable); the block split
keeps global batch ``k`` == ``order[k*G:(k+1)*G]`` in mesh-device order
for ANY world size, which is what makes a 3->2->3 resize bit-exact
against the never-resized run.
"""

from __future__ import annotations

import logging

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.parallel.mesh import get_rank, get_world_size
from imaginaire_tpu.registry import resolve

logger = logging.getLogger(__name__)


class DataLoader:
    def __init__(self, dataset, batch_size, shuffle=True, seed=0,
                 drop_last=True, num_workers=0, prefetch_batches=2,
                 shard_by_process=True, global_batch_size=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.drop_last = drop_last
        self.num_workers = num_workers
        self.prefetch_batches = max(prefetch_batches, 1)
        # False = every process sees every item, in order — required when
        # the items are sequential frames of one pinned video sequence
        # (the video eval harness shards by *sequence* instead)
        self.shard_by_process = shard_by_process
        # one-shot batch skip for mid-epoch resume (resilience/, ISSUE
        # 7): the next __iter__ drops the first N index-batches of the
        # (deterministically seeded) epoch order without loading them
        self._skip_batches = 0
        # elastic (ISSUE 11): a set global_batch_size pins the GLOBAL
        # batch and switches to the block-contiguous split; the
        # per-host batch size becomes global // live-world, re-derived
        # at every access so the SAME loader object keeps yielding
        # correctly after an in-process mesh resize
        self.global_batch_size = (int(global_batch_size)
                                  if global_batch_size else None)
        self._warned_indivisible = None

    @property
    def batch_size(self):
        if self.global_batch_size:
            world = get_world_size() if self.shard_by_process else 1
            share, rem = divmod(self.global_batch_size, max(world, 1))
            if rem and self._warned_indivisible != world:
                self._warned_indivisible = world
                logger.warning(
                    "global_batch_size %d is not divisible by world "
                    "size %d — flooring the per-host batch to %d "
                    "(global batch shrinks to %d; cross-world-size "
                    "bit-exactness is lost at this world)",
                    self.global_batch_size, world, max(share, 1),
                    max(share, 1) * world)
            return max(share, 1)
        return self._batch_size

    @batch_size.setter
    def batch_size(self, value):
        self._batch_size = value

    def set_epoch(self, epoch):
        self.epoch = epoch

    def fast_forward(self, n_batches):
        """Skip the first ``n_batches`` of the NEXT epoch pass (one-shot).

        The epoch order is a pure function of (seed, epoch), so the
        skipped prefix is exactly the batches a killed run already
        consumed — no item is loaded or decoded for them."""
        self._skip_batches = max(int(n_batches), 0)

    def _consume_skip(self, n_batches_total):
        skip = min(self._skip_batches, n_batches_total)
        self._skip_batches = 0
        return skip

    def _fetch(self, idx):
        """One dataset item, with transient-IO retry (a flaky NFS read
        must not kill a run) and the chaos harness's loader fault site."""
        from imaginaire_tpu.resilience import chaos, retry_call

        def _read():
            chaos.get().maybe_io_error("loader")
            return self.dataset[int(idx)]

        return retry_call(_read, label="loader")

    def __len__(self):
        if self.global_batch_size and self.shard_by_process:
            # block mode: the epoch is measured in GLOBAL batches, a
            # world-size-invariant count (each host sees len() batches
            # of its share of every global batch)
            return max(len(self.dataset) // self.global_batch_size, 1)
        shards = get_world_size() if self.shard_by_process else 1
        n = len(self.dataset) // shards
        if self.drop_last:
            return max(n // self.batch_size, 1)
        return (n + self.batch_size - 1) // self.batch_size

    def _order(self):
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        if not self.shard_by_process:
            return order
        world = get_world_size()
        if self.global_batch_size:
            # block-contiguous split (ISSUE 11): global batch k is
            # order[k*G:(k+1)*G] regardless of world size; host i owns
            # rows [i*share, (i+1)*share) of each. Concatenated across
            # hosts in process order (== mesh-device order under the
            # even-spread sub-mesh pick), every global batch is
            # IDENTICAL at any world size — the property the elastic
            # bit-exactness drill checks.
            g = self.global_batch_size
            share = self.batch_size
            nb = len(order) // g
            blocks = order[:nb * g].reshape(nb, g)
            i = get_rank()
            return blocks[:, i * share:(i + 1) * share].reshape(-1)
        # every process must see the SAME number of items per epoch
        # (ISSUE 8): the bare strided split hands early ranks one item
        # more when len(dataset) is not divisible — on a pod that means
        # one host finishes its epoch (and enters the end-of-epoch
        # checkpoint barrier) while its peers are still blocked in a
        # step collective waiting for it: a guaranteed desync every
        # epoch. Truncating to the common floor (the contract __len__
        # already promises) keeps all ranks in lockstep; the dropped
        # remainder rotates with the epoch shuffle.
        usable = (len(order) // world) * world
        return order[:usable][get_rank()::world]

    def __iter__(self):
        if self.num_workers > 0:
            yield from self._iter_prefetch()
            return
        order = self._order()
        skip = self._consume_skip(len(order) // self.batch_size
                                  if self.batch_size else 0)
        order = order[skip * self.batch_size:]
        batch = []
        for idx in order:
            batch.append(self._fetch(idx))
            if len(batch) == self.batch_size:
                yield self._collate(batch)
                batch = []
        if batch and not self.drop_last:
            yield self._collate(batch)

    def _iter_prefetch(self):
        """Worker-threaded pipeline (the num_workers contract of the
        reference's DataLoader, ref: utils/dataset.py:56-61): samples
        load+decode in a thread pool (cv2/numpy release the GIL; packed
        shards read through the native C++ pool) while the trainer
        consumes the previous batch; a bounded queue caps read-ahead.

        Lifecycle: worker exceptions travel through the queue and re-raise
        in the consumer; abandoning the iterator early (next(iter(...)),
        break, GeneratorExit) sets a stop flag and drains the queue so the
        producer's blocked put always unwinds — no deadlock either way."""
        import queue
        import threading
        from concurrent.futures import ThreadPoolExecutor

        order = self._order()
        batches = [order[i:i + self.batch_size]
                   for i in range(0, len(order), self.batch_size)]
        if self.drop_last and batches and \
                len(batches[-1]) < self.batch_size:
            batches.pop()
        batches = batches[self._consume_skip(len(batches)):]
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_batches)
        stop = threading.Event()
        sentinel = object()

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def produce():
            try:
                with ThreadPoolExecutor(self.num_workers) as pool:
                    for idxs in batches:
                        if stop.is_set():
                            return
                        futures = [pool.submit(self._fetch, int(i))
                                   for i in idxs]
                        put(self._collate([f.result() for f in futures]))
            except BaseException as e:  # forwarded to the consumer
                put(e)
            finally:
                put(sentinel)

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            producer.join(timeout=10)

    @staticmethod
    def _collate(items):
        out = {}
        for k in items[0]:
            vals = [it[k] for it in items]
            if isinstance(vals[0], np.ndarray) and vals[0].dtype != object:
                out[k] = np.stack(vals, axis=0)
            else:
                out[k] = vals
        return out


def _build_dataset(cfg, is_inference=False, is_test=False):
    """(ref: utils/dataset.py:24-43)."""
    dataset_cls = resolve(cfg.test_data.type if is_test else cfg.data.type,
                          "Dataset")
    return dataset_cls(cfg, is_inference=is_inference, is_test=is_test)


def get_train_and_val_dataloader(cfg, seed=0):
    """(ref: utils/dataset.py:63-83)."""
    train_ds = _build_dataset(cfg, is_inference=False)
    val_ds = _build_dataset(cfg, is_inference=True)
    num_workers = cfg_get(cfg.data, "num_workers", 0)
    prefetch = cfg_get(cfg.data, "prefetch", 2)
    # elastic pods (ISSUE 11): data.train.global_batch_size pins the
    # GLOBAL batch and activates the block-contiguous split — the
    # per-host batch follows the live world size across resizes
    global_bs = cfg_get(cfg.data.train, "global_batch_size", None)
    train = DataLoader(train_ds, cfg_get(cfg.data.train, "batch_size", 1),
                       shuffle=True, seed=seed, num_workers=num_workers,
                       prefetch_batches=prefetch,
                       global_batch_size=global_bs)
    val = DataLoader(val_ds, cfg_get(cfg.data.val, "batch_size", 1),
                     shuffle=False, seed=seed, num_workers=num_workers,
                     prefetch_batches=prefetch,
                     global_batch_size=cfg_get(cfg.data.val,
                                               "global_batch_size",
                                               None))
    return train, val


def get_test_dataloader(cfg):
    ds = _build_dataset(cfg, is_inference=True, is_test=True)
    return DataLoader(ds, cfg_get(cfg.test_data.test, "batch_size", 1),
                      shuffle=False, drop_last=False)

"""Batching + per-host sharding loader
(ref: imaginaire/utils/dataset.py:24-83).

Replaces DataLoader + DistributedSampler: each JAX process takes the
index slice ``process_index::process_count`` of the shuffled epoch
(ref sharding: utils/dataset.py:46-50), batches on the host, and yields
dicts of stacked NHWC arrays. ``set_epoch`` reseeds the shuffle like
``DistributedSampler.set_epoch`` (ref: train.py:70).
"""

from __future__ import annotations

import numpy as np

from imaginaire_tpu.config import cfg_get
from imaginaire_tpu.parallel.mesh import get_rank, get_world_size
from imaginaire_tpu.registry import resolve


class DataLoader:
    def __init__(self, dataset, batch_size, shuffle=True, seed=0,
                 drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.drop_last = drop_last

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        n = len(self.dataset) // get_world_size()
        if self.drop_last:
            return max(n // self.batch_size, 1)
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        order = order[get_rank()::get_world_size()]
        batch = []
        for idx in order:
            batch.append(self.dataset[int(idx)])
            if len(batch) == self.batch_size:
                yield self._collate(batch)
                batch = []
        if batch and not self.drop_last:
            yield self._collate(batch)

    @staticmethod
    def _collate(items):
        out = {}
        for k in items[0]:
            vals = [it[k] for it in items]
            if isinstance(vals[0], np.ndarray) and vals[0].dtype != object:
                out[k] = np.stack(vals, axis=0)
            else:
                out[k] = vals
        return out


def _build_dataset(cfg, is_inference=False, is_test=False):
    """(ref: utils/dataset.py:24-43)."""
    dataset_cls = resolve(cfg.test_data.type if is_test else cfg.data.type,
                          "Dataset")
    return dataset_cls(cfg, is_inference=is_inference, is_test=is_test)


def get_train_and_val_dataloader(cfg, seed=0):
    """(ref: utils/dataset.py:63-83)."""
    train_ds = _build_dataset(cfg, is_inference=False)
    val_ds = _build_dataset(cfg, is_inference=True)
    train = DataLoader(train_ds, cfg_get(cfg.data.train, "batch_size", 1),
                       shuffle=True, seed=seed)
    val = DataLoader(val_ds, cfg_get(cfg.data.val, "batch_size", 1),
                     shuffle=False, seed=seed)
    return train, val


def get_test_dataloader(cfg):
    ds = _build_dataset(cfg, is_inference=True, is_test=True)
    return DataLoader(ds, cfg_get(cfg.test_data.test, "batch_size", 1),
                      shuffle=False, drop_last=False)

"""Model zoo: generators and discriminators."""

"""MUNIT discriminator (ref: imaginaire/discriminators/munit.py:11-110).

One discriminator per domain: multi-resolution patch (scene images,
pixel-correspondence-preserving) or global residual (centered objects),
selected by ``patch_wise``. Outputs out_ab/out_ba (+ real and
reconstruction heads) with the features used by consistency
regularization.
"""

from __future__ import annotations

from typing import Any

from flax import linen as nn

from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.models.discriminators.multires_patch import (
    MultiResPatchDiscriminator,
)
from imaginaire_tpu.models.discriminators.residual import ResDiscriminator


def _make_domain_dis(dis_cfg, patch_key, weight_shared, name):
    dis_cfg = as_attrdict(dis_cfg)
    if cfg_get(dis_cfg, patch_key, True):
        return MultiResPatchDiscriminator(
            num_discriminators=cfg_get(dis_cfg, "num_discriminators", 3),
            kernel_size=cfg_get(dis_cfg, "kernel_size", 3),
            num_filters=cfg_get(dis_cfg, "num_filters", 64),
            num_layers=cfg_get(dis_cfg, "num_layers", 4),
            max_num_filters=cfg_get(dis_cfg, "max_num_filters", 512),
            activation_norm_type=cfg_get(dis_cfg, "activation_norm_type", "none"),
            weight_norm_type=cfg_get(dis_cfg, "weight_norm_type", ""),
            weight_shared=weight_shared,
            remat=cfg_get(dis_cfg, "remat", "none"),
            name=name)
    return ResDiscriminator(
        num_filters=cfg_get(dis_cfg, "num_filters", 64),
        max_num_filters=cfg_get(dis_cfg, "max_num_filters", 512),
        first_kernel_size=cfg_get(dis_cfg, "first_kernel_size", 1),
        num_layers=cfg_get(dis_cfg, "num_layers", 4),
        padding_mode=cfg_get(dis_cfg, "padding_mode", "zeros"),
        activation_norm_type=cfg_get(dis_cfg, "activation_norm_type", ""),
        weight_norm_type=cfg_get(dis_cfg, "weight_norm_type", ""),
        aggregation=cfg_get(dis_cfg, "aggregation", "conv"),
        order=cfg_get(dis_cfg, "order", "pre_act"),
        remat=cfg_get(dis_cfg, "remat", "none"),
        name=name)


class Discriminator(nn.Module):
    """(ref: discriminators/munit.py:11-110)."""

    dis_cfg: Any
    data_cfg: Any = None
    patch_key: str = "patch_wise"
    weight_shared: bool = False

    def setup(self):
        self.discriminator_a = _make_domain_dis(
            self.dis_cfg, self.patch_key, self.weight_shared, "dis_a")
        self.discriminator_b = _make_domain_dis(
            self.dis_cfg, self.patch_key, self.weight_shared, "dis_b")

    def __call__(self, data, net_G_output, real=True, gan_recon=False,
                 training=False):
        out = {}
        out_ab, fea_ab, _ = self.discriminator_b(net_G_output["images_ab"],
                                                 training=training)
        out_ba, fea_ba, _ = self.discriminator_a(net_G_output["images_ba"],
                                                 training=training)
        out.update(out_ab=out_ab, out_ba=out_ba, fea_ab=fea_ab, fea_ba=fea_ba)
        if real:
            out_a, fea_a, _ = self.discriminator_a(data["images_a"],
                                                   training=training)
            out_b, fea_b, _ = self.discriminator_b(data["images_b"],
                                                   training=training)
            out.update(out_a=out_a, out_b=out_b, fea_a=fea_a, fea_b=fea_b)
        if gan_recon:
            out_aa, fea_aa, _ = self.discriminator_a(net_G_output["images_aa"],
                                                     training=training)
            out_bb, fea_bb, _ = self.discriminator_b(net_G_output["images_bb"],
                                                     training=training)
            out.update(out_aa=out_aa, out_bb=out_bb,
                       fea_aa=fea_aa, fea_bb=fea_bb)
        return out

"""SPADE combined discriminator (ref: imaginaire/discriminators/spade.py):
FPSE FPN discriminator + N multi-resolution patch discriminators over
concat(label, image). Output list = [fpse pred2, pred3, pred4, patch...];
features only from the patch Ds (FM loss), matching the reference.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.models.discriminators.fpse import FPSEDiscriminator
from imaginaire_tpu.models.discriminators.multires_patch import (
    NLayerPatchDiscriminator,
    _downsample2x_bilinear,
)
from imaginaire_tpu.utils.data import (
    get_paired_input_label_channel_number,
)


class Discriminator(nn.Module):
    dis_cfg: Any
    data_cfg: Any

    def setup(self):
        dis_cfg = as_attrdict(self.dis_cfg)
        data_cfg = as_attrdict(self.data_cfg)
        video = str(cfg_get(data_cfg, "type", "")).endswith("paired_videos")
        num_labels = get_paired_input_label_channel_number(data_cfg, video=video)
        num_filters = cfg_get(dis_cfg, "num_filters", 128)
        weight_norm_type = cfg_get(dis_cfg, "weight_norm_type", "spectral")
        remat = cfg_get(dis_cfg, "remat", "none")
        self.num_discriminators = cfg_get(dis_cfg, "num_discriminators", 2)
        self.patch_ds = [
            NLayerPatchDiscriminator(
                kernel_size=cfg_get(dis_cfg, "kernel_size", 3),
                num_filters=num_filters,
                num_layers=cfg_get(dis_cfg, "num_layers", 5),
                max_num_filters=cfg_get(dis_cfg, "max_num_filters", 512),
                activation_norm_type=cfg_get(dis_cfg, "activation_norm_type", "none"),
                weight_norm_type=weight_norm_type,
                remat=remat,
                name=f"patch_d_{i}",
            )
            for i in range(self.num_discriminators)
        ]
        self.fpse_discriminator = FPSEDiscriminator(
            num_labels=num_labels,
            num_filters=num_filters,
            kernel_size=cfg_get(dis_cfg, "fpse_kernel_size", 3),
            weight_norm_type=weight_norm_type,
            activation_norm_type=cfg_get(dis_cfg, "fpse_activation_norm_type", "none"),
            remat=remat,
            name="fpse",
        )

    def _single_forward(self, label, image, training):
        """(ref: discriminators/spade.py:73-89)."""
        pred2, pred3, pred4 = self.fpse_discriminator(image, label, training=training)
        outputs = [pred2, pred3, pred4]
        features_list = []
        x = jnp.concatenate([label, image], axis=-1)
        for i, d in enumerate(self.patch_ds):
            logits, feats = d(x, training=training)
            outputs.append(logits)
            features_list.append(feats)
            if i != self.num_discriminators - 1:
                x = _downsample2x_bilinear(x)
        return outputs, features_list

    def __call__(self, data, net_G_output, training=False):
        out = {}
        out["real_outputs"], out["real_features"] = self._single_forward(
            data["label"], data["images"], training)
        out["fake_outputs"], out["fake_features"] = self._single_forward(
            data["label"], net_G_output["fake_images"], training)
        return out

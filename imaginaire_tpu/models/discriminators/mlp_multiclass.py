"""MLP multi-class classifier discriminator
(ref: imaginaire/discriminators/mlp_multiclass.py:13-110; pose data).

Dropout schedule matches the reference: 0.1 growing 1.5x per layer,
capped at 0.5. Dropout draws from the 'dropout' RNG stream when training.
"""

from __future__ import annotations

from typing import Any

import numpy as np
from flax import linen as nn

from imaginaire_tpu.config import as_attrdict, cfg_get
from imaginaire_tpu.layers import LinearBlock


class Discriminator(nn.Module):
    dis_cfg: Any
    data_cfg: Any = None

    @nn.compact
    def __call__(self, data, training=False):
        dis_cfg = as_attrdict(self.dis_cfg)
        num_labels = dis_cfg.num_labels
        num_layers = cfg_get(dis_cfg, "num_layers", 5)
        num_filters = cfg_get(dis_cfg, "num_filters", 512)
        activation_norm_type = cfg_get(dis_cfg, "activation_norm_type", "batch")
        nonlinearity = cfg_get(dis_cfg, "nonlinearity", "leakyrelu")

        x = data["data"]
        x = x.reshape(x.shape[0], -1)
        dropout_ratio = 0.1
        x = LinearBlock(num_filters, activation_norm_type=activation_norm_type,
                        nonlinearity=nonlinearity, order="CNA", name="fc_in")(
            x, training=training)
        x = nn.Dropout(dropout_ratio, deterministic=not training)(x)
        for n in range(num_layers):
            dropout_ratio = float(np.minimum(dropout_ratio * 1.5, 0.5))
            x = LinearBlock(num_filters, activation_norm_type=activation_norm_type,
                            nonlinearity=nonlinearity, order="CNA", name=f"fc_{n}")(
                x, training=training)
            x = nn.Dropout(dropout_ratio, deterministic=not training)(x)
        scores = LinearBlock(num_labels, name="fc_out")(x, training=training)
        return {"results": scores}
